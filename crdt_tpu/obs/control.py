"""SLO-driven control plane: deterministic tick-synchronous rules.

Rounds 16-21 built the sensor half of a feedback loop — per-tenant
burn rates (:mod:`crdt_tpu.obs.slo`), admission-queue pressure,
pool/resident occupancy, snapshot fallback counts — and every one of
those signals was write-only: nothing read them back, so a flooding
tenant kept its static :class:`crdt_tpu.guard.tenant.TenantBudget`
until an operator intervened. This module (round 22, ROADMAP item 2
"CLOSE THE LOOP") is the actuator half: a :class:`Controller` that
:class:`crdt_tpu.models.multidoc.MultiDocServer` consults exactly
once per tick, reading a plain-dict **sensor snapshot** and answering
an :class:`Actuation` — per-tenant budget overrides, an LRU
protection set, a ``max_rows_per_dispatch`` setpoint, and a
checkpoint-cadence trigger.

**Determinism is the contract.** No rule reads a wall clock; every
window, cooldown, and hysteresis counter is indexed by the server's
tick number, and tenants are visited in sorted order. An identical
sensor trace therefore replays to a byte-identical decision ledger
(:meth:`Controller.replay`, pinned in ``tests/test_control.py``),
which is what turns "the budget dropped" from magic into
observability: ``tools/obsq.py control`` answers *why did tenant T's
budget drop at tick 412* offline from the JSONL dump alone.

**Rules** (each with a tick-indexed cooldown so an oscillating sensor
cannot flap a setpoint faster than ``cooldown_ticks``):

- ``budget_squeeze`` — a tenant whose burn rate breaches ``burn_hi``
  gets its admission budget divided by ``squeeze_div`` (floor 1) and
  its docs join the LRU protection set.
- ``budget_restore`` — a squeezed tenant that stays at or below
  ``burn_lo`` for ``restore_after`` consecutive observed ticks gets
  its static budget back (hysteresis: one clean tick is not enough).
- ``rows_squeeze`` / ``rows_restore`` — total pending bytes above
  ``pace_pending_bytes`` halves ``max_rows_per_dispatch`` (floor
  ``rows_floor``); sustained calm restores the base value.
- ``checkpoint_cadence`` — every ``checkpoint_every_ticks`` ticks or
  ``checkpoint_every_bytes`` settled bytes, ask the server for a
  background checkpoint so a restart never replays more than one
  cadence of WAL tail (ROADMAP item 4 remainder c).

Every decision lands in the bounded :class:`ControlLedger` (tick,
rule, sensors, old -> new setpoint, cooldown state), served live at
the ``/control`` HTTP endpoint, annotated into the Perfetto tick
timeline as instant events, and federated by the fleet collector as
placement *advice* rows.

Tracer emission (README "Control plane" registry; gated on
``tracer.enabled``): counters ``control.decisions`` (+
``control.decisions{rule=}``), ``control.cooldown_skips``,
``control.ledger_dropped``; gauges ``control.setpoint{knob=}``.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import (Any, Callable, Dict, FrozenSet, List, NamedTuple,
                    Optional, Tuple)

from crdt_tpu.obs.tracer import get_tracer

DEFAULT_BURN_HI = 0.5
DEFAULT_BURN_LO = 0.25
DEFAULT_SQUEEZE_DIV = 4
DEFAULT_RESTORE_AFTER = 3
DEFAULT_COOLDOWN_TICKS = 8
DEFAULT_LEDGER_CAPACITY = 1024
DEFAULT_TRACE_CAPACITY = 4096
DEFAULT_ROWS_FLOOR = 1024

RULES = (
    "budget_squeeze", "budget_restore",
    "rows_squeeze", "rows_restore",
    "checkpoint_cadence",
)


class ControlLedger:
    """Bounded decision log: every rule firing, oldest-first.

    Rows are plain JSON-ready dicts; :meth:`to_jsonl` renders them
    with sorted keys so a replayed controller's ledger compares
    byte-for-byte. When the ring is full the oldest row is dropped
    and counted (``control.ledger_dropped`` — gated lower-is-better
    in ``tools/metrics_diff.py``: a hot control loop that churns its
    own audit trail is a finding, not a feature).
    """

    def __init__(self, capacity: int = DEFAULT_LEDGER_CAPACITY):
        self.capacity = max(1, int(capacity))
        self._rows: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.total = 0
        self.dropped = 0

    def append(self, row: Dict[str, Any]) -> None:
        tracer = get_tracer()
        with self._lock:
            if len(self._rows) == self.capacity:
                self.dropped += 1
                if tracer.enabled:
                    tracer.count("control.ledger_dropped", 1)
            self._rows.append(row)
            self.total += 1

    def rows(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._rows)

    def tail(self, n: int) -> List[Dict[str, Any]]:
        with self._lock:
            if n <= 0:
                return []
            return list(self._rows)[-n:]

    def to_jsonl(self) -> str:
        return "".join(
            json.dumps(r, sort_keys=True) + "\n" for r in self.rows()
        )

    def dump_jsonl(self, path: str) -> int:
        """Write the ledger as JSONL; returns the row count."""
        rows = self.rows()
        with open(path, "w") as f:
            for r in rows:
                f.write(json.dumps(r, sort_keys=True) + "\n")
        return len(rows)


class Actuation(NamedTuple):
    """One tick's actuator outputs, applied by the server.

    ``tenant_limits`` maps tenant -> ``(max_bytes, max_updates)``
    overrides (the full current override set, not a delta — the
    server reconciles). ``max_rows`` is ``None`` when the pacing
    setpoint is unchanged. ``rows`` carries the ledger rows appended
    THIS tick so the server can annotate its timeline without
    re-scanning the ledger.
    """

    tenant_limits: Dict[Any, Tuple[int, int]]
    protect: FrozenSet
    max_rows: Optional[int]
    checkpoint: bool
    rows: List[Dict[str, Any]]


class Controller:
    """Deterministic per-tick rule engine (see module doc).

    ``observe(sensors)`` is the whole read-side API: the server
    builds one JSON-ready sensor snapshot per tick and the controller
    answers an :class:`Actuation`. The snapshot is also recorded in a
    bounded trace ring so :meth:`replay` can re-run the exact
    decision sequence offline.
    """

    def __init__(self, *,
                 burn_hi: float = DEFAULT_BURN_HI,
                 burn_lo: float = DEFAULT_BURN_LO,
                 squeeze_div: int = DEFAULT_SQUEEZE_DIV,
                 restore_after: int = DEFAULT_RESTORE_AFTER,
                 cooldown_ticks: int = DEFAULT_COOLDOWN_TICKS,
                 ledger_capacity: int = DEFAULT_LEDGER_CAPACITY,
                 trace_capacity: int = DEFAULT_TRACE_CAPACITY,
                 pace_pending_bytes: Optional[int] = None,
                 rows_floor: int = DEFAULT_ROWS_FLOOR,
                 checkpoint_every_ticks: Optional[int] = None,
                 checkpoint_every_bytes: Optional[int] = None):
        self.burn_hi = float(burn_hi)
        self.burn_lo = float(burn_lo)
        self.squeeze_div = max(2, int(squeeze_div))
        self.restore_after = max(1, int(restore_after))
        self.cooldown_ticks = max(0, int(cooldown_ticks))
        self.pace_pending_bytes = (
            int(pace_pending_bytes) if pace_pending_bytes else None
        )
        self.rows_floor = max(1, int(rows_floor))
        self.checkpoint_every_ticks = (
            int(checkpoint_every_ticks) if checkpoint_every_ticks
            else None
        )
        self.checkpoint_every_bytes = (
            int(checkpoint_every_bytes) if checkpoint_every_bytes
            else None
        )
        self.ledger = ControlLedger(ledger_capacity)
        # bounded sensor trace: the replay/audit input
        self.trace: deque = deque(maxlen=max(1, int(trace_capacity)))
        self.decisions = 0
        self.cooldown_skips = 0
        # rule state — ALL tick-indexed, never wall-clock
        self._overrides: Dict[Any, Tuple[int, int]] = {}
        self._squeezed_at: Dict[Any, int] = {}
        # round 24: monotonic advice sequencing — every squeeze
        # stamps its advice row with a fresh seq, so the fleet
        # placement loop consuming federated rows can drop
        # duplicated/reordered advice idempotently
        self._advice_seq = 0
        self._squeezed_seq: Dict[Any, int] = {}
        # round 24: optional destination hint — the fleet layer
        # wires this to ``HashRing.least_loaded_successor`` so
        # advice rows name WHERE to move the tenant, not just away
        # from here. Not part of config(): replay without the hook
        # reproduces every decision (target is advisory, never an
        # input to the rules).
        self.placement_hint: Optional[Callable[[Any],
                                               Optional[str]]] = None
        self._clean: Dict[Any, int] = {}
        self._last_burn: Dict[Any, float] = {}
        self._cooldown_until: Dict[Any, int] = {}
        self._base_rows: Optional[int] = None
        self._rows_setpoint: Optional[int] = None
        self._rows_calm = 0
        self._last_ckpt_tick = 0
        self._ckpt_bytes_mark = 0

    # -- config / reporting --------------------------------------------

    def config(self) -> Dict[str, Any]:
        return {
            "burn_hi": self.burn_hi,
            "burn_lo": self.burn_lo,
            "squeeze_div": self.squeeze_div,
            "restore_after": self.restore_after,
            "cooldown_ticks": self.cooldown_ticks,
            "pace_pending_bytes": self.pace_pending_bytes,
            "rows_floor": self.rows_floor,
            "checkpoint_every_ticks": self.checkpoint_every_ticks,
            "checkpoint_every_bytes": self.checkpoint_every_bytes,
            "ledger_capacity": self.ledger.capacity,
        }

    def overrides(self) -> Dict[Any, Tuple[int, int]]:
        return dict(self._overrides)

    def advice(self) -> List[Dict[str, Any]]:
        """Placement advice for the fleet layer: one row per tenant
        the controller is actively squeezing — ROADMAP item 2's
        rebalance hint, consumed cross-process by
        ``fleet.loop.PlacementLoop`` (round 24). ``seq`` is
        monotonic per squeeze (duplicate/reordered rows dedup at
        the consumer); ``target`` is the advised destination (the
        least-loaded ring successor when the fleet layer wires
        :attr:`placement_hint`, ``None`` in-process)."""
        rows = []
        for t in sorted(self._overrides, key=str):
            target = None
            if self.placement_hint is not None:
                target = self.placement_hint(t)
            rows.append({
                "action": "rebalance_away",
                "tenant": str(t),
                "since_tick": self._squeezed_at.get(t, 0),
                "burn": round(self._last_burn.get(t, 0.0), 4),
                "seq": self._squeezed_seq.get(t, 0),
                "target": target,
            })
        return rows

    def report(self, limit: int = 128) -> Dict[str, Any]:
        """JSON-ready state: the ``/control`` endpoint payload."""
        return {
            "config": self.config(),
            "decisions": self.decisions,
            "cooldown_skips": self.cooldown_skips,
            "ledger_total": self.ledger.total,
            "ledger_dropped": self.ledger.dropped,
            "setpoints": {
                "max_rows": self._rows_setpoint,
                "tenants": {
                    str(t): list(v)
                    for t, v in sorted(
                        self._overrides.items(),
                        key=lambda kv: str(kv[0]),
                    )
                },
            },
            "advice": self.advice(),
            "rows": self.ledger.tail(max(0, int(limit))),
        }

    # -- the rule engine -----------------------------------------------

    def _cooled(self, key, tick: int) -> bool:
        """True when ``key``'s cooldown has expired at ``tick``;
        counts the skip otherwise."""
        until = self._cooldown_until.get(key, 0)
        if tick < until:
            self.cooldown_skips += 1
            tracer = get_tracer()
            if tracer.enabled:
                tracer.count("control.cooldown_skips", 1)
            return False
        return True

    def _decide(self, tick: int, rule: str, tenant, knob: str,
                old, new, sensors: Dict[str, Any],
                cooldown_key=None) -> Dict[str, Any]:
        if cooldown_key is not None:
            self._cooldown_until[cooldown_key] = (
                tick + self.cooldown_ticks
            )
        row = {
            "tick": tick,
            "rule": rule,
            "tenant": None if tenant is None else str(tenant),
            "knob": knob,
            "old": old,
            "new": new,
            "sensors": sensors,
            "cooldown_until": (
                self._cooldown_until.get(cooldown_key, 0)
                if cooldown_key is not None else 0
            ),
        }
        self.ledger.append(row)
        self.decisions += 1
        tracer = get_tracer()
        if tracer.enabled:
            tracer.count("control.decisions", 1)
            tracer.count("control.decisions", 1,
                         labels={"rule": rule})
        return row

    def _gauge_setpoint(self, knob: str, value) -> None:
        tracer = get_tracer()
        if tracer.enabled and value is not None:
            tracer.gauge("control.setpoint", float(value),
                         labels={"knob": knob})

    def observe(self, sensors: Dict[str, Any]) -> Actuation:
        """Run every rule against one tick's sensor snapshot.

        ``sensors`` must be JSON-ready (the trace IS the replay
        input) with at least ``tick``; recognized keys: ``max_rows``,
        ``pending_bytes``, ``settled_bytes``,
        ``budget {max_bytes,max_updates}``, and per-tenant
        ``tenants {t: {burn, shed, pending_bytes}}``.
        """
        self.trace.append(sensors)
        tick = int(sensors.get("tick", 0))
        rows: List[Dict[str, Any]] = []
        budget = sensors.get("budget") or {}
        base_bytes = int(budget.get("max_bytes", 1) or 1)
        base_updates = int(budget.get("max_updates", 1) or 1)
        tenants = sensors.get("tenants") or {}
        checkpoint = False

        # -- per-tenant budget squeeze / restore (sorted: determinism)
        for t in sorted(tenants, key=str):
            s = tenants[t] or {}
            burn = float(s.get("burn", 0.0))
            self._last_burn[t] = burn
            key = ("budget", t)
            if t not in self._overrides:
                if burn >= self.burn_hi and self._cooled(key, tick):
                    new = (max(1, base_bytes // self.squeeze_div),
                           max(1, base_updates // self.squeeze_div))
                    self._overrides[t] = new
                    self._squeezed_at[t] = tick
                    self._clean[t] = 0
                    self._advice_seq += 1
                    self._squeezed_seq[t] = self._advice_seq
                    rows.append(self._decide(
                        tick, "budget_squeeze", t, "tenant_budget",
                        [base_bytes, base_updates], list(new),
                        {"burn": round(burn, 4),
                         "shed": int(s.get("shed", 0)),
                         "pending_bytes":
                             int(s.get("pending_bytes", 0))},
                        cooldown_key=key,
                    ))
                    self._gauge_setpoint("tenant_budget_bytes",
                                         new[0])
                    self._gauge_setpoint("tenant_budget_updates",
                                         new[1])
            else:
                if burn <= self.burn_lo:
                    self._clean[t] = self._clean.get(t, 0) + 1
                else:
                    self._clean[t] = 0
                if (self._clean.get(t, 0) >= self.restore_after
                        and self._cooled(key, tick)):
                    old = self._overrides.pop(t)
                    self._squeezed_at.pop(t, None)
                    self._clean.pop(t, None)
                    rows.append(self._decide(
                        tick, "budget_restore", t, "tenant_budget",
                        list(old), [base_bytes, base_updates],
                        {"burn": round(burn, 4),
                         "clean_ticks": self.restore_after},
                        cooldown_key=key,
                    ))
                    self._gauge_setpoint("tenant_budget_bytes",
                                         base_bytes)
                    self._gauge_setpoint("tenant_budget_updates",
                                         base_updates)

        # -- dispatch pacing: max_rows_per_dispatch ---------------------
        max_rows: Optional[int] = None
        if self.pace_pending_bytes:
            if self._base_rows is None:
                self._base_rows = int(sensors.get("max_rows", 0) or 0)
            pending = int(sensors.get("pending_bytes", 0))
            cur = (self._rows_setpoint if self._rows_setpoint
                   is not None else self._base_rows)
            if pending >= self.pace_pending_bytes:
                self._rows_calm = 0
                new_rows = max(self.rows_floor, cur // 2)
                if new_rows < cur and self._cooled("rows", tick):
                    self._rows_setpoint = max_rows = new_rows
                    rows.append(self._decide(
                        tick, "rows_squeeze", None, "max_rows",
                        cur, new_rows,
                        {"pending_bytes": pending},
                        cooldown_key="rows",
                    ))
                    self._gauge_setpoint("max_rows", new_rows)
            elif self._rows_setpoint is not None:
                if pending < self.pace_pending_bytes // 2:
                    self._rows_calm += 1
                else:
                    self._rows_calm = 0
                if (self._rows_calm >= self.restore_after
                        and self._cooled("rows", tick)):
                    old = self._rows_setpoint
                    self._rows_setpoint = None
                    self._rows_calm = 0
                    max_rows = self._base_rows
                    rows.append(self._decide(
                        tick, "rows_restore", None, "max_rows",
                        old, self._base_rows,
                        {"pending_bytes": pending,
                         "calm_ticks": self.restore_after},
                        cooldown_key="rows",
                    ))
                    self._gauge_setpoint("max_rows", self._base_rows)

        # -- background checkpoint cadence ------------------------------
        settled = int(sensors.get("settled_bytes", 0))
        due_ticks = (
            self.checkpoint_every_ticks is not None
            and tick - self._last_ckpt_tick
            >= self.checkpoint_every_ticks
        )
        due_bytes = (
            self.checkpoint_every_bytes is not None
            and settled - self._ckpt_bytes_mark
            >= self.checkpoint_every_bytes
        )
        if due_ticks or due_bytes:
            checkpoint = True
            rows.append(self._decide(
                tick, "checkpoint_cadence", None, "checkpoint",
                self._last_ckpt_tick, tick,
                {"settled_bytes": settled - self._ckpt_bytes_mark,
                 "by": "ticks" if due_ticks else "bytes"},
            ))
            self._last_ckpt_tick = tick
            self._ckpt_bytes_mark = settled
            self._gauge_setpoint("checkpoint_tick", tick)

        return Actuation(
            tenant_limits=dict(self._overrides),
            protect=frozenset(self._overrides),
            max_rows=max_rows,
            checkpoint=checkpoint,
            rows=rows,
        )

    # -- offline replay -------------------------------------------------

    @classmethod
    def replay(cls, trace, **config) -> "Controller":
        """Re-run a recorded sensor trace through a fresh controller.

        With the same config, ``replay(list(c.trace),
        **c.config_kwargs)`` produces a ledger whose
        :meth:`ControlLedger.to_jsonl` is byte-identical to the
        original — the determinism pin, and the offline audit path
        (``obsq control``)."""
        c = cls(**config)
        for sensors in trace:
            c.observe(sensors)
        return c
