"""Divergence sentinel: snapshot-hash beacons over anti-entropy.

CRDT convergence failures are the worst kind of bug: two replicas
whose state vectors agree (every op delivered) but whose STATES
differ (a merge-order bug, a corrupted store, a byzantine peer) look
perfectly healthy to the sync protocol — nothing retries, nothing
repairs, the fork is silent and permanent. The sentinel turns that
into an observable event:

- each replica periodically broadcasts a **beacon** riding the
  anti-entropy cadence: its state vector, a digest of its canonical
  state snapshot (``encode_state_as_update()`` — byte-identical
  across converged replicas, the invariant tests/test_faults.py
  pins), and a digest of its delete set;
- a receiver whose state vector EQUALS the sender's compares digests:
  equal SVs + equal delete sets + different snapshot digests is, by
  CRDT determinism, impossible for honest replicas — the sentinel
  raises a divergence event carrying a flight-recorder dump for the
  postmortem. Unequal SVs (or delete-set digests: tombstones ride
  outside state vectors, so a delete-only update in flight is lag,
  not divergence) are ordinary propagation lag and stay silent.

The check is sound, not complete: a fork confined to tombstones alone
hides behind the delete-set guard until a record lands on either
side. That trade keeps the sentinel silent across every honest
transient the sync protocol produces.
"""

from __future__ import annotations

import hashlib
from typing import Any, Callable, Dict, List, Optional

from crdt_tpu.obs.recorder import FlightRecorder, get_recorder
from crdt_tpu.obs.tracer import Tracer, get_tracer


def state_digest(doc) -> str:
    """Digest of the doc's canonical full-state snapshot. Converged
    replicas encode byte-identical snapshots (pinned invariant), so
    equal states <=> equal digests."""
    return hashlib.sha1(doc.encode_state_as_update()).hexdigest()[:16]


def delete_set_digest(doc) -> str:
    """Digest of the doc's normalized delete-set ranges (tombstones
    live OUTSIDE state vectors; the sentinel must not call a
    tombstone-only deficit a fork)."""
    ds = doc.engine.delete_set()
    h = hashlib.sha1()
    for c, s, n in ds.iter_all():
        h.update(f"{c}:{s}:{n};".encode())
    return h.hexdigest()[:16]


class DivergenceSentinel:
    """Per-replica sentinel state: builds outgoing beacons, checks
    incoming ones, raises divergence events."""

    def __init__(
        self,
        doc,
        *,
        topic: str,
        replica: str,
        tracer: Optional[Tracer] = None,
        recorder: Optional[FlightRecorder] = None,
        on_divergence: Optional[Callable[[Dict[str, Any]], None]] = None,
    ):
        self.doc = doc
        self.topic = topic
        self.replica = replica
        self._tracer = tracer
        self._recorder = recorder
        self.on_divergence = on_divergence
        self.events: List[Dict[str, Any]] = []
        self.max_events = 64  # bounded: divergence is permanent, so
                              # an un-deduped fork would grow forever
        self.beacons_sent = 0
        self.beacons_checked = 0
        # digest cache keyed by (sv bytes, ds digest): same SV + same
        # delete set => same state for THIS doc, so a quiescent mesh
        # pays one full-state encode per change, not per beacon
        self._digest_cache: Optional[tuple] = None
        # (peer, local, remote) triples already raised: a permanent
        # fork must not re-event (and re-dump) on every later beacon
        self._raised: set = set()

    # injected globals resolve per call so set_tracer/set_recorder
    # installed after replica construction still take effect
    @property
    def tracer(self) -> Tracer:
        return self._tracer if self._tracer is not None else get_tracer()

    @property
    def recorder(self) -> FlightRecorder:
        return (
            self._recorder if self._recorder is not None
            else get_recorder()
        )

    def _digests(self) -> tuple:
        """(state digest, ds digest), cached until the doc's state
        vector or delete set changes (both cheap to key on)."""
        sv_key = self.doc.encode_state_vector()
        ds_d = delete_set_digest(self.doc)
        cached = self._digest_cache
        if cached is not None and cached[0] == sv_key \
                and cached[1] == ds_d:
            return cached[2], ds_d
        st = state_digest(self.doc)
        self._digest_cache = (sv_key, ds_d, st)
        return st, ds_d

    def beacon_payload(self) -> Dict[str, Any]:
        """The broadcastable beacon body (caller adds transport
        framing: meta/public_key/state_vector)."""
        self.beacons_sent += 1
        self.tracer.count("sentinel.beacons_sent")
        st, ds_d = self._digests()
        payload = {"digest": st, "ds_digest": ds_d}
        self.recorder.record(
            "beacon.send", topic=self.topic, replica=self.replica,
            digest=st,
        )
        return payload

    def check(self, from_pk: str, peer_sv, digest: str,
              ds_digest: str) -> Optional[Dict[str, Any]]:
        """Compare a received beacon against local state. Returns the
        divergence event when one fires, else None (silent)."""
        self.beacons_checked += 1
        tracer = self.tracer
        tracer.count("sentinel.beacons_checked")
        mine_sv = self.doc.state_vector()
        if peer_sv != mine_sv:
            # ordinary lag: ops still in flight
            tracer.count("sentinel.sv_lag")
            return None
        my_digest, my_ds = self._digests()
        if ds_digest != my_ds:
            # tombstone-only deficit in flight (delete sets ride
            # outside SVs); anti-entropy repairs it — not a fork
            tracer.count("sentinel.ds_lag")
            return None
        if digest == my_digest:
            tracer.count("sentinel.agree")
            return None
        # equal SVs, equal delete sets, different state: silent
        # divergence. Raise loudly, with the evidence attached —
        # ONCE per (peer, fork): divergence is permanent, so later
        # beacons of the same fork only bump the counter
        tracer.count("sentinel.divergence")
        fork_key = (from_pk, my_digest, digest)
        if fork_key in self._raised:
            return None
        self._raised.add(fork_key)
        recorder = self.recorder
        event = {
            "kind": "divergence",
            "topic": self.topic,
            "replica": self.replica,
            "peer": from_pk,
            "local_digest": my_digest,
            "peer_digest": digest,
            "state_vector": {
                int(c): int(k) for c, k in mine_sv.clocks.items()
            },
            "flight_recorder": recorder.dump_jsonl(),
        }
        recorder.record(
            "divergence", topic=self.topic, replica=self.replica,
            peer=from_pk, local_digest=my_digest, peer_digest=digest,
        )
        if len(self.events) < self.max_events:
            self.events.append(event)
        if self.on_divergence is not None:
            self.on_divergence(event)
        return event


class MultiDocSentinel:
    """Divergence sentinel for multi-doc serving (round 14): beacons
    carry PER-DOC digests, so a fork is attributed to the one doc
    that diverged — on a server converging thousands of tenants in
    one dispatch, "some doc forked" is not actionable, "doc X
    forked" is.

    ``source`` is anything with a ``doc_digests()`` returning
    ``{doc_id: {"digest": str, "ops": int}}``
    (:meth:`crdt_tpu.models.multidoc.MultiDocServer.doc_digests`).
    The op count is the lag guard standing in for the single-doc
    sentinel's state-vector equality: unequal counts mean one side
    has not admitted the other's ops yet — propagation lag, silent
    (``sentinel.doc_lag``). Equal counts with unequal digests is a
    fork in THAT doc: one ``sentinel.doc_divergence`` count and one
    event naming the doc, deduped per (peer, doc, digest pair) like
    the single-doc sentinel's permanent-fork rule. Docs only the
    peer serves are skipped (placement, not health).

    Digest cost (round 15): the server's ``doc_digests()`` caches
    per-doc digests on (op count, serve tick), so every beacon this
    sentinel sends or checks recomputes digests only for the docs
    that moved since the last one — a clean doc costs zero digest
    work (``sentinel.doc_digest_skips``, pinned in
    tests/test_multidoc.py)."""

    def __init__(self, source, *, topic: str, replica: str,
                 tracer: Optional[Tracer] = None,
                 recorder: Optional[FlightRecorder] = None,
                 on_divergence: Optional[
                     Callable[[Dict[str, Any]], None]] = None):
        self.source = source
        self.topic = topic
        self.replica = replica
        self._tracer = tracer
        self._recorder = recorder
        self.on_divergence = on_divergence
        self.events: List[Dict[str, Any]] = []
        self.max_events = 64
        self._raised: set = set()

    @property
    def tracer(self) -> Tracer:
        return self._tracer if self._tracer is not None else get_tracer()

    @property
    def recorder(self) -> FlightRecorder:
        return (
            self._recorder if self._recorder is not None
            else get_recorder()
        )

    def beacon_payload(self) -> Dict[str, Any]:
        """The broadcastable multi-doc beacon body."""
        self.tracer.count("sentinel.beacons_sent")
        docs = self.source.doc_digests()
        self.recorder.record(
            "beacon.send", topic=self.topic, replica=self.replica,
            size=len(docs),
        )
        return {"docs": docs}

    def check(self, from_pk: str,
              payload: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Compare a received multi-doc beacon doc by doc. Returns
        the divergence events that fired (possibly empty)."""
        tracer = self.tracer
        tracer.count("sentinel.beacons_checked")
        mine = self.source.doc_digests()
        fired: List[Dict[str, Any]] = []
        for doc_id, theirs in (payload.get("docs") or {}).items():
            ours = mine.get(doc_id)
            if ours is None:
                continue  # not served here: placement, not health
            if ours["ops"] != theirs.get("ops"):
                tracer.count("sentinel.doc_lag")
                continue
            if ours["digest"] == theirs.get("digest"):
                tracer.count("sentinel.agree")
                continue
            tracer.count("sentinel.doc_divergence")
            fork_key = (from_pk, doc_id, ours["digest"],
                        theirs.get("digest"))
            if fork_key in self._raised:
                continue
            self._raised.add(fork_key)
            event = {
                "kind": "divergence",
                "topic": self.topic,
                "replica": self.replica,
                "peer": from_pk,
                "doc": doc_id,
                "local_digest": ours["digest"],
                "peer_digest": theirs.get("digest"),
                "flight_recorder": self.recorder.dump_jsonl(),
            }
            self.recorder.record(
                "divergence", topic=self.topic, replica=self.replica,
                peer=from_pk, local_digest=ours["digest"],
                peer_digest=theirs.get("digest"),
            )
            if len(self.events) < self.max_events:
                self.events.append(event)
            if self.on_divergence is not None:
                self.on_divergence(event)
            fired.append(event)
        return fired
