"""Metrics export: Prometheus text exposition + JSON snapshot.

Both read the ONE shared schema — ``Tracer.report()`` — so a scrape,
a committed ``BENCH_OUT.json``, and an interactive ``report()`` all
describe the same numbers with the same names:

- counters  -> ``# TYPE <ns>_<name> counter`` (labels preserved:
  a tracer key ``name{k="v"}`` exposes as-is after sanitization)
- gauges    -> ``# TYPE <ns>_<name> gauge``
- spans     -> ``# TYPE <ns>_<name>_seconds histogram`` with the
  tracer's log-2 bucket edges as cumulative ``_bucket{le="..."}``
  series plus ``_sum`` / ``_count``

Metric names are sanitized to the Prometheus charset
(``[a-zA-Z_:][a-zA-Z0-9_:]*``): every other character becomes ``_``,
a leading digit gets a ``_`` prefix. Dots in span names (the
``converge.dispatch`` registry convention) therefore export as
``converge_dispatch``.

Sanitization is LOSSY, so two distinct tracer keys can land on one
Prometheus series (``a.b-c`` and ``a.b_c`` both export ``a_b_c``;
a counter and a gauge sharing one raw name would even emit duplicate
``# TYPE`` lines — a fatal exposition parse error). Round 18 closes
that hazard: colliding names are detected across all three sections
and EVERY colliding member is disambiguated deterministically with a
crc32 suffix of its (section, raw-name) pair — order-independent, so
the same report always exports the same series (pinned in
tests/test_obs.py). Collision-free names export exactly as before.
"""

from __future__ import annotations

import json
import re
import zlib
from typing import Any, Dict, Optional, Tuple

_INVALID = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_metric_name(name: str) -> str:
    """Sanitize one metric name to the Prometheus charset."""
    out = _INVALID.sub("_", name)
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def _split_labels(key: str) -> Tuple[str, str]:
    """``name{k="v"}`` -> (name, '{k="v"}'); plain names pass through."""
    if key.endswith("}") and "{" in key:
        name, _, rest = key.partition("{")
        return name, "{" + rest
    return key, ""


def _final_names(report: Dict[str, Any], ns: str) -> Dict[Tuple[str, str], str]:
    """Map every (section, raw-base-name) to its exported series
    name, disambiguating sanitization collisions. Label variants of
    ONE raw name share one series name (that is grouping, not a
    collision); two DIFFERENT raw names — or one raw name in two
    sections, which would duplicate the TYPE line — landing on the
    same sanitized output each get a deterministic ``_<crc32>``
    suffix keyed on their own (section, raw) pair."""
    wanted: Dict[str, set] = {}
    for section, suffix in (
        ("counters", ""), ("gauges", ""), ("spans", "_seconds"),
    ):
        for key in report.get(section, {}):
            # span keys export whole (labels folded into the name,
            # as ever); counter/gauge labels split off and regroup
            raw = key if section == "spans" else _split_labels(key)[0]
            final = f"{ns}_{sanitize_metric_name(raw)}{suffix}"
            wanted.setdefault(final, set()).add((section, raw))
    out: Dict[Tuple[str, str], str] = {}
    for final, members in wanted.items():
        if len(members) == 1:
            ((section, raw),) = members
            out[(section, raw)] = final
        else:
            for section, raw in members:
                tag = zlib.crc32(
                    f"{section}:{raw}".encode()
                ) & 0xFFFFFFFF
                out[(section, raw)] = f"{final}_{tag:08x}"
    return out


def to_prometheus(report: Optional[Dict[str, Any]] = None,
                  *, namespace: str = "crdt") -> str:
    """Render a ``Tracer.report()`` dict (default: the process-global
    tracer's) in Prometheus text exposition format 0.0.4."""
    if report is None:
        from crdt_tpu.obs.tracer import get_tracer

        report = get_tracer().report()
    ns = sanitize_metric_name(namespace)
    finals = _final_names(report, ns)
    lines = []
    for section, mtype in (("counters", "counter"), ("gauges", "gauge")):
        # ONE TYPE line per base metric name, all label sets grouped
        # under it (a duplicate TYPE line is a fatal exposition parse
        # error); rows sort by FINAL name so disambiguated label
        # variants stay adjacent under their one TYPE line
        rows = []
        for key, value in report.get(section, {}).items():
            raw, labels = _split_labels(key)
            rows.append((finals[(section, raw)], labels, value))
        last_name = None
        for name, labels, value in sorted(rows):
            if name != last_name:
                lines.append(f"# TYPE {name} {mtype}")
                last_name = name
            lines.append(f"{name}{labels} {value}")
    for key, span in report.get("spans", {}).items():
        name = finals[("spans", key)]
        lines.append(f"# TYPE {name} histogram")
        cum = 0
        finite = {
            le: n for le, n in span.get("buckets", {}).items()
            if le != "+Inf"
        }
        for le in sorted(finite, key=float):
            cum += finite[le]
            lines.append(f'{name}_bucket{{le="{le}"}} {cum}')
        lines.append(f'{name}_bucket{{le="+Inf"}} {span["count"]}')
        lines.append(f"{name}_sum {span['total_s']}")
        lines.append(f"{name}_count {span['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


def snapshot_json(report: Optional[Dict[str, Any]] = None) -> str:
    """The JSON snapshot: ``Tracer.report()`` serialized verbatim (the
    same object ``bench.py`` embeds under ``"tracer"``)."""
    if report is None:
        from crdt_tpu.obs.tracer import get_tracer

        report = get_tracer().report()
    return json.dumps(report, sort_keys=True)
