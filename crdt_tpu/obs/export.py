"""Metrics export: Prometheus text exposition + JSON snapshot.

Both read the ONE shared schema — ``Tracer.report()`` — so a scrape,
a committed ``BENCH_OUT.json``, and an interactive ``report()`` all
describe the same numbers with the same names:

- counters  -> ``# TYPE <ns>_<name> counter`` (labels preserved:
  a tracer key ``name{k="v"}`` exposes as-is after sanitization)
- gauges    -> ``# TYPE <ns>_<name> gauge``
- spans     -> ``# TYPE <ns>_<name>_seconds histogram`` with the
  tracer's log-2 bucket edges as cumulative ``_bucket{le="..."}``
  series plus ``_sum`` / ``_count``

Metric names are sanitized to the Prometheus charset
(``[a-zA-Z_:][a-zA-Z0-9_:]*``): every other character becomes ``_``,
a leading digit gets a ``_`` prefix. Dots in span names (the
``converge.dispatch`` registry convention) therefore export as
``converge_dispatch``.
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, Optional, Tuple

_INVALID = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_metric_name(name: str) -> str:
    """Sanitize one metric name to the Prometheus charset."""
    out = _INVALID.sub("_", name)
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def _split_labels(key: str) -> Tuple[str, str]:
    """``name{k="v"}`` -> (name, '{k="v"}'); plain names pass through."""
    if key.endswith("}") and "{" in key:
        name, _, rest = key.partition("{")
        return name, "{" + rest
    return key, ""


def to_prometheus(report: Optional[Dict[str, Any]] = None,
                  *, namespace: str = "crdt") -> str:
    """Render a ``Tracer.report()`` dict (default: the process-global
    tracer's) in Prometheus text exposition format 0.0.4."""
    if report is None:
        from crdt_tpu.obs.tracer import get_tracer

        report = get_tracer().report()
    ns = sanitize_metric_name(namespace)
    lines = []
    for section, mtype in (("counters", "counter"), ("gauges", "gauge")):
        # ONE TYPE line per base metric name, all label sets grouped
        # under it (a duplicate TYPE line is a fatal exposition parse
        # error, and sorted report keys put label variants adjacent)
        last_name = None
        for key, value in report.get(section, {}).items():
            raw, labels = _split_labels(key)
            name = f"{ns}_{sanitize_metric_name(raw)}"
            if name != last_name:
                lines.append(f"# TYPE {name} {mtype}")
                last_name = name
            lines.append(f"{name}{labels} {value}")
    for key, span in report.get("spans", {}).items():
        name = f"{ns}_{sanitize_metric_name(key)}_seconds"
        lines.append(f"# TYPE {name} histogram")
        cum = 0
        finite = {
            le: n for le, n in span.get("buckets", {}).items()
            if le != "+Inf"
        }
        for le in sorted(finite, key=float):
            cum += finite[le]
            lines.append(f'{name}_bucket{{le="{le}"}} {cum}')
        lines.append(f'{name}_bucket{{le="+Inf"}} {span["count"]}')
        lines.append(f"{name}_sum {span['total_s']}")
        lines.append(f"{name}_count {span['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


def snapshot_json(report: Optional[Dict[str, Any]] = None) -> str:
    """The JSON snapshot: ``Tracer.report()`` serialized verbatim (the
    same object ``bench.py`` embeds under ``"tracer"``)."""
    if report is None:
        from crdt_tpu.obs.tracer import get_tracer

        report = get_tracer().report()
    return json.dumps(report, sort_keys=True)
