"""Sync flight recorder: bounded ring buffer of structured events.

A Dapper-style record of an update's life at the sync seams — mutate
-> encode -> broadcast -> (drop/delay/relay) -> integrate -> converge
— kept in a fixed-size ring so it is always cheap and always recent.
Producers are the transport layers (``net/replica.py``,
``net/udp_router.py``, ``net/faults.py``, ``parallel/gossip.py``);
the consumer is a human doing a postmortem: ``dump_jsonl()`` on
demand, or automatically attached to the divergence sentinel's event
when silent divergence is detected.

Events are plain dicts: ``{"ts": <monotonic seconds>, "kind": str,
...}`` with producer-chosen fields (``topic``, ``peer``, ``replica``,
``digest``, ``size``, ``tid`` — see README "Observability" for the
event-kind registry). Disabled by default; when disabled every
``record()`` is a single attribute check. Thread-safe (one lock; the
ring is a deque with maxlen, so wraparound is O(1) and allocation-
free at steady state).
"""

from __future__ import annotations

import json
import threading
import time
import zlib
from collections import deque
from typing import Any, Dict, List, Optional


def update_digest(data: bytes) -> str:
    """Short stable digest of an update blob for event correlation
    (crc32 — identification, not integrity; envelopes are already
    authenticated at the transport)."""
    return f"{zlib.crc32(bytes(data)) & 0xFFFFFFFF:08x}"


class FlightRecorder:
    """Bounded ring of structured sync events."""

    def __init__(self, capacity: int = 4096, *, enabled: bool = False):
        self.enabled = enabled
        self.capacity = capacity
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self.recorded = 0  # total ever recorded (ring may have evicted)

    def record(self, kind: str, **fields: Any) -> None:
        if not self.enabled:
            return
        ev = {"ts": time.monotonic(), "kind": kind}
        ev.update(fields)
        with self._lock:
            self._ring.append(ev)
            self.recorded += 1

    def events(self, kind: Optional[str] = None) -> List[Dict[str, Any]]:
        """Snapshot of the ring (oldest first), optionally filtered."""
        with self._lock:
            evs = list(self._ring)
        if kind is not None:
            evs = [e for e in evs if e["kind"] == kind]
        return evs

    def dump_jsonl(self, path: Optional[str] = None) -> str:
        """The ring as JSONL (one event per line, oldest first); when
        ``path`` is given the dump is also written there."""
        text = "\n".join(
            json.dumps(e, sort_keys=True, default=str)
            for e in self.events()
        )
        if text:
            text += "\n"
        if path is not None:
            with open(path, "w") as f:
                f.write(text)
        return text

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


_recorder = FlightRecorder(enabled=False)


def get_recorder() -> FlightRecorder:
    return _recorder


def set_recorder(recorder: FlightRecorder) -> FlightRecorder:
    global _recorder
    _recorder = recorder
    return recorder
