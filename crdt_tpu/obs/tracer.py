"""Thread-safe phase tracer with log-bucketed latency histograms.

Replaces the aggregating count/total/max tracer that lived in
``crdt_tpu/utils/trace.py`` (which documented itself as single-thread
only while ``models/streaming.py`` decodes on a thread pool — a latent
race on every shared-dict update). This one takes a lock around every
mutation; the off-path cost when disabled stays a single attribute
check (``span`` returns one shared no-op context manager, ``count`` /
``gauge`` / ``observe`` return before touching any state).

Spans aggregate count / total / max / min AND a base-2 log-bucketed
histogram (1 microsecond floor), so ``report()`` carries tail
latencies (p50/p90/p99) per phase, not just means — the difference
between "converge averaged 12 ms" and "one dispatch in a hundred
stalls 400 ms behind the tunnel".

The public surface is a strict superset of the old tracer:
``get_tracer() / set_tracer / span / count / gauge /
counters(prefix) / report / to_json / reset`` all behave identically
(``report()`` keeps ``count/total_s/mean_s/max_s`` per span and adds
``min_s/p50_s/p90_s/p99_s/buckets``). New: ``observe(name, seconds)``
records a duration measured elsewhere (e.g. propagation lag stamped
by a trace id) into the same histogram machinery, and ``count`` /
``gauge`` accept a ``labels`` dict rendered Prometheus-style into the
metric key (``name{k="v"}``). See README "Observability"; subclassers
of the old Tracer: see MIGRATING.
"""

from __future__ import annotations

import json
import threading
import time
from bisect import bisect_left
from contextlib import nullcontext
from typing import Any, Dict, Optional

# base-2 bucket upper edges, 1us floor: bucket k holds durations in
# (edge[k-1], edge[k]] — an observation exactly AT an edge lands in
# that edge's bucket (bisect_left semantics, pinned by test_obs).
# 40 edges reach ~5.5e5 s; anything beyond lands in the +Inf bucket.
N_BUCKETS = 40
BUCKET_EDGES_S = tuple(1e-6 * (1 << k) for k in range(N_BUCKETS))
_OVERFLOW = N_BUCKETS  # index of the +Inf bucket


def bucket_index(seconds: float) -> int:
    """Histogram bucket for a duration (upper-edge inclusive)."""
    if seconds <= BUCKET_EDGES_S[0]:
        return 0
    return bisect_left(BUCKET_EDGES_S, seconds)


class Histogram:
    """Log2-bucketed duration aggregate (the span accumulator).

    Public since round 18: the SLO ledger (:mod:`crdt_tpu.obs.slo`)
    keeps per-tenant latency histograms on exactly these edges, so a
    scrape and an SLO report bucket identically."""

    __slots__ = ("count", "total_s", "max_s", "min_s", "buckets")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self.min_s = float("inf")
        self.buckets: Dict[int, int] = {}

    def add(self, dt: float) -> None:
        self.count += 1
        self.total_s += dt
        if dt > self.max_s:
            self.max_s = dt
        if dt < self.min_s:
            self.min_s = dt
        b = bucket_index(dt)
        self.buckets[b] = self.buckets.get(b, 0) + 1

    def summary(self) -> Dict[str, Any]:
        """The per-span report dict (shared by ``Tracer.report()`` and
        the SLO ledger's per-tenant summaries)."""
        return {
            "count": self.count,
            "total_s": self.total_s,
            "mean_s": self.total_s / self.count if self.count else 0.0,
            "max_s": self.max_s,
            "min_s": self.min_s if self.count else 0.0,
            "p50_s": self.quantile(0.50),
            "p90_s": self.quantile(0.90),
            "p99_s": self.quantile(0.99),
            "buckets": {
                (
                    f"{BUCKET_EDGES_S[b]:.9g}"
                    if b < _OVERFLOW else "+Inf"
                ): n
                for b, n in sorted(self.buckets.items())
            },
        }

    def quantile(self, q: float) -> float:
        """Bucket-resolution quantile: the upper edge of the bucket
        holding the q-rank observation, clamped to the observed max
        (so p99 never reports above the true maximum). Edge
        semantics (pinned in test_obs): an empty histogram answers
        0.0 for every q; ``q=0`` is the rank-1 (minimum-bucket)
        estimate; ``q>=1`` is the observed max; a single observation
        answers that observation at every q."""
        if not self.count:
            return 0.0
        rank = max(1, min(self.count, int(q * self.count + 0.5)))
        cum = 0
        for b in sorted(self.buckets):
            cum += self.buckets[b]
            if cum >= rank:
                edge = (
                    BUCKET_EDGES_S[b] if b < _OVERFLOW else self.max_s
                )
                return min(edge, self.max_s)
        return self.max_s


# legacy alias: subclassers of the round-8 tracer reached the span
# accumulator under this name (MIGRATING "Tracer subclassers")
_Span = Histogram


# shared no-op context manager: the disabled-tracer span (stdlib
# nullcontext is reusable and reentrant)
_NULL_SPAN = nullcontext()


class _LiveSpan:
    __slots__ = ("_tracer", "_name", "_t0")

    def __init__(self, tracer: "Tracer", name: str):
        self._tracer = tracer
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return None

    def __exit__(self, *exc):
        self._tracer.observe(self._name, time.perf_counter() - self._t0)
        return False


def _esc_label(value: Any) -> str:
    """Prometheus exposition label-value escaping (backslash, quote,
    newline). Label values are caller-controlled since round 18 (doc
    ids become ``tenant=`` labels) — an unescaped ``"`` or newline
    would corrupt the whole /metrics scrape, and a newline could
    inject arbitrary exposition lines."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _labeled(name: str, labels: Optional[Dict[str, Any]]) -> str:
    if not labels:
        return name
    inner = ",".join(
        f'{k}="{_esc_label(labels[k])}"' for k in sorted(labels)
    )
    return f"{name}{{{inner}}}"


class Tracer:
    """Aggregating phase timer + counters + gauges. Thread-safe: all
    mutations take one lock (sub-microsecond uncontended; the timed
    region of a span is measured OUTSIDE the lock)."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._spans: Dict[str, _Span] = {}
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}

    # -- phases ----------------------------------------------------------
    def span(self, name: str):
        if not self.enabled:
            return _NULL_SPAN
        return _LiveSpan(self, name)

    def observe(self, name: str, seconds: float) -> None:
        """Record an externally measured duration into ``name``'s
        histogram (same aggregate a ``span`` produces)."""
        if not self.enabled:
            return
        with self._lock:
            s = self._spans.get(name)
            if s is None:
                s = self._spans[name] = Histogram()
            s.add(seconds)

    def quantile(self, name: str, q: float) -> float:
        """Bucket-resolution quantile of one span's histogram (0.0
        for a span never observed — the always-on serving path must
        be able to probe a quantile without try/except)."""
        with self._lock:
            s = self._spans.get(name)
            return s.quantile(q) if s is not None else 0.0

    # -- counters / gauges ----------------------------------------------
    def count(self, name: str, n: int = 1,
              labels: Optional[Dict[str, Any]] = None) -> None:
        if not self.enabled:
            return
        key = _labeled(name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def gauge(self, name: str, value: float,
              labels: Optional[Dict[str, Any]] = None) -> None:
        if not self.enabled:
            return
        key = _labeled(name, labels)
        with self._lock:
            self._gauges[key] = value

    def counters(self, prefix: str = "") -> Dict[str, int]:
        """Counter snapshot, optionally filtered by name prefix —
        e.g. ``counters("router.relay")`` for the relay path or
        ``counters("replica.probe")`` for the retry schedule (the
        partition-tolerance counters: ``router.dial_retries``,
        ``router.predict_probes``, ``router.relay_*``,
        ``replica.probe_retries``, ``replica.anti_entropy_rounds`` —
        a stable contract, see README "Observability")."""
        with self._lock:
            return {
                k: v for k, v in sorted(self._counters.items())
                if k.startswith(prefix)
            }

    # -- reporting -------------------------------------------------------
    def report(self) -> Dict[str, Any]:
        """One plain JSON-ready dict — the shared schema the JSON
        snapshot, the Prometheus exposition, and ``bench.py``'s
        embedded evidence all read."""
        with self._lock:
            spans = {
                k: s.summary() for k, s in sorted(self._spans.items())
            }
            return {
                "spans": spans,
                "counters": dict(sorted(self._counters.items())),
                "gauges": dict(sorted(self._gauges.items())),
            }

    def to_json(self) -> str:
        return json.dumps(self.report())

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
            self._counters.clear()
            self._gauges.clear()


_tracer = Tracer(enabled=False)


def get_tracer() -> Tracer:
    return _tracer


def set_tracer(tracer: Tracer) -> Tracer:
    global _tracer
    _tracer = tracer
    return tracer
