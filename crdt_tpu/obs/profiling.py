"""Device-side profiling hooks: XProf capture + dispatch annotations.

- :func:`jax_profile` wraps ``jax.profiler.start_trace/stop_trace``
  so a convergence dispatch can be captured for TensorBoard/XProf.
  Hardened (vs the old ``utils/trace.py`` version): a failure inside
  the block can never leave the profiler running, a failing
  ``stop_trace`` never masks the body's exception, and environments
  whose jax lacks a profiler (or ``ProfileOptions`` — absent in the
  pinned jax 0.4.x) degrade with a clear ``RuntimeError`` instead of
  an opaque ``AttributeError`` mid-setup.
- :func:`device_annotation` is the per-dispatch annotation seam: a
  ``jax.profiler.TraceAnnotation`` context manager when available (so
  XProf timelines attribute each converge dispatch / streaming shard
  to its phase), a shared no-op otherwise. Resolution is cached after
  the first call; the steady-state cost without a profiler is one
  global check.
"""

from __future__ import annotations

from contextlib import contextmanager, nullcontext
from typing import Iterator, Optional

_NULL_CTX = nullcontext()  # reusable/reentrant stdlib no-op
_annotation_cls: Optional[object] = None  # None = unresolved, False = absent


def device_annotation(name: str):
    """Context manager annotating enclosed dispatches for XProf."""
    global _annotation_cls
    if _annotation_cls is None:
        try:
            import jax

            _annotation_cls = jax.profiler.TraceAnnotation
        except Exception:
            _annotation_cls = False
    if not _annotation_cls:
        return _NULL_CTX
    return _annotation_cls(name)


@contextmanager
def jax_profile(log_dir: str, *, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a device trace (TensorBoard/XProf format) around a
    block — e.g. one ``converge_maps`` dispatch or a fleet step."""
    try:
        import jax

        profiler = jax.profiler
        start = profiler.start_trace
        stop = profiler.stop_trace
    except (ImportError, AttributeError) as exc:
        raise RuntimeError(
            "jax profiler unavailable (CPU-only or stripped jax build): "
            f"{exc!r}"
        ) from exc
    kwargs = {}
    opts_cls = getattr(profiler, "ProfileOptions", None)
    if opts_cls is not None:
        # newer jax: host tracer level rides ProfileOptions; absent on
        # the pinned 0.4.x line, where start_trace takes no options
        try:
            opts = opts_cls()
            opts.host_tracer_level = host_tracer_level
            kwargs["profiler_options"] = opts
        except Exception:
            pass
    try:
        start(log_dir, **kwargs)
    except Exception as exc:
        raise RuntimeError(
            f"jax profiler failed to start ({log_dir!r}): {exc!r}"
        ) from exc
    try:
        yield
    except BaseException:
        # the body failed: stop the profiler so it cannot leak into
        # (and corrupt) the next capture, but never mask the real
        # error with a stop_trace failure
        try:
            stop()
        except Exception:
            pass
        raise
    else:
        stop()
