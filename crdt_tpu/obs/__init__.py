"""Observability subsystem: tracer, flight recorder, sentinel, export,
and the round-18 serving surfaces (SLO ledger, tick timeline, HTTP
scrape endpoint).

The framework's evidence layer (ROADMAP north star: converging 1k
replicas x 100k ops needs to be *seen*, not just claimed):

- :mod:`crdt_tpu.obs.tracer` — thread-safe phase tracer with
  log-bucketed latency histograms (p50/p90/p99/max per span), labeled
  counters and gauges. One process-global instance, disabled by
  default; every hot-path hook is a single attribute check when off.
- :mod:`crdt_tpu.obs.recorder` — bounded ring buffer of structured
  sync events (monotonic ts, kind, replica/topic, update digest, byte
  size), dumpable as JSONL on demand or automatically on divergence.
- :mod:`crdt_tpu.obs.sentinel` — the divergence sentinel: periodic
  snapshot-hash beacons riding the anti-entropy cadence turn silent
  divergence (equal state vectors, unequal state) into an observable
  event carrying a flight-recorder dump.
- :mod:`crdt_tpu.obs.export` — Prometheus text-format exposition and
  the JSON snapshot (the same schema as ``Tracer.report()``), with
  deterministic disambiguation of sanitization collisions.
- :mod:`crdt_tpu.obs.slo` — per-tenant SLO accounting for the
  serving path: ingest-to-converged / ingest-to-served latency
  histograms, breach counters against a configurable objective
  (``CRDT_TPU_SLO_MS``), burn-rate gauges, route-mix counters.
- :mod:`crdt_tpu.obs.timeline` — the tick-timeline profiler: a
  bounded ring of per-tick phase records with dispatch in-flight
  windows, per-tick ``overlap_efficiency`` / ``stall_ms``, exported
  as Chrome/Perfetto trace-event JSON.
- :mod:`crdt_tpu.obs.http` — stdlib-only scrape endpoint
  (``/metrics`` / ``/snapshot`` / ``/events`` / ``/timeline``; with
  a collector attached, ``/fleet`` / ``/fleet/timeline``).
- :mod:`crdt_tpu.obs.propagation` — round 19: the wire trace
  context (origin tid + bounded route-tagged path records) carried
  on update/sync-answer/AE frames, per-hop lag attribution
  (``replica.hop_lag{route=}``, ``replica.birth_to_visibility``),
  and the tid-pairing/diverge analysis core shared by ``obsq`` and
  the collector.
- :mod:`crdt_tpu.obs.collector` — round 19: the live fleet
  collector federating N processes' scrape endpoints (proc-labeled
  registries, live cross-process path reconstruction + divergence
  correlation, merged Perfetto timelines).
- :mod:`crdt_tpu.obs.control` — round 22: the SLO-driven control
  plane — a deterministic tick-synchronous rule engine over the
  sensors above (burn rates, queue/pool pressure) actuating the
  serving knobs (tenant budget squeeze/restore with hysteresis,
  LRU protection, dispatch pacing, checkpoint cadence), every
  decision in a bounded auditable ledger served at ``/control``.
- :mod:`crdt_tpu.obs.profiling` — ``jax_profile`` (device trace
  capture that cannot leak a running profiler) and per-dispatch
  ``device_annotation`` XProf annotations.

See README "Observability" / "Observability v2" for the
metric/span/event name registry; ``tools/obsq.py`` is the offline
query CLI over flight-recorder dumps.
"""

from crdt_tpu.obs.collector import FleetCollector, merge_perfetto
from crdt_tpu.obs.control import Actuation, ControlLedger, Controller
from crdt_tpu.obs.export import snapshot_json, to_prometheus
from crdt_tpu.obs.http import ObsHTTPServer
from crdt_tpu.obs.profiling import device_annotation, jax_profile
from crdt_tpu.obs.propagation import (
    PropagationLedger,
    TraceContext,
    decode_context,
    encode_context,
    get_propagation,
    set_propagation,
)
from crdt_tpu.obs.recorder import (
    FlightRecorder,
    get_recorder,
    set_recorder,
)
from crdt_tpu.obs.sentinel import (
    DivergenceSentinel,
    MultiDocSentinel,
    delete_set_digest,
    state_digest,
)
from crdt_tpu.obs.slo import SLOLedger
from crdt_tpu.obs.timeline import TickTimeline, get_timeline, set_timeline
from crdt_tpu.obs.tracer import Histogram, Tracer, get_tracer, set_tracer

__all__ = [
    "Actuation",
    "ControlLedger",
    "Controller",
    "DivergenceSentinel",
    "MultiDocSentinel",
    "FleetCollector",
    "FlightRecorder",
    "Histogram",
    "ObsHTTPServer",
    "PropagationLedger",
    "SLOLedger",
    "TickTimeline",
    "TraceContext",
    "Tracer",
    "decode_context",
    "delete_set_digest",
    "device_annotation",
    "encode_context",
    "get_propagation",
    "get_recorder",
    "get_timeline",
    "get_tracer",
    "jax_profile",
    "merge_perfetto",
    "set_propagation",
    "set_recorder",
    "set_timeline",
    "set_tracer",
    "snapshot_json",
    "state_digest",
    "to_prometheus",
]
