"""Tick-timeline profiler: a bounded ring of per-tick phase records.

The round-15 serve() loop overlaps host staging with in-flight
converge dispatches (the streaming executor's double-buffer
discipline applied at the server level), but until round 18 that
overlap was only *claimed* by aggregate counters. This profiler makes
it *visible and gateable*: each tick records its host phases
(prepare, fair_order, route, pack, unpack, settle — plus ingest in
the serve loop) as wall intervals and each converge dispatch as an
async in-flight window (enqueue -> fetch-complete), then computes

- ``overlap_efficiency`` — the round-6 overlap accounting over the
  tick's lanes (host phases + the merged device window):
  ``(busy - wall) / (busy - longest)``, 0 = fully serial, 1 = the
  wall collapsed onto the single longest lane;
- ``stall_ms`` — time the host spent *blocked* inside result fetches
  (the converge_wait analogue): the double-buffer's failure signature
  is stall growing while efficiency shrinks.

Records live in a fixed-size ring (always cheap, always recent) and
export as Chrome/Perfetto trace-event JSON (:meth:`TickTimeline.
to_perfetto` — ``ui.perfetto.dev`` renders a serve() run as a
zoomable timeline with the dispatch windows on their own track), or
as plain dicts (:meth:`records`). Disabled by default; when disabled
every hook is a single attribute check and :meth:`phase` returns one
shared no-op context manager — the same free-when-off contract as the
tracer. The record-building methods are called only from the single
tick thread; the ring itself is locked so ``/timeline`` scrapes and
``records()`` reads are safe from any thread.

Tracer emission at each tick end (README "Observability v2"):
gauges ``timeline.overlap_efficiency`` / ``timeline.stall_ms`` (the
last tick's values — gateable in ``tools/metrics_diff.py``), counter
``timeline.ticks``.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from contextlib import nullcontext
from typing import Any, Dict, List, Optional

from crdt_tpu.obs.tracer import get_tracer

_NULL_PHASE = nullcontext()


def overlap_of(lanes: Dict[str, float], wall_s: float) -> float:
    """The round-6 overlap efficiency over per-lane busy seconds:
    (busy - wall) / (busy - longest), clamped to [0, 1]. 0 = fully
    serial, 1 = wall collapsed onto the longest lane."""
    busy = sum(lanes.values())
    longest = max(lanes.values(), default=0.0)
    hideable = busy - longest
    if hideable > 1e-9:
        eff = (busy - wall_s) / hideable
    else:
        eff = 1.0 if wall_s <= busy + 1e-9 else 0.0
    return min(max(eff, 0.0), 1.0)


class _PhaseCM:
    __slots__ = ("_tl", "_name", "_t0")

    def __init__(self, tl: "TickTimeline", name: str):
        self._tl = tl
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return None

    def __exit__(self, *exc):
        self._tl.add_phase(
            self._name, self._t0, time.perf_counter()
        )
        return False


class TickTimeline:
    """Bounded ring of structured per-tick phase records."""

    def __init__(self, capacity: int = 256, *, enabled: bool = False):
        self.enabled = enabled
        self.capacity = capacity
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self.recorded = 0          # ticks ever recorded (ring evicts)
        self._cur: Optional[Dict[str, Any]] = None
        # epoch: perf_counter origin for the exported microsecond
        # timestamps, pinned at the first recorded tick
        self._epoch: Optional[float] = None

    # -- record building (single tick thread) --------------------------

    def tick_begin(self, tick: int, label: str = "tick") -> None:
        if not self.enabled:
            return
        t0 = time.perf_counter()
        if self._epoch is None:
            self._epoch = t0
        self._cur = {
            "tick": tick,
            "label": label,
            "t0": t0,
            "phases": [],      # (name, start_s, end_s)
            "dispatches": [],  # {i, enq, fetch0, end}
            "stall_s": 0.0,
        }

    def phase(self, name: str):
        """Context manager timing one host phase of the current tick
        (no-op when disabled or outside a tick)."""
        if not self.enabled or self._cur is None:
            return _NULL_PHASE
        return _PhaseCM(self, name)

    def add_phase(self, name: str, t0: float, t1: float) -> None:
        if not self.enabled or self._cur is None:
            return
        self._cur["phases"].append((name, t0, t1))

    def instant(self, name: str,
                args: Optional[Dict[str, Any]] = None) -> None:
        """Mark a point event inside the current tick (round 22: the
        control plane stamps one per decision, so a budget squeeze
        is visible AT the tick it fired on the Perfetto track).
        No-op when disabled or outside a tick."""
        if not self.enabled or self._cur is None:
            return
        self._cur.setdefault("instants", []).append(
            (name, time.perf_counter(), args or {})
        )

    def dispatch_begin(self, t: Optional[float] = None) -> Optional[int]:
        """A converge dispatch was enqueued (its async in-flight
        window opens). Returns a token for :meth:`dispatch_end`, or
        None when disabled. ``t`` overrides the enqueue stamp for
        producers that enqueued on another thread (the streaming
        stager)."""
        if not self.enabled or self._cur is None:
            return None
        d = {
            "i": len(self._cur["dispatches"]),
            "enq": time.perf_counter() if t is None else t,
            "fetch0": None,
            "end": None,
        }
        self._cur["dispatches"].append(d)
        return d["i"]

    def dispatch_end(self, token: Optional[int],
                     fetch_t0: float, fetch_t1: float) -> None:
        """The dispatch's result fetch completed; ``fetch_t0..t1`` is
        the host's *blocked* wait (the stall)."""
        if not self.enabled or self._cur is None or token is None:
            return
        d = self._cur["dispatches"][token]
        d["fetch0"] = fetch_t0
        d["end"] = fetch_t1
        self._cur["stall_s"] += max(0.0, fetch_t1 - fetch_t0)

    def tick_end(self, extra_busy: Optional[Dict[str, float]] = None
                 ) -> Optional[Dict[str, Any]]:
        """Close the current tick: compute the overlap accounting,
        push the record into the ring, publish the gauges.
        ``extra_busy`` adds lanes measured elsewhere (the streaming
        executor's per-stage busy sums)."""
        if not self.enabled or self._cur is None:
            return None
        cur, self._cur = self._cur, None
        t_end = time.perf_counter()
        wall = t_end - cur["t0"]
        lanes: Dict[str, float] = {}
        for name, a, b in cur["phases"]:
            lanes[name] = lanes.get(name, 0.0) + max(0.0, b - a)
        device = _merged_windows(
            [(d["enq"], d["end"]) for d in cur["dispatches"]
             if d["end"] is not None]
        )
        if device > 0.0:
            lanes["dispatch"] = device
        if extra_busy:
            for k, v in extra_busy.items():
                lanes[k] = lanes.get(k, 0.0) + float(v)
        eff = overlap_of(lanes, wall)
        rec = {
            "tick": cur["tick"],
            "label": cur["label"],
            "t0": cur["t0"],
            "wall_s": wall,
            "phases": cur["phases"],
            "dispatches": cur["dispatches"],
            "stall_s": cur["stall_s"],
            "stall_ms": cur["stall_s"] * 1e3,
            "overlap_efficiency": eff,
            "lanes": lanes,
            "instants": cur.get("instants", []),
        }
        with self._lock:
            self._ring.append(rec)
            self.recorded += 1
        tracer = get_tracer()
        if tracer.enabled:
            tracer.count("timeline.ticks")
            tracer.gauge("timeline.overlap_efficiency", eff)
            tracer.gauge("timeline.stall_ms", rec["stall_ms"])
        return rec

    # -- reads / export ------------------------------------------------

    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def to_perfetto(self, *, pid: Optional[int] = None,
                    process_name: Optional[str] = None
                    ) -> Dict[str, Any]:
        """The ring as Chrome trace-event JSON (the subset Perfetto
        renders): host phases on tid 1, dispatch in-flight windows on
        tid 2, a counter track for overlap efficiency. Timestamps are
        microseconds from the first recorded tick.

        ``pid`` defaults to the PROCESS identity (``os.getpid()``) —
        round 18 emitted one flat pid, so collector-merged timelines
        from multiple processes collided onto one track; now every
        process exports under its own pid and ``process_name``
        (default ``crdt_tpu.serve[<pid>]``), and the fleet
        collector's merge re-pids deterministically on top (see
        :func:`crdt_tpu.obs.collector.merge_perfetto`)."""
        if pid is None:
            import os

            pid = os.getpid()
        if process_name is None:
            process_name = f"crdt_tpu.serve[{pid}]"
        epoch = self._epoch if self._epoch is not None else 0.0

        def us(t: float) -> float:
            return round((t - epoch) * 1e6, 1)

        events: List[Dict[str, Any]] = [
            {"name": "process_name", "ph": "M", "ts": 0,
             "pid": pid, "tid": 0,
             "args": {"name": process_name}},
            {"name": "thread_name", "ph": "M", "ts": 0,
             "pid": pid, "tid": 1, "args": {"name": "host"}},
            {"name": "thread_name", "ph": "M", "ts": 0,
             "pid": pid, "tid": 2, "args": {"name": "device"}},
        ]
        for rec in self.records():
            targs = {"tick": rec["tick"],
                     "stall_ms": round(rec["stall_ms"], 3),
                     "overlap_efficiency": round(
                         rec["overlap_efficiency"], 4)}
            events.append({
                "name": f"{rec['label']}[{rec['tick']}]",
                "ph": "X", "ts": us(rec["t0"]),
                "dur": round(rec["wall_s"] * 1e6, 1),
                "pid": pid, "tid": 1, "cat": "tick", "args": targs,
            })
            for name, a, b in rec["phases"]:
                events.append({
                    "name": name, "ph": "X", "ts": us(a),
                    "dur": round(max(0.0, b - a) * 1e6, 1),
                    "pid": pid, "tid": 1, "cat": "phase",
                    "args": {"tick": rec["tick"]},
                })
            for d in rec["dispatches"]:
                if d["end"] is None:
                    continue
                events.append({
                    "name": f"dispatch({d['i']})", "ph": "X",
                    "ts": us(d["enq"]),
                    "dur": round((d["end"] - d["enq"]) * 1e6, 1),
                    "pid": pid, "tid": 2, "cat": "dispatch",
                    "args": {
                        "tick": rec["tick"],
                        "fetch_wait_ms": round(
                            (d["end"] - d["fetch0"]) * 1e3, 3
                        ) if d["fetch0"] is not None else None,
                    },
                })
            for name, t, iargs in rec.get("instants", ()):
                # ph "i": a Perfetto instant — the control plane's
                # decision markers land on the host track at the
                # moment the rule fired (scope "t": thread-scoped)
                events.append({
                    "name": name, "ph": "i", "ts": us(t),
                    "pid": pid, "tid": 1, "cat": "control",
                    "s": "t", "args": dict(iargs,
                                           tick=rec["tick"]),
                })
            events.append({
                "name": "overlap_efficiency", "ph": "C",
                "ts": us(rec["t0"]), "pid": pid, "tid": 1,
                "args": {"value": round(rec["overlap_efficiency"], 4)},
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def perfetto_json(self, path: Optional[str] = None) -> str:
        text = json.dumps(self.to_perfetto())
        if path is not None:
            with open(path, "w") as f:
                f.write(text)
        return text


def _merged_windows(spans: List[tuple]) -> float:
    """Total length of the union of [a, b) intervals — the device
    lane's occupancy without double-counting windows the
    double-buffer overlapped with each other."""
    total = 0.0
    end = float("-inf")
    for a, b in sorted(spans):
        if b <= end:
            continue
        total += b - max(a, end)
        end = b
    return total


_timeline = TickTimeline(enabled=False)


def get_timeline() -> TickTimeline:
    return _timeline


def set_timeline(timeline: TickTimeline) -> TickTimeline:
    global _timeline
    _timeline = timeline
    return timeline
