"""Live fleet collector: federate N processes' obs endpoints.

Every instrumented process already serves ``/metrics /snapshot
/events /timeline`` (:class:`crdt_tpu.obs.http.ObsHTTPServer`); until
round 19 correlating them meant dumping rings to disk and running
``obsq`` offline. The collector is the live federation tier the
ROADMAP item-2 fleet presupposes:

- **scrape or push**: :meth:`FleetCollector.scrape` pulls every
  registered process's ``/snapshot`` + ``/events`` + ``/timeline``
  over stdlib ``urllib`` (bounded timeout; a dead process counts
  ``collector.scrape_errors`` and keeps its last snapshot), and
  :meth:`FleetCollector.push` accepts the same payloads pushed by a
  process that cannot be scraped;
- **fleet registries**: counters/gauges/spans re-keyed with a
  ``proc=`` label (``replica.updates_applied{proc="p1"}``) plus
  fleet-wide counter sums, one dict;
- **live cross-process correlation**: the merged event streams run
  through the SAME analysis core offline ``obsq`` uses
  (:mod:`crdt_tpu.obs.propagation`) — trace-id pairing, per-route
  hop-lag percentiles, full-path reconstruction (``pair_rate``), and
  ``obsq diverge``'s divergence correlation, promoted from offline
  to live;
- **merged Perfetto timelines**: :func:`merge_perfetto` re-pids each
  process's trace-event JSON deterministically so the fleet renders
  as one zoomable multi-process timeline (the round-19 pid
  namespacing in ``timeline.to_perfetto`` makes raw exports
  collision-free too).

Collector-process metrics (stable registry rows): gauge
``collector.procs`` (processes with a live snapshot), counters
``collector.scrapes`` / ``collector.scrape_errors`` /
``collector.events_ingested`` / ``collector.divergences``, gauge
``collector.pair_rate`` (fraction of traced receives whose full path
reconstructs — the fleet acceptance number).

Serve it: ``ObsHTTPServer(collector=col)`` adds ``GET /fleet`` (the
fleet report as JSON) and ``GET /fleet/timeline`` (merged Perfetto).
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from crdt_tpu.obs.propagation import (
    correlate_divergences,
    pair_latency,
)
from crdt_tpu.obs.tracer import get_tracer

# scrape responses are bounded before json-parse: a misconfigured
# endpoint (or a hostile one) must cost a capped read, not memory
_MAX_BODY = 32 * 1024 * 1024
_EVENTS_LIMIT = 4096


def _proc_key(name: str, metric: str) -> str:
    """Re-key one process metric with its proc label, composing with
    existing labels (``a.b{x="y"}`` -> ``a.b{proc="p",x="y"}``)."""
    esc = str(name).replace("\\", "\\\\").replace('"', '\\"')
    if metric.endswith("}") and "{" in metric:
        base, inner = metric[:-1].split("{", 1)
        return f'{base}{{proc="{esc}",{inner}}}'
    return f'{metric}{{proc="{esc}"}}'


def merge_perfetto(traces: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-process Chrome trace-event JSON into one fleet
    trace: processes sort by name and take pids 1..N (deterministic —
    child os.getpid()s are not), every event is re-pidded, and each
    process's ``process_name`` metadata is rewritten to the proc name
    so the Perfetto UI groups tracks by process identity."""
    events: List[Dict[str, Any]] = []
    for pid, name in enumerate(sorted(traces), start=1):
        trace = traces[name] or {}
        for ev in trace.get("traceEvents", ()):
            if not isinstance(ev, dict):
                continue
            ev = dict(ev, pid=pid)
            if ev.get("name") == "process_name":
                ev["args"] = {"name": str(name)}
            events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


class FleetCollector:
    """Federates N processes' obs surfaces into one fleet view."""

    def __init__(self, procs: Optional[Dict[str, str]] = None, *,
                 timeout_s: float = 3.0,
                 events_limit: int = _EVENTS_LIMIT):
        self._lock = threading.Lock()
        self._urls: Dict[str, str] = dict(procs or {})
        self._snapshots: Dict[str, Dict[str, Any]] = {}
        self._events: Dict[str, List[Dict[str, Any]]] = {}
        self._timelines: Dict[str, Dict[str, Any]] = {}
        # round 22: per-process /control reports (ledger tail +
        # placement advice) — absent for processes without a control
        # plane (the endpoint 404s; the scrape tolerates it)
        self._controls: Dict[str, Dict[str, Any]] = {}
        self.timeout_s = timeout_s
        self.events_limit = events_limit
        self.scrapes = 0
        self.scrape_errors = 0
        # divergences already counted on the tracer: the same event
        # sits in the merged stream across scrapes, and re-counting
        # it per fleet_report() would inflate the health counter.
        # Bounded (insertion-ordered dict, oldest evicted) like every
        # other obs structure — a long-lived collector watching a
        # divergence-prone fleet must not grow without bound; an
        # evicted key's event has long aged out of the source rings.
        self._counted_divs: "OrderedDict[tuple, None]" = OrderedDict()

    # -- membership ------------------------------------------------------

    def add_proc(self, name: str, base_url: str) -> None:
        """Register one process's ObsHTTPServer base URL."""
        with self._lock:
            self._urls[str(name)] = base_url.rstrip("/")

    @property
    def procs(self) -> List[str]:
        """Processes with a LIVE surface (at least one successful
        scrape or push); registered-but-silent ones show up in the
        fleet report's ``stale_procs`` instead."""
        with self._lock:
            return sorted(
                set(self._snapshots) | set(self._events)
                | set(self._timelines)
            )

    # -- ingest: push ----------------------------------------------------

    def push(self, name: str, *,
             snapshot: Optional[Dict[str, Any]] = None,
             events: Optional[List[Dict[str, Any]]] = None,
             timeline: Optional[Dict[str, Any]] = None,
             control: Optional[Dict[str, Any]] = None) -> None:
        """Push-mode ingest: a process (or a test) hands the same
        payloads a scrape would fetch. Partial pushes update only the
        supplied surfaces."""
        name = str(name)
        tagged = None
        if events is not None:
            tagged = [dict(e, proc=name) for e in events
                      if isinstance(e, dict)]
            # explicit zero-guard: tagged[-0:] would keep EVERYTHING
            # (the same falsy-slice hazard _filter_events documents)
            tagged = tagged[-self.events_limit:] \
                if self.events_limit else []
        with self._lock:
            if snapshot is not None:
                self._snapshots[name] = snapshot
            if tagged is not None:
                self._events[name] = tagged
            if timeline is not None:
                self._timelines[name] = timeline
            if control is not None:
                self._controls[name] = control
        if tagged is not None:
            get_tracer().count(
                "collector.events_ingested", len(tagged)
            )

    # -- ingest: scrape --------------------------------------------------

    def _get(self, url: str) -> bytes:
        with urllib.request.urlopen(
            url, timeout=self.timeout_s
        ) as resp:
            return resp.read(_MAX_BODY)

    def scrape(self) -> Dict[str, bool]:
        """One scrape round over every registered URL. Returns
        {proc: ok}; a failing process keeps its last good surfaces
        (the fleet view degrades to stale, never to absent)."""
        with self._lock:
            urls = dict(self._urls)
        ok: Dict[str, bool] = {}
        tracer = get_tracer()
        for name, base in sorted(urls.items()):
            try:
                snap = json.loads(self._get(f"{base}/snapshot"))
                ev_lines = self._get(
                    f"{base}/events?limit={self.events_limit}"
                ).decode("utf-8", "replace")
                events = [
                    json.loads(ln) for ln in ev_lines.splitlines()
                    if ln.strip()
                ]
                timeline = json.loads(self._get(f"{base}/timeline"))
            except (OSError, ValueError, urllib.error.URLError):
                # concurrent /fleet handlers (ThreadingHTTPServer)
                # may scrape at once: the health counters the fleet
                # report publishes must not lose increments
                with self._lock:
                    self.scrape_errors += 1
                tracer.count("collector.scrape_errors")
                ok[name] = False
                continue
            # the control surface is OPTIONAL (round 22): a process
            # without a control plane 404s here, which must neither
            # fail the scrape nor count as a scrape error
            control = None
            try:
                control = json.loads(self._get(f"{base}/control"))
            except (OSError, ValueError, urllib.error.URLError):
                pass
            self.push(name, snapshot=snap, events=events,
                      timeline=timeline, control=control)
            ok[name] = True
        with self._lock:
            self.scrapes += 1
            n_live = len(self._snapshots)
        tracer.count("collector.scrapes")
        tracer.gauge("collector.procs", n_live)
        return ok

    # -- fleet views -----------------------------------------------------

    def merged_events(self) -> List[Dict[str, Any]]:
        """Every ingested event, oldest-first on the shared monotonic
        timebase, each tagged ``proc=`` — the exact shape the
        propagation analysis core (and obsq) consumes."""
        with self._lock:
            evs = [e for lst in self._events.values() for e in lst]
        evs.sort(key=lambda e: (e.get("ts", 0.0),
                                str(e.get("proc", ""))))
        return evs

    def fleet_metrics(self) -> Dict[str, Any]:
        """Counters/gauges re-keyed with ``proc=`` labels plus
        fleet-wide sums of every unlabeled counter."""
        out: Dict[str, Any] = {"counters": {}, "gauges": {},
                               "sums": {}}
        with self._lock:
            snaps = dict(self._snapshots)
        for name, snap in sorted(snaps.items()):
            tr = (snap or {}).get("tracer") or {}
            for section in ("counters", "gauges"):
                for metric, value in (tr.get(section) or {}).items():
                    out[section][_proc_key(name, metric)] = value
                    if section == "counters" and "{" not in metric \
                            and isinstance(value, (int, float)):
                        out["sums"][metric] = \
                            out["sums"].get(metric, 0) + value
        return out

    def fleet_report(self) -> Dict[str, Any]:
        """The /fleet payload: membership, proc-labeled registries,
        live cross-process propagation + divergence correlation —
        one JSON-ready dict. Publishes ``collector.pair_rate`` and
        ``collector.divergences`` on the collector's tracer."""
        events = self.merged_events()
        latency = pair_latency(events)
        # pair_latency already ran the reconstruction over the same
        # events — reuse it instead of a second O(events) scan
        paths = latency["paths"]
        diverge = correlate_divergences(events)
        tracer = get_tracer()
        tracer.gauge("collector.pair_rate", paths["pair_rate"])
        tracer.gauge("collector.procs", len(self.procs))
        fresh = 0
        for d in diverge["events"]:
            key = (d["src"], json.dumps(d["divergence"],
                                        sort_keys=True, default=str))
            with self._lock:
                if key in self._counted_divs:
                    continue
                self._counted_divs[key] = None
                while len(self._counted_divs) > 4096:
                    self._counted_divs.popitem(last=False)
            fresh += 1
        if fresh:
            tracer.count("collector.divergences", fresh)
        live = set(self.procs)
        with self._lock:
            stale = sorted(set(self._urls) - live)
            controls = dict(self._controls)
        return {
            "procs": self.procs,
            "stale_procs": stale,
            "scrapes": self.scrapes,
            "scrape_errors": self.scrape_errors,
            "events": len(events),
            "metrics": self.fleet_metrics(),
            "latency": latency,
            "paths": paths,
            "divergence": diverge,
            # round 22: each process's live control report (ledger
            # tail, setpoints) plus the flattened proc-tagged advice
            # rows — ROADMAP item 2's rebalance hints, federated
            # here, consumed by a later round's placement loop
            "control": controls,
            "advice": self.fleet_advice(),
        }

    def fleet_advice(self) -> List[Dict[str, Any]]:
        """Every process's placement-advice rows, proc-tagged, in
        deterministic (proc, tenant) order."""
        with self._lock:
            controls = dict(self._controls)
        out: List[Dict[str, Any]] = []
        for name in sorted(controls):
            for row in (controls[name] or {}).get("advice") or ():
                if isinstance(row, dict):
                    out.append(dict(row, proc=name))
        return out

    def merged_perfetto(self) -> Dict[str, Any]:
        with self._lock:
            traces = dict(self._timelines)
        return merge_perfetto(traces)
