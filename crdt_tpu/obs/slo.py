"""Per-tenant SLO accounting for the multi-tenant serving path.

The round-15 serve() loop made ``MultiDocServer`` a live tick server,
but its user-visible behavior — how long a tenant's update waits
between ingest and being readable — existed only as one aggregate
latency per doc. This ledger (round 18, ROADMAP items 1/2
precondition) closes the loop per blob: every update admitted by
:meth:`crdt_tpu.models.multidoc.MultiDocServer.submit` is stamped,
the settle path ends the *ingest-to-converged* clock, the tick end
(state readable to every consumer) ends *ingest-to-served*, and both
land in per-tenant log2 histograms on the tracer's own bucket edges
(:class:`crdt_tpu.obs.tracer.Histogram` — an SLO report and a
Prometheus scrape bucket identically).

**Objective + breaches.** ``slo_ms`` (constructor, or
``CRDT_TPU_SLO_MS``; default 250 ms) is the ingest-to-served
objective. A blob breaches when it is served later than the
objective — or when it is **shed**: an update trimmed by the
admission budget is never served at all, which misses any finite
objective by definition, so shed counts fold into the breach ledger
(the flooding-tenant acceptance pin: breach == shed == the admission
oracle, while untouched neighbors hold zero). ``burn_rate`` is the
breach fraction over a sliding window of the tenant's most recent
outcomes (served + shed), the gauge an on-call human watches while
the total counters only ever grow.

**Route mix.** Every doc-serve is attributed to the route that
produced it — ``delta`` (resident incremental splice), ``cold``
(full replay through the packed batch, promotions included),
``fallback`` (a packed batch that degraded to per-doc dispatches) —
and sheds ride the same table, so a perpetually-cold or flooding
tenant is diagnosable from metrics alone.

Tracer emission (README "Observability v2" registry; every call is
gated on ``tracer.enabled`` so the ledger adds no tracer cost when
tracing is off): counters ``slo.breaches`` (+ ``slo.breaches{tenant=}``),
``slo.route_delta`` / ``slo.route_cold`` / ``slo.route_fallback`` /
``slo.route_shed`` (labeled per tenant), gauges ``slo.burn_rate``
(worst tenant) + ``slo.burn_rate{tenant=}``, and the
``slo.ingest_to_converged`` / ``slo.ingest_to_served`` latency
histograms (span registry).
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Any, Dict, Iterable, Optional

from crdt_tpu.obs.tracer import Histogram, get_tracer

_SLO_MS_ENV = "CRDT_TPU_SLO_MS"
DEFAULT_SLO_MS = 250.0
DEFAULT_BURN_WINDOW = 128

ROUTES = ("delta", "cold", "fallback", "shed")
_ROUTE_COUNTERS = {
    "delta": "slo.route_delta",
    "cold": "slo.route_cold",
    "fallback": "slo.route_fallback",
    "shed": "slo.route_shed",
}


def _env_slo_ms() -> float:
    raw = os.environ.get(_SLO_MS_ENV, "")
    if raw == "":
        return DEFAULT_SLO_MS
    try:
        return max(0.0, float(raw))
    except ValueError:
        return DEFAULT_SLO_MS


class _TenantSLO:
    __slots__ = ("converged", "served", "breaches", "routes", "window")

    def __init__(self, window: int):
        self.converged = Histogram()
        self.served = Histogram()
        self.breaches = 0
        self.routes = {r: 0 for r in ROUTES}
        # sliding breach window: most recent served/shed outcomes,
        # True = breached (burn rate = mean over the window)
        self.window: deque = deque(maxlen=window)

    def burn_rate(self) -> float:
        if not self.window:
            return 0.0
        return sum(self.window) / len(self.window)


class SLOLedger:
    """Per-tenant ingest-latency objective ledger (see module doc).

    Thread-safe like the tracer (one lock per mutation): the serve()
    loop settles docs while its ingest hook admits more, and an HTTP
    scrape may call :meth:`report` from its own thread at any time.
    """

    def __init__(self, slo_ms: Optional[float] = None, *,
                 burn_window: int = DEFAULT_BURN_WINDOW):
        if slo_ms is None:
            slo_ms = _env_slo_ms()
        self.slo_ms = float(slo_ms)
        self.slo_s = self.slo_ms / 1e3
        self.burn_window = int(burn_window)
        self._lock = threading.Lock()
        self._tenants: Dict[Any, _TenantSLO] = {}

    # -- accounting (called by the serving path) -----------------------

    def _tenant(self, tenant) -> _TenantSLO:
        t = self._tenants.get(tenant)
        if t is None:
            t = self._tenants[tenant] = _TenantSLO(self.burn_window)
        return t

    def converged(self, tenant, latencies_s: Iterable[float],
                  route: str) -> None:
        """Blobs of one tenant just settled (moved from the in-flight
        window into converged history) via ``route``; each latency is
        submit -> settle. The route is counted once per settle batch
        (one doc-serve), the histogram once per blob."""
        lats = list(latencies_s)
        tracer = get_tracer()
        with self._lock:
            t = self._tenant(tenant)
            for dt in lats:
                t.converged.add(dt)
            t.routes[route] += 1
        if tracer.enabled:
            # crdtlint: emits=slo.route_delta,slo.route_cold,slo.route_fallback
            tracer.count(_ROUTE_COUNTERS[route], 1,
                         labels={"tenant": tenant})
            for dt in lats:
                tracer.observe("slo.ingest_to_converged", dt)

    def served(self, tenant, latencies_s: Iterable[float]) -> None:
        """The same blobs became *readable* (tick end); each latency
        is submit -> served, checked against the objective."""
        lats = list(latencies_s)
        breached = 0
        tracer = get_tracer()
        with self._lock:
            t = self._tenant(tenant)
            for dt in lats:
                t.served.add(dt)
                bad = dt > self.slo_s
                t.window.append(bad)
                if bad:
                    breached += 1
            t.breaches += breached
            burn = t.burn_rate()
        if tracer.enabled:
            for dt in lats:
                tracer.observe("slo.ingest_to_served", dt)
            if breached:
                tracer.count("slo.breaches", breached)
                tracer.count("slo.breaches", breached,
                             labels={"tenant": tenant})
            # only the per-tenant gauge here: the global worst-tenant
            # gauge scans every tenant, which would make a tick's
            # served loop O(tenants^2) — it publishes once per tick
            # instead (:meth:`publish_worst`)
            tracer.gauge("slo.burn_rate", burn,
                         labels={"tenant": tenant})

    def shed(self, tenant, n: int = 1) -> None:
        """``n`` of the tenant's pending blobs were trimmed by the
        admission budget: never served, so each one is a breach of
        any finite objective (and a ``shed`` route outcome)."""
        if n <= 0:
            return
        tracer = get_tracer()
        with self._lock:
            t = self._tenant(tenant)
            t.routes["shed"] += n
            t.breaches += n
            for _ in range(n):
                t.window.append(True)
            burn = t.burn_rate()
        if tracer.enabled:
            tracer.count("slo.breaches", n)
            tracer.count("slo.breaches", n, labels={"tenant": tenant})
            # crdtlint: emits=slo.route_shed
            tracer.count(_ROUTE_COUNTERS["shed"], n,
                         labels={"tenant": tenant})
            tracer.gauge("slo.burn_rate", burn,
                         labels={"tenant": tenant})

    # -- reads ---------------------------------------------------------

    def breaches(self, tenant) -> int:
        with self._lock:
            t = self._tenants.get(tenant)
            return t.breaches if t is not None else 0

    def route_counts(self, tenant) -> Dict[str, int]:
        with self._lock:
            t = self._tenants.get(tenant)
            return dict(t.routes) if t is not None \
                else {r: 0 for r in ROUTES}

    def _worst_burn_locked(self) -> float:
        return max(
            (t.burn_rate() for t in self._tenants.values()),
            default=0.0,
        )

    def worst_burn_rate(self) -> float:
        with self._lock:
            return self._worst_burn_locked()

    def publish_worst(self) -> float:
        """Publish the global worst-tenant burn-rate gauge
        (``slo.burn_rate``, unlabeled). One O(tenants) scan — called
        once per tick by the serving loop, never per served blob."""
        worst = self.worst_burn_rate()
        tracer = get_tracer()
        if tracer.enabled:
            tracer.gauge("slo.burn_rate", worst)
        return worst

    def control_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Cheap per-tenant sensor slice for the control plane
        (round 22): burn rate, shed count, and total breaches per
        tenant — no histograms, so the controller's once-per-tick
        read stays O(tenants). Keys are the ORIGINAL tenant objects
        (the server joins them against its own doc table); the
        controller stringifies for its JSON ledger."""
        with self._lock:
            return {
                k: {
                    "burn": round(t.burn_rate(), 4),
                    "shed": t.routes["shed"],
                    "breaches": t.breaches,
                }
                for k, t in self._tenants.items()
            }

    def report(self) -> Dict[str, Any]:
        """JSON-ready per-tenant summary — the ``/snapshot`` section
        and the ``bench --multitenant`` evidence block."""
        with self._lock:
            tenants = {
                str(k): {
                    "breaches": t.breaches,
                    "burn_rate": round(t.burn_rate(), 4),
                    "routes": dict(t.routes),
                    "ingest_to_converged": t.converged.summary(),
                    "ingest_to_served": t.served.summary(),
                }
                for k, t in sorted(
                    self._tenants.items(), key=lambda kv: str(kv[0])
                )
            }
        return {
            "slo_ms": self.slo_ms,
            "tenants": tenants,
            "total_breaches": sum(
                t["breaches"] for t in tenants.values()
            ),
            "worst_burn_rate": max(
                (t["burn_rate"] for t in tenants.values()),
                default=0.0,
            ),
        }

    def reset(self) -> None:
        with self._lock:
            self._tenants.clear()
