"""Pull-based observability endpoint — stdlib only, zero new deps.

One background thread serves the four surfaces a fleet scheduler or
an on-call human scrapes while ``MultiDocServer.serve()`` (or any
instrumented process) runs:

- ``GET /metrics``   — the Prometheus text exposition of the
  process-global tracer (:func:`crdt_tpu.obs.export.to_prometheus`).
- ``GET /snapshot``  — JSON: the full tracer report plus whatever
  extra sections the host process registered (the server's per-tenant
  SLO report, timeline summary — ``snapshot_extra``).
- ``GET /events``    — the flight-recorder tail as JSONL, filterable:
  ``?kind=`` (exact event kind), ``?doc=`` (matches an event's
  ``doc`` or ``topic`` field), ``?peer=`` (matches ``peer`` or
  ``replica``), ``?limit=`` (newest N).
- ``GET /timeline``  — the tick-timeline ring as Perfetto
  trace-event JSON (open it at ui.perfetto.dev).
- ``GET /control``   — the control plane's live report (round 22,
  ``control=`` a :class:`crdt_tpu.obs.control.Controller`): config,
  decision/cooldown counters, current setpoints, placement advice,
  and the ledger tail (``?limit=`` rows, default 128).

Reads are snapshots under the producers' own locks (tracer, recorder
and timeline are all thread-safe), so scraping never blocks the tick
loop beyond those sub-microsecond critical sections. The server binds
127.0.0.1 by default and ``port=0`` picks a free port (``.port``
reports the bound one) — tests and bench runs never collide.

    from crdt_tpu.obs.http import ObsHTTPServer
    obs = ObsHTTPServer(port=0, snapshot_extra=lambda: {
        "slo": server.slo.report(),
    })
    obs.start()
    print(obs.url)           # http://127.0.0.1:<port>
    ...
    obs.stop()
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional
from urllib.parse import parse_qs, urlparse


def _filter_events(events, q: Dict[str, list]) -> list:
    kind = q.get("kind", [None])[0]
    doc = q.get("doc", [None])[0]
    peer = q.get("peer", [None])[0]
    limit = q.get("limit", [None])[0]
    out = []
    for e in events:
        if kind is not None and e.get("kind") != kind:
            continue
        if doc is not None and str(e.get("doc", e.get("topic"))) != doc:
            continue
        if peer is not None and \
                str(e.get("peer", e.get("replica"))) != peer:
            continue
        out.append(e)
    if limit is not None:
        try:
            n = max(0, int(limit))
        except ValueError:
            return out
        # newest-N semantics: n=0 means none (out[-0:] would be ALL)
        out = out[max(0, len(out) - n):] if n else []
    return out


class ObsHTTPServer:
    """Scrape endpoint over the process-global obs singletons.

    With ``collector=`` (a :class:`crdt_tpu.obs.collector.
    FleetCollector`), the server additionally exposes the fleet
    surfaces: ``GET /fleet`` (scrape every registered process, then
    the fleet report — proc-labeled registries, live cross-process
    trace pairing and divergence correlation; ``?scrape=0`` reports
    from the last ingest instead) and ``GET /fleet/timeline`` (the
    collector-merged multi-process Perfetto trace)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 snapshot_extra: Optional[
                     Callable[[], Dict[str, Any]]] = None,
                 collector: Optional[Any] = None,
                 control: Optional[Any] = None):
        self._extra = snapshot_extra
        self.collector = collector
        self.control = control
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            # scrapes are high-frequency; server-side request logging
            # to stderr would be pure noise
            def log_message(self, fmt, *args):  # noqa: ARG002
                pass

            def do_GET(self):  # noqa: N802 (http.server contract)
                try:
                    body, ctype, status = outer._route(self.path)
                except Exception as exc:  # never kill the serve loop
                    body = json.dumps(
                        {"error": repr(exc)}
                    ).encode()
                    ctype, status = "application/json", 500
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    # -- routing -------------------------------------------------------

    def _route(self, path: str):
        from crdt_tpu.obs.export import to_prometheus
        from crdt_tpu.obs.recorder import get_recorder
        from crdt_tpu.obs.timeline import get_timeline
        from crdt_tpu.obs.tracer import get_tracer

        u = urlparse(path)
        if u.path == "/metrics":
            return (to_prometheus().encode(),
                    "text/plain; version=0.0.4", 200)
        if u.path == "/snapshot":
            snap: Dict[str, Any] = {"tracer": get_tracer().report()}
            if self._extra is not None:
                snap.update(self._extra() or {})
            return (json.dumps(snap, sort_keys=True, default=str)
                    .encode(), "application/json", 200)
        if u.path == "/events":
            evs = _filter_events(
                get_recorder().events(), parse_qs(u.query)
            )
            text = "\n".join(
                json.dumps(e, sort_keys=True, default=str)
                for e in evs
            )
            if text:
                text += "\n"
            return text.encode(), "application/x-ndjson", 200
        if u.path == "/timeline":
            return (get_timeline().perfetto_json().encode(),
                    "application/json", 200)
        if self.control is not None and u.path == "/control":
            q = parse_qs(u.query)
            try:
                limit = max(0, int(q.get("limit", ["128"])[0]))
            except ValueError:
                limit = 128
            return (json.dumps(
                self.control.report(limit), sort_keys=True,
                default=str,
            ).encode(), "application/json", 200)
        if self.collector is not None and u.path == "/fleet":
            q = parse_qs(u.query)
            if q.get("scrape", ["1"])[0] not in ("0", "false"):
                self.collector.scrape()
            return (json.dumps(
                self.collector.fleet_report(), sort_keys=True,
                default=str,
            ).encode(), "application/json", 200)
        if self.collector is not None and u.path == "/fleet/timeline":
            return (json.dumps(
                self.collector.merged_perfetto()
            ).encode(), "application/json", 200)
        routes = ["/metrics", "/snapshot", "/events", "/timeline"]
        if self.control is not None:
            routes += ["/control"]
        if self.collector is not None:
            routes += ["/fleet", "/fleet/timeline"]
        return (json.dumps({
            "error": "unknown path",
            "routes": routes,
        }).encode(), "application/json", 404)

    # -- lifecycle -----------------------------------------------------

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ObsHTTPServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="crdt-obs-http", daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread.join(timeout=5.0)
            self._thread = None
        self._httpd.server_close()

    def __enter__(self) -> "ObsHTTPServer":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False
