"""Causal cross-replica tracing: wire trace context + lag attribution.

Round 18 stamped outbound updates with an origin trace id and a hop
count, but the pairing lived in offline ``obsq`` dumps and the hop
field had no incrementer past the first edge — the process boundary
was the end of visibility. This module is the distributed-tracing
plane the fleet/gateway tiers (ROADMAP items 1–2) presuppose:

- **Wire trace context** (:class:`TraceContext`): a compact, bounded
  causal context carried on update / sync-answer / anti-entropy
  frames — the origin trace id ``(client, seq, monotonic_ts)`` plus
  one **path record per forward leg**: ``(replica, route, delta_us)``
  where ``route`` is one of :data:`ROUTES` and ``delta_us`` is the
  stamping process's monotonic offset from the origin timestamp
  (microseconds; comparable across processes on one host — Linux
  ``CLOCK_MONOTONIC`` is boot-anchored — and uniformly shifted across
  hosts, exactly like the round-18 tid). Encoded with the lib0
  primitives (:mod:`crdt_tpu.codec.lib0`); decoded DEFENSIVELY — a
  hostile context (oversized hop list, negative delta, truncated or
  trailing bytes, non-bytes payload) raises ``ValueError`` and is
  dropped by callers without touching the update it rode on. The
  decode path is in the crdtlint wire-taint / decode-allocation scope
  (CL10xx/CL11xx), so the fences are machine-checked.
- **Per-hop lag attribution** (:class:`PropagationLedger`): receivers
  decompose origin-to-visibility into per-leg, route-tagged
  latencies — leg *i*'s lag is ``path[i+1].delta - path[i].delta``
  (the final leg closes against the receive stamp) — into tracer
  histograms ``replica.hop_lag{route=...}`` and the end-to-end
  ``replica.birth_to_visibility`` span, so "why is convergence slow"
  answers with *which hop on which route*. The ledger also keeps the
  wire-overhead accounting (``propagation.context_bytes`` vs
  ``propagation.traced_update_bytes``; gauge
  ``propagation.wire_overhead_ratio``) that bounds the tracing tax.
- **Analysis core** (:func:`pair_latency`, :func:`reconstruct_paths`,
  :func:`correlate_divergences`): the tid-pairing / path-completeness
  / divergence-correlation logic shared VERBATIM by the offline
  ``tools/obsq.py`` CLI and the live fleet collector
  (:mod:`crdt_tpu.obs.collector`) — offline dumps and live scrapes
  answer the same questions through one implementation.

Knobs: ``CRDT_TPU_TRACE_SAMPLE`` (0..1, default 1 — deterministic
per-tid sampling, crc32-derived so every replica agrees on which tids
are traced) and ``CRDT_TPU_TRACE_MAX_HOPS`` (default 8 — forward
seams refuse to grow a context past the bound and count
``propagation.hops_capped`` instead). Stdlib-only: the analysis lane
(obsq) must import this without jax.
"""

from __future__ import annotations

import math
import os
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from crdt_tpu.codec.lib0 import Decoder, Encoder
from crdt_tpu.obs.tracer import Histogram, get_tracer

# route tags, one per forward-leg kind; the wire carries the index
ROUTES: Tuple[str, ...] = (
    "direct", "predicted", "relayed", "anti_entropy", "sync_answer",
)
_ROUTE_CODE = {r: i for i, r in enumerate(ROUTES)}

_VERSION = 1
# hard wire bounds (the decode fences; every one raises ValueError):
# a context larger than this is hostile before a single field parses
MAX_CONTEXT_BYTES = 512
MAX_REPLICA_ID = 16      # path-record replica ids are short prefixes
_MAX_TID = 1 << 53       # JS-safe integers, like every honest tid
_MAX_DELTA_US = 1 << 53


def max_hops() -> int:
    """The per-context hop bound (``CRDT_TPU_TRACE_MAX_HOPS``)."""
    try:
        n = int(os.environ.get("CRDT_TPU_TRACE_MAX_HOPS", "8"))
    except ValueError:
        return 8
    return max(1, min(n, 64))


def sample_rate() -> float:
    """The origin sampling rate (``CRDT_TPU_TRACE_SAMPLE``)."""
    try:
        r = float(os.environ.get("CRDT_TPU_TRACE_SAMPLE", "1"))
    except ValueError:
        return 1.0
    return min(max(r, 0.0), 1.0)


def sampled(client: int, seq: int, rate: float) -> bool:
    """Deterministic per-tid sampling decision: crc32-derived (no
    process salt), so every replica — and every offline analysis —
    agrees on which trace ids carry context."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return zlib.crc32(f"{client}:{seq}".encode()) / 2**32 < rate


class TraceContext:
    """Origin tid + bounded per-leg path records."""

    __slots__ = ("origin_client", "origin_seq", "origin_ts", "hops")

    def __init__(self, origin_client: int, origin_seq: int,
                 origin_ts: float,
                 hops: Optional[List[Tuple[str, str, int]]] = None):
        self.origin_client = origin_client
        self.origin_seq = origin_seq
        self.origin_ts = origin_ts
        # [(replica, route, delta_us)] — delta_us is the stamping
        # process's monotonic offset from origin_ts at send time
        self.hops: List[Tuple[str, str, int]] = list(hops or [])

    @property
    def tid(self) -> List[Any]:
        return [self.origin_client, self.origin_seq, self.origin_ts]

    @property
    def tid_key(self) -> Tuple[int, int]:
        return (self.origin_client, self.origin_seq)

    def path_json(self) -> List[List[Any]]:
        """The path as plain JSON (the shape recorder events carry)."""
        return [[r, rt, d] for r, rt, d in self.hops]

    def __repr__(self):
        legs = "→".join(f"{r}[{rt}]" for r, rt, _ in self.hops)
        return (f"TraceContext({self.origin_client}:{self.origin_seq}"
                f" {legs})")


def start_context(client: int, seq: int, replica: str,
                  route: str = "direct",
                  ts: Optional[float] = None) -> TraceContext:
    """A fresh context at the origin: one path record for the first
    send leg, delta 0 by definition."""
    if ts is None:
        ts = time.monotonic()
    return TraceContext(
        client, seq, ts, [(str(replica)[:MAX_REPLICA_ID], route, 0)]
    )


def append_hop(ctx: TraceContext, replica: str, route: str,
               delta_us: int) -> bool:
    """Append one forward-leg record, honoring the max-hops bound.
    Returns False (and counts ``propagation.hops_capped``) when the
    context is already at the bound — the path is then truncated, not
    unbounded."""
    if len(ctx.hops) >= max_hops():
        get_tracer().count("propagation.hops_capped")
        return False
    ctx.hops.append(
        (str(replica)[:MAX_REPLICA_ID], route, max(0, int(delta_us)))
    )
    get_tracer().count("propagation.hops_appended")
    return True


def encode_context(ctx: TraceContext) -> bytes:
    """Compact lib0 wire form: version byte, origin tid, hop count,
    then one (replica varString, route uint8, delta varInt) triple
    per path record."""
    enc = Encoder()
    enc.write_uint8(_VERSION)
    enc.write_var_uint(int(ctx.origin_client))
    enc.write_var_uint(int(ctx.origin_seq))
    enc.write_float64(float(ctx.origin_ts))
    enc.write_var_uint(len(ctx.hops))
    for replica, route, delta_us in ctx.hops:
        enc.write_var_string(str(replica)[:MAX_REPLICA_ID])
        enc.write_uint8(_ROUTE_CODE.get(route, 0))
        enc.write_var_int(int(delta_us))
    return enc.to_bytes()


def decode_context(blob) -> TraceContext:
    """Decode a wire trace context, failing CLOSED: any hostile shape
    — non-bytes payload, oversized blob or hop list, out-of-range
    tid, negative or absurd delta, unknown route or version,
    truncation, trailing garbage — raises ``ValueError`` (only), so
    the poll-loop isolation that guards update decodes covers this
    field too."""
    if not isinstance(blob, (bytes, bytearray)):
        raise ValueError("trace context is not bytes")
    if len(blob) > MAX_CONTEXT_BYTES:
        raise ValueError("trace context exceeds wire bound")
    dec = Decoder(bytes(blob))
    version = dec.read_uint8()
    if version != _VERSION:
        raise ValueError(f"unknown trace context version {version}")
    client = dec.read_var_uint()
    seq = dec.read_var_uint()
    if client >= _MAX_TID or seq >= _MAX_TID:
        raise ValueError("trace context tid out of range")
    ts = dec.read_float64()
    if not math.isfinite(ts):
        # a NaN origin stamp poisons every delta; +/-inf would
        # overflow the microsecond conversions at the forward seams
        raise ValueError("trace context origin ts is not finite")
    n_hops = dec.read_var_uint()
    # buffer-anchored first (a hop is >= 3 wire bytes, so a count
    # past the remaining byte budget is hostile before the protocol
    # bound even applies), then the protocol max-hops bound
    if n_hops > dec.remaining() or n_hops > max_hops():
        raise ValueError("trace context hop list exceeds bound")
    hops: List[Tuple[str, str, int]] = []
    for _ in range(n_hops):  # body reads wire bytes every iteration
        replica = dec.read_var_string()
        if len(replica) > MAX_REPLICA_ID:
            raise ValueError("trace context replica id too long")
        route_code = dec.read_uint8()
        if route_code >= len(ROUTES):
            raise ValueError("unknown trace context route tag")
        delta_us = dec.read_var_int()
        if delta_us < 0:
            raise ValueError("negative trace context ts-delta")
        if delta_us >= _MAX_DELTA_US:
            raise ValueError("trace context ts-delta out of range")
        hops.append((replica, ROUTES[route_code], delta_us))
    if dec.has_content():
        raise ValueError("trailing bytes after trace context")
    return TraceContext(client, seq, ts, hops)


def decode_or_none(blob, *, count: bool = True
                   ) -> Optional[TraceContext]:
    """Admission wrapper for untrusted contexts: a reject is counted
    (``propagation.malformed_contexts``) and returns None — the
    update the context rode on is untouched either way.
    ``count=False`` is for the forward/retag seams, where the
    RECEIVING replica is the authoritative counter (a relayed
    hostile context must read as one, not two)."""
    if blob is None:
        return None
    try:
        return decode_context(blob)
    except ValueError:
        if count:
            get_tracer().count("propagation.malformed_contexts")
        return None


def retag_last_hop(blob: bytes, route: str) -> bytes:
    """Rewrite the newest path record's route tag (the send seam's
    transport attribution: a 'direct' leg that actually rides a
    predicted or relayed path). Semantic tags (anti_entropy,
    sync_answer) are preserved; failures return the blob unchanged —
    attribution must never break delivery."""
    ctx = decode_or_none(blob, count=False)
    if ctx is None or not ctx.hops:
        return blob
    replica, old_route, delta = ctx.hops[-1]
    if old_route != "direct" or route not in _ROUTE_CODE:
        return blob
    ctx.hops[-1] = (replica, route, delta)
    return encode_context(ctx)


def append_hop_wire(blob: bytes, replica: str, route: str,
                    hop_ts: Optional[float] = None) -> bytes:
    """The forward-seam hop incrementer on WIRE form: decode, append
    one path record stamped at ``hop_ts`` (monotonic; defaults to
    now), re-encode. Failures — malformed context, hop bound — return
    the blob unchanged (truncated beats dropped)."""
    ctx = decode_or_none(blob, count=False)
    if ctx is None:
        return blob
    if hop_ts is None:
        hop_ts = time.monotonic()
    if not math.isfinite(hop_ts):
        return blob  # a hostile stamp attributes nothing
    # clamp into the wire-legal range: the decoded origin ts is
    # finite, but a far-future stamp must not overflow the varint
    delta_us = int(min(float(_MAX_DELTA_US - 1),
                       max(0.0, hop_ts - ctx.origin_ts) * 1e6))
    if not append_hop(ctx, replica, route, delta_us):
        return blob
    return encode_context(ctx)


def hop_legs(path: List, origin_ts: float,
             recv_ts: float) -> List[Tuple[str, str, float]]:
    """Per-leg (replica, route, lag_seconds) attribution: leg *i*
    closes at leg *i+1*'s stamp, the final leg at the receive stamp.
    Accepts both decoded hop tuples and the JSON path shape; lags are
    clamped at 0 (cross-host clock offsets must not go negative)."""
    legs: List[Tuple[str, str, float]] = []
    total = max(0.0, recv_ts - origin_ts)
    for i, hop in enumerate(path):
        replica, route, delta_us = hop[0], hop[1], hop[2]
        if not isinstance(delta_us, (int, float)) or route not in _ROUTE_CODE:
            return []  # a malformed offline path attributes nothing
        start_s = max(0.0, float(delta_us) / 1e6)
        if i + 1 < len(path):
            nxt = path[i + 1][2]
            if not isinstance(nxt, (int, float)):
                return []
            end_s = max(0.0, float(nxt) / 1e6)
        else:
            end_s = total
        legs.append((str(replica), str(route),
                     max(0.0, end_s - start_s)))
    return legs


class PropagationLedger:
    """End-to-end birth-to-visibility ledger + per-route hop lag.

    One process-global instance (:func:`get_propagation` /
    :func:`set_propagation`), fed by the replica's send/receive seams
    when observability is on. Keeps route-tagged lag histograms and
    the wire-overhead accounting, mirrors everything into the
    process-global tracer (so ``/metrics`` scrapes and BENCH_OUT
    artifacts carry it), and reports as one JSON-ready dict."""

    def __init__(self):
        self._lock = threading.Lock()
        self._route_lag: Dict[str, Histogram] = {}
        self._e2e = Histogram()
        self.contexts_sent = 0
        self.contexts_received = 0
        self.context_bytes = 0
        self.traced_update_bytes = 0

    # -- producer seams --------------------------------------------------

    def record_send(self, ctx_bytes: bytes, update_bytes: int) -> None:
        """A context was attached at a send seam: count the tracing
        tax against the payload it rode on."""
        with self._lock:
            self.contexts_sent += 1
            self.context_bytes += len(ctx_bytes)
            self.traced_update_bytes += max(0, int(update_bytes))
            ratio = (
                self.context_bytes / self.traced_update_bytes
                if self.traced_update_bytes else 0.0
            )
        tracer = get_tracer()
        if tracer.enabled:
            tracer.count("propagation.contexts_sent")
            tracer.count("propagation.context_bytes", len(ctx_bytes))
            tracer.count(
                "propagation.traced_update_bytes",
                max(0, int(update_bytes)),
            )
            tracer.gauge("propagation.wire_overhead_ratio", ratio)

    def record_receipt(self, ctx: TraceContext,
                       recv_ts: Optional[float] = None) -> int:
        """A traced frame became visible here: attribute every leg to
        its route and close the birth-to-visibility clock. Returns
        the hop count (the frame's delivery depth)."""
        if recv_ts is None:
            recv_ts = time.monotonic()
        legs = hop_legs(ctx.hops, ctx.origin_ts, recv_ts)
        e2e = max(0.0, recv_ts - ctx.origin_ts)
        tracer = get_tracer()
        with self._lock:
            self.contexts_received += 1
            for _, route, lag in legs:
                h = self._route_lag.get(route)
                if h is None:
                    h = self._route_lag[route] = Histogram()
                h.add(lag)
            self._e2e.add(e2e)
        if tracer.enabled:
            tracer.count("propagation.contexts_received")
            for _, route, lag in legs:
                # crdtlint: emits=replica.hop_lag
                tracer.observe(
                    f'replica.hop_lag{{route="{route}"}}', lag
                )
            tracer.observe("replica.birth_to_visibility", e2e)
        return len(ctx.hops)

    # -- reporting -------------------------------------------------------

    def report(self) -> Dict[str, Any]:
        with self._lock:
            ratio = (
                self.context_bytes / self.traced_update_bytes
                if self.traced_update_bytes else 0.0
            )
            return {
                "contexts_sent": self.contexts_sent,
                "contexts_received": self.contexts_received,
                "context_bytes": self.context_bytes,
                "traced_update_bytes": self.traced_update_bytes,
                "wire_overhead_ratio": ratio,
                "birth_to_visibility": self._e2e.summary(),
                "hop_lag_by_route": {
                    r: h.summary()
                    for r, h in sorted(self._route_lag.items())
                },
            }


_ledger = PropagationLedger()


def get_propagation() -> PropagationLedger:
    return _ledger


def set_propagation(ledger: PropagationLedger) -> PropagationLedger:
    global _ledger
    _ledger = ledger
    return ledger


# ---------------------------------------------------------------------------
# analysis core — shared by tools/obsq.py (offline dumps) and the
# fleet collector (live scrapes); events are plain recorder dicts
# ---------------------------------------------------------------------------


def _percentiles(sorted_vals: List[float]) -> Dict[str, float]:
    def q(p: float) -> float:
        if not sorted_vals:
            return 0.0
        i = min(len(sorted_vals) - 1,
                max(0, int(p * len(sorted_vals) + 0.5) - 1))
        return sorted_vals[i]

    return {
        "count": len(sorted_vals),
        "p50_s": q(0.50),
        "p90_s": q(0.90),
        "p99_s": q(0.99),
        "max_s": sorted_vals[-1] if sorted_vals else 0.0,
    }


def _tid_key(ev: Dict[str, Any]) -> Optional[Tuple[Any, Any]]:
    t = ev.get("tid")
    if isinstance(t, (list, tuple)) and len(t) >= 2:
        a, b = t[0], t[1]
        # events carry wire tids verbatim, so elements can be any
        # JSON shape: only hashable scalars make a pairing key (an
        # unhashable hostile tid must not TypeError out of obsq or
        # a live /fleet request)
        if isinstance(a, (int, float, str)) and \
                isinstance(b, (int, float, str)):
            return (a, b)
    return None


# every ORIGIN-frame event kind (each stamps a fresh tid + context):
# broadcasts, sync-answer diffs, anti-entropy deltas — receives pair
# back against any of them
ORIGIN_KINDS = frozenset({"update.send", "sync.answer", "ae.delta"})


def pair_latency(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """send/recv pairing by trace id across every loaded source: the
    cross-process propagation story. One send may fan out to many
    receivers; every (send, recv) pair contributes one latency. The
    round-19 additions: per-ROUTE leg-lag percentiles decomposed from
    the carried path records, and the path-reconstruction stats the
    fleet acceptance gate reads."""
    sends: Dict[tuple, float] = {}
    for e in events:
        t = e.get("tid")
        key = _tid_key(e)
        if e.get("kind") in ORIGIN_KINDS and key is not None \
                and isinstance(t, (list, tuple)) and len(t) >= 3:
            try:
                sends.setdefault(key, float(t[2]))
            except (TypeError, ValueError):
                continue
    lats: List[float] = []
    unmatched_recv = 0
    hops: Dict[str, int] = {}
    route_legs: Dict[str, List[float]] = {}
    for e in events:
        if e.get("kind") != "update.recv":
            continue
        key = _tid_key(e)
        if key is not None and key in sends and isinstance(
                e.get("ts"), (int, float)):
            lats.append(max(0.0, e["ts"] - sends[key]))
        else:
            unmatched_recv += 1
        h = e.get("hop")
        hkey = str(h) if isinstance(h, int) else "unknown"
        hops[hkey] = hops.get(hkey, 0) + 1
        path = e.get("path")
        t = e.get("tid")
        if (isinstance(path, list) and path
                and isinstance(t, (list, tuple)) and len(t) >= 3
                and isinstance(e.get("ts"), (int, float))
                and isinstance(t[2], (int, float))):
            for _, route, lag in hop_legs(path, float(t[2]), e["ts"]):
                route_legs.setdefault(route, []).append(lag)
    lats.sort()
    paths = reconstruct_paths(events)
    return {
        "sends": len(sends),
        "pairs": len(lats),
        "unmatched_recv": unmatched_recv,
        "propagation": _percentiles(lats),
        "hops": dict(sorted(hops.items())),
        "routes": {
            r: _percentiles(sorted(v))
            for r, v in sorted(route_legs.items())
        },
        "paths": paths,
    }


def reconstruct_paths(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Path completeness across sources: a traced receive is COMPLETE
    when its carried path parses, its hop count matches the path
    depth, every leg carries a known route tag, and its origin tid
    pairs back to an ``update.send`` in some loaded source. The
    ``pair_rate`` (complete / traced receives) is the fleet-leg
    acceptance number — 1.0 means every sampled frame's full path
    reconstructs across processes."""
    send_tids = set()
    origin_procs = set()
    for e in events:
        if e.get("kind") in ORIGIN_KINDS:
            k = _tid_key(e)
            if k is not None:
                send_tids.add(k)
                src = e.get("_src", e.get("proc"))
                if src is not None:
                    origin_procs.add(str(src))
    traced = complete = 0
    routes: Dict[str, int] = {}
    incomplete: List[Dict[str, Any]] = []
    for e in events:
        if e.get("kind") != "update.recv":
            continue
        path = e.get("path")
        if not isinstance(path, list) or not path:
            continue
        traced += 1
        ok = True
        seen_routes = []
        for hop in path:
            if (not isinstance(hop, (list, tuple)) or len(hop) < 3
                    or hop[1] not in _ROUTE_CODE):
                ok = False
                break
            seen_routes.append(hop[1])
        hop_field = e.get("hop")
        if ok and isinstance(hop_field, int) and hop_field != len(path):
            ok = False
        if ok and _tid_key(e) not in send_tids:
            ok = False
        if ok:
            complete += 1
            for r in seen_routes:
                routes[r] = routes.get(r, 0) + 1
        elif len(incomplete) < 8:
            incomplete.append({
                "tid": e.get("tid"), "path": path,
                "src": e.get("_src", e.get("proc")),
            })
    return {
        "sends": len(send_tids),
        "traced_recvs": traced,
        "complete": complete,
        "pair_rate": (complete / traced) if traced else 0.0,
        "routes": dict(sorted(routes.items())),
        "origin_procs": sorted(origin_procs),
        "incomplete_sample": incomplete,
    }


def correlate_divergences(events: List[Dict[str, Any]],
                          context: int = 8) -> Dict[str, Any]:
    """Correlate divergence events across the loaded sources: for
    each, the trailing ``context`` events per source on the same
    topic before the divergence, with digests surfaced for eyeballing
    which update the two sides last disagreed on. (Moved verbatim
    from the round-18 ``obsq diverge`` — offline dumps and live
    collector snapshots share this one implementation.)"""
    out: List[Dict[str, Any]] = []
    divs = [e for e in events if e.get("kind") == "divergence"]
    for div in divs:
        topic = div.get("topic")
        ts = div.get("ts", float("inf"))
        per_src: Dict[str, List[Dict[str, Any]]] = {}
        for e in events:
            if e is div or e.get("ts", 0.0) > ts:
                continue
            if topic is not None and \
                    e.get("topic") not in (None, topic):
                continue
            src = str(e.get("_src", e.get("proc", "?")))
            per_src.setdefault(src, []).append(e)
        ctx = {
            src: [
                {k: ev.get(k) for k in
                 ("ts", "kind", "peer", "replica", "digest", "tid",
                  "hop", "path", "size") if k in ev}
                for ev in evs[-context:]
            ]
            for src, evs in sorted(per_src.items())
        }
        digests = {
            src: [e.get("digest") for e in evs if e.get("digest")]
            for src, evs in ctx.items()
        }
        common = set.intersection(
            *(set(d) for d in digests.values())
        ) if len(digests) > 1 else set()
        out.append({
            "divergence": {
                k: div.get(k) for k in
                ("ts", "topic", "peer", "replica", "local_digest",
                 "peer_digest", "doc") if k in div
            },
            "src": str(div.get("_src", div.get("proc", "?"))),
            "context": ctx,
            "last_common_digests": sorted(common),
        })
    return {"divergences": len(divs), "events": out}
