"""In-process fleet fabrics: deterministic message plumbing.

Two transports sit behind ``FleetNode.fabric``:

- :class:`MemFabric` — the chaos harness's loopback: per-process
  FIFO queues, scripted partitions, process kill/revive, and a
  seeded fault hook (``net/faults.py`` schedules) deciding
  drop/duplicate per frame. Fully deterministic: frame order is
  send order, faults key on per-link frame counts, never randomness
  at call time.

- :class:`UdpFabric` — the same interface over the round-7 sealed
  ``UdpEndpoint`` streams for the subprocess smoke leg: every frame
  is SecureBox-sealed to the peer (the header never travels in the
  clear), and the reliable-message layer handles fragmentation and
  retry.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Tuple


class MemFabric:
    """Loopback fabric with scripted chaos."""

    def __init__(self, *, faults=None):
        # faults: an object with decide(src, dst, kind, n) ->
        # {"drop": bool, "dup": int} (see
        # net.faults.HandoffFaultSchedule); None = perfect links
        self.faults = faults
        self._queues: Dict[str, deque] = {}
        self._nodes: Dict[str, object] = {}
        self._link_n: Dict[Tuple[str, str], int] = {}
        self._partitions: List[Tuple[frozenset, frozenset]] = []
        self.dead: set = set()
        self.sent = 0
        self.dropped = 0
        self.duplicated = 0

    def register(self, proc: str, node) -> None:
        self._nodes[proc] = node
        self._queues.setdefault(proc, deque())

    def node(self, proc: str):
        return self._nodes.get(proc)

    # -- chaos levers --------------------------------------------------

    def partition(self, group_a, group_b) -> None:
        self._partitions.append(
            (frozenset(group_a), frozenset(group_b)))

    def heal(self) -> None:
        self._partitions = []

    def kill(self, proc: str) -> None:
        """Process death: its queue is torn down (in-flight frames
        die with it) and frames to/from it drop until revive."""
        self.dead.add(proc)
        self._queues[proc] = deque()

    def revive(self, proc: str, node=None) -> None:
        self.dead.discard(proc)
        if node is not None:
            self._nodes[proc] = node
        self._queues.setdefault(proc, deque())

    def _blocked(self, src: str, dst: str) -> bool:
        for a, b in self._partitions:
            if (src in a and dst in b) or (src in b and dst in a):
                return True
        return False

    # -- the wire ------------------------------------------------------

    def send(self, src: str, dst: str, data: bytes) -> None:
        self.sent += 1
        if src in self.dead or dst in self.dead or \
                self._blocked(src, dst):
            self.dropped += 1
            return
        n = self._link_n.get((src, dst), 0) + 1
        self._link_n[(src, dst)] = n
        copies = 1
        if self.faults is not None:
            # kind peeks past the header for fault targeting; a
            # failed peek still delivers (fault layer, not codec)
            from . import wire

            dec = wire.decode_frame(data)
            kind = dec[0].get("kind", "") if dec else ""
            verdict = self.faults.decide(src, dst, kind, n) or {}
            if verdict.get("drop"):
                self.dropped += 1
                return
            copies += int(verdict.get("dup", 0))
            self.duplicated += copies - 1
        q = self._queues.setdefault(dst, deque())
        for _ in range(copies):
            q.append((src, data))

    def deliver(self, proc: str) -> List[Tuple[str, bytes]]:
        if proc in self.dead:
            return []
        q = self._queues.setdefault(proc, deque())
        out = list(q)
        q.clear()
        return out


class UdpFabric:
    """``MemFabric``'s interface over sealed UDP — one endpoint per
    process, a static peer book mapping proc name -> (addr, port,
    SecureBox). Frames ride the reliable-message layer."""

    def __init__(self, proc: str, endpoint, peers: Dict[str, tuple]):
        self.proc = proc
        self.endpoint = endpoint
        # peers: name -> (ip, port, SecureBox)
        self.peers = dict(peers)
        self._port_of = {name: p[1] for name, p in self.peers.items()}
        self._by_port = {p[1]: name for name, p in self.peers.items()}

    def register(self, proc: str, node) -> None:
        pass  # the peer book is static; nothing to wire

    def send(self, src: str, dst: str, data: bytes) -> None:
        peer = self.peers.get(dst)
        if peer is None:
            return
        ip, port, box = peer
        self.endpoint.send(ip, port, box.encrypt(data))

    def deliver(self, proc: str) -> List[Tuple[str, bytes]]:
        self.endpoint.poll()
        out: List[Tuple[str, bytes]] = []
        for _ip, port, sealed in self.endpoint.recv_all():
            src = self._by_port.get(port, "")
            box = self.peers.get(src, (None, None, None))[2]
            if box is None:
                continue
            try:
                out.append((src, box.decrypt(sealed)))
            except ValueError:
                continue
        return out
