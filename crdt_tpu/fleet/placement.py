"""Deterministic doc placement + epoch-fenced ownership leases.

The cross-process half of ROADMAP item 2 starts here: N
``MultiDocServer`` processes agree on which one OWNS each doc without
talking to each other, and every ownership transfer is fenced by a
monotonically increasing epoch so a partitioned ex-owner can never
fork a doc.

Two pieces:

- :class:`HashRing` — a consistent-hash ring over the member set.
  Hashing is sha1-based (``stable_hash``), NOT Python ``hash()``:
  the mapping must be identical across processes and interpreter
  runs (PYTHONHASHSEED randomizes ``hash``). Virtual nodes smooth
  the distribution; member join/leave moves only the docs whose
  arc changed (the minimal-movement property
  ``tests/test_placement.py`` pins).

- :class:`LeaseTable` — per-doc ``(epoch, owner)`` fencing state.
  Epoch 1 is seeded deterministically from the ring (every process
  derives the same initial owner with zero communication); every
  migration commits ``epoch + 1``. :meth:`LeaseTable.admit` is the
  single fencing gate every inter-server frame, serve, and
  WAL/snapshot write passes through: a stale epoch is refused and
  counted (``fleet.fence_rejects{op=...}``), an equal epoch from a
  different claimant is a FORK and refused
  (``fleet.fork_refused``), a newer epoch is adopted (higher epoch
  always wins — that is what makes the fence safe across a
  partition heal). Grants persist through an attached snapshot
  store blob so a crashed process restarts with the epochs it held,
  never the ring defaults.
"""

from __future__ import annotations

import bisect
import hashlib
import json
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

from crdt_tpu.obs import get_tracer

LEASE_BLOB = "fleet.leases"


def stable_hash(key: str) -> int:
    """64-bit process-stable hash (sha1 prefix) — the ring metric."""
    h = hashlib.sha1(key.encode("utf-8")).digest()
    return int.from_bytes(h[:8], "big")


class FencingToken(NamedTuple):
    """The ``(epoch, proc)`` stamp every fenced operation carries."""

    epoch: int
    proc: str


class HashRing:
    """Consistent-hash ring: doc -> owner process, deterministic."""

    def __init__(self, members: Sequence[str], *, vnodes: int = 64):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.vnodes = int(vnodes)
        self._members: List[str] = []
        self._points: List[Tuple[int, str]] = []
        self._keys: List[int] = []
        for m in members:
            self.add(m)

    @property
    def members(self) -> List[str]:
        return sorted(self._members)

    def add(self, member: str) -> None:
        if member in self._members:
            return
        self._members.append(member)
        for v in range(self.vnodes):
            self._points.append(
                (stable_hash("%s#%d" % (member, v)), member))
        self._points.sort()
        self._keys = [p[0] for p in self._points]

    def remove(self, member: str) -> None:
        if member not in self._members:
            return
        self._members.remove(member)
        self._points = [p for p in self._points if p[1] != member]
        self._keys = [p[0] for p in self._points]

    def owner(self, doc) -> str:
        """The member owning ``doc``'s arc (epoch-1 ownership)."""
        if not self._points:
            raise ValueError("ring has no members")
        i = bisect.bisect_right(self._keys, stable_hash(str(doc)))
        return self._points[i % len(self._points)][1]

    def successors(self, doc, k: int) -> List[str]:
        """First ``k`` DISTINCT members clockwise of ``doc`` (the
        owner first) — the candidate destinations for rebalance."""
        if not self._points:
            return []
        out: List[str] = []
        i = bisect.bisect_right(self._keys, stable_hash(str(doc)))
        n = len(self._points)
        for j in range(n):
            m = self._points[(i + j) % n][1]
            if m not in out:
                out.append(m)
                if len(out) >= k:
                    break
        return out

    def least_loaded_successor(
        self, doc, *, exclude: Sequence[str] = (),
        loads: Optional[Dict[str, float]] = None,
    ) -> Optional[str]:
        """Advised migration destination: among the doc's ring
        successors minus ``exclude`` (the breaching owner), the one
        with the smallest ``loads`` value; ring order breaks ties,
        so every process computes the same hint."""
        cands = [m for m in self.successors(doc, len(self._members))
                 if m not in set(exclude)]
        if not cands:
            return None
        if not loads:
            return cands[0]
        return min(cands, key=lambda m: (float(loads.get(m, 0.0)), m))


class LeaseTable:
    """Per-doc ``(epoch, owner)`` state + the fencing gate.

    Deterministic counters (``fence_rejects`` / ``fork_refused``)
    mirror the tracer rows so the chaos harness can assert on them
    with tracing disabled, like ``snap_fallback_count`` does.
    """

    def __init__(self, proc: str, ring: HashRing, *, store=None):
        self.proc = str(proc)
        self.ring = ring
        self.store = store
        self._leases: Dict[str, Tuple[int, str]] = {}
        self.fence_rejects = 0
        self.fork_refused = 0
        if store is not None:
            self._load()

    # -- persistence (the crash-safety half of fencing) ----------------

    def _load(self) -> None:
        raw = self.store.get_blob(LEASE_BLOB)
        if raw is None:
            return
        try:
            data = json.loads(raw)
        except ValueError:
            return
        for d, v in data.items():
            try:
                self._leases[d] = (int(v[0]), str(v[1]))
            except (TypeError, ValueError, IndexError):
                continue

    def _save(self) -> None:
        if self.store is None:
            return
        self.store.put_blob(
            LEASE_BLOB,
            json.dumps({d: list(v) for d, v in
                        sorted(self._leases.items())},
                       sort_keys=True).encode())

    # -- reads ---------------------------------------------------------

    def lease(self, doc) -> Tuple[int, str]:
        """Current ``(epoch, owner)`` — ring-seeded at epoch 1 when
        no grant has ever been recorded for the doc."""
        d = str(doc)
        got = self._leases.get(d)
        if got is not None:
            return got
        return (1, self.ring.owner(d))

    def epoch_of(self, doc) -> int:
        return self.lease(doc)[0]

    def owner_of(self, doc) -> str:
        return self.lease(doc)[1]

    def holds(self, doc) -> bool:
        """Does THIS process own ``doc`` right now?"""
        return self.owner_of(doc) == self.proc

    def token(self, doc) -> FencingToken:
        """The stamp this process puts on fenced operations for
        ``doc`` (callers check :meth:`holds` first)."""
        return FencingToken(self.epoch_of(doc), self.proc)

    def owned_docs(self, docs) -> List[str]:
        return [str(d) for d in docs if self.holds(d)]

    def epochs_of(self, docs) -> Dict[str, int]:
        return {str(d): self.epoch_of(d) for d in docs}

    def recorded(self) -> Dict[str, Tuple[int, str]]:
        """Every EXPLICITLY granted lease (ring-default docs are
        absent) — the restart path walks this to find docs this
        process owns but whose state needs re-seeding."""
        return dict(self._leases)

    # -- writes --------------------------------------------------------

    def grant(self, doc, epoch: int, owner: str) -> bool:
        """Record a lease transfer. Refuses to move BACKWARD: a
        grant below the recorded epoch is a stale claim (returns
        False, counted); an equal-epoch grant to a DIFFERENT owner
        is a fork attempt and refused. Persisted when a store is
        attached, so the fence survives a crash+restart."""
        d = str(doc)
        cur_e, cur_o = self.lease(d)
        epoch = int(epoch)
        if epoch < cur_e:
            self.reject(d, "grant")
            return False
        if epoch == cur_e and owner != cur_o:
            self.fork_refused += 1
            tracer = get_tracer()
            if tracer.enabled:
                tracer.count("fleet.fork_refused")
            return False
        self._leases[d] = (epoch, str(owner))
        self._save()
        return True

    def admit(self, doc, token: FencingToken, *, op: str) -> bool:
        """THE fencing gate. A frame/write/serve stamped ``token``
        is admitted iff it is not behind the recorded lease:

        - ``token.epoch < held`` -> refused + counted (stale owner);
        - ``token.epoch == held`` but a different proc than the
          recorded owner -> refused + ``fleet.fork_refused`` (two
          claimants at one epoch can only mean a fork attempt);
        - ``token.epoch > held`` -> ADOPTED (the sender holds a
          newer lease this process missed) and admitted.
        """
        d = str(doc)
        cur_e, cur_o = self.lease(d)
        if token.epoch < cur_e:
            self.reject(d, op)
            return False
        if token.epoch == cur_e:
            if token.proc != cur_o:
                self.fork_refused += 1
                tracer = get_tracer()
                if tracer.enabled:
                    tracer.count("fleet.fork_refused")
                return False
            return True
        self._leases[d] = (int(token.epoch), str(token.proc))
        self._save()
        return True

    def reject(self, doc: str, op: str) -> None:
        self.fence_rejects += 1
        tracer = get_tracer()
        if tracer.enabled:
            tracer.count("fleet.fence_rejects", labels={"op": op})
