"""One fleet process: a ``MultiDocServer`` wrapped in ownership.

``FleetNode`` is the glue object the chaos harness and the
subprocess smoke leg both drive: ring + lease table + migrator
around one server, every doc-state operation passing the fencing
gate first. The transport is a seam (``fabric``): the in-process
harness uses :class:`crdt_tpu.fleet.fabric.MemFabric`, the smoke
leg adapts the round-7 sealed ``UdpEndpoint`` — frames are
identical bytes either way (``fleet/wire.py``).

Ownership semantics:

- ``submit`` admits only docs this process owns; a mis-routed
  submit answers with the believed owner (``fleet.redirects``) so
  clients re-aim instead of forking.
- ``digest``/serving refuse docs the process does not own
  (``fleet.fence_rejects{op=serve}``) — the no-double-serve half of
  the fork guard.
- every ``beacon_every`` ticks the node broadcasts its owned docs'
  epochs (the round-8 sentinel idea applied to ownership): a
  receiver holding a STALE lease adopts the newer epoch and demotes
  itself (``fleet.demotions`` — the partitioned ex-owner healing
  path), an equal-epoch rival claim is refused as a fork
  (``fleet.fork_refused``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from crdt_tpu.models.multidoc import MultiDocServer
from crdt_tpu.obs import get_tracer

from . import wire
from .migration import Migrator, remove_doc
from .placement import FencingToken, HashRing, LeaseTable


class FleetNode:
    def __init__(self, proc: str, members, fabric, *,
                 store=None,
                 vnodes: int = 64,
                 timeout_ticks: int = 8,
                 beacon_every: int = 4,
                 crash_plan=None,
                 server: Optional[MultiDocServer] = None,
                 server_kw: Optional[Dict[str, Any]] = None):
        self.proc = str(proc)
        self.fabric = fabric
        self.store = store
        self.ring = HashRing(members, vnodes=vnodes)
        self.lease = LeaseTable(self.proc, self.ring, store=store)
        if server is None:
            kw = dict(server_kw or {})
            kw.setdefault("snap_store", store)
            server = MultiDocServer(**kw)
        self.server = server
        self.migrator = Migrator(self, timeout_ticks=timeout_ticks,
                                 crash_plan=crash_plan)
        self.tick_count = 0
        self.beacon_every = int(beacon_every)
        # deterministic odometers (tracer rows mirror these)
        self.redirects = 0
        self.demotions = 0
        if fabric is not None:
            fabric.register(self.proc, self)

    # -- transport -----------------------------------------------------

    def send(self, dst: str, header: Dict[str, Any],
             payload: bytes = b"") -> None:
        self.fabric.send(self.proc, dst,
                         wire.encode_frame(header, payload))

    def drain_inbox(self) -> int:
        n = 0
        for src, data in self.fabric.deliver(self.proc):
            self.handle(src, data)
            n += 1
        return n

    def handle(self, src: str, data: bytes) -> None:
        dec = wire.decode_frame(data)
        if dec is None:
            return
        header, payload = dec
        kind = header.get("kind")
        mig = self.migrator
        if kind == "update":
            self._on_update(header, payload)
        elif kind == "redirect":
            self._on_redirect(header)
        elif kind == "beacon":
            self._on_beacon(header)
        elif kind == "offer":
            mig.on_offer(header, payload)
        elif kind == "rehydrated":
            mig.on_rehydrated(header)
        elif kind == "commit":
            mig.on_commit(header, payload)
        elif kind == "ack":
            mig.on_ack(header)
        elif kind == "nack":
            mig.on_nack(header)
        elif kind == "probe":
            mig.on_probe(header)
        elif kind == "probe_reply":
            mig.on_probe_reply(header)

    # -- the tick loop -------------------------------------------------

    def tick(self):
        """One fleet tick: settle inbound frames, run the server
        tick, advance migrations, emit ownership beacons."""
        self.drain_inbox()
        rep = self.server.tick()
        self.tick_count += 1
        self.migrator.step_tick()
        if self.beacon_every and \
                self.tick_count % self.beacon_every == 0:
            self._emit_beacons()
        return rep

    # -- client ingest (fenced) ----------------------------------------

    def submit(self, doc, blob: bytes) -> Tuple[str, Any]:
        """Admit one client update. Returns ``("ok", shed)`` when
        this process owns the doc, ``("buffered", None)`` when the
        doc is mid-handoff (the blob rides the commit frame), or
        ``("redirect", owner)`` so the client re-aims."""
        d = str(doc)
        if self.migrator.buffer_update(d, blob):
            return ("buffered", None)
        if not self.lease.holds(d):
            self.redirects += 1
            tracer = get_tracer()
            if tracer.enabled:
                tracer.count("fleet.redirects")
            return ("redirect", self.lease.owner_of(d))
        return ("ok", self.server.submit(d, blob))

    def forward(self, doc, blob: bytes) -> None:
        """Inter-node route: ship the update to the believed owner,
        stamped with this node's lease view — the receiver's fence
        corrects a stale view via redirect."""
        d = str(doc)
        e, o = self.lease.lease(d)
        self.send(o, {"kind": "update", "doc": d, "epoch": e,
                      "proc": self.proc}, bytes(blob))

    def _on_update(self, header: Dict[str, Any],
                   payload: bytes) -> None:
        d = str(header.get("doc", ""))
        src = str(header.get("proc", ""))
        if self.migrator.buffer_update(d, payload):
            return
        if not self.lease.holds(d):
            self.lease.reject(d, "update")
            e, o = self.lease.lease(d)
            self.send(src, {"kind": "redirect", "doc": d,
                            "epoch": e, "owner": o,
                            "proc": self.proc})
            return
        self.server.submit(d, payload)

    def _on_redirect(self, header: Dict[str, Any]) -> None:
        d = str(header.get("doc", ""))
        e = int(header.get("epoch", 0))
        o = str(header.get("owner", ""))
        if e >= self.lease.epoch_of(d):
            self.lease.grant(d, e, o)

    # -- serving (fenced) ----------------------------------------------

    def digest(self, doc) -> Optional[str]:
        """Serve the doc's canonical digest — refused (and counted)
        when this process does not own it: the half of the fork
        guard a stale ex-owner hits first."""
        d = str(doc)
        if not self.lease.holds(d) or self.migrator.migrating(d):
            self.lease.reject(d, "serve")
            return None
        return self.server.digest(d)

    # -- ownership beacons (the sentinel seam) -------------------------

    def _emit_beacons(self) -> None:
        owned = {d: self.lease.epoch_of(d)
                 for d in sorted(self.server._docs, key=str)
                 if self.lease.holds(d)}
        if not owned:
            return
        tracer = get_tracer()
        for peer in self.ring.members:
            if peer == self.proc:
                continue
            self.send(peer, {"kind": "beacon", "proc": self.proc,
                             "docs": owned})
            if tracer.enabled:
                tracer.count("fleet.beacons_sent")

    def _on_beacon(self, header: Dict[str, Any]) -> None:
        sender = str(header.get("proc", ""))
        docs = header.get("docs")
        if not isinstance(docs, dict):
            return
        for d in sorted(docs, key=str):
            try:
                e = int(docs[d])
            except (TypeError, ValueError):
                continue
            was_mine = self.lease.holds(d)
            # admit() does the whole ladder: stale claim refused +
            # counted, equal-epoch rival refused as a fork, newer
            # epoch adopted
            if self.lease.admit(str(d), FencingToken(e, sender),
                                op="beacon") and was_mine and \
                    not self.lease.holds(d):
                # we were the partitioned ex-owner: demote — stop
                # serving and drop the stale copy (the new owner
                # carries the doc now)
                self.demotions += 1
                tracer = get_tracer()
                if tracer.enabled:
                    tracer.count("fleet.demotions")
                self.migrator.outbound.pop(str(d), None)
                remove_doc(self.server, str(d))

    # -- migration entry + durability ----------------------------------

    def migrate(self, doc, dst: str) -> bool:
        return self.migrator.start(doc, dst)

    def checkpoint(self) -> int:
        return self.server.checkpoint(fence=self.lease)

    def restore(self) -> int:
        """Warm restart: rehydrate the server (fence-checked),
        re-seed any doc this process owns by granted lease but the
        checkpoint missed (a handoff committed after the last
        cadence: the commit path stashed its full history), and
        resume any migration the crashed process left in flight."""
        n = self.server.restore(fence=self.lease)
        tracer = get_tracer()
        for d in sorted(self.lease.recorded()):
            _e, o = self.lease.recorded()[d]
            if o != self.proc or d in self.server._docs:
                continue
            raw = self.store.get_blob("fleet.tail.%s" % d) \
                if self.store is not None else None
            blobs = wire.unpack_blobs(raw) if raw else None
            if not blobs:
                continue
            for b in blobs:
                self.server.submit(d, b)
            if tracer.enabled:
                tracer.count("migration.tail_restores")
        self.migrator.resume_intent()
        return n

    # -- load report (the placement loop's tie-breaker) ----------------

    def load(self) -> float:
        return float(self.server.pending_bytes() +
                     self.server.resident_bytes_total())
