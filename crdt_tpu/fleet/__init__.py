"""Cross-process fleet serving (ROADMAP item 2, round 24).

Doc-sharded serving across N ``MultiDocServer`` processes:
deterministic consistent-hash placement with epoch-fenced ownership
leases (``placement``), crash-safe live migration over the sealed
transport (``migration``), the node glue (``node``), deterministic
chaos fabrics (``fabric``), and the placement loop consuming the
federated ``rebalance_away`` advice (``loop``). README "Fleet
serving" documents the semantics and the counter registry.
"""

from .fabric import MemFabric, UdpFabric
from .loop import PlacementLoop
from .migration import MIGRATION_STEPS, Migrator, adopt_doc, remove_doc
from .node import FleetNode
from .placement import FencingToken, HashRing, LeaseTable, stable_hash

__all__ = [
    "FencingToken", "FleetNode", "HashRing", "LeaseTable",
    "MemFabric", "MIGRATION_STEPS", "Migrator", "PlacementLoop",
    "UdpFabric", "adopt_doc", "remove_doc", "stable_hash",
]
