"""The placement loop: federated advice in, migrations out.

Closes ROADMAP item 2's last arc: the controllers already emit
``rebalance_away`` advice per squeezed tenant, the
``FleetCollector`` already federates those rows proc-tagged at
``/fleet`` — this loop CONSUMES them and actuates live migrations,
with the same discipline as the in-process controller:

- **idempotent**: rows dedup on ``(proc, tenant)`` + the round-24
  monotonic ``seq`` — a duplicated or reordered advice row (the
  chaos schedule injects both) can never double-start a handoff;
- **hysteresis**: advice must persist ``hysteresis`` consecutive
  polls before actuating (a one-poll burn spike is not a reason to
  move a doc);
- **budgeted**: at most ``budget_per_tick`` migrations start per
  poll, docs already mid-handoff are skipped;
- **auditable**: every decision (and every skip reason) appends to
  a replayable :class:`crdt_tpu.obs.control.ControlLedger`, same
  JSONL schema as the in-process controller's.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from crdt_tpu.obs import get_tracer
from crdt_tpu.obs.control import ControlLedger

from .placement import HashRing


class PlacementLoop:
    """``observe(tick, rows)`` per poll; ``resolve(proc)`` maps a
    proc name to its actuator (an object with ``migrate(doc, dst)``
    and ``lease`` — a :class:`FleetNode` or an RPC stub)."""

    def __init__(self, ring: HashRing,
                 resolve: Callable[[str], Any], *,
                 hysteresis: int = 2,
                 budget_per_tick: int = 1,
                 ledger: Optional[ControlLedger] = None):
        self.ring = ring
        self.resolve = resolve
        self.hysteresis = max(1, int(hysteresis))
        self.budget_per_tick = max(1, int(budget_per_tick))
        self.ledger = ledger if ledger is not None else ControlLedger()
        self._seen_seq: Dict[tuple, int] = {}
        self._streak: Dict[tuple, int] = {}
        # deterministic odometers
        self.migrations = 0
        self.dup_drops = 0

    def _log(self, tick: int, row: Dict[str, Any]) -> None:
        self.ledger.append(dict(row, tick=int(tick),
                                rule="migrate"))

    def observe(self, tick: int, rows: List[Dict[str, Any]], *,
                loads: Optional[Dict[str, float]] = None
                ) -> List[Dict[str, Any]]:
        """One poll over collector-shaped advice rows (each row:
        ``action``/``tenant``/``proc`` + the round-24 ``seq`` /
        ``target``). Returns the started migrations."""
        tracer = get_tracer()
        # fold this poll's rows: max-seq row per (proc, tenant),
        # counting the duplicates the fold removed
        fresh: Dict[tuple, Dict[str, Any]] = {}
        for row in rows:
            if row.get("action") != "rebalance_away":
                continue
            key = (str(row.get("proc", "")),
                   str(row.get("tenant", "")))
            seq = int(row.get("seq", 0) or 0)
            prev = fresh.get(key)
            if prev is not None:
                self.dup_drops += 1
                if tracer.enabled:
                    tracer.count("fleet.advice_dups")
                if seq <= int(prev.get("seq", 0) or 0):
                    continue
            fresh[key] = row
        # stale replays: a seq at or below the last ACTUATED one
        # for the key is the same advice coming around again
        for key in sorted(fresh):
            if int(fresh[key].get("seq", 0) or 0) <= \
                    self._seen_seq.get(key, -1):
                self.dup_drops += 1
                if tracer.enabled:
                    tracer.count("fleet.advice_dups")
                del fresh[key]
        # hysteresis streaks
        for key in list(self._streak):
            if key not in fresh:
                del self._streak[key]
        started: List[Dict[str, Any]] = []
        for key in sorted(fresh):
            self._streak[key] = self._streak.get(key, 0) + 1
        for key in sorted(fresh):
            if len(started) >= self.budget_per_tick:
                break
            if self._streak[key] < self.hysteresis:
                continue
            src, tenant = key
            row = fresh[key]
            node = self.resolve(src)
            if node is None:
                continue
            dst = row.get("target") or \
                self.ring.least_loaded_successor(
                    tenant, exclude=[src], loads=loads)
            if not dst or dst == src:
                continue
            if node.migrator.migrating(tenant):
                self._log(tick, {"tenant": tenant, "src": src,
                                 "dst": dst, "action": "skip",
                                 "why": "in_flight"})
                continue
            if not node.migrate(tenant, dst):
                self._log(tick, {"tenant": tenant, "src": src,
                                 "dst": dst, "action": "skip",
                                 "why": "refused"})
                continue
            self._seen_seq[key] = int(row.get("seq", 0) or 0)
            self._streak[key] = 0
            self.migrations += 1
            if tracer.enabled:
                tracer.count("fleet.migrations_started")
            dec = {"tenant": tenant, "src": src, "dst": dst,
                   "seq": int(row.get("seq", 0) or 0),
                   "burn": row.get("burn"), "action": "migrate"}
            self._log(tick, dec)
            started.append(dec)
        return started
