"""Inter-server frame codec for the fleet layer.

One binary framing for every fleet message — forwarded updates,
migration offers/commits, probes, and ownership beacons — whether it
rides the in-process chaos fabric or the round-7 sealed UDP streams
(``net/transport.py`` encrypts the WHOLE frame, so the header is
never on the wire in the clear).

Layout::

    b"CFR1" | u32 header_len | header_json | payload bytes

The header is a flat JSON dict carrying ``kind`` plus the fencing
stamp (``epoch``/``proc``) and message-specific fields; the payload
is opaque bytes (snapshot generations, history blobs). Multi-blob
payloads are length-prefixed (:func:`pack_blobs`). Decode is
defensive: damaged frames return ``None`` and count
``fleet.frames_malformed`` — a fleet peer is still an untrusted
input once the seal is off.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, List, Optional, Tuple

from crdt_tpu.obs import get_tracer

MAGIC = b"CFR1"
_MAX_HEADER = 1 << 20

# frame kinds (the protocol surface; migration.py documents the
# state machine they drive)
KINDS = frozenset({
    "update",      # forwarded client update: doc, epoch, proc + blob
    "redirect",    # ownership hint back to a mis-routed sender
    "offer",       # migration step 2: snapshot/tail payload
    "rehydrated",  # dst -> src: payload adopted, awaiting commit
    "commit",      # src -> dst: epoch bump + late tail blobs
    "ack",         # dst -> src: serving at the new epoch
    "nack",        # dst -> src: migration unknown/refused
    "probe",       # who owns doc? (the ack-loss resolver)
    "probe_reply",
    "beacon",      # sentinel: owned-doc epochs, fork detection
})


def encode_frame(header: Dict[str, Any], payload: bytes = b"") -> bytes:
    hj = json.dumps(header, sort_keys=True,
                    separators=(",", ":")).encode()
    return MAGIC + struct.pack("<I", len(hj)) + hj + payload


def decode_frame(
    data: bytes,
) -> Optional[Tuple[Dict[str, Any], bytes]]:
    """Parse one frame; ``None`` (counted) on any damage."""
    try:
        if len(data) < 8 or data[:4] != MAGIC:
            raise ValueError("bad magic")
        (hlen,) = struct.unpack("<I", data[4:8])
        if hlen > _MAX_HEADER or 8 + hlen > len(data):
            raise ValueError("bad header length")
        header = json.loads(data[8:8 + hlen])
        if not isinstance(header, dict):
            raise ValueError("header not a dict")
        kind = header.get("kind")
        if kind not in KINDS:
            raise ValueError("unknown kind")
        return header, data[8 + hlen:]
    except (ValueError, struct.error, UnicodeDecodeError):
        tracer = get_tracer()
        if tracer.enabled:
            tracer.count("fleet.frames_malformed")
        return None


def pack_blobs(blobs: List[bytes]) -> bytes:
    """Length-prefixed blob list (u32 count, then u32+bytes each)."""
    parts = [struct.pack("<I", len(blobs))]
    for b in blobs:
        parts.append(struct.pack("<I", len(b)))
        parts.append(bytes(b))
    return b"".join(parts)


def unpack_blobs(data: bytes) -> Optional[List[bytes]]:
    """Inverse of :func:`pack_blobs`; ``None`` on damage (the
    caller's frame already counted, this keeps the refusal exact)."""
    try:
        if len(data) < 4:
            raise ValueError("short")
        (n,) = struct.unpack("<I", data[:4])
        if n > len(data):  # each blob needs >= 4 bytes of prefix
            raise ValueError("count")
        off = 4
        out: List[bytes] = []
        for _ in range(n):
            if off + 4 > len(data):
                raise ValueError("truncated prefix")
            (ln,) = struct.unpack("<I", data[off:off + 4])
            off += 4
            if off + ln > len(data):
                raise ValueError("truncated blob")
            out.append(data[off:off + ln])
            off += ln
        if off != len(data):
            raise ValueError("trailing bytes")
        return out
    except (ValueError, struct.error):
        tracer = get_tracer()
        if tracer.enabled:
            tracer.count("fleet.frames_malformed")
        return None
