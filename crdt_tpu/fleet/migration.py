"""Crash-safe live doc migration between fleet processes.

The protocol (one doc, source ``src`` -> destination ``dst``, lease
``(e, src)`` -> ``(e+1, dst)``)::

    src                                  dst
    ---  drain in-flight tick window
    ---  [intent blob: step=ship]
    ---  offer {e+1} + snapshot/tail --> rehydrate (NOT serving)
         rehydrated {e+1}            <--
    ---  [intent blob: step=commit]
    ---  grant (e+1, dst) LOCALLY  (src is fenced from here on)
    ---  commit {e+1} + late tail  --> grant (e+1, dst), serve
         ack {e+1}                 <--
    ---  drop doc, clear intent

Crash/partition at ANY step falls down a counted recovery ladder
(``migration.recovery{step=...}``), never into a fork:

- ``drain``/``ship``: nothing granted anywhere — src (or its
  restart, via the intent blob) keeps serving; dst's half-adopted
  state times out waiting for commit and is discarded.
- ``rehydrate`` (dst dies mid-adopt / offer lost): src's
  rehydrated-wait deadline aborts the migration; the tail buffer
  re-ingests and src keeps serving.
- ``commit`` (partition or dst crash after src granted away): src
  is fenced — it can NOT just resume (that is the fork the fence
  exists to prevent). It probes: an answer proving dst serves at
  ``e+1`` completes the handoff (``step=ack``: the ack was lost);
  an explicit NACK from dst proves the commit never landed, and
  ONLY then does src reclaim at ``e+2``. Silence keeps the doc
  fenced (unavailable, never forked) and keeps probing.
- source CRASH: the lease table and a small intent blob are
  persisted in the snapshot store, so the restarted process knows
  a migration was in flight, counts the recovery at the recorded
  step, and re-enters the ladder above instead of blindly serving.

Updates submitted to src during the handoff buffer into the
migration tail and ride the commit frame — an acked update is never
dropped by a successful migration, and an aborted one re-ingests
the buffer. Warm docs ship a snapshot generation
(``storage/snapshot.py``) + the history sidecar; cold docs ship the
admitted WAL tail (their blob history).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from crdt_tpu.obs import get_tracer

from . import wire
from .placement import FencingToken

MIGRATION_STEPS = ("drain", "ship", "rehydrate", "commit", "ack")
INTENT_BLOB = "fleet.migration.intent"

DEFAULT_TIMEOUT_TICKS = 8


def _count(name: str, labels: Optional[Dict[str, str]] = None) -> None:
    tracer = get_tracer()
    if tracer.enabled:
        # crdtlint: emits=migration.started,migration.completed,migration.recovery,migration.tail_blobs,snap.fallbacks
        tracer.count(name, labels=labels)


def adopt_doc(server, doc, snap_payload: bytes,
              hist_blobs: List[bytes]) -> bool:
    """Adopt a shipped doc into ``server`` (the dst half of the
    round-15 promotion path, mirroring ``restore()``'s per-doc
    body): history re-seeded from the shipped blobs, the snapshot
    generation rehydrated warm when present and intact, the
    documented cold rung otherwise. Returns True when resident."""
    from crdt_tpu.models.multidoc import _DocState
    from crdt_tpu.storage.snapshot import decode_payload, rehydrate

    st = server._docs.setdefault(doc, _DocState())
    st.blobs = list(hist_blobs)
    st.pending.clear()
    st.pending_ts.clear()
    st.in_flight = []
    st.in_flight_ts = []
    st.stale = True
    st.no_promote_len = -1
    st._digest = None
    eng = None
    if snap_payload:
        try:
            eng = rehydrate(decode_payload(snap_payload),
                            pool=server.pool)
        except ValueError:
            server.snap_fallback_count += 1
            _count("snap.fallbacks", {"reason": "rehydrate"})
            eng = None
    if eng is None:
        return False
    st.resident = eng
    st.stale = False
    st.cache = {}
    server._adopt_engine(doc)
    return st.resident is not None


def remove_doc(server, doc) -> None:
    """Drop a handed-off doc from the source server (pool extents,
    resident budget, pending-byte odometer all reconciled)."""
    st = server._docs.get(doc)
    if st is None:
        return
    if st.resident is not None:
        server._drop_resident(doc)
    freed = sum(len(b) for b in st.pending) + \
        sum(len(b) for b in st.in_flight)
    server._pending_total = max(0, server._pending_total - freed)
    del server._docs[doc]


class Outbound:
    """Source-side migration record for one doc."""

    __slots__ = ("doc", "dst", "epoch_new", "step", "deadline",
                 "tail", "probe_deadline")

    def __init__(self, doc: str, dst: str, epoch_new: int):
        self.doc = doc
        self.dst = dst
        self.epoch_new = int(epoch_new)
        self.step = "drain"
        self.deadline = 0
        self.probe_deadline = 0
        # updates accepted during the handoff; ride the commit frame
        self.tail: List[bytes] = []


class Inbound:
    """Destination-side record: adopted, awaiting the epoch bump."""

    __slots__ = ("doc", "src", "epoch_new", "deadline")

    def __init__(self, doc: str, src: str, epoch_new: int,
                 deadline: int):
        self.doc = doc
        self.src = src
        self.epoch_new = int(epoch_new)
        self.deadline = deadline


class Migrator:
    """Per-node migration engine. ``node`` provides the seams
    (server, lease table, frame send, snapshot-store blobs); every
    timeout is TICK-indexed — wall clocks never steer recovery, the
    chaos matrix replays bit-for-bit."""

    def __init__(self, node, *, timeout_ticks: int =
                 DEFAULT_TIMEOUT_TICKS, crash_plan=None):
        self.node = node
        self.timeout_ticks = int(timeout_ticks)
        # guard.faults.MigrationCrashPlan (or None): raises
        # SimulatedCrash at scripted step boundaries — the chaos
        # harness's kill-at-step-k lever
        self.crash_plan = crash_plan
        self.outbound: Dict[str, Outbound] = {}
        self.inbound: Dict[str, Inbound] = {}
        # deterministic odometers (tracer rows mirror these)
        self.started = 0
        self.completed = 0
        self.recoveries: Dict[str, int] = {}

    # -- intent persistence (source crash safety) ----------------------

    def _write_intent(self, m: Outbound, step: str) -> None:
        store = self.node.store
        if store is None:
            return
        store.put_blob(INTENT_BLOB, json.dumps({
            "doc": m.doc, "dst": m.dst, "epoch_new": m.epoch_new,
            "step": step,
        }, sort_keys=True).encode())

    def _clear_intent(self) -> None:
        store = self.node.store
        if store is not None:
            store.put_blob(INTENT_BLOB, b"{}")

    def resume_intent(self) -> Optional[str]:
        """Called on node restart: a dangling intent blob means the
        process died mid-migration. Count the recovery at the
        recorded step and re-enter the ladder: pre-commit steps
        resume serving (nothing was granted); a commit-step intent
        re-arms the probe path — the lease table already persisted
        the grant, so the restart stays fenced."""
        store = self.node.store
        if store is None:
            return None
        raw = store.get_blob(INTENT_BLOB)
        if not raw:
            return None
        try:
            intent = json.loads(raw)
        except ValueError:
            intent = {}
        if not intent or "doc" not in intent:
            return None
        step = str(intent.get("step", "ship"))
        self._recover(step)
        if step == "commit":
            m = Outbound(str(intent["doc"]), str(intent["dst"]),
                         int(intent.get("epoch_new", 0)))
            m.step = "wait_ack"
            m.deadline = self.node.tick_count + self.timeout_ticks
            self.outbound[m.doc] = m
            self._send_probe(m)
        else:
            self._clear_intent()
        return step

    def _recover(self, step: str) -> None:
        self.recoveries[step] = self.recoveries.get(step, 0) + 1
        _count("migration.recovery", {"step": step})

    # -- source side ---------------------------------------------------

    def start(self, doc, dst: str) -> bool:
        """Begin migrating ``doc`` to ``dst``. Refused when this
        process does not own the doc or a handoff is already in
        flight (the placement loop's budget/skip logic relies on
        the False)."""
        doc = str(doc)
        node = self.node
        if doc in self.outbound or doc in self.inbound:
            return False
        if not node.lease.holds(doc) or dst == node.proc:
            return False
        m = Outbound(doc, dst, node.lease.epoch_of(doc) + 1)
        self.outbound[doc] = m
        self.started += 1
        _count("migration.started")
        self._write_intent(m, "drain")
        return True

    def buffer_update(self, doc: str, blob: bytes) -> bool:
        """An update for a doc mid-handoff: buffer it into the tail
        (it rides the commit frame) instead of the server. Returns
        True when buffered. Only valid BEFORE the commit frame is
        cut — past that the tail has shipped and the lease has moved,
        so the caller's fence check redirects the update to the new
        owner instead (buffering here would silently drop it)."""
        m = self.outbound.get(str(doc))
        if m is None or m.step not in ("drain", "wait_rehydrated"):
            return False
        m.tail.append(bytes(blob))
        _count("migration.tail_blobs")
        return True

    def migrating(self, doc) -> bool:
        return str(doc) in self.outbound or str(doc) in self.inbound

    def _maybe_crash(self, step: str) -> None:
        if self.crash_plan is not None:
            self.crash_plan.check(step)

    def _ship(self, m: Outbound) -> None:
        """Build + send the offer payload: warm docs ship the
        snapshot generation + history sidecar, cold docs the WAL
        tail (admitted blob history). Pending-but-unconverged blobs
        move into the migration tail so nothing admitted is lost."""
        from crdt_tpu.storage.snapshot import encode_engine

        node = self.node
        st = node.server._docs.get(m.doc)
        mode = "tail"
        snap = b""
        hist: List[bytes] = []
        if st is not None:
            # drain pending into the tail buffer (they were never
            # converged here; dst converges them post-commit)
            while st.pending:
                m.tail.append(st.pending.popleft())
            while st.pending_ts:
                st.pending_ts.popleft()
            freed = sum(len(b) for b in m.tail)
            node.server._pending_total = max(
                0, node.server._pending_total - freed)
            if st.resident is not None:
                try:
                    snap = encode_engine(st.resident,
                                         seq=len(st.blobs))
                    hist = [st.resident.encode_state_as_update()]
                    mode = "snap"
                except ValueError:
                    snap, hist, mode = b"", list(st.blobs), "tail"
            else:
                hist = list(st.blobs)
        self._write_intent(m, "ship")
        self._maybe_crash("ship")
        node.send(m.dst, {
            "kind": "offer", "doc": m.doc, "epoch": m.epoch_new,
            "proc": node.proc, "mode": mode,
        }, wire.pack_blobs([snap] + hist))
        m.step = "wait_rehydrated"
        m.deadline = node.tick_count + self.timeout_ticks

    def _commit(self, m: Outbound) -> None:
        node = self.node
        self._write_intent(m, "commit")
        # the point of no unfenced return: src hands the lease to
        # dst locally FIRST, so even a crash right here leaves src
        # fenced (persisted) rather than forkable
        node.lease.grant(m.doc, m.epoch_new, m.dst)
        self._maybe_crash("commit")
        node.send(m.dst, {
            "kind": "commit", "doc": m.doc, "epoch": m.epoch_new,
            "proc": node.proc,
        }, wire.pack_blobs(list(m.tail)))
        m.step = "wait_ack"
        m.deadline = node.tick_count + self.timeout_ticks

    def _abort(self, m: Outbound, step: str) -> None:
        """Pre-grant abort: re-ingest the tail, keep serving."""
        node = self.node
        self.outbound.pop(m.doc, None)
        self._clear_intent()
        for blob in m.tail:
            node.server.submit(m.doc, blob)
        self._recover(step)

    def _complete(self, m: Outbound) -> None:
        node = self.node
        remove_doc(node.server, m.doc)
        self.outbound.pop(m.doc, None)
        self._clear_intent()
        self.completed += 1
        _count("migration.completed")

    def _send_probe(self, m: Outbound) -> None:
        self.node.send(m.dst, {
            "kind": "probe", "doc": m.doc, "proc": self.node.proc,
        })
        m.probe_deadline = self.node.tick_count + self.timeout_ticks

    def step_tick(self) -> None:
        """Advance every in-flight migration one tick (called from
        ``FleetNode.tick`` AFTER the server tick, so drain sees the
        settled window)."""
        node = self.node
        now = node.tick_count
        for doc in sorted(self.outbound):
            m = self.outbound[doc]
            if m.step == "drain":
                st = node.server._docs.get(doc)
                self._maybe_crash("drain")
                if st is None or not st.in_flight:
                    self._ship(m)
            elif m.step == "wait_rehydrated" and now >= m.deadline:
                # dst died mid-rehydrate or the offer was lost:
                # nothing granted — source keeps serving
                self._abort(m, "rehydrate")
            elif m.step == "wait_ack" and now >= m.deadline:
                if now >= m.probe_deadline:
                    self._send_probe(m)
        for doc in sorted(self.inbound):
            inb = self.inbound[doc]
            if now >= inb.deadline:
                # commit never arrived: discard the half-adopted
                # doc — the lease never moved, src still owns it
                self.inbound.pop(doc, None)
                remove_doc(node.server, doc)
                self._recover("commit")

    # -- frame handlers (both sides) -----------------------------------

    def on_offer(self, header: Dict[str, Any],
                 payload: bytes) -> None:
        node = self.node
        doc = str(header.get("doc", ""))
        epoch_new = int(header.get("epoch", 0))
        src = str(header.get("proc", ""))
        # fence the offer with the CURRENT lease: the proposer must
        # be the owner proposing exactly epoch+1
        cur_e, cur_o = node.lease.lease(doc)
        if src != cur_o or epoch_new != cur_e + 1:
            node.lease.reject(doc, "offer")
            node.send(src, {"kind": "nack", "doc": doc,
                            "epoch": epoch_new, "proc": node.proc})
            return
        blobs = wire.unpack_blobs(payload)
        if blobs is None or not blobs:
            node.send(src, {"kind": "nack", "doc": doc,
                            "epoch": epoch_new, "proc": node.proc})
            return
        self._maybe_crash("rehydrate")
        adopt_doc(node.server, doc, blobs[0], blobs[1:])
        self.inbound[doc] = Inbound(
            doc, src, epoch_new,
            node.tick_count + 2 * self.timeout_ticks)
        node.send(src, {"kind": "rehydrated", "doc": doc,
                        "epoch": epoch_new, "proc": node.proc})

    def on_rehydrated(self, header: Dict[str, Any]) -> None:
        m = self.outbound.get(str(header.get("doc", "")))
        if m is None or m.step != "wait_rehydrated":
            return
        if int(header.get("epoch", 0)) != m.epoch_new or \
                str(header.get("proc", "")) != m.dst:
            return
        self._commit(m)

    def on_commit(self, header: Dict[str, Any],
                  payload: bytes) -> None:
        node = self.node
        doc = str(header.get("doc", ""))
        epoch_new = int(header.get("epoch", 0))
        src = str(header.get("proc", ""))
        inb = self.inbound.get(doc)
        if inb is None:
            # duplicate commit after we already took over: re-ack
            # (idempotent — the first ack may have been lost)
            if node.lease.lease(doc) == (epoch_new, node.proc):
                node.send(src, {"kind": "ack", "doc": doc,
                                "epoch": epoch_new,
                                "proc": node.proc})
            return
        if epoch_new != inb.epoch_new or src != inb.src:
            return
        tail = wire.unpack_blobs(payload)
        self.inbound.pop(doc, None)
        # durability BEFORE the ack: stash the doc's full admitted
        # history (shipped blobs + commit tail) in the store, so a
        # dst crash right after taking ownership restores the doc
        # from the stash instead of losing a committed handoff
        # (FleetNode.restore re-seeds it, counted
        # ``migration.tail_restores``)
        if node.store is not None:
            st = node.server._docs.get(doc)
            hist = list(st.blobs) if st is not None else []
            node.store.put_blob("fleet.tail.%s" % doc,
                                wire.pack_blobs(hist + list(tail or [])))
        node.lease.grant(doc, epoch_new, node.proc)
        for blob in tail or []:
            node.server.submit(doc, blob)
        node.send(src, {"kind": "ack", "doc": doc,
                        "epoch": epoch_new, "proc": node.proc})

    def on_ack(self, header: Dict[str, Any]) -> None:
        m = self.outbound.get(str(header.get("doc", "")))
        if m is None or m.step != "wait_ack":
            return
        if int(header.get("epoch", 0)) != m.epoch_new:
            return
        self._maybe_crash("ack")
        self._complete(m)

    def on_nack(self, header: Dict[str, Any]) -> None:
        doc = str(header.get("doc", ""))
        m = self.outbound.get(doc)
        if m is None:
            return
        node = self.node
        if m.step == "wait_rehydrated":
            self._abort(m, "ship")
            return
        if m.step == "wait_ack":
            # EXPLICIT proof the commit never landed: dst does not
            # hold the migration. Reclaim at epoch_new + 1 — a
            # higher epoch than the failed grant, so any late
            # commit replay at epoch_new is fenced off
            node.lease.grant(m.doc, m.epoch_new + 1, node.proc)
            self.outbound.pop(doc, None)
            self._clear_intent()
            for blob in m.tail:
                node.server.submit(m.doc, blob)
            self._recover("commit")

    def on_probe(self, header: Dict[str, Any]) -> None:
        node = self.node
        doc = str(header.get("doc", ""))
        src = str(header.get("proc", ""))
        e, o = node.lease.lease(doc)
        if o == node.proc and doc not in self.inbound:
            node.send(src, {"kind": "probe_reply", "doc": doc,
                            "epoch": e, "owner": o,
                            "proc": node.proc})
            return
        if doc in self.inbound:
            # a probe means the source's ack wait expired — the
            # NACK below is BINDING ("I have not committed, and now
            # never will"): cancel the inbound so a delayed commit
            # frame can't make this node start serving after the
            # source reclaims (the double-serve window the fence
            # exists to close)
            self.inbound.pop(doc, None)
            remove_doc(node.server, doc)
            self._recover("commit")
        node.send(src, {"kind": "nack", "doc": doc,
                        "epoch": 0, "proc": node.proc})

    def on_probe_reply(self, header: Dict[str, Any]) -> None:
        m = self.outbound.get(str(header.get("doc", "")))
        if m is None or m.step != "wait_ack":
            return
        epoch = int(header.get("epoch", 0))
        owner = str(header.get("owner", ""))
        if owner == m.dst and epoch >= m.epoch_new:
            # dst IS serving — only the ack was lost
            self._complete(m)
            self._recover("ack")
