"""Trace replay — BASELINE config #5 as a product API.

``replay_trace(blobs)`` ingests a batch of v1 update blobs (a captured
swarm trace, a persistence log, a sync backlog) through the firehose
path end to end:

  1. decode: one native-codec pass -> columnar union + contents
     (:mod:`crdt_tpu.codec.native`, Python fallback included);
  2. converge: HBM-resident union, one LWW map dispatch + one YATA
     sequence dispatch (:class:`crdt_tpu.ops.resident.ResidentColumns`);
  3. gather: winner/order indices return in ONE packed int32 transfer;
  4. materialize: the plain-JSON ``crdt.c`` cache, tombstones applied;
  5. compact: one snapshot blob (the log squashed — what a fresh
     replica needs instead of the whole history).

This is the library form of what ``bench.py`` measures; the benchmark
imports these stages so the timed pipeline IS the product pipeline.
Differential-tested against the scalar document path in
tests/test_models.py.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from crdt_tpu.codec import native
from crdt_tpu.core.ids import DeleteSet
from crdt_tpu.obs.tracer import get_tracer


class ReplayResult(NamedTuple):
    cache: dict        # converged plain-JSON state (crdt.c)
    snapshot: bytes    # compacted single-blob log
    n_ops: int         # unit items replayed
    path: str = "device"  # which engine converged (see replay_trace)


def decode(blobs: Sequence[bytes]) -> Dict:
    """Wire -> canonical columnar union (native C codec when built;
    duplicate ids from redelivered blobs are dropped, first wins)."""
    with get_tracer().span("decode"):
        return native.dedup_columns(
            native.decode_updates_columns_any(blobs)
        )


def stage(dec: Dict) -> Tuple[Dict[str, np.ndarray], DeleteSet]:
    """Kernel-facing columns + merged delete set."""
    return native.kernel_columns(dec), native.ds_from_triples(dec["ds"])


def converge(cols: Dict[str, np.ndarray], *,
             clients: Optional[Sequence[int]] = None):
    """One union convergence. Returns an opaque handle for
    :func:`gather`.

    Fast path: the packed single-dispatch pipeline
    (:mod:`crdt_tpu.ops.packed` — one upload, one fused kernel, one
    fetch). Falls back to the general resident path when the batch
    exceeds the packed key bounds (>=2^25 parents, >=2^21 map keys,
    clocks >= 2^40).

    ``clients`` only affects the RESIDENT fallback (it seeds that
    path's client table). The packed plan interns its own
    order-preserving table, which is equivalent for convergence: the
    sibling rules compare clients only through a monotone mapping, so
    any order-preserving table yields the identical document. Callers
    that need a fleet-shared table to be the one actually used (e.g.
    to reuse a resident store across batches) should route through
    :class:`crdt_tpu.ops.resident.ResidentColumns` directly.

    Multi-chip (round 13): when more than one device is visible and
    the union is big enough (``CRDT_TPU_SHARDS`` /
    ``CRDT_TPU_SHARD_MIN_ROWS``; :func:`crdt_tpu.ops.shard.
    active_for`), the union partitions by whole segments over the
    mesh and converges in ONE ``shard_map`` program — byte-identical
    outputs (tests/test_shard.py), only the per-shard state vectors
    cross chips."""
    from crdt_tpu.ops import packed

    from crdt_tpu.ops import shard as shard_ops

    if shard_ops.active_for(len(cols["client"])):
        splan = shard_ops.stage(cols)
        if splan is not None:
            return ("packed", shard_ops.converge(splan))

    # eager row shipping: each staged row starts its async upload as
    # soon as its layout pass completes, hiding transfer behind the
    # remaining staging work (and the seq block ships at its own
    # bucket width) — see packed.stage. Only ABOVE a size threshold:
    # each put pays the tunnel's fixed per-interaction latency, so
    # four puts on a small batch cost three extra round-trips for
    # nothing (measured: a 20k-op text replay went 0.24s -> 0.54s
    # before this gate existed)
    put = None
    if len(cols["client"]) >= packed.EAGER_PUT_MIN_ROWS:
        from crdt_tpu.ops.device import xfer_put

        put = xfer_put
    plan = packed.stage(cols, put=put)
    if plan is not None:
        return ("packed", packed.converge(plan))
    return ("resident", _converge_resident(cols, clients))


def _converge_resident(cols, clients):
    import jax

    from crdt_tpu.ops.device import bucket_pow2
    from crdt_tpu.ops.resident import ResidentColumns

    n = len(cols["client"])
    rc = ResidentColumns(
        capacity=n,
        clients=clients if clients is not None
        else np.unique(cols["client"][cols["valid"]]),
    )
    # tight segment bound: distinct (map parent, key) pairs + sequence
    # roots (the capacity default doubles the ranking kernel's span)
    n_segs = segment_bound(cols)
    # fused: splice + both kernels = ONE dispatch
    maps_out, seq_out = rc.append_converge(
        cols, num_segments=bucket_pow2(n_segs)
    )
    jax.block_until_ready(maps_out)
    jax.block_until_ready(seq_out)
    return rc, maps_out, seq_out


def parent_spec(dec: Dict, row: int) -> Tuple:
    """("root", name) or ("item", client, clock) of a row's parent."""
    pr = dec["parent_root"][row]
    if pr >= 0:
        return ("root", dec["roots"][pr])
    return (
        "item",
        int(dec["parent_client"][row]),
        int(dec["parent_clock"][row]),
    )


def gather(dec: Dict, ds: DeleteSet, handle):
    """Winner rows + visibility + per-sequence document orders (keyed
    by parent spec — root name or item id) from a :func:`converge`
    handle.

    Right origins (honest prepends/mid-inserts): the packed path
    orders attachment groups AT STAGING — the exact conflict-scan
    ranks ride the client column into the fused dispatch
    (ops.packed._stage_rights) — so only segments carrying shapes the
    sibling-rank model cannot express (dangling/cross-parent rights,
    rights into a member's subtree, orphan subtrees: the plan's
    ``hard_rows``) re-order on the host. The resident fallback keeps
    the blanket host detour for every right-bearing parent."""
    with get_tracer().span("gather"):
        return _gather(dec, ds, handle)


def _gather(dec: Dict, ds: DeleteSet, handle):
    if handle[0] == "packed":
        win_rows, seq_orders = _assemble_packed(dec, handle[1])
        hard = getattr(handle[1], "hard_rows", ())
        if hard:
            affected = {parent_spec(dec, int(r)) for r in hard}
            seq_orders.update(_host_seq_orders(dec, affected))
        return finish_assembly(
            dec, ds, win_rows, seq_orders, blanket_rights=False
        )
    win_rows, seq_orders = _assemble_resident(dec, handle[1])
    return finish_assembly(dec, ds, win_rows, seq_orders)


def finish_assembly(dec: Dict, ds: DeleteSet, win_rows, seq_orders,
                    *, blanket_rights: bool = True):
    """Shared assembly tail for every convergence engine (resident,
    packed, fleet): the blanket right-origin host detour — applied
    when the producing kernels ignore rights entirely, skipped when
    the producer already ordered its expressible rights at staging —
    then crafted-map-chain repair and winner visibility. One
    implementation, so a future right-origin fix reaches every
    route."""
    if blanket_rights:
        rc_col, kid_col = dec["right_client"], dec["key_id"]
        right_seq_rows = np.flatnonzero((rc_col >= 0) & (kid_col < 0))
        if len(right_seq_rows):
            affected = {parent_spec(dec, int(r)) for r in right_seq_rows}
            seq_orders.update(_host_seq_orders(dec, affected))
    win_rows = _fix_map_chains_with_rights(dec, win_rows)
    win_vis = visible_mask(dec, win_rows, ds)
    return win_rows, win_vis, seq_orders


def segment_key(pa: np.ndarray, kid: np.ndarray) -> np.ndarray:
    """ONE packed (parent, key) segment identity, shared by the
    segment-count bound below and the mesh partitioner
    (crdt_tpu.models.fleet.shard_trace): parents shifted past the
    2^20 key space; the no-key sentinel occupies its own slot per
    parent. Both consumers must agree bit-for-bit — the partitioner's
    correctness rests on whole segments staying co-located."""
    pa = np.asarray(pa, np.int64)
    kid = np.asarray(kid, np.int64)
    return (pa << 21) | np.where(kid >= 0, kid, 1 << 20)


def segment_bound(cols: Dict[str, np.ndarray]) -> int:
    """Tight distinct-segment count for the convergence kernels:
    distinct (map parent, key) pairs + sequence parents."""
    if not len(np.asarray(cols["parent_a"])):
        return 1
    return len(np.unique(segment_key(cols["parent_a"], cols["key_id"])))


def _assemble_packed(dec: Dict, res, row_map=None):
    """Vectorized host assembly of the packed kernel's one fetch.
    ``row_map`` translates the result's row space into ``dec``'s (the
    streaming executor stages each chunk's rows separately, so its
    results come back chunk-local); None means they already agree."""
    win = res.win_rows[res.win_rows >= 0]
    m = res.stream_row >= 0
    rows, segs = res.stream_row[m], res.stream_seg[m]
    if row_map is not None:
        win = row_map[win]
        rows = row_map[rows]
    win_rows = win.tolist()
    seq_orders: dict = {}
    if len(rows):
        cuts = np.r_[0, np.flatnonzero(segs[1:] != segs[:-1]) + 1, len(segs)]
        for a, b in zip(cuts[:-1], cuts[1:]):
            chunk = rows[a:b].tolist()
            spec = parent_spec(dec, chunk[0])
            # extend on recurrence: the sharder's cross-shard subtree
            # pre-cut (round 23) emits one list's pieces as separate
            # runs — shard-concatenated in exact piece order, so
            # appending reproduces the unsplit stream bit-for-bit
            if spec in seq_orders:
                seq_orders[spec].extend(chunk)
            else:
                seq_orders[spec] = chunk
    return win_rows, seq_orders


def _assemble_resident(dec: Dict, out):
    rc, maps_out, seq_out = out
    from crdt_tpu.ops.device import fetch_packed_i32

    order, winners, sorder, sseg, srank = fetch_packed_i32(
        maps_out[0], maps_out[2], seq_out[0], seq_out[1], seq_out[2]
    )
    win_rows = [int(order[w]) for w in winners if w >= 0]
    n = len(dec["client"])
    seq_pairs: dict = {}
    for p in np.flatnonzero(srank >= 0):
        row = int(sorder[p])
        if row < n:
            seq_pairs.setdefault(int(sseg[p]), []).append(
                (int(srank[p]), row)
            )
    seq_orders = {}
    for sid, pairs in seq_pairs.items():
        pairs.sort()
        rows = [r for _, r in pairs]
        seq_orders[parent_spec(dec, rows[0])] = rows
    return win_rows, seq_orders


def _host_seq_orders(dec: Dict, specs_needed: set):
    """Exact sequence orders for the given parent specs via the host
    machinery (right origins, attachment groups, hostile shapes).

    The subset keeps full-union semantics: every id referenced from the
    subset but living OUTSIDE it (GC fillers, foreign parents' rows)
    joins as a GC stub — the ordering machinery then drops/hardens
    those references exactly as it would with the whole union in hand,
    while truly dangling references stay absent (members pend)."""
    from crdt_tpu.core.records import ItemRecord
    from crdt_tpu.core.store import K_GC
    from crdt_tpu.ops.yata import order_sequences

    kid_col, kind_col = dec["key_id"], dec["kind"]
    n = len(kid_col)
    rows = [
        i for i in range(n)
        if kid_col[i] < 0 and kind_col[i] != K_GC
        and parent_spec(dec, i) in specs_needed
    ]
    records, _ = native.decoded_to_records(dec, rows)
    sub_ids = {r.id for r in records}
    id_row = {
        (int(dec["client"][i]), int(dec["clock"][i])): i for i in range(n)
    }
    stubs = {
        ref
        for r in records
        for ref in (r.origin, r.right)
        if ref is not None and ref not in sub_ids and ref in id_row
    }
    records += [
        ItemRecord(client=c, clock=k, kind=K_GC) for c, k in stubs
    ]
    return {
        spec: [id_row[i] for i in ids]
        for spec, ids in order_sequences(records).items()
        if spec in specs_needed
    }


def _fix_map_chains_with_rights(dec: Dict, win_rows, bad_rows=None,
                                chain_rows=None, union_ids=None):
    """Crafted rights on MAP rows shift chain tails in ways the argmax
    kernel cannot express; recompute exactly those chains' tails via
    the scalar chain order. The optional subsets are the streaming
    executor's seams: ``bad_rows`` restricts the repair to a chunk's
    right-bearing map rows (so one chunk never emits another chunk's
    tails), ``chain_rows`` restricts the chain-membership scan to the
    chunk's rows (sound because segments never split across chunks),
    and ``union_ids`` shares one precomputed whole-union id set across
    chunks instead of rebuilding it per call. Defaults scan the whole
    union."""
    from crdt_tpu.core.records import ItemRecord
    from crdt_tpu.ops.yata import order_hard_segment

    rc_col, kid_col = dec["right_client"], dec["key_id"]
    if bad_rows is None:
        bad = np.flatnonzero((rc_col >= 0) & (kid_col >= 0))
    else:
        bad = np.asarray(bad_rows, np.int64)
    if not len(bad):
        return win_rows
    affected = {(parent_spec(dec, int(r)), int(kid_col[r])) for r in bad}
    chains: Dict[Tuple, List[int]] = {}
    for i in (range(len(kid_col)) if chain_rows is None else chain_rows):
        i = int(i)
        if kid_col[i] >= 0:
            key = (parent_spec(dec, i), int(kid_col[i]))
            if key in affected:
                chains.setdefault(key, []).append(i)
    id_row = {
        (int(dec["client"][i]), int(dec["clock"][i])): i
        for rows in chains.values()
        for i in rows
    }
    if union_ids is None:
        union_ids = {
            (int(dec["client"][i]), int(dec["clock"][i]))
            for i in range(len(kid_col))
        }
    patched = dict.fromkeys(affected)
    for key, rows in chains.items():
        recs = [
            ItemRecord(
                client=int(dec["client"][i]), clock=int(dec["clock"][i]),
                origin=(
                    (int(dec["origin_client"][i]),
                     int(dec["origin_clock"][i]))
                    if dec["origin_client"][i] >= 0 else None
                ),
                right=(
                    (int(dec["right_client"][i]),
                     int(dec["right_clock"][i]))
                    if dec["right_client"][i] >= 0 else None
                ),
                parent_root="x",  # chain order ignores parent identity
            )
            for i in rows
        ]
        ordered = order_hard_segment(
            recs, ref_exists=lambda ref: ref in union_ids
        )
        patched[key] = id_row[ordered[-1]] if ordered else None
    out = []
    for row in win_rows:
        key = (parent_spec(dec, row), int(kid_col[row]))
        if key in affected:
            continue  # replaced by the exact tail below
        out.append(row)
    out.extend(r for r in patched.values() if r is not None)
    return out


def rows_visible(
    row_client: np.ndarray,
    row_clock: np.ndarray,
    del_c: np.ndarray,
    del_s: np.ndarray,
    del_e: np.ndarray,
) -> np.ndarray:
    """Vectorized tombstone test against delete RANGES — never
    expanded ids: a few delete-set bytes can legitimately declare
    ranges covering a whole GC'd history, so membership is an interval
    search (adversarial matrix, tests/test_yjs_fixtures.py). Ranges
    must be DISJOINT and sorted per client (DeleteSet.normalize's
    invariant). Clients remap densely before packing; the 41-bit clock
    field keeps the exclusive range end (up to the 1<<40 wire bound)
    out of the client bits. Shared by the cold replay's visible_mask
    and the incremental replay's cached-tombstone path."""
    if not len(del_c):
        return np.ones(len(row_client), bool)
    row_client = np.asarray(row_client, np.int64)
    del_c = np.asarray(del_c, np.int64)
    uniq = np.unique(np.concatenate([row_client, del_c]))
    qk = (
        np.searchsorted(uniq, row_client).astype(np.int64) << 41
    ) | np.asarray(row_clock, np.int64)
    dc = np.searchsorted(uniq, del_c).astype(np.int64) << 41
    starts = dc | np.asarray(del_s, np.int64)
    ends = dc | np.asarray(del_e, np.int64)
    order = np.argsort(starts)
    starts, ends = starts[order], ends[order]
    pos = np.searchsorted(starts, qk, side="right") - 1
    posc = np.clip(pos, 0, len(starts) - 1)
    return ~((pos >= 0) & (qk < ends[posc]))


def visible_mask(dec: Dict, rows: List[int], ds: DeleteSet) -> List[bool]:
    """Tombstone visibility for specific rows (vectorized)."""
    if not rows:
        return []
    idx = np.asarray(rows)
    trip = list(ds.iter_all())  # normalized: disjoint, client-sorted
    del_c = np.asarray([c for c, _, _ in trip], np.int64)
    del_s = np.asarray([s for _, s, _ in trip], np.int64)
    del_e = np.asarray([s + n for _, s, n in trip], np.int64)
    return list(rows_visible(
        dec["client"][idx], dec["clock"][idx], del_c, del_s, del_e
    ))


def materialize(dec: Dict, ds: DeleteSet, win_rows, win_vis,
                seq_orders) -> dict:
    """Winner rows + sequence orders -> the plain-JSON cache, with
    tombstoned sequence members dropped (the engine's visible walk).
    Nested collections (a Y.Array/Y.Map stored under a map key or a
    sequence slot) materialize recursively through their type items."""
    cache, ix_group = assemble_cache(
        dec, ds, win_rows, win_vis, seq_orders
    )
    finish_cache(cache, dec, ix_group)
    return cache


def assemble_cache(dec: Dict, ds: DeleteSet, win_rows, win_vis,
                   seq_orders) -> Tuple[dict, Dict[str, int]]:
    """The per-subset half of :func:`materialize`: builds the cache
    entries for exactly the root specs present in ``win_rows`` /
    ``seq_orders``. The streaming executor calls this once per chunk
    (each chunk owning whole root subtrees, so nested type items
    resolve within the chunk) and merges the parts; the returned
    ``ix_group`` is the subset's slice of the reserved ``ix`` index
    root, consumed by :func:`finish_cache` once every part is in."""
    with get_tracer().span("materialize"):
        return _assemble_cache(dec, ds, win_rows, win_vis, seq_orders)


def _assemble_cache(dec: Dict, ds: DeleteSet, win_rows, win_vis,
                    seq_orders) -> Tuple[dict, Dict[str, int]]:
    from crdt_tpu.core.store import K_TYPE, TYPE_MAP

    keys = dec["keys"]
    kid = dec["key_id"]
    client, clock = dec["client"], dec["clock"]
    kind_col, tref = dec["kind"], dec["type_ref"]
    contents = dec["contents"]

    # vectorized tombstone test for every sequence row at once (the
    # per-row ds.contains walk was ~half of materialize at 100k ops)
    all_seq_rows = sorted(
        {int(r) for rows in seq_orders.values() for r in rows}
    )
    seq_vis = dict(
        zip(all_seq_rows, visible_mask(dec, all_seq_rows, ds))
    )

    # visible map winners grouped by their parent spec
    map_groups: Dict[Tuple, Dict[str, int]] = {}
    for row, vis in zip(win_rows, win_vis):
        if not vis:
            continue
        map_groups.setdefault(parent_spec(dec, row), {})[
            keys[kid[row]]
        ] = row

    def value_of(row: int, depth: int):
        if kind_col[row] == K_TYPE:
            spec = ("item", int(client[row]), int(clock[row]))
            is_map = tref[row] == TYPE_MAP
            return collection(spec, is_map, depth + 1)
        return contents[row]

    def collection(spec: Tuple, is_map: bool, depth: int):
        if depth > 64:
            return None  # malformed cyclic nesting: cut, don't recurse
        if is_map:
            return {
                k: value_of(r, depth)
                for k, r in map_groups.get(spec, {}).items()
            }
        return [
            value_of(r, depth)
            for r in seq_orders.get(spec, ())
            if seq_vis[int(r)]
        ]

    cache: dict = {}
    for spec in map_groups:
        # the reserved collection-kind index stays internal, exactly
        # as the document API's `c` hides it
        if spec[0] == "root" and spec[1] != "ix":
            cache[spec[1]] = collection(spec, True, 0)
    for spec in seq_orders:
        if spec[0] == "root" and spec[1] not in cache:
            cache[spec[1]] = collection(spec, False, 0)
    return cache, map_groups.get(("root", "ix"), {})


def finish_cache(cache: dict, dec: Dict,
                 ix_group: Dict[str, int]) -> dict:
    """The cross-subset tail of :func:`materialize`: roots registered
    in the ix index but with no visible content (e.g. a map whose
    every key was tombstoned) still materialize — empty — exactly
    like the document cache. Runs once, after every subset's
    :func:`assemble_cache` part has merged into ``cache``."""
    contents = dec["contents"]
    for name, row in ix_group.items():
        if name not in cache and name != "ix":
            cache[name] = [] if contents[row] == "array" else {}
    return cache


def compact(dec: Dict, ds: DeleteSet) -> bytes:
    """Snapshot compaction: the whole replayed union as one blob."""
    with get_tracer().span("compact"):
        return native.encode_from_columns_any(dec, ds)


def replay_trace(
    blobs: Sequence[bytes],
    *,
    clients: Optional[Sequence[int]] = None,
    route: str = "device",
) -> ReplayResult:
    """One-shot: blobs in, converged cache + compacted snapshot out.

    ``route`` picks the convergence engine:

    - ``"device"`` (default) — the packed single-dispatch pipeline,
      always. The default stays pinned so differential suites that
      use this function as their independent cold oracle keep
      exercising the device kernels, and published device numbers are
      never silently host numbers.
    - ``"host"`` — the IDENTICAL fused convergence executed on the
      process's local CPU backend (:func:`crdt_tpu.ops.packed.
      converge_host`): zero accelerator interactions, byte-identical
      kernel outputs. Unions the packed stager cannot express fall
      back to the replica machinery below.
    - ``"auto"`` — the PRODUCT rule: apply the same session-calibrated
      host/device crossover the live replica uses. On a tunnelled
      platform a small replay is floored by fixed per-interaction
      latency, not merge speed — below the threshold the union
      converges on the local backend (``"host"``), above it the
      accelerator pipeline runs.
    - ``"replica"`` — ingest through :class:`crdt_tpu.models.
      incremental.IncrementalReplay` pinned to its host path: the
      identical code a LIVE resident replica runs on this backlog
      (kept as a third independent engine for differential suites and
      for measuring the replica ingest itself).
    - ``"fleet"`` — the mesh axis: each blob is treated as one
      replica's pending broadcast and the whole set converges as ONE
      sharded gossip+merge round over the device mesh
      (:func:`crdt_tpu.models.fleet.fleet_replay` — the reference's
      full-mesh propagate round, crdt.js:385,445, as a collective).
      Requires a causally complete union, like the device route.
    - ``"stream"`` — the device pipeline, OVERLAPPED: chunked decode,
      async double-buffered converge dispatches, incremental per-chunk
      materialization (:func:`crdt_tpu.models.streaming.stream_replay`
      — the default engine for the scale replay; same outputs as
      ``"device"``, differential-tested byte-identical).

    All engines are differential-tested against each other and the
    scalar oracle; ``ReplayResult.path`` records which one ran."""
    if route == "stream":
        from crdt_tpu.models.streaming import stream_replay

        return stream_replay(blobs, clients=clients)
    if route == "fleet":
        from crdt_tpu.models.fleet import fleet_replay

        return fleet_replay(blobs)
    dec = decode(blobs)
    n = len(dec["client"])
    use_host = False
    if route in ("host", "replica"):
        use_host = True
    elif route == "auto":
        from crdt_tpu.models.incremental import IncrementalReplay

        # the live replica's exact rule (one shared implementation:
        # static floor first, session probe beyond it)
        use_host = IncrementalReplay.crossover_use_host(n)
    elif route != "device":
        raise ValueError(f"unknown route {route!r}")
    if use_host and route != "replica":
        from crdt_tpu.ops import packed

        cols, ds = stage(dec)
        # wide staging: this route never touches the link (local CPU
        # backend), so the narrow encode + widening prelude would be
        # pure overhead AND would credit xfer.* savings for bytes
        # that never cross anything
        plan = packed.stage(cols, wide=True)
        if plan is not None:
            handle = ("packed", packed.converge_host(plan))
            win_rows, win_vis, seq_orders = gather(dec, ds, handle)
            cache = materialize(dec, ds, win_rows, win_vis, seq_orders)
            return ReplayResult(
                cache=cache, snapshot=compact(dec, ds), n_ops=n,
                path="host",
            )
        # inexpressible plan (key-width overflow): replica machinery
    if use_host:
        from crdt_tpu.models.incremental import IncrementalReplay

        # minimal capacity: the resident device matrix is never used
        # on this route (device_min_rows pins every round to host), so
        # sizing it to the trace would allocate a large dead buffer
        inc = IncrementalReplay(
            capacity=1 << 10,
            device_min_rows=1 << 62,  # host path, zero device work
        )
        inc.apply_decoded(dec)  # decoded once above, never twice
        ds = native.ds_from_triples(dec["ds"])
        return ReplayResult(
            cache=dict(inc.cache), snapshot=compact(dec, ds), n_ops=n,
            path="replica",
        )
    cols, ds = stage(dec)
    handle = converge(cols, clients=clients)
    win_rows, win_vis, seq_orders = gather(dec, ds, handle)
    cache = materialize(dec, ds, win_rows, win_vis, seq_orders)
    return ReplayResult(
        cache=cache, snapshot=compact(dec, ds), n_ops=n, path="device"
    )


def cold_start(doc_name: str, persistence, snapshots=None,
               *, pool=None):
    """Bring a doc up from durable state, preferring snapshot +
    WAL-tail over full-history replay (round 21, ROADMAP item 4).

    The recovery ladder, top rung first:

    1. newest valid snapshot generation (damage is counted and
       skipped inside ``SnapshotStore.load_latest``) rehydrated into
       a live engine, plus ``persistence.get_updates_since`` for the
       tail the snapshot does not cover;
    2. if the tail does not settle exactly (stashed/rootless rows —
       a snapshot from a FOREIGN log, or coverage skew), counted
       ``snap.fallbacks{reason="tail_stash"}`` and down one rung;
    3. full WAL replay through a fresh ``IncrementalReplay`` — the
       byte-identical baseline every upper rung must match.

    Returns ``(engine, path)`` with path in {"snapshot", "wal"}."""
    from crdt_tpu.models.incremental import IncrementalReplay

    tracer = get_tracer()
    if snapshots is not None:
        loaded = snapshots.load_latest(doc_name)
        if loaded is not None:
            from crdt_tpu.storage import snapshot as snap_mod

            snap, seq = loaded
            eng = None
            try:
                eng = snap_mod.rehydrate(snap, pool=pool)
                eng.apply(persistence.get_updates_since(doc_name, seq))
            except ValueError:
                if tracer.enabled:
                    tracer.count("snap.fallbacks",
                                 labels={"reason": "rehydrate"})
            else:
                if not (eng._pending or eng._rootless):
                    return eng, "snapshot"
                if tracer.enabled:
                    tracer.count("snap.fallbacks",
                                 labels={"reason": "tail_stash"})
            # abandoned rung: give back any pooled registration
            if eng is not None and eng.pool is not None:
                eng.pool.release(eng)
                eng.pool = None
    eng = IncrementalReplay(pool=pool)
    eng.apply(persistence.get_all_updates(doc_name))
    return eng, "wal"
