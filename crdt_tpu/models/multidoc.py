"""Multi-tenant batched serving: one converge dispatch for many docs.

ROOFLINE.md pins a fixed per-dispatch floor on the tunnelled platform
(~6 ms on the v5e-class rig), so a server hosting thousands of SMALL
independent docs pays almost pure overhead when each doc converges in
its own dispatch — a 64-op doc costs the same floor as a 100k-op one.
This module is ROADMAP open item 2: amortize the floor by packing many
docs' deltas into ONE fused converge per tick.

The engine is the round-14 staging tentpole: doc-id is a first-class
segment column in :mod:`crdt_tpu.ops.packed` (client ids fold into
doc-composite ids, parent refs intern doc-major), so a whole tenant
batch converges in one program with per-doc outputs byte-identical to
each doc converged alone (tests/test_multidoc.py pins {2, 3, 17} docs
with mixed LWW/YATA ops, deletes, and empty docs on both the
single-chip and forced-2-device sharded routes — the sharded
partition places whole DOCS per chip first).

:class:`MultiDocServer` is the tick loop on top:

- **submit** — per-tenant admission queues under the
  :class:`crdt_tpu.guard.tenant.TenantBudget` byte/count budget:
  a flooding tenant's own backlog is trimmed oldest-first
  (keep-the-newest), other tenants' queues and converged bytes are
  untouched (the round-10 "degrade, don't die" rule, tenant-scoped).
- **prepare** — the ingest-side work (wire decode + kernel-column
  staging) runs per doc OFF the tick, the way the streaming executor
  already overlaps decode against in-flight converges: a real
  deployment decodes updates where they arrive; the tick spends its
  time on the dispatch it exists to amortize. ``tick()`` prepares
  any stale doc itself, so calling ``prepare()`` is an optimization,
  never a correctness requirement.
- **tick** — dirty docs order least-recently-served-first
  (:func:`crdt_tpu.guard.tenant.fair_order`), bin-pack into dispatch
  batches bounded by ``max_rows_per_dispatch`` rows
  (:func:`~crdt_tpu.guard.tenant.pack_batches`; the staged buckets
  round up to powers of two, so the cap IS the padded bucket
  ceiling), and each batch converges in one dispatch — the sharded
  multi-chip route when active (docs partition whole across chips),
  the single-chip packed plan otherwise, with a per-doc fallback
  when a batch exceeds the packed staging bounds.
- **unpack** — the one fetched result splits back into per-doc
  caches/digests. Plain docs (root-parented content rows, no right
  origins, no nested types — the overwhelming small-tenant shape)
  take a VECTORIZED unpack: one global visibility pass over the
  whole batch (doc-composite delete ranges), one stable partition
  of the winner/stream arrays by doc, then a tight per-doc cache
  build. Anything else — nested collections, right origins, GC/
  format rows, hard segments, the ``ix`` index root — routes that
  doc's slice through the stock replay gather/materialize, so the
  fast path can never change bytes (differential-pinned either way).

Per-doc digests feed the multi-doc divergence sentinel
(:class:`crdt_tpu.obs.sentinel.MultiDocSentinel`), which attributes
a fork to the ONE doc that diverged.

Evidence: ``converge.docs_packed`` (docs per staged plan, counted at
the staging seam), ``tenant.*`` counters/gauges (README
"Observability" registry), and the ``bench.py --multitenant`` leg
publishing ``docs_converged_per_s`` / ``p99_per_doc_ms`` /
``dispatches_per_tick`` against the one-dispatch-per-doc baseline
(the same server with ``pack_docs=False``: the stock per-doc replay
pipeline), regression-gated in ``tools/metrics_diff.py``.

Env knobs: ``CRDT_TPU_MT_MAX_ROWS`` (dispatch row cap, default
2^16), ``CRDT_TPU_MT_PENDING_BYTES`` / ``CRDT_TPU_MT_PENDING_UPDATES``
(per-tenant admission budget defaults).
"""

from __future__ import annotations

import hashlib
import os
import time
from collections import deque
from typing import Dict, List, NamedTuple, Optional, Sequence

import numpy as np

from crdt_tpu.guard.tenant import TenantBudget, fair_order, pack_batches
from crdt_tpu.models import replay as rp
from crdt_tpu.obs.tracer import get_tracer
from crdt_tpu.ops import packed
from crdt_tpu.ops.device import NULLI

_MAX_ROWS_ENV = "CRDT_TPU_MT_MAX_ROWS"
_PENDING_BYTES_ENV = "CRDT_TPU_MT_PENDING_BYTES"
_PENDING_UPDATES_ENV = "CRDT_TPU_MT_PENDING_UPDATES"


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    if raw == "":
        return default
    try:
        return max(1, int(raw))
    except ValueError:
        return default


def cache_digest(cache: dict) -> str:
    """Canonical digest of a converged cache: top-level root names
    sorted, values repr'd (C-speed). Below the top level, equal
    CONVERGED states hold equal structures in equal order — winner
    and stream orders are deterministic functions of the union, the
    tentpole's per-doc identity guarantee — so repr is canonical for
    the comparison surfaces the digest serves (fast vs stock unpack,
    packed vs per-doc baseline, server vs server over one topic)."""
    body = ",".join(
        "%r:%r" % (k, cache[k]) for k in sorted(cache, key=str)
    )
    return hashlib.sha1(body.encode()).hexdigest()[:16]


def _fast_unpack_ok(dec) -> bool:
    """May this doc take the vectorized unpack? Only the plain shape
    the tight cache build reproduces bit-for-bit: every row a
    root-parented content row (JSON/binary/string/any), no right
    origins, no reserved ``ix`` index root. Everything else routes
    through the stock replay gather/materialize."""
    from crdt_tpu.core.store import K_ANY, K_BINARY, K_JSON, K_STRING

    kind = np.asarray(dec["kind"])
    if len(kind) == 0:
        return True
    if not np.isin(kind, (K_JSON, K_BINARY, K_STRING, K_ANY)).all():
        return False
    if (np.asarray(dec["right_client"]) >= 0).any():
        return False
    if not (np.asarray(dec["parent_root"]) >= 0).all():
        return False
    return "ix" not in dec["roots"]


class _DocState:
    __slots__ = ("blobs", "pending", "cache", "digest", "n_ops",
                 "dirty_since", "latency_s", "served_tick",
                 "dec", "cols", "ds", "fast_ok", "stale")

    def __init__(self):
        self.blobs: List[bytes] = []      # admitted, converged history
        self.pending: deque = deque()     # admitted, awaiting a tick
        self.cache: dict = {}
        self.digest: str = cache_digest({})
        self.n_ops: int = 0
        self.dirty_since: Optional[float] = None
        self.latency_s: Optional[float] = None
        self.served_tick: int = -1
        self.dec = None                   # prepared decode (full history)
        self.cols = None                  # prepared kernel columns
        self.ds = None                    # prepared delete set
        self.fast_ok = False
        self.stale = True                 # prepared state out of date


class TickReport(NamedTuple):
    docs: int              # docs converged this tick
    dispatches: int        # converge dispatches issued
    rows: int              # total staged rows
    fallback_docs: int     # docs that fell back to per-doc dispatch
    batches: tuple = ()    # docs per dispatch, in dispatch order


class MultiDocServer:
    """Tick-batched multi-tenant converge server (see module doc).

    A tick re-converges each dirty doc's FULL admitted history (the
    cold staged path — the same replay semantics every differential
    suite oracles against), so per-doc outputs are exactly what
    ``replay_trace`` of the same blobs yields. ``pack_docs=False``
    degrades to one dispatch per doc through the stock replay
    pipeline — the one-dispatch-per-doc baseline the bench leg
    measures the packing win against."""

    def __init__(self, *, max_rows_per_dispatch: Optional[int] = None,
                 tenant_max_pending_bytes: Optional[int] = None,
                 tenant_max_pending_updates: Optional[int] = None,
                 shards: Optional[int] = None,
                 pack_docs: bool = True):
        self.max_rows = (max_rows_per_dispatch
                         if max_rows_per_dispatch is not None
                         else _env_int(_MAX_ROWS_ENV, 1 << 16))
        self.budget = TenantBudget(
            max_bytes=(tenant_max_pending_bytes
                       if tenant_max_pending_bytes is not None
                       else _env_int(_PENDING_BYTES_ENV, 1 << 22)),
            max_updates=(tenant_max_pending_updates
                         if tenant_max_pending_updates is not None
                         else _env_int(_PENDING_UPDATES_ENV, 4096)),
        )
        self.shards = shards
        self.pack_docs = pack_docs
        self.ticks = 0
        self.shed_count = 0
        self.shed_bytes = 0
        self._docs: Dict = {}
        # running pending-queue byte total: the gauge (and the
        # public accessor) must not re-scan every tenant's deque on
        # each admitted blob — ingest stays O(1) per update
        self._pending_total = 0

    # ---- admission (the ingest side) ---------------------------------

    def submit(self, doc_id, blob: bytes) -> int:
        """Admit one update blob for ``doc_id``. Returns how many of
        the tenant's pending updates were SHED to fit its budget (0 =
        admitted with room)."""
        st = self._docs.setdefault(doc_id, _DocState())
        if st.dirty_since is None:
            st.dirty_since = time.perf_counter()
        st.pending.append(bytes(blob))
        self._pending_total += len(blob)
        st.stale = True
        tracer = get_tracer()
        if tracer.enabled:
            tracer.count("tenant.submitted")
        shed = self.budget.trim(st.pending)
        if shed:
            nbytes = sum(len(b) for b in shed)
            self.shed_count += len(shed)
            self.shed_bytes += nbytes
            self._pending_total -= nbytes
            if tracer.enabled:
                tracer.count("tenant.shed", len(shed))
                tracer.count("tenant.shed_bytes", nbytes)
        if tracer.enabled:
            tracer.gauge("tenant.pending_bytes", self.pending_bytes())
        return len(shed)

    def submit_many(self, doc_id, blobs: Sequence[bytes]) -> int:
        if not blobs:
            # registering an empty doc: a NEW state, already settled
            # (nothing to decode, cache/digest default to empty). An
            # EXISTING doc is left completely untouched — clearing
            # its stale flag here would make prepare() skip a dirty
            # doc and tick() read outdated columns
            if doc_id not in self._docs:
                st = _DocState()
                st.stale = False
                self._docs[doc_id] = st
            return 0
        return sum(self.submit(doc_id, b) for b in blobs)

    def prepare(self) -> int:
        """Run the ingest-side decode + kernel-column staging for
        every stale doc (full admitted history). Idempotent; the tick
        calls it for anything the ingest thread has not covered.
        Returns the number of docs prepared."""
        n = 0
        for st in self._docs.values():
            if not st.stale:
                continue
            dec = rp.decode(st.blobs + list(st.pending))
            st.cols, st.ds = rp.stage(dec)
            st.dec = dec
            st.fast_ok = _fast_unpack_ok(dec)
            st.stale = False
            n += 1
        return n

    def pending_bytes(self) -> int:
        return self._pending_total

    def dirty_docs(self) -> List:
        return [d for d, st in self._docs.items() if st.pending]

    # ---- results -----------------------------------------------------

    def doc_ids(self) -> List:
        return list(self._docs)

    def cache(self, doc_id) -> dict:
        return self._docs[doc_id].cache

    def digest(self, doc_id) -> str:
        return self._docs[doc_id].digest

    def latency_s(self, doc_id) -> Optional[float]:
        """Submit-to-converged latency of the doc's last service."""
        return self._docs[doc_id].latency_s

    def doc_digests(self) -> Dict:
        """The multi-doc sentinel's beacon source: per-doc digest +
        op count (the count is the lag guard — unequal counts are
        propagation lag, not a fork)."""
        return {
            d: {"digest": st.digest, "ops": st.n_ops}
            for d, st in self._docs.items()
        }

    # ---- the tick loop -----------------------------------------------

    def tick(self) -> TickReport:
        """Converge every dirty doc: fairness-ordered admission,
        bin-packed dispatch batches, per-doc unpack (see module doc).
        One tick fully drains the dirty set — fairness decides WHO
        shares a dispatch, the row cap decides how many dispatches."""
        self.ticks += 1
        self.prepare()
        dirty = fair_order(self.dirty_docs(),
                           {d: self._docs[d].served_tick
                            for d in self._docs})
        if not dirty:
            return TickReport(0, 0, 0, 0)
        tracer = get_tracer()
        staged = [(d, len(self._docs[d].dec["client"])) for d in dirty]
        batches = (pack_batches(staged, self.max_rows)
                   if self.pack_docs else [[d] for d, _ in staged])
        dispatches = 0
        fallback = 0
        rows = 0
        sizes = []
        # double-buffered pipeline (the streaming executor's overlap
        # pattern): while batch i executes on device, the host stages
        # + dispatches batch i+1 and unpacks batch i-1 — the fetch is
        # the only synchronization point
        inflight: deque = deque()
        for batch in batches:
            n_disp, n_fb, handle = self._converge_batch(batch)
            dispatches += n_disp
            fallback += n_fb
            rows += sum(len(self._docs[d].dec["client"]) for d in batch)
            sizes.append(len(batch))
            if handle is not None:
                inflight.append((batch, handle))
                if len(inflight) > 1:
                    self._finish_batch(*inflight.popleft())
            else:
                self._settle(batch)
        while inflight:
            self._finish_batch(*inflight.popleft())
        if tracer.enabled:
            tracer.count("tenant.docs_converged", len(dirty))
            tracer.gauge("tenant.dispatch_docs",
                         max(sizes) if sizes else 0)
            tracer.gauge("tenant.pending_bytes", self.pending_bytes())
            if fallback:
                tracer.count("tenant.fallback_docs", fallback)
        return TickReport(len(dirty), dispatches, rows, fallback,
                          tuple(sizes))

    # ---- converge engines --------------------------------------------

    def _finish_doc(self, doc_id, res) -> None:
        """One doc's packed result through the STOCK replay gather +
        materialize (res rows are local to the doc's decode) — the
        exact path, used for the per-doc baseline and every shape
        the vectorized unpack refuses."""
        st = self._docs[doc_id]
        dec, ds = st.dec, st.ds
        w, v, o = rp.gather(dec, ds, ("packed", res))
        st.cache = rp.materialize(dec, ds, w, v, o)
        st.digest = cache_digest(st.cache)
        st.n_ops = len(dec["client"])

    def _converge_one(self, doc_id) -> None:
        """Per-doc dispatch: the ordinary replay converge (packed /
        sharded / resident routes, exactly the one-shot pipeline)."""
        st = self._docs[doc_id]
        if not len(st.dec["client"]):
            self._finish_empty(doc_id)
            return
        handle = rp.converge(st.cols)
        w, v, o = rp.gather(st.dec, st.ds, handle)
        st.cache = rp.materialize(st.dec, st.ds, w, v, o)
        st.digest = cache_digest(st.cache)
        st.n_ops = len(st.dec["client"])

    def _converge_batch(self, batch) -> tuple:
        """Stage + (async) dispatch one batch. Returns (dispatches,
        fallback_docs, in-flight handle or None when the batch was
        settled synchronously)."""
        live = [d for d in batch
                if len(self._docs[d].dec["client"])]
        live_set = set(live)
        for d in batch:
            if d not in live_set:
                self._finish_empty(d)
        if len(live) == 0:
            return 0, 0, None
        if len(live) == 1 or not self.pack_docs:
            for d in live:
                self._converge_one(d)
            return len(live), 0, None
        comb, row_off = _concat_cols(
            [self._docs[d].cols for d in live]
        )
        handle = self._dispatch_async(comb)
        if handle is None:
            # the batch exceeded the packed staging bounds: degrade
            # to per-doc dispatches (correct, just un-amortized),
            # and say so in the evidence
            for d in live:
                self._converge_one(d)
            return len(live), len(live), None
        return 1, 0, (live, comb, row_off, handle)

    def _finish_batch(self, batch, work) -> None:
        """Fetch one in-flight batch dispatch, unpack per doc, stamp
        latencies/service bookkeeping."""
        from crdt_tpu.ops import shard as shard_ops

        live, comb, row_off, (route, h) = work
        fetch = (shard_ops.converge_fetch if route == "shard"
                 else packed.converge_fetch)
        self._unpack(live, comb, row_off, fetch(h))
        self._settle(batch)

    def _settle(self, batch) -> None:
        done = time.perf_counter()
        for d in batch:
            st = self._docs[d]
            self._pending_total -= sum(len(b) for b in st.pending)
            st.blobs.extend(st.pending)
            st.pending.clear()
            if st.dirty_since is not None:
                st.latency_s = done - st.dirty_since
            st.dirty_since = None
            st.served_tick = self.ticks

    def _finish_empty(self, doc_id) -> None:
        st = self._docs[doc_id]
        st.cache, st.n_ops = {}, 0
        st.digest = cache_digest({})

    def _dispatch_async(self, comb):
        """Enqueue one converge dispatch over the combined multi-doc
        columns: sharded route when active (partitioned by whole
        docs), the single-chip packed plan otherwise. Returns a
        (route, handle) pair for :meth:`_finish_batch`, or None when
        staging refused."""
        from crdt_tpu.ops import shard as shard_ops

        n = len(comb["client"])
        if shard_ops.active_for(n, self.shards):
            splan = shard_ops.stage(comb, n_shards=self.shards)
            if splan is not None:
                return ("shard", shard_ops.converge_async(splan))
        plan = packed.stage(comb)
        if plan is None:
            return None
        return ("packed", packed.converge_async(plan))

    # ---- the multi-doc unpack ----------------------------------------

    def _unpack(self, live, comb, row_off, res) -> None:
        """Split one combined result into per-doc caches/digests.

        The global work is vectorized ONCE for the whole batch: the
        visibility of every row against its own doc's delete ranges
        (doc-composite clients, one interval search), and a stable
        partition of the winner/stream arrays by doc (segments never
        cross docs, so each doc's slice keeps its oracle order; the
        stable sort also covers the sharded route, where shards emit
        docs out of submission order). Per doc, the plain shape gets
        the tight cache build; anything else replays its slice
        through the stock gather/materialize."""
        win_all = np.asarray(res.win_rows)
        win_all = win_all[win_all >= 0]
        srow_all = np.asarray(res.stream_row)
        sm = srow_all >= 0
        srow_all = srow_all[sm]
        sseg_all = np.asarray(res.stream_seg)[sm]
        wdoc = np.searchsorted(row_off, win_all, side="right") - 1
        worder = np.argsort(wdoc, kind="stable")
        win_all, wdoc = win_all[worder], wdoc[worder]
        sorder = np.argsort(sdoc := np.searchsorted(
            row_off, srow_all, side="right") - 1, kind="stable")
        srow_all, sseg_all, sdoc = (
            srow_all[sorder], sseg_all[sorder], sdoc[sorder]
        )
        D = len(live)
        wcut = np.searchsorted(wdoc, np.arange(D + 1))
        scut = np.searchsorted(sdoc, np.arange(D + 1))
        vis = _global_visibility(
            comb, [self._docs[d].ds for d in live]
        )
        hard = sorted(int(r) for r in res.hard_rows)
        hdocs = (set(
            (np.searchsorted(row_off, hard, side="right") - 1).tolist()
        ) if hard else frozenset())
        for i, d in enumerate(live):
            st = self._docs[d]
            lo, hi = int(row_off[i]), int(row_off[i + 1])
            has_hard = i in hdocs
            if st.fast_ok and not has_hard:
                st.cache = _fast_cache(
                    st.dec, lo,
                    win_all[wcut[i]:wcut[i + 1]],
                    srow_all[scut[i]:scut[i + 1]],
                    sseg_all[scut[i]:scut[i + 1]],
                    vis,
                )
                st.digest = cache_digest(st.cache)
                st.n_ops = len(st.dec["client"])
            else:
                self._finish_doc(d, packed.PackedResult(
                    win_rows=win_all[wcut[i]:wcut[i + 1]] - lo,
                    stream_seg=sseg_all[scut[i]:scut[i + 1]],
                    stream_row=srow_all[scut[i]:scut[i + 1]] - lo,
                    hard_rows=tuple(
                        r - lo for r in hard if lo <= r < hi
                    ),
                ))


def _concat_cols(cols_list):
    """Concatenate per-doc kernel columns into one multi-doc column
    set with the ``doc`` segment column, plus the caller-row offsets
    of each doc (``row_off[i] .. row_off[i+1]`` is doc i's range)."""
    comb = {
        k: np.concatenate([np.asarray(c[k]) for c in cols_list])
        for k in cols_list[0]
    }
    comb["doc"] = np.concatenate([
        np.full(len(c["client"]), i, np.int64)
        for i, c in enumerate(cols_list)
    ])
    row_off = np.cumsum(
        [0] + [len(c["client"]) for c in cols_list]
    )
    return comb, row_off


def _global_visibility(comb, ds_list):
    """Tombstone visibility for EVERY row of a combined batch in one
    interval search: clients compose with the doc column (one doc's
    delete ranges can never touch another doc's rows), delete
    triples from clients absent from the batch are dropped (they
    cannot cover any row). Returns a bool mask over the combined
    caller rows, or None when no doc carries tombstones (all
    visible)."""
    uniq = np.unique(np.asarray(comb["client"], np.int64))
    C = len(uniq) + 1
    dc: list = []
    dstart: list = []
    dend: list = []
    for i, ds in enumerate(ds_list):
        for c, s, n in ds.iter_all():
            r = int(np.searchsorted(uniq, c))
            if r < len(uniq) and uniq[r] == c:
                dc.append(i * C + r)
                dstart.append(s)
                dend.append(s + n)
    if not dc:
        return None
    comp = (
        np.asarray(comb["doc"], np.int64) * C
        + np.searchsorted(uniq, np.asarray(comb["client"], np.int64))
    )
    return rp.rows_visible(
        comp, np.asarray(comb["clock"], np.int64),
        np.asarray(dc, np.int64), np.asarray(dstart, np.int64),
        np.asarray(dend, np.int64),
    )


def _fast_cache(dec, lo, win, srow, sseg, vis) -> dict:
    """The tight cache build for a plain doc (see `_fast_unpack_ok`):
    map winners keyed into their root dicts, sequence streams cut at
    segment boundaries, tombstoned rows dropped — the exact cache the
    stock materialize produces for this shape (differential-pinned in
    tests/test_multidoc.py). ``win``/``srow`` are combined-space rows
    (``lo`` rebases), ``vis`` the global visibility mask (None = all
    visible)."""
    roots = dec["roots"]
    keys_t = dec["keys"]
    pr = dec["parent_root"]
    kid = dec["key_id"]
    contents = dec["contents"]
    cache: dict = {}
    if vis is None:
        for g in win.tolist():
            r = g - lo
            root = roots[pr[r]]
            grp = cache.get(root)
            if grp is None:
                grp = cache[root] = {}
            grp[keys_t[kid[r]]] = contents[r]
    else:
        for g, ok in zip(win.tolist(), vis[win].tolist()):
            if not ok:
                continue
            r = g - lo
            root = roots[pr[r]]
            grp = cache.get(root)
            if grp is None:
                grp = cache[root] = {}
            grp[keys_t[kid[r]]] = contents[r]
    if len(srow):
        edges = np.flatnonzero(sseg[1:] != sseg[:-1]) + 1
        cuts = [0] + edges.tolist() + [len(sseg)]
        for a, b in zip(cuts[:-1], cuts[1:]):
            rows_g = srow[a:b]
            first = int(rows_g[0]) - lo
            root = roots[pr[first]]
            if vis is None:
                vals = [contents[r - lo] for r in rows_g.tolist()]
            else:
                vals = [
                    contents[r - lo]
                    for r, ok in zip(rows_g.tolist(),
                                     vis[rows_g].tolist())
                    if ok
                ]
            if root not in cache:
                cache[root] = vals
    return cache
