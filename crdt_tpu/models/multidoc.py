"""Multi-tenant batched serving: one converge dispatch for many docs,
and delta-cost ticks for the docs the server already holds.

ROOFLINE.md pins a fixed per-dispatch floor on the tunnelled platform
(~6 ms on the v5e-class rig), so a server hosting thousands of SMALL
independent docs pays almost pure overhead when each doc converges in
its own dispatch — a 64-op doc costs the same floor as a 100k-op one.
This module is ROADMAP open item 2: amortize the floor by packing many
docs' deltas into ONE fused converge per tick (round 14), and make the
STEADY STATE — a 3-op delta landing on a 100k-op doc the server
already converged — cost a delta, not a cold replay (round 15).

The cold engine is the round-14 staging tentpole: doc-id is a
first-class segment column in :mod:`crdt_tpu.ops.packed` (client ids
fold into doc-composite ids, parent refs intern doc-major), so a whole
tenant batch converges in one program with per-doc outputs
byte-identical to each doc converged alone (tests/test_multidoc.py
pins {2, 3, 17} docs with mixed LWW/YATA ops, deletes, and empty docs
on both the single-chip and forced-2-device sharded routes — the
sharded partition places whole DOCS per chip first).

The warm engine is the round-15 tentpole — **delta ticks**: each doc
the server keeps serving holds RESIDENT state across ticks (an
:class:`crdt_tpu.models.incremental.IncrementalReplay`: device-side
converged matrix above the crossover, host winner/order caches always)
and a dirty doc whose new ops are **SV-admissible** to the incremental
route (per-client clocks contiguous with the resident state vector,
every origin/right/parent ref resolvable — the engine's own admission
gate, probed read-only by :meth:`IncrementalReplay.delta_admissible`)
stages ONLY its delta: the host path splices winners/orders in
O(delta), the device path ships the delta block against the resident
matrix (:func:`crdt_tpu.ops.packed.stage_resident_delta` +
``_splice_select_converge`` — history never restages). Anything else
falls back PER DOC to the stock cold replay through the round-14
packed batch: offset clocks (a gap the cold oracle would admit but the
engine would stash), an evicted resident, first sight. Fallbacks are
conservative — they cost a cold replay, never bytes — and the two
routes are digest-identical by construction (differential-pinned).

Resident memory is bounded: :class:`crdt_tpu.guard.tenant.
ResidentBudget` (``CRDT_TPU_MT_RESIDENT_BYTES``) ledgers each doc's
resident bytes; overflow evicts the least-recently-served docs'
resident state back to cold replay (``tenant.resident_evictions``),
enforced at every commit so the ledger never exceeds the budget —
evicted docs reconverge byte-identically on their next touch.

Serving discipline per tick:

- **submit** — per-tenant admission queues under the
  :class:`crdt_tpu.guard.tenant.TenantBudget` byte/count budget:
  a flooding tenant's own backlog is trimmed oldest-first
  (keep-the-newest), other tenants' queues and converged bytes are
  untouched (the round-10 "degrade, don't die" rule, tenant-scoped).
- **prepare** — the ingest-side work runs per doc OFF the tick:
  resident docs decode only their PENDING delta (plus the
  admissibility probe); cold docs decode their full history and
  stage kernel columns as before. ``tick()`` prepares any stale doc
  itself, so calling ``prepare()`` is an optimization, never a
  correctness requirement.
- **tick** — dirty docs order least-recently-served-first
  (:func:`crdt_tpu.guard.tenant.fair_order`) and route: admissible
  deltas splice into their resident engines (zero dispatches below
  the host/device crossover); docs served before but not resident
  PROMOTE (one engine build over the full history, budget
  permitting — the one-time warm cost that buys every later delta
  tick); the rest bin-pack into the round-14 cold dispatch batches
  (``max_rows_per_dispatch``, double-buffered async dispatches,
  vectorized unpack with the stock gather as exact fallback).
- **serve** — the live-ingest scheduler (round 15): a bounded tick
  loop over a STREAM of updates whose ingest hook drains the next
  batches while a tick's converge dispatches are in flight, so
  steady-state throughput is bounded by delta size, not doc size.

Per-doc digests are canonical (dict keys sorted at every depth — the
delta route builds map dicts in integration order, the cold
materialize in winner order) and LAZY: converging never digests;
:meth:`MultiDocServer.digest` / :meth:`doc_digests` compute on read
and cache per (op count, serve tick), so a beacon over a mostly-clean
doc population costs digest work only for the docs that moved
(``sentinel.doc_digest_skips``). They feed the multi-doc divergence
sentinel (:class:`crdt_tpu.obs.sentinel.MultiDocSentinel`), which
attributes a fork to the ONE doc that diverged.

Evidence: ``converge.docs_packed`` (docs per staged plan, counted at
the staging seam), ``tenant.*`` counters/gauges (README
"Observability" registry — round 15 adds ``tenant.delta_docs`` /
``delta_rows`` / ``promotions`` / ``delta_fallbacks`` /
``resident_evictions`` and the ``tenant.resident_bytes`` /
``resident_docs`` gauges), and the ``bench.py --multitenant`` legs:
round-14 packing (``docs_converged_per_s`` vs the one-dispatch-per-doc
baseline) plus the round-15 steady-state leg (N ticks of small deltas
on large resident docs vs the full-replay tick, ``steady.speedup``),
both digest-asserted against the cold oracle and regression-gated in
``tools/metrics_diff.py``.

Observability v2 (round 18): every admitted blob is SLO-stamped at
submit; the settle path closes its ingest-to-converged clock and the
tick end its ingest-to-served clock into the per-tenant ledger at
:attr:`MultiDocServer.slo` (:class:`crdt_tpu.obs.slo.SLOLedger` —
breach counters against ``slo_ms=`` / ``CRDT_TPU_SLO_MS``, burn-rate
gauges, delta/cold/fallback/shed route mix; a shed blob is a breach
by definition). Each tick also records its phase intervals and
dispatch in-flight windows into the process-global tick timeline
(:mod:`crdt_tpu.obs.timeline` — per-tick ``overlap_efficiency`` /
``stall_ms``, Perfetto export), and both are scrapeable live via
:class:`crdt_tpu.obs.http.ObsHTTPServer` while ``serve()`` runs.

Env knobs: ``CRDT_TPU_MT_MAX_ROWS`` (dispatch row cap, default
2^16), ``CRDT_TPU_MT_PENDING_BYTES`` / ``CRDT_TPU_MT_PENDING_UPDATES``
(per-tenant admission budget defaults), ``CRDT_TPU_MT_RESIDENT_BYTES``
(resident-state budget; unset = unbounded), ``CRDT_TPU_MT_DELTA_TICKS``
(``0`` pins every tick to the round-14 full-replay path),
``CRDT_TPU_SLO_MS`` (ingest-to-served objective, default 250).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from collections import deque
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence

import numpy as np

from crdt_tpu.guard.tenant import (
    ResidentBudget,
    TenantBudget,
    fair_order,
    pack_batches,
)
from crdt_tpu.models import replay as rp
from crdt_tpu.models.incremental import IncrementalReplay
from crdt_tpu.obs.slo import SLOLedger
from crdt_tpu.obs.timeline import get_timeline
from crdt_tpu.obs.tracer import get_tracer
from crdt_tpu.ops import packed

_MAX_ROWS_ENV = "CRDT_TPU_MT_MAX_ROWS"
_PENDING_BYTES_ENV = "CRDT_TPU_MT_PENDING_BYTES"
_PENDING_UPDATES_ENV = "CRDT_TPU_MT_PENDING_UPDATES"
_RESIDENT_BYTES_ENV = "CRDT_TPU_MT_RESIDENT_BYTES"
_DELTA_TICKS_ENV = "CRDT_TPU_MT_DELTA_TICKS"
_POOL_BYTES_ENV = "CRDT_TPU_MT_POOL_BYTES"


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    if raw == "":
        return default
    try:
        return max(1, int(raw))
    except ValueError:
        return default


def _canon(v, out: List[str]) -> None:
    if isinstance(v, dict):
        out.append("{")
        for k in sorted(v, key=str):
            out.append("%r:" % (k,))
            _canon(v[k], out)
            out.append(",")
        out.append("}")
    elif isinstance(v, (list, tuple)):
        # sequences of plain scalars (the overwhelming cache shape —
        # a 100k-element text stream) repr whole at C speed; only a
        # sequence that can reach a dict needs per-element recursion
        # for the key sort
        if not any(isinstance(x, (dict, list, tuple)) for x in v):
            out.append(repr(list(v) if isinstance(v, tuple) else v))
            return
        out.append("[")
        for x in v:
            _canon(x, out)
            out.append(",")
        out.append("]")
    else:
        out.append(repr(v))


def cache_digest(cache: dict) -> str:
    """Canonical digest of a converged cache: dict keys sorted at
    EVERY depth, sequence order preserved. Round 15 made the
    canonicalization recursive — the delta route's incremental
    engine builds its map dicts in integration order while the cold
    materialize builds them in winner order, and equal converged
    STATES must digest equal regardless of which route produced
    them. Document order (lists) is itself the converged output, so
    it stays order-sensitive."""
    out: List[str] = []
    _canon(cache, out)
    return hashlib.sha1("".join(out).encode()).hexdigest()[:16]


def _fast_unpack_ok(dec) -> bool:
    """May this doc take the vectorized unpack? Only the plain shape
    the tight cache build reproduces bit-for-bit: every row a
    root-parented content row (JSON/binary/string/any), no right
    origins, no reserved ``ix`` index root. Everything else routes
    through the stock replay gather/materialize."""
    from crdt_tpu.core.store import K_ANY, K_BINARY, K_JSON, K_STRING

    kind = np.asarray(dec["kind"])
    if len(kind) == 0:
        return True
    if not np.isin(kind, (K_JSON, K_BINARY, K_STRING, K_ANY)).all():
        return False
    if (np.asarray(dec["right_client"]) >= 0).any():
        return False
    if not (np.asarray(dec["parent_root"]) >= 0).all():
        return False
    return "ix" not in dec["roots"]


class _DocState:
    __slots__ = ("blobs", "pending", "in_flight", "cache", "n_ops",
                 "dirty_since", "latency_s", "served_tick",
                 "dec", "cols", "ds", "fast_ok", "stale",
                 "resident", "delta_dec", "delta_ok", "no_promote_len",
                 "pending_ts", "in_flight_ts",
                 "_digest", "_digest_key")

    def __init__(self):
        self.blobs: List[bytes] = []      # admitted, converged history
        self.pending: deque = deque()     # admitted, awaiting prepare
        # SLO stamps (round 18): one submit timestamp per pending /
        # in-flight blob, moved in lockstep with the blob queues so
        # the settle path can close each blob's ingest-to-converged
        # clock and the tick end its ingest-to-served clock
        self.pending_ts: deque = deque()
        self.in_flight_ts: List[float] = []
        # admitted blobs a prepared decode COVERS, still unconverged.
        # Live ingest (the serve() hook) can append to ``pending``
        # while this tick's dispatches are in flight; settle moves
        # exactly ``in_flight`` into history, so a mid-tick arrival
        # can never be marked converged without being converged.
        self.in_flight: List[bytes] = []
        self.cache: dict = {}
        self.n_ops: int = 0
        self.dirty_since: Optional[float] = None
        self.latency_s: Optional[float] = None
        self.served_tick: int = -1
        self.dec = None                   # prepared decode (full history)
        self.cols = None                  # prepared kernel columns
        self.ds = None                    # prepared delete set
        self.fast_ok = False
        self.stale = True                 # prepared state out of date
        # round 15: the delta-tick route
        self.resident: Optional[IncrementalReplay] = None
        self.delta_dec = None             # prepared PENDING-only decode
        self.delta_ok = False             # delta admissible this tick
        # history length (blob count) at which the engine last
        # refused this doc (stash leftovers / an inadmissible delta):
        # promotion retries only once the history has GROWN past it —
        # a later delta may fill the clock gap, so the pin is not
        # permanent, but an unchanged history is never re-attempted
        self.no_promote_len = -1
        self._digest: Optional[str] = None
        self._digest_key = None

    def history_len(self) -> int:
        return len(self.blobs) + len(self.in_flight) + \
            len(self.pending)


class TickReport(NamedTuple):
    docs: int              # docs converged this tick
    dispatches: int        # converge dispatches issued
    rows: int              # total staged rows (cold history + deltas)
    fallback_docs: int     # docs that fell back to per-doc dispatch
    batches: tuple = ()    # docs per dispatch, in dispatch order
    delta_docs: int = 0    # docs served via the resident delta route
    delta_rows: int = 0    # delta rows those docs staged (their whole
    #                        staging cost — history stayed resident)
    promotions: int = 0    # docs promoted to resident this tick
    pool_dispatches: int = 0  # pooled flush dispatches (round 20:
    #                           0 or 1 — every warm doc's device-route
    #                           delta batched into one converge)


class ServeReport(NamedTuple):
    ticks: int
    docs: int              # doc-serves summed over all ticks
    delta_docs: int
    cold_docs: int         # cold-replay serves (incl. promotions)
    promotions: int
    dispatches: int
    submitted: int         # updates admitted from the source


class MultiDocServer:
    """Tick-batched multi-tenant converge server (see module doc).

    A tick serves each dirty doc by the cheapest EXACT route: an
    SV-admissible delta splices into the doc's resident incremental
    engine (delta-cost — the steady state); otherwise the doc
    re-converges its full admitted history through the round-14
    packed cold path (the same replay semantics every differential
    suite oracles against), so per-doc outputs are always exactly
    what ``replay_trace`` of the same blobs yields.
    ``delta_ticks=False`` (or ``pack_docs=False`` for the
    one-dispatch-per-doc shape) degrades to the stock full-replay
    tick — the baselines the bench legs measure against."""

    def __init__(self, *, max_rows_per_dispatch: Optional[int] = None,
                 tenant_max_pending_bytes: Optional[int] = None,
                 tenant_max_pending_updates: Optional[int] = None,
                 shards: Optional[int] = None,
                 pack_docs: bool = True,
                 delta_ticks: Optional[bool] = None,
                 resident_max_bytes: Optional[int] = None,
                 slo_ms: Optional[float] = None,
                 pool: Optional[bool] = None,
                 pool_max_bytes: Optional[int] = None,
                 snap_store=None,
                 control=None,
                 checkpoint_every_ticks: Optional[int] = None,
                 checkpoint_every_bytes: Optional[int] = None):
        self.max_rows = (max_rows_per_dispatch
                         if max_rows_per_dispatch is not None
                         else _env_int(_MAX_ROWS_ENV, 1 << 16))
        self.budget = TenantBudget(
            max_bytes=(tenant_max_pending_bytes
                       if tenant_max_pending_bytes is not None
                       else _env_int(_PENDING_BYTES_ENV, 1 << 22)),
            max_updates=(tenant_max_pending_updates
                         if tenant_max_pending_updates is not None
                         else _env_int(_PENDING_UPDATES_ENV, 4096)),
        )
        if delta_ticks is None:
            delta_ticks = os.environ.get(_DELTA_TICKS_ENV, "1") != "0"
        self.delta_ticks = bool(delta_ticks)
        if resident_max_bytes is None:
            env = os.environ.get(_RESIDENT_BYTES_ENV, "")
            resident_max_bytes = int(env) if env else None
        self.rbudget = ResidentBudget(resident_max_bytes)
        # pooled resident matrix (round 20): every promoted engine
        # shares ONE device allocation, and the tick's above-crossover
        # deltas batch into ONE flush dispatch. ``pool=False`` (or
        # CRDT_TPU_MT_POOL_BYTES=0) keeps the per-doc private
        # matrices — the unpooled oracle the differential suite and
        # the bench baseline measure against. Construction is host
        # bookkeeping only; the matrix allocates on the first flush.
        if pool_max_bytes is None:
            env = os.environ.get(_POOL_BYTES_ENV, "")
            pool_max_bytes = int(env) if env else None
        if pool is None:
            pool = pool_max_bytes != 0
        self.pool = None
        if self.delta_ticks and pool and pool_max_bytes != 0:
            from crdt_tpu.ops.resident import ResidentPool

            self.pool = ResidentPool(max_bytes=pool_max_bytes)
        # snapshot store (round 21): when attached (explicitly or
        # via CRDT_TPU_SNAP_DIR), evicted residents write a snapshot
        # on the way out and promotions rehydrate from it instead of
        # rebuilding over the full history; checkpoint()/restore()
        # round-trip the WHOLE resident set through it. Absent store
        # = every path below is stock round-15 behavior.
        if snap_store is None:
            from crdt_tpu.storage.snapshot import store_from_env

            snap_store = store_from_env()
        self.snap_store = snap_store
        self.shards = shards
        self.pack_docs = pack_docs
        self.ticks = 0
        self.shed_count = 0
        self.shed_bytes = 0
        self.eviction_count = 0
        self.delta_fallback_count = 0
        self._docs: Dict = {}
        # docs already served by the CURRENT tick (aliased to the
        # tick loop's set): protected best-effort from budget sweeps
        self._serving: set = set()
        # live-ingest hook (serve()): called while a tick's converge
        # dispatches are in flight, so the NEXT tick's decode overlaps
        # this tick's device work
        self._ingest_hook: Optional[Callable[[], int]] = None
        # running pending-queue byte total: the gauge (and the
        # public accessor) must not re-scan every tenant's deque on
        # each admitted blob — ingest stays O(1) per update
        self._pending_total = 0
        # per-tenant SLO ledger (round 18): submit stamps close at
        # settle (ingest-to-converged) and tick end (ingest-to-
        # served); sheds fold into the breach ledger. ``slo_ms=None``
        # reads CRDT_TPU_SLO_MS (default 250 ms).
        self.slo = SLOLedger(slo_ms)
        # (tenant, submit stamps) settled this tick, awaiting the
        # tick-end served stamp
        self._served_buf: List = []
        # control plane (round 22): a deterministic per-tick rule
        # engine over the sensors above (burn rates, queue pressure,
        # settled bytes) actuating the knobs above (tenant budget
        # overrides, LRU protection, max_rows pacing, checkpoint
        # cadence). ``control=True`` builds the default
        # :class:`crdt_tpu.obs.control.Controller`; a Controller
        # instance is adopted as-is; ``None``/``False`` with no
        # cadence params disables the whole phase (zero tick cost).
        # ``checkpoint_every_ticks=``/``checkpoint_every_bytes=``
        # ride the controller's actuation path (ROADMAP item 4c) —
        # setting either implies a controller.
        if control is True or (
            control is None
            and (checkpoint_every_ticks or checkpoint_every_bytes)
        ):
            from crdt_tpu.obs.control import Controller

            control = Controller()
        self.control = control or None
        if self.control is not None:
            if checkpoint_every_ticks is not None:
                self.control.checkpoint_every_ticks = int(
                    checkpoint_every_ticks)
            if checkpoint_every_bytes is not None:
                self.control.checkpoint_every_bytes = int(
                    checkpoint_every_bytes)
        # docs on control-squeezed tenants: shielded from the LRU
        # sweep (best-effort, like ``_serving`` — the budget bound
        # stays hard)
        self._protected: set = set()
        # settled-byte odometer for the bytes-based cadence rule
        self._settled_since_ckpt = 0
        self.cadence_checkpoints = 0
        # deterministic snapshot-fallback odometer (the tracer's
        # ``snap.fallbacks`` counter is enabled-gated; the control
        # sensor must not be)
        self.snap_fallback_count = 0

    # ---- admission (the ingest side) ---------------------------------

    def submit(self, doc_id, blob: bytes) -> int:
        """Admit one update blob for ``doc_id``. Returns how many of
        the tenant's pending updates were SHED to fit its budget (0 =
        admitted with room)."""
        st = self._docs.setdefault(doc_id, _DocState())
        now = time.perf_counter()
        if st.dirty_since is None:
            st.dirty_since = now
        st.pending.append(bytes(blob))
        st.pending_ts.append(now)
        self._pending_total += len(blob)
        st.stale = True
        tracer = get_tracer()
        if tracer.enabled:
            tracer.count("tenant.submitted")
        shed_n = self._trim_tenant(doc_id, st)
        if tracer.enabled:
            tracer.gauge("tenant.pending_bytes", self.pending_bytes())
        return shed_n

    def _trim_tenant(self, doc_id, st) -> int:
        """Apply the tenant's admission budget — the static one, or
        a control-plane override (:meth:`crdt_tpu.guard.tenant.
        TenantBudget.limits`) — to its pending queue, with the full
        shed bookkeeping: shed counters, SLO breaches, submit-stamp
        lockstep. Called per submit, and by the control phase right
        after a squeeze (immediate containment: the flooder's
        backlog shrinks THIS tick, not on its next submit)."""
        shed = self.budget.trim(st.pending, tenant=doc_id)
        if not shed:
            return 0
        nbytes = sum(len(b) for b in shed)
        self.shed_count += len(shed)
        self.shed_bytes += nbytes
        self._pending_total -= nbytes
        # trim pops oldest-first; the stamp queue follows in
        # lockstep, and every shed blob is an SLO breach (it will
        # never be served)
        for _ in shed:
            st.pending_ts.popleft()
        self.slo.shed(doc_id, len(shed))
        tracer = get_tracer()
        if tracer.enabled:
            tracer.count("tenant.shed", len(shed))
            tracer.count("tenant.shed_bytes", nbytes)
        return len(shed)

    def submit_many(self, doc_id, blobs: Sequence[bytes]) -> int:
        if not blobs:
            # registering an empty doc: a NEW state, already settled
            # (nothing to decode, cache/digest default to empty). An
            # EXISTING doc is left completely untouched — clearing
            # its stale flag here would make prepare() skip a dirty
            # doc and tick() read outdated columns
            if doc_id not in self._docs:
                st = _DocState()
                st.stale = False
                self._docs[doc_id] = st
            return 0
        return sum(self.submit(doc_id, b) for b in blobs)

    def prepare(self) -> int:
        """Run the ingest-side work for every stale doc: resident
        docs decode only their PENDING delta and probe admissibility;
        cold docs decode + stage their full admitted history. Docs
        that will PROMOTE this tick are left to the tick (the engine
        build decodes for itself — a throwaway cold staging would be
        pure waste). Idempotent; the tick calls it for anything the
        ingest thread has not covered. Returns the number of docs
        prepared."""
        n = 0
        for d, st in list(self._docs.items()):
            if not st.stale:
                continue
            st.delta_ok = False
            if self.delta_ticks and (st.pending or st.in_flight):
                if st.resident is not None:
                    self._take_pending(st)
                    dec = IncrementalReplay.decode_delta(st.in_flight)
                    if st.resident.delta_admissible(dec):
                        st.delta_dec = dec
                        st.delta_ok = True
                        st.stale = False
                        n += 1
                        continue
                    # inadmissible (offset clocks, unresolvable
                    # refs): the resident engine cannot absorb this
                    # delta exactly — release it, cold-replay
                    self._drop_resident(d)
                if self._promotable(st):
                    # leave stale: the tick's promotion decodes for
                    # itself, or cold-prepares on a budget refusal
                    continue
            self._prepare_cold_one(st)
            n += 1
        return n

    @staticmethod
    def _take_pending(st) -> None:
        """Move the admission queue into the in-flight window a
        prepared decode will cover (see ``_DocState.in_flight``)."""
        if st.pending:
            st.in_flight.extend(st.pending)
            st.pending.clear()
            st.in_flight_ts.extend(st.pending_ts)
            st.pending_ts.clear()

    def _prepare_cold_one(self, st) -> None:
        self._take_pending(st)
        dec = rp.decode(st.blobs + st.in_flight)
        st.cols, st.ds = rp.stage(dec)
        st.dec = dec
        st.fast_ok = _fast_unpack_ok(dec)
        st.stale = False

    def _promotable(self, st) -> bool:
        return (self.delta_ticks and st.resident is None
                and st.history_len() != st.no_promote_len
                and st.served_tick >= 0)

    def pending_bytes(self) -> int:
        return self._pending_total

    def dirty_docs(self) -> List:
        return [d for d, st in self._docs.items()
                if st.pending or st.in_flight]

    # ---- results -----------------------------------------------------

    def doc_ids(self) -> List:
        return list(self._docs)

    def cache(self, doc_id) -> dict:
        return self._cache_of(self._docs[doc_id])

    @staticmethod
    def _cache_of(st) -> dict:
        # resident docs serve the engine's LAZY view: a delta tick
        # never materializes (the engine only marks touched segments
        # dirty); the flush happens here, on read — the engine's own
        # cache contract, surfaced through the server
        return st.resident.cache if st.resident is not None \
            else st.cache

    def digest(self, doc_id) -> str:
        """Canonical digest of the doc's converged cache, computed
        LAZILY and cached per (op count, serve tick): converging
        never digests, and a clean doc re-beacons at zero digest
        cost (round-15 satellite)."""
        return self._digest_of(self._docs[doc_id])

    def _digest_of(self, st) -> str:
        key = (st.n_ops, st.served_tick)
        if st._digest is None or st._digest_key != key:
            st._digest = cache_digest(self._cache_of(st))
            st._digest_key = key
        return st._digest

    def latency_s(self, doc_id) -> Optional[float]:
        """Submit-to-converged latency of the doc's last service."""
        return self._docs[doc_id].latency_s

    def is_resident(self, doc_id) -> bool:
        """Does this doc currently hold resident incremental state
        (vs. cold-replaying on its next touch)?"""
        return self._docs[doc_id].resident is not None

    def resident_doc_count(self) -> int:
        return self.rbudget.docs()

    def resident_bytes_total(self) -> int:
        return self.rbudget.total

    def resident_peak_bytes(self) -> int:
        return self.rbudget.peak

    def doc_digests(self) -> Dict:
        """The multi-doc sentinel's beacon source: per-doc digest +
        op count (the count is the lag guard — unequal counts are
        propagation lag, not a fork). Digests cached per (op count,
        serve tick): docs untouched since the last beacon are
        SKIPPED, counted as ``sentinel.doc_digest_skips`` — a beacon
        over a mostly-clean population costs digest work only for
        the docs that moved."""
        tracer = get_tracer()
        skips = 0
        out = {}
        for d, st in self._docs.items():
            if (st._digest is not None
                    and st._digest_key == (st.n_ops, st.served_tick)):
                skips += 1
            out[d] = {"digest": self._digest_of(st), "ops": st.n_ops}
        if tracer.enabled and skips:
            tracer.count("sentinel.doc_digest_skips", skips)
        return out

    # ---- the tick loop -----------------------------------------------

    def tick(self) -> TickReport:
        """Converge every dirty doc: fairness-ordered admission, then
        per doc the cheapest exact route — admissible deltas through
        the resident engines, promotions for warm docs without one,
        bin-packed cold dispatch batches for the rest (see module
        doc). One tick fully drains the dirty set — fairness decides
        WHO goes first, the row cap decides how many dispatches."""
        self.ticks += 1
        tl = get_timeline()
        tl.tick_begin(self.ticks)
        # control phase (round 22) FIRST: the rules read the sensor
        # state the PREVIOUS tick settled (burn rates, queue bytes),
        # actuate the knobs this tick runs under, and fire BEFORE the
        # idle early-return so the checkpoint cadence covers quiet
        # ticks too
        if self.control is not None:
            with tl.phase("control"):
                self._run_control(tl)
        with tl.phase("prepare"):
            self.prepare()
        with tl.phase("fair_order"):
            dirty = fair_order(self.dirty_docs(),
                               {d: self._docs[d].served_tick
                                for d in self._docs})
        if not dirty:
            tl.tick_end()
            return TickReport(0, 0, 0, 0)
        tracer = get_tracer()
        # route decision per dirty doc. Promotion-time eviction must
        # not thrash docs ALREADY served this tick (their resident
        # state is freshest), so those are protected from the
        # budget's LRU sweep; docs still waiting their turn are fair
        # game — they reroute to the cold path when it comes.
        served_set: set = set()
        self._serving = served_set
        delta_served: List = []
        cold: List = []
        delta_rows = 0
        promotions = 0
        try:
            with tl.phase("route"):
                for d in dirty:
                    st = self._docs[d]
                    if st.delta_ok and st.resident is not None:
                        delta_rows += self._apply_delta(d)
                        delta_served.append(d)
                        served_set.add(d)
                        continue
                    if st.stale:
                        if self._try_promote(
                            d,
                            protect=(served_set | {d}
                                     | self._protected),
                        ):
                            promotions += 1
                            served_set.add(d)
                            continue
                        self._prepare_cold_one(st)
                    cold.append(d)
        finally:
            self._serving = set()
        pool_disp = 0
        if self.pool is not None and self.pool.has_pending():
            # the tick's ONE pooled dispatch (round 20): every warm
            # doc's above-crossover delta deferred during routing
            # splices + converges here, before anything settles or
            # reads — O(1) device-route dispatches per tick however
            # many docs went warm
            with tl.phase("pool"):
                pool_disp = self.pool.flush()
        with tl.phase("settle"):
            for d in delta_served:
                self._settle([d], route="delta")
        n_delta = len(delta_served)

        staged = [(d, len(self._docs[d].dec["client"])) for d in cold]
        batches = (pack_batches(staged, self.max_rows)
                   if self.pack_docs else [[d] for d, _ in staged])
        dispatches = pool_disp
        fallback = 0
        rows = delta_rows
        sizes = []
        # double-buffered pipeline (the streaming executor's overlap
        # pattern): while batch i executes on device, the host stages
        # + dispatches batch i+1, unpacks batch i-1, and drains the
        # live-ingest hook — the fetch is the only synchronization
        # point
        inflight: deque = deque()
        for batch in batches:
            with tl.phase("pack"):
                n_disp, n_fb, handle = self._converge_batch(batch)
            dispatches += n_disp
            fallback += n_fb
            rows += sum(len(self._docs[d].dec["client"]) for d in batch)
            sizes.append(len(batch))
            if handle is not None:
                inflight.append((batch, handle, tl.dispatch_begin()))
                hook = self._ingest_hook
                if hook is not None:
                    # ingest overlaps the in-flight dispatch
                    with tl.phase("ingest"):
                        hook()
                if len(inflight) > 1:
                    self._finish_batch(*inflight.popleft())
            else:
                with tl.phase("settle"):
                    self._settle(
                        batch, route="fallback" if n_fb else "cold"
                    )
        while inflight:
            self._finish_batch(*inflight.popleft())
        self.rbudget.note_peak()
        # SLO: everything settled this tick became READABLE now —
        # the ingest-to-served clock closes at the tick boundary,
        # not at each batch's settle (a reader sees tick state)
        if self._served_buf:
            t_served = time.perf_counter()
            for tenant, stamps in self._served_buf:
                self.slo.served(
                    tenant, (t_served - t for t in stamps)
                )
            self._served_buf.clear()
            self.slo.publish_worst()
        tl.tick_end()
        if tracer.enabled:
            tracer.count("tenant.docs_converged", len(dirty))
            tracer.gauge("tenant.dispatch_docs",
                         max(sizes) if sizes else 0)
            tracer.gauge("tenant.pending_bytes", self.pending_bytes())
            tracer.gauge("tenant.resident_bytes", self.rbudget.total)
            tracer.gauge("tenant.resident_docs", self.rbudget.docs())
            if self.pool is not None:
                tracer.gauge("tenant.pool_bytes",
                             self.pool.device_bytes())
                tracer.gauge("tenant.pool_docs",
                             self.pool.doc_count())
            if n_delta:
                tracer.count("tenant.delta_docs", n_delta)
            if delta_rows:
                tracer.count("tenant.delta_rows", delta_rows)
            if promotions:
                tracer.count("tenant.promotions", promotions)
            if fallback:
                tracer.count("tenant.fallback_docs", fallback)
        return TickReport(len(dirty), dispatches, rows, fallback,
                          tuple(sizes), n_delta, delta_rows,
                          promotions, pool_disp)

    # ---- the control plane (round 22) --------------------------------

    def _run_control(self, tl) -> None:
        """One controller consult per tick: build the JSON-ready
        sensor snapshot (per-tenant burn/shed from the SLO ledger,
        queue + pool + resident pressure, the settled-byte odometer),
        run the deterministic rules, apply the actuation — budget
        overrides with an IMMEDIATE trim of the squeezed backlog,
        the LRU protection set, the ``max_rows`` setpoint, a cadence
        checkpoint — and annotate every decision into the tick
        timeline as a Perfetto instant."""
        slo = self.slo.control_snapshot()
        tenants = {}
        byname = {}
        for d, s in slo.items():
            st = self._docs.get(d)
            pend = 0
            if st is not None:
                pend = (sum(len(b) for b in st.pending)
                        + sum(len(b) for b in st.in_flight))
            name = str(d)
            byname[name] = d
            tenants[name] = {
                "burn": s["burn"],
                "shed": int(s["shed"]),
                "breaches": int(s["breaches"]),
                "pending_bytes": pend,
            }
        sensors = {
            "tick": self.ticks,
            "max_rows": self.max_rows,
            "pending_bytes": self._pending_total,
            "settled_bytes": self._settled_since_ckpt,
            "budget": {
                "max_bytes": self.budget.max_bytes,
                "max_updates": self.budget.max_updates,
            },
            "tenants": tenants,
            "pool_bytes": (self.pool.device_bytes()
                           if self.pool is not None else 0),
            "pool_compactions": (self.pool.compactions
                                 if self.pool is not None else 0),
            "resident_bytes": self.rbudget.total,
            "snap_fallbacks": self.snap_fallback_count,
        }
        act = self.control.observe(sensors)
        # reconcile the budget override set (controller answers the
        # FULL set, keyed by stringified tenant — map back to the
        # server's own doc ids)
        for t in list(self.budget.overrides()):
            if str(t) not in act.tenant_limits:
                self.budget.clear_override(t)
        for name in sorted(act.tenant_limits):
            mb, mu = act.tenant_limits[name]
            t = byname.get(name, name)
            self.budget.set_override(t, mb, mu)
            st = self._docs.get(t)
            if st is not None and st.pending:
                # immediate containment: the flooder's backlog
                # shrinks to the squeezed budget THIS tick
                self._trim_tenant(t, st)
        self._protected = {byname.get(n, n) for n in act.protect}
        if act.max_rows is not None:
            self.max_rows = int(act.max_rows)
        if act.checkpoint and self.snap_store is not None:
            # background cadence checkpoint (ROADMAP item 4c): a
            # restart replays at most one cadence of WAL tail
            self.checkpoint()
            self.cadence_checkpoints += 1
            tracer = get_tracer()
            if tracer.enabled:
                tracer.count("snap.cadence_writes")
        for row in act.rows:
            tl.instant("control:%s" % row["rule"], {
                "tenant": row["tenant"],
                "knob": row["knob"],
                "old": row["old"],
                "new": row["new"],
            })

    # ---- the live-ingest scheduler -----------------------------------

    def serve(self, source, *, max_ticks: Optional[int] = None,
              idle_ticks: int = 1) -> ServeReport:
        """Live-ingest tick loop (round 15): drive the server against
        a STREAM of updates instead of a pre-drained backlog.
        ``source`` is an iterator whose each ``next()`` yields an
        iterable of ``(doc_id, blob)`` pairs (or None for an idle
        poll); exhaustion means the stream drained. Each loop
        iteration admits one batch and ticks; while a tick's converge
        dispatches are IN FLIGHT the ingest hook drains further
        batches into the admission queues, so the next tick's decode
        overlaps this tick's device work (the streaming executor's
        overlap discipline at the server level). The loop is bounded:
        ``max_ticks`` caps it hard, and it stops after ``idle_ticks``
        consecutive empty ticks (immediately, once the source is
        exhausted and nothing is dirty)."""
        it = iter(source)
        state = {"exhausted": False, "submitted": 0}

        def pull() -> int:
            if state["exhausted"]:
                return 0
            try:
                batch = next(it)
            except StopIteration:
                state["exhausted"] = True
                return 0
            n = 0
            for doc_id, blob in (batch or ()):
                self.submit(doc_id, blob)
                n += 1
            state["submitted"] += n
            return n

        ticks = docs = delta = promo = disp = idle = 0
        while max_ticks is None or ticks < max_ticks:
            pull()
            self._ingest_hook = pull
            try:
                rep = self.tick()
            finally:
                self._ingest_hook = None
            ticks += 1
            docs += rep.docs
            delta += rep.delta_docs
            promo += rep.promotions
            disp += rep.dispatches
            if rep.docs == 0:
                if state["exhausted"] and not self.dirty_docs():
                    break
                idle += 1
                if idle >= idle_ticks:
                    break
            else:
                idle = 0
        return ServeReport(ticks, docs, delta, docs - delta, promo,
                           disp, state["submitted"])

    # ---- the delta route (round 15) ----------------------------------

    def _apply_delta(self, d) -> int:
        """One admissible delta through the doc's resident engine:
        the delta rows are the only staging this doc pays — host-
        exact splices below the crossover, a delta-only device
        splice against the resident matrix above it."""
        st = self._docs[d]
        dec, st.delta_dec, st.delta_ok = st.delta_dec, None, False
        k = int(len(dec["client"]))
        st.resident.apply_decoded(dec)
        self._adopt_engine(d)
        return k

    def _try_promote(self, d, *, protect=frozenset()) -> bool:
        """Build a resident engine over the doc's full history (the
        one-time warm cost that buys every later delta tick). Refused
        when the budget cannot fit the ESTIMATED footprint even after
        LRU eviction, or when the engine cannot settle the history
        exactly (stashed/rootless leftovers — offset clocks, refs
        that never arrive: such a doc stays cold until its history
        GROWS again, when a retry may find the gap filled)."""
        st = self._docs[d]
        if not self._promotable(st):
            return False
        self._take_pending(st)
        est_rows = (st.n_ops
                    + sum(len(b) for b in st.in_flight) // 8 + 1)
        est = IncrementalReplay.estimate_resident_bytes(est_rows)
        if not self.rbudget.fits(
            est, lru=self._lru_residents(protect),
            evict=self._evict_resident,
        ):
            return False
        eng = self._rehydrate_candidate(d, st)
        if eng is None:
            eng = IncrementalReplay(pool=self.pool)
            eng.apply(st.blobs + st.in_flight)
        if eng._pending or eng._rootless:
            st.no_promote_len = st.history_len()
            self._release_pool(eng)
            return False
        st.resident = eng
        self._adopt_engine(d)
        self._settle([d])
        return True

    def _rehydrate_candidate(self, d, st):
        """The round-21 promotion shortcut: a stored snapshot whose
        coverage is a PREFIX of the doc's admitted history
        rehydrates and applies only the uncovered tail — the
        eviction-then-resubmit case pays delta cost, not a full
        engine rebuild. Any problem (damage, coverage skew, a tail
        that stashes) returns None and the stock full-history build
        runs; correctness never depends on the snapshot."""
        if self.snap_store is None:
            return None
        loaded = self.snap_store.load_latest(d)
        if loaded is None:
            return None
        snap, seq = loaded
        if seq > len(st.blobs):
            self.snap_fallback_count += 1
            tracer = get_tracer()
            if tracer.enabled:
                tracer.count("snap.fallbacks",
                             labels={"reason": "coverage"})
            return None
        from crdt_tpu.storage.snapshot import rehydrate

        eng = None
        try:
            eng = rehydrate(snap, pool=self.pool)
            eng.apply(st.blobs[seq:] + st.in_flight)
        except ValueError:
            if eng is not None:
                self._release_pool(eng)
            self.snap_fallback_count += 1
            tracer = get_tracer()
            if tracer.enabled:
                tracer.count("snap.fallbacks",
                             labels={"reason": "rehydrate"})
            return None
        if eng._pending or eng._rootless:
            # the tail did not settle over this snapshot (foreign or
            # skewed coverage): fall back to the stock build rather
            # than pinning no_promote_len on the doc
            self._release_pool(eng)
            self.snap_fallback_count += 1
            tracer = get_tracer()
            if tracer.enabled:
                tracer.count("snap.fallbacks",
                             labels={"reason": "tail_stash"})
            return None
        return eng

    def _adopt_engine(self, d) -> None:
        """Commit a doc's engine-converged state: op count from the
        engine, digest invalidated (the cache itself stays LAZY —
        reads flush it through :meth:`_cache_of`, so a delta tick
        pays zero materialization), resident bytes ledgered — and
        the budget enforced at the commit, so the ledger NEVER
        exceeds it (a doc that alone outgrows the whole budget is
        evicted on the spot and stays cold until its history
        grows)."""
        st = self._docs[d]
        st.n_ops = st.resident.cols.n
        st._digest = None
        self.rbudget.set_doc(d, st.resident.resident_bytes())
        if self.rbudget.max_bytes is not None:
            # protection is best-effort (docs already served this
            # tick hold the freshest state — evicting one buys a
            # full re-promotion on its next delta), the bound is
            # hard: if the protected sweep cannot reach it, sweep
            # again without protection, and a doc that ALONE
            # outgrows the whole budget is evicted on the spot (and
            # not re-attempted until its history grows)
            self._enforce_budget(
                protect={d} | self._serving | self._protected
            )
            if self.rbudget.total > self.rbudget.max_bytes:
                self._enforce_budget(protect={d})
            if self.rbudget.total > self.rbudget.max_bytes:
                self._evict_resident(d)
                st.no_promote_len = st.history_len()
        self.rbudget.note_peak()

    def _release_pool(self, eng) -> None:
        """Free a discarded engine's pooled extent (LRU eviction,
        delta fallback, failed promotion). Release may trigger the
        pool's bounded compaction — the hole squeeze the
        ``tenant.pool_compactions`` counter evidences. The engine's
        own read path already flushed any deferred round (cache
        materializes before every release site)."""
        if eng is not None and eng.pool is not None:
            eng.pool.release(eng)
            eng.pool = None

    def _lru_residents(self, protect=frozenset()) -> List:
        return sorted(
            (d for d, st in self._docs.items()
             if st.resident is not None and d not in protect),
            key=lambda d: (self._docs[d].served_tick, str(d)),
        )

    def _enforce_budget(self, protect=frozenset()) -> None:
        for d in self._lru_residents(protect):
            if self.rbudget.total <= self.rbudget.max_bytes:
                break
            self._evict_resident(d)

    def _evict_resident(self, d) -> None:
        """Budget pressure: release the doc's resident state back to
        cold replay. Its converged cache stays served; only the
        engine memory goes — the doc reconverges byte-identically
        (cold, or via a fresh promotion) on its next touch."""
        st = self._docs[d]
        if st.resident is None:
            return
        st.cache = st.resident.cache  # materialize the lazy view
        self._snapshot_on_evict(d, st)
        self._release_pool(st.resident)
        st.resident = None
        st.delta_dec = None
        st.delta_ok = False
        if st.pending or st.in_flight:
            st.stale = True  # re-route what was prepared as a delta
        self.rbudget.drop_doc(d)
        self.eviction_count += 1
        tracer = get_tracer()
        if tracer.enabled:
            tracer.count("tenant.resident_evictions")
            tracer.gauge("tenant.resident_bytes", self.rbudget.total)
            tracer.gauge("tenant.resident_docs", self.rbudget.docs())

    def _snapshot_on_evict(self, d, st) -> None:
        """Kill the eviction cold-start tax (round 21): a resident
        doc leaving the budget writes a snapshot covering its
        settled ``blobs`` prefix, so eviction-then-resubmit
        rehydrates + applies the delta instead of re-replaying the
        whole history. Budget-permitting and best-effort: a refused
        or failed write (counted inside the store) just means the
        next promotion pays the stock rebuild. Skipped when the
        engine's coverage is ambiguous (un-settled in-flight blobs)
        — a wrong coverage cursor would be corrected by the
        tail-stash fallback, but never writing it is cheaper."""
        if self.snap_store is None or st.in_flight:
            return
        from crdt_tpu.storage.snapshot import encode_engine

        try:
            payload = encode_engine(st.resident, seq=len(st.blobs))
        except ValueError:
            return
        if self.snap_store.write(d, payload, len(st.blobs)):
            tracer = get_tracer()
            if tracer.enabled:
                tracer.count("snap.evict_writes")

    def _drop_resident(self, d) -> None:
        """Inadmissible delta: the resident engine cannot absorb it
        exactly — release it and fall back to the cold route (the
        conservative direction: a fallback costs a cold replay,
        never bytes). The refusal also stamps ``no_promote_len``: a
        promotion over this SAME history would stash the same rows
        the probe just refused, so the guaranteed-futile full engine
        build is skipped until new history arrives."""
        st = self._docs[d]
        if st.resident is None:
            return
        st.cache = st.resident.cache  # materialize the lazy view
        self._release_pool(st.resident)
        st.resident = None
        st.delta_dec = None
        st.delta_ok = False
        st.no_promote_len = st.history_len()
        self.rbudget.drop_doc(d)
        self.delta_fallback_count += 1
        tracer = get_tracer()
        if tracer.enabled:
            tracer.count("tenant.delta_fallbacks")

    # ---- checkpoint / restore (round 21) -----------------------------

    def checkpoint(self, store=None, *, fence=None) -> int:
        """Snapshot the WHOLE resident set into ``store`` (default:
        the attached ``snap_store``). Per resident doc: one snapshot
        generation covering its settled ``blobs`` prefix plus a
        sidecar history blob (``encode_state_as_update`` — the
        encode is paid NOW so a restore never decodes more than it
        must), tied together by a manifest sidecar. Docs with
        un-settled in-flight state are skipped (call between ticks
        for full coverage). Returns the number of docs
        checkpointed; counted ``tenant.checkpoint_docs``.

        ``fence`` (round 24): a lease view — ``.proc`` plus
        ``.epoch_of(doc)`` (``fleet.placement.LeaseTable`` or any
        duck-type) — stamps the checkpoint with the fencing epochs
        this process held per doc (a separate ``checkpoint.fence``
        blob; the manifest shape is unchanged). ``restore(fence=)``
        refuses docs stamped NEWER than the restoring process's
        lease."""
        from crdt_tpu.storage.snapshot import encode_engine

        store = store if store is not None else self.snap_store
        if store is None:
            raise ValueError("checkpoint: no snapshot store attached")
        tracer = get_tracer()
        manifest = {}
        done = 0
        for d, st in sorted(self._docs.items(), key=lambda kv:
                            str(kv[0])):
            if st.resident is None or st.in_flight:
                continue
            seq = len(st.blobs)
            try:
                payload = encode_engine(st.resident, seq=seq)
            except ValueError:
                continue
            if not store.write(d, payload, seq):
                continue
            hist = st.resident.encode_state_as_update()
            store.put_blob("%s.hist" % d, hist)
            manifest[str(d)] = {"seq": seq, "n_ops": st.n_ops}
            done += 1
            if tracer.enabled:
                tracer.count("tenant.checkpoint_docs")
        store.put_blob(
            "checkpoint.manifest",
            json.dumps(manifest, sort_keys=True).encode())
        if fence is not None:
            store.put_blob(
                "checkpoint.fence",
                json.dumps({
                    "proc": str(getattr(fence, "proc", "")),
                    "epochs": {d: int(fence.epoch_of(d))
                               for d in sorted(manifest)},
                }, sort_keys=True).encode())
        return done

    def restore(self, store=None, *, fence=None) -> int:
        """Rehydrate the resident set a :meth:`checkpoint` wrote —
        the whole-server warm restart. Per manifest doc: snapshot ->
        live engine re-registered with the pool and the resident
        budget (ledgers rebuild through the stock ``_adopt_engine``
        commit), history re-seeded from the sidecar blob so every
        later route (re-promotion, cold fallback, digesting) sees an
        equivalent doc. A damaged snapshot falls back to the sidecar
        blob COLD (served correctly, promoted on its next touch);
        a missing sidecar skips the doc. Returns docs restored
        warm.

        ``fence`` (round 24): a doc stamped with a NEWER fencing
        epoch than this process holds is REFUSED, not silently
        adopted — the checkpoint belongs to a lease this process
        never held (a cross-wired store, a rolled-back lease
        table), and serving it would fork the doc past the fence.
        Counted ``snap.fallbacks{reason=stale_epoch}``."""
        from crdt_tpu.storage.snapshot import rehydrate

        store = store if store is not None else self.snap_store
        if store is None:
            raise ValueError("restore: no snapshot store attached")
        raw = store.get_blob("checkpoint.manifest")
        if raw is None:
            return 0
        try:
            manifest = json.loads(raw)
        except ValueError:
            return 0
        stamped = {}
        if fence is not None:
            raw_f = store.get_blob("checkpoint.fence")
            if raw_f:
                try:
                    stamped = json.loads(raw_f).get("epochs") or {}
                except (ValueError, AttributeError):
                    stamped = {}
        tracer = get_tracer()
        warm = 0
        for d in sorted(manifest):
            if fence is not None and \
                    int(stamped.get(d, 0)) > int(fence.epoch_of(d)):
                self.snap_fallback_count += 1
                if tracer.enabled:
                    tracer.count("snap.fallbacks",
                                 labels={"reason": "stale_epoch"})
                continue
            hist = store.get_blob("%s.hist" % d)
            if hist is None:
                continue
            st = self._docs.setdefault(d, _DocState())
            st.blobs = [hist]
            st.pending.clear()
            st.pending_ts.clear()
            st.in_flight = []
            st.in_flight_ts = []
            st.stale = True
            st.no_promote_len = -1
            st._digest = None
            eng = None
            loaded = store.load_latest(d)
            if loaded is not None:
                snap, _seq = loaded
                try:
                    eng = rehydrate(snap, pool=self.pool)
                except ValueError:
                    self.snap_fallback_count += 1
                    if tracer.enabled:
                        tracer.count("snap.fallbacks",
                                     labels={"reason": "rehydrate"})
                    eng = None
            if eng is None:
                # cold rung: the doc serves from the sidecar blob
                # via the stock replay path on its next touch
                st.n_ops = int(manifest[d].get("n_ops", 0))
                continue
            st.resident = eng
            st.stale = False
            st.cache = {}
            self._adopt_engine(d)
            if st.resident is None:
                continue  # budget evicted it right back
            warm += 1
        if tracer.enabled:
            tracer.gauge("tenant.resident_docs", self.rbudget.docs())
            tracer.gauge("tenant.resident_bytes", self.rbudget.total)
        return warm

    # ---- converge engines (the round-14 cold path) -------------------

    def _finish_doc(self, doc_id, res) -> None:
        """One doc's packed result through the STOCK replay gather +
        materialize (res rows are local to the doc's decode) — the
        exact path, used for the per-doc baseline and every shape
        the vectorized unpack refuses."""
        st = self._docs[doc_id]
        dec, ds = st.dec, st.ds
        w, v, o = rp.gather(dec, ds, ("packed", res))
        st.cache = rp.materialize(dec, ds, w, v, o)
        st._digest = None
        st.n_ops = len(dec["client"])

    def _converge_one(self, doc_id) -> None:
        """Per-doc dispatch: the ordinary replay converge (packed /
        sharded / resident routes, exactly the one-shot pipeline)."""
        st = self._docs[doc_id]
        if not len(st.dec["client"]):
            self._finish_empty(doc_id)
            return
        handle = rp.converge(st.cols)
        w, v, o = rp.gather(st.dec, st.ds, handle)
        st.cache = rp.materialize(st.dec, st.ds, w, v, o)
        st._digest = None
        st.n_ops = len(st.dec["client"])

    def _converge_batch(self, batch) -> tuple:
        """Stage + (async) dispatch one batch. Returns (dispatches,
        fallback_docs, in-flight handle or None when the batch was
        settled synchronously)."""
        live = [d for d in batch
                if len(self._docs[d].dec["client"])]
        live_set = set(live)
        for d in batch:
            if d not in live_set:
                self._finish_empty(d)
        if len(live) == 0:
            return 0, 0, None
        if len(live) == 1 or not self.pack_docs:
            for d in live:
                self._converge_one(d)
            return len(live), 0, None
        comb, row_off = _concat_cols(
            [self._docs[d].cols for d in live]
        )
        handle = self._dispatch_async(comb)
        if handle is None:
            # the batch exceeded the packed staging bounds: degrade
            # to per-doc dispatches (correct, just un-amortized),
            # and say so in the evidence
            for d in live:
                self._converge_one(d)
            return len(live), len(live), None
        return 1, 0, (live, comb, row_off, handle)

    def _finish_batch(self, batch, work, tok=None) -> None:
        """Fetch one in-flight batch dispatch, unpack per doc, stamp
        latencies/service bookkeeping. ``tok`` closes the dispatch's
        timeline window; the fetch span is the tick's stall."""
        from crdt_tpu.ops import shard as shard_ops

        live, comb, row_off, (route, h) = work
        fetch = (shard_ops.converge_fetch if route == "shard"
                 else packed.converge_fetch)
        tl = get_timeline()
        t0 = time.perf_counter()
        res = fetch(h)
        t1 = time.perf_counter()
        tl.dispatch_end(tok, t0, t1)
        with tl.phase("unpack"):
            self._unpack(live, comb, row_off, res)
        with tl.phase("settle"):
            self._settle(batch)

    def _settle(self, batch, route: str = "cold") -> None:
        done = time.perf_counter()
        for d in batch:
            st = self._docs[d]
            nbytes = sum(len(b) for b in st.in_flight)
            self._pending_total -= nbytes
            self._settled_since_ckpt += nbytes
            st.blobs.extend(st.in_flight)
            st.in_flight.clear()
            if st.in_flight_ts:
                # SLO: ingest-to-converged closes here per blob; the
                # submit stamps park until the tick end stamps
                # ingest-to-served (state readable)
                self.slo.converged(
                    d, (done - t for t in st.in_flight_ts), route,
                )
                self._served_buf.append((d, tuple(st.in_flight_ts)))
                st.in_flight_ts.clear()
            if st.dirty_since is not None:
                st.latency_s = done - st.dirty_since
            st.served_tick = self.ticks
            # mid-tick arrivals (live ingest overlapping this tick's
            # dispatches) stay pending: the doc remains dirty and its
            # latency clock restarts at this serve
            st.dirty_since = done if st.pending else None

    def _finish_empty(self, doc_id) -> None:
        st = self._docs[doc_id]
        st.cache, st.n_ops = {}, 0
        st._digest = None

    def _dispatch_async(self, comb):
        """Enqueue one converge dispatch over the combined multi-doc
        columns: sharded route when active (partitioned by whole
        docs), the single-chip packed plan otherwise. Returns a
        (route, handle) pair for :meth:`_finish_batch`, or None when
        staging refused."""
        from crdt_tpu.ops import shard as shard_ops

        n = len(comb["client"])
        if shard_ops.active_for(n, self.shards):
            splan = shard_ops.stage(comb, n_shards=self.shards)
            if splan is not None:
                return ("shard", shard_ops.converge_async(splan))
        plan = packed.stage(comb)
        if plan is None:
            return None
        return ("packed", packed.converge_async(plan))

    # ---- the multi-doc unpack ----------------------------------------

    def _unpack(self, live, comb, row_off, res) -> None:
        """Split one combined result into per-doc caches/digests.

        The global work is vectorized ONCE for the whole batch: the
        visibility of every row against its own doc's delete ranges
        (doc-composite clients, one interval search), and a stable
        partition of the winner/stream arrays by doc (segments never
        cross docs, so each doc's slice keeps its oracle order; the
        stable sort also covers the sharded route, where shards emit
        docs out of submission order). Per doc, the plain shape gets
        the tight cache build; anything else replays its slice
        through the stock gather/materialize."""
        win_all = np.asarray(res.win_rows)
        win_all = win_all[win_all >= 0]
        srow_all = np.asarray(res.stream_row)
        sm = srow_all >= 0
        srow_all = srow_all[sm]
        sseg_all = np.asarray(res.stream_seg)[sm]
        wdoc = np.searchsorted(row_off, win_all, side="right") - 1
        worder = np.argsort(wdoc, kind="stable")
        win_all, wdoc = win_all[worder], wdoc[worder]
        sorder = np.argsort(sdoc := np.searchsorted(
            row_off, srow_all, side="right") - 1, kind="stable")
        srow_all, sseg_all, sdoc = (
            srow_all[sorder], sseg_all[sorder], sdoc[sorder]
        )
        D = len(live)
        wcut = np.searchsorted(wdoc, np.arange(D + 1))
        scut = np.searchsorted(sdoc, np.arange(D + 1))
        vis = _global_visibility(
            comb, [self._docs[d].ds for d in live]
        )
        hard = sorted(int(r) for r in res.hard_rows)
        hdocs = (set(
            (np.searchsorted(row_off, hard, side="right") - 1).tolist()
        ) if hard else frozenset())
        for i, d in enumerate(live):
            st = self._docs[d]
            lo, hi = int(row_off[i]), int(row_off[i + 1])
            has_hard = i in hdocs
            if st.fast_ok and not has_hard:
                st.cache = _fast_cache(
                    st.dec, lo,
                    win_all[wcut[i]:wcut[i + 1]],
                    srow_all[scut[i]:scut[i + 1]],
                    sseg_all[scut[i]:scut[i + 1]],
                    vis,
                )
                st._digest = None
                st.n_ops = len(st.dec["client"])
            else:
                self._finish_doc(d, packed.PackedResult(
                    win_rows=win_all[wcut[i]:wcut[i + 1]] - lo,
                    stream_seg=sseg_all[scut[i]:scut[i + 1]],
                    stream_row=srow_all[scut[i]:scut[i + 1]] - lo,
                    hard_rows=tuple(
                        r - lo for r in hard if lo <= r < hi
                    ),
                ))


def _concat_cols(cols_list):
    """Concatenate per-doc kernel columns into one multi-doc column
    set with the ``doc`` segment column, plus the caller-row offsets
    of each doc (``row_off[i] .. row_off[i+1]`` is doc i's range)."""
    comb = {
        k: np.concatenate([np.asarray(c[k]) for c in cols_list])
        for k in cols_list[0]
    }
    comb["doc"] = np.concatenate([
        np.full(len(c["client"]), i, np.int64)
        for i, c in enumerate(cols_list)
    ])
    row_off = np.cumsum(
        [0] + [len(c["client"]) for c in cols_list]
    )
    return comb, row_off


def _global_visibility(comb, ds_list):
    """Tombstone visibility for EVERY row of a combined batch in one
    interval search: clients compose with the doc column (one doc's
    delete ranges can never touch another doc's rows), delete
    triples from clients absent from the batch are dropped (they
    cannot cover any row). Returns a bool mask over the combined
    caller rows, or None when no doc carries tombstones (all
    visible)."""
    uniq = np.unique(np.asarray(comb["client"], np.int64))
    C = len(uniq) + 1
    dc: list = []
    dstart: list = []
    dend: list = []
    for i, ds in enumerate(ds_list):
        for c, s, n in ds.iter_all():
            r = int(np.searchsorted(uniq, c))
            if r < len(uniq) and uniq[r] == c:
                dc.append(i * C + r)
                dstart.append(s)
                dend.append(s + n)
    if not dc:
        return None
    comp = (
        np.asarray(comb["doc"], np.int64) * C
        + np.searchsorted(uniq, np.asarray(comb["client"], np.int64))
    )
    return rp.rows_visible(
        comp, np.asarray(comb["clock"], np.int64),
        np.asarray(dc, np.int64), np.asarray(dstart, np.int64),
        np.asarray(dend, np.int64),
    )


def _fast_cache(dec, lo, win, srow, sseg, vis) -> dict:
    """The tight cache build for a plain doc (see `_fast_unpack_ok`):
    map winners keyed into their root dicts, sequence streams cut at
    segment boundaries, tombstoned rows dropped — the exact cache the
    stock materialize produces for this shape (differential-pinned in
    tests/test_multidoc.py). ``win``/``srow`` are combined-space rows
    (``lo`` rebases), ``vis`` the global visibility mask (None = all
    visible)."""
    roots = dec["roots"]
    keys_t = dec["keys"]
    pr = dec["parent_root"]
    kid = dec["key_id"]
    contents = dec["contents"]
    cache: dict = {}
    if vis is None:
        for g in win.tolist():
            r = g - lo
            root = roots[pr[r]]
            grp = cache.get(root)
            if grp is None:
                grp = cache[root] = {}
            grp[keys_t[kid[r]]] = contents[r]
    else:
        for g, ok in zip(win.tolist(), vis[win].tolist()):
            if not ok:
                continue
            r = g - lo
            root = roots[pr[r]]
            grp = cache.get(root)
            if grp is None:
                grp = cache[root] = {}
            grp[keys_t[kid[r]]] = contents[r]
    if len(srow):
        edges = np.flatnonzero(sseg[1:] != sseg[:-1]) + 1
        cuts = [0] + edges.tolist() + [len(sseg)]
        for a, b in zip(cuts[:-1], cuts[1:]):
            rows_g = srow[a:b]
            first = int(rows_g[0]) - lo
            root = roots[pr[first]]
            if vis is None:
                vals = [contents[r - lo] for r in rows_g.tolist()]
            else:
                vals = [
                    contents[r - lo]
                    for r, ok in zip(rows_g.tolist(),
                                     vis[rows_g].tolist())
                    if ok
                ]
            if root not in cache:
                cache[root] = vals
    return cache
