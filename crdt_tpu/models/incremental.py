"""Incremental device replay — per-round cost scales with the delta.

The cold replay (:mod:`crdt_tpu.models.replay`) re-stages and
re-converges the whole union every call; fine for one-shot trace
ingestion, wasteful for a long-lived replica consuming update batches
forever (the product's steady state, crdt.js:294 called per gossip
round). :class:`IncrementalReplay` keeps the op columns RESIDENT in
device memory (the north star's "columnar tensors in HBM") and, per
batch:

  1. ships ONLY the packed delta to the device;
  2. splices it into the resident matrix and re-converges ONLY the
     segments the delta touches (one fused dispatch —
     :func:`crdt_tpu.ops.packed._splice_select_converge`);
  3. updates host-side per-segment caches (map winners, sequence
     orders) and rebuilds just the affected root collections of the
     plain-JSON cache.

Admission is vectorized AND engine-faithful: dedup, stable interning,
and the implicit-parent resolution of wire runs (origin-else-right
chains, ``crdt_tpu.ops.merge.resolve_parents`` semantics) run as numpy
passes — resolution itself is host-side pointer doubling, O(log chain)
array rounds instead of a per-row walk. Out-of-order delivery follows
the engine's rule (``Engine._blocker_of``): a row integrates only when
its per-client clock run is contiguous and its origin/right/item-
parent have arrived; blocked rows stash in ``_pending`` and retry on
every apply, so intermediate states match ``Engine.apply_records``
under the same arrival order. (Hostile dependency CYCLES — impossible
under causal delivery — admit as a group, matching the cold replay's
convention rather than pending forever.)

Segments whose rows carry right origins re-order through the exact
host machinery (:func:`crdt_tpu.ops.yata.order_sequences`) — same
split as the cold path's gather. Delete sets only change visibility,
never winners or order, so delete-only batches rebuild caches without
any device work.

Host-path segments below the crossover converge INCREMENTALLY
(round 4; fixes the round-3 advisor/VERDICT finding that right-origin
marking made every later touch re-order the whole segment): each
sequence segment keeps an engine-style linked chain (``_lnk_next`` /
``_lnk_prev``, the same structure ``Engine._next/_prev`` uses), and a
remote delta integrates row by row through the verbatim YATA conflict
scan (``Engine._integrate_into_chain``, crdt.js:294) — O(delta x scan
window), independent of document size. Map deltas whose origin is the
current chain tail advance the winner in O(1). Any shape outside the
incremental preconditions (cross-segment/GC origins, unresolvable
refs, accounting mismatches) falls back to the exact whole-segment
machinery, so exactness never rests on the fast path.

The plain-JSON cache is LAZY: a round marks touched segments dirty
and the ``cache`` property flushes them on read, so a replica
consuming a firehose of updates pays zero materialization until
someone actually looks (local fast-path ops still patch it in place
when it is fresh).

Differential-tested against the cold replay and the scalar engine in
tests/test_incremental.py.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from crdt_tpu.compat import enable_x64
from crdt_tpu.codec import native
from crdt_tpu.core.ids import DeleteSet
from crdt_tpu.ops.device import bucket_pow2
from crdt_tpu.ops import packed as pk


def _octave(n: int, floor: int) -> int:
    """Factor-8 size bucket for the incremental dispatch's static
    shapes. A long-lived replica's touched-segment populations GROW
    monotonically, so fine-grained (pow2) buckets cross a boundary —
    and pay a fresh ~50s XLA compile — every doubling; factor-8 steps
    compile a handful of variants over the store's whole lifetime.
    The kernel's width-dependent cost (sorts, gathers) scales far
    sublinearly, so 8x padding costs milliseconds against compiles
    that cost minutes."""
    b = floor
    while b < n:
        b *= 8
    return b


class _Cols:
    """Growing host-side row store (the union's metadata columns)."""

    INT_COLS = (
        "client", "clock", "kid", "pref", "oc", "ock",
        "right_client", "right_clock", "kind", "type_ref",
    )

    def __init__(self):
        self.n = 0
        self._cap = 1024
        self._a = {
            name: np.zeros(self._cap, np.int64) for name in self.INT_COLS
        }
        self.contents: List = []

    def col(self, name) -> np.ndarray:
        return self._a[name][: self.n]

    def append(self, arrays: Dict[str, np.ndarray], contents):
        k = len(contents)
        while self.n + k > self._cap:
            self._cap *= 2
        for name in self.INT_COLS:
            if len(self._a[name]) < self._cap:
                grown = np.zeros(self._cap, np.int64)
                grown[: self.n] = self._a[name][: self.n]
                self._a[name] = grown
            self._a[name][self.n : self.n + k] = arrays[name]
        self.contents.extend(contents)
        self.n += k

    def append_row(self, client, clock, kid, pref, oc, ock, rc, rk,
                   kind, tref, content) -> int:
        """Scalar append for the local-op fast path: one row, plain
        Python ints, no numpy temporaries."""
        i = self.n
        if i + 1 > self._cap:
            while i + 1 > self._cap:
                self._cap *= 2
            for name in self.INT_COLS:
                grown = np.zeros(self._cap, np.int64)
                grown[:i] = self._a[name][:i]
                self._a[name] = grown
        a = self._a
        a["client"][i] = client
        a["clock"][i] = clock
        a["kid"][i] = kid
        a["pref"][i] = pref
        a["oc"][i] = oc
        a["ock"][i] = ock
        a["right_client"][i] = rc
        a["right_clock"][i] = rk
        a["kind"][i] = kind
        a["type_ref"][i] = tref
        self.contents.append(content)
        self.n = i + 1
        return i


class IncrementalReplay:
    """A long-lived replica state fed by v1 update blobs.

    ``device_min_rows`` is the host/device crossover: when the rows of
    a round's touched segments total fewer than this, convergence runs
    through the exact host machinery against the resident columns and
    the round does ZERO device work — its rows accumulate, and the
    next device round splices the whole unspliced tail in its one
    upload (``n_dev`` marks the boundary; admission appends in order,
    so host row ids and device positions stay identical). Measured
    through the tunnelled single chip a device round costs ~0.1-0.3s
    of fixed interaction latency regardless of size, so small deltas —
    a collaborator's keystrokes, a replica's own ops — are host-won;
    firehose rounds and cold gaps go to the device. The default
    (``device_min_rows=None``) AUTO-CALIBRATES per session: one
    dispatch-latency probe on the first device-eligible round feeds
    the cost model in :meth:`_calibrate` (the tunnel's weather moves
    2-4x between sessions, so no static number is ever right — VERDICT
    r3 item 2). ``CRDT_TPU_DEVICE_MIN`` or the constructor argument
    pin it explicitly; BENCH_r0N.json's ``rounds`` table publishes
    both the measured crossover and the session's calibration."""

    # process-wide host/device crossover calibration (one probe per
    # session — the tunnel's per-dispatch latency moves 2-4x between
    # sessions, so any static default is wrong somewhere; VERDICT r3
    # item 2). Filled lazily by _calibrate().
    _calib: Dict[str, Optional[float]] = {
        "t_interact_ms": None, "host_us_per_row": None,
        "dev_us_per_row": None, "threshold": None,
    }
    # FALLBACK per-row costs, used only if a probe fails (its jax
    # call raising): every session normally MEASURES both — the host
    # cost by ingesting a real synthetic blob through the pinned host
    # path, the device cost from the tunnel's measured round-trip
    # bandwidth (VERDICT r4 item 6: no hardcoded constants behind the
    # threshold).
    _HOST_US_PER_ROW_FALLBACK = 3.0
    _DEV_US_PER_ROW_FALLBACK = 1.0

    @classmethod
    def _calibrate(cls) -> Dict[str, Optional[float]]:
        """One-time session probes -> the row count where a
        3-interaction device round beats the host path's per-row
        cost. Floored at 4096 so a fast local backend never routes
        keystroke rounds to a compile.

        Three measurements, all recorded (``calibration_info``):

        - ``t_interact_ms`` — median single-shot dispatch latency
          (the tunnel's fixed per-interaction cost);
        - ``host_us_per_row`` — a REAL 4096-op map blob ingested by a
          throwaway replay pinned to the host path (decode + admit +
          integrate, the exact code a host round runs);
        - ``dev_us_per_row`` — the measured device round-trip
          bandwidth, charged at the round's ~72 bytes/row (8 int64
          delta lanes up, one int64 result lane down); on-device
          kernel time per row is negligible against the transfer.
        """
        if cls._calib["threshold"] is None:
            import time as _t

            import jax
            import jax.numpy as jnp
            import numpy as _np

            f = jax.jit(lambda v: v + 1)
            x = jnp.arange(128)
            jax.block_until_ready(f(x))  # compile, and flip lazy mode
            _np.asarray(f(x))  # force sync execution mode (axon trap)
            lat = []
            for _ in range(3):
                t0 = _t.perf_counter()
                jax.block_until_ready(f(x))
                lat.append(_t.perf_counter() - t0)
            t_i = sorted(lat)[1]

            # host per-row: a real map-set blob through the pinned
            # host path of a throwaway replay (min of 2 fresh ingests)
            host_us: Optional[float] = None
            try:
                from crdt_tpu.codec import v1 as _v1c
                from crdt_tpu.core.ids import DeleteSet as _DSp
                from crdt_tpu.core.records import ItemRecord as _IRp

                n_p = 4096
                recs = [
                    _IRp(client=1, clock=k, parent_root="_calib",
                         key=f"k{k & 255}", content=k,
                         origin=(1, k - 256) if k >= 256 else None)
                    for k in range(n_p)
                ]
                blob_p = _v1c.encode_update(recs, _DSp())
                best = float("inf")
                for _ in range(2):
                    probe = cls(capacity=n_p + 64,
                                device_min_rows=1 << 62)
                    t0 = _t.perf_counter()
                    probe.apply([blob_p])
                    best = min(best, _t.perf_counter() - t0)
                host_us = best * 1e6 / n_p
            except Exception:
                pass
            if host_us is None:
                host_us = cls._HOST_US_PER_ROW_FALLBACK

            # device per-row: measured round-trip bandwidth at the
            # round's bytes/row (device_put compiles nothing)
            dev_us: Optional[float] = None
            try:
                n_b = 1 << 18
                buf = _np.zeros(n_b, _np.int64)
                _np.asarray(jax.device_put(buf))  # warm the path
                t0 = _t.perf_counter()
                _np.asarray(jax.device_put(buf))
                t_rt = _t.perf_counter() - t0
                bw = (2 * 8 * n_b) / max(t_rt - t_i, 1e-6)  # bytes/s
                dev_us = 72.0 / bw * 1e6
            except Exception:
                pass
            if dev_us is None:
                dev_us = cls._DEV_US_PER_ROW_FALLBACK

            per_row_us = max(host_us - dev_us, 0.5)
            cls._calib = {
                "t_interact_ms": round(t_i * 1e3, 2),
                # 6 decimals: a fast LOCAL backend's measured per-row
                # transfer cost can be ~1e-5 us (the clamp regime) —
                # recorded as the tiny number it is, never as a
                # fabricated floor
                "host_us_per_row": round(host_us, 6),
                "dev_us_per_row": round(dev_us, 6),
                "threshold": max(4096, int(3 * t_i * 1e9 / per_row_us
                                           / 1e3)),
            }
        return cls._calib

    # static floor: below this, never pay the calibration probe's
    # device interactions just to learn the work belongs on host
    _CROSSOVER_FLOOR = 16384

    @classmethod
    def crossover_use_host(cls, n_rows: int) -> bool:
        """The host/device crossover decision for ``n_rows`` of
        touched work — the ONE implementation shared by the live
        replica's rounds and the cold replay's "auto" route."""
        if n_rows < cls._CROSSOVER_FLOOR:
            return True
        return n_rows < cls._calibrate()["threshold"]

    @classmethod
    def calibration_info(cls) -> Dict[str, Optional[float]]:
        """The session's measured crossover (probing if needed) — the
        bench records this next to the crossover table it implies."""
        return dict(cls._calibrate())

    def __init__(self, capacity: int = 1 << 14,
                 device_min_rows: Optional[int] = None,
                 pool=None):
        import jax
        import jax.numpy as jnp

        self._jax, self._jnp = jax, jnp
        if device_min_rows is None:
            import os

            env = os.environ.get("CRDT_TPU_DEVICE_MIN")
            # None = AUTO: calibrate on the first device-eligible
            # round (never at construction — replicas must come up
            # without touching the device)
            device_min_rows = int(env) if env else None
        self.device_min_rows = device_min_rows
        self.cols = _Cols()
        self.ds = DeleteSet()
        self._cache: dict = {}
        self._dirty: set = set()  # segkeys whose cache view is stale
        self.last_touched_roots: List[str] = []
        self.last_touched_keys: Dict[str, set] = {}
        # stable interners
        self._keys: Dict[str, int] = {}
        self._key_names: List[str] = []
        self._prefs: Dict[Tuple, int] = {}
        self._pref_spec: List[Tuple] = []  # pref -> parent spec
        self._pref_item_c: List[int] = []  # pref -> item-parent id
        self._pref_item_k: List[int] = []  # (-1, -1 for root specs)
        self._next_clock: Dict[int, int] = {}
        self._clients: List[int] = []      # sorted raw ids
        self._dense: Dict[int, int] = {}
        self._id_row: Dict[Tuple[int, int], int] = {}
        # per-segment state (keyed by int segkey)
        self._seg_rows: Dict[int, List[int]] = {}
        self._seg_kid: Dict[int, int] = {}        # -1 for sequences
        self._seg_rights: Dict[int, bool] = {}
        self._win: Dict[int, int] = {}            # map segkey -> winner row
        self._order: Dict[int, List[int]] = {}    # seq segkey -> rows
        # lazy row->position maps over _order (O(1) anchor lookups for
        # the resident doc's local ops — advisor finding, round 3).
        # Invalidated whenever a segment's order is reassigned
        # (_set_order) or mid-spliced; rebuilt on demand.
        self._order_pos: Dict[int, Dict[int, int]] = {}
        # engine-style linked chains (Engine._next/_prev) for host-path
        # sequence segments: the incremental integrate scan splices
        # these in O(window); the _order list is then a stale
        # materialization rebuilt lazily by order_list()
        self._lnk_next: Dict[int, int] = {}
        self._lnk_prev: Dict[int, int] = {}
        self._lnk_head: Dict[int, int] = {}       # segkey -> first row
        self._lnk_tail: Dict[int, int] = {}
        self._linked: set = set()                 # segkeys with live links
        self._order_stale: set = set()            # linked, list out of date
        # per-segment ORDER EPOCH: bumped on every mutation that can
        # shift document positions or visibility (splices, wholesale
        # reorders, delete-touched rounds). Position caches held by
        # callers (the resident doc's insert cursor) validate against
        # it instead of guessing staleness.
        self._order_epoch: Dict[int, int] = {}
        self._root_segs: Dict[str, set] = {}      # root name -> segkeys
        self._spec_root: Dict[Tuple, str] = {}
        self._rootless: set = set()               # segkeys awaiting a root
        # engine-faithful admission: rows whose per-client clock run
        # has a gap, or whose origin/right has not arrived, stash here
        # (columns + content keyed by id) and retry on every apply
        self._pending: Dict[Tuple[int, int], Tuple] = {}
        # pending-stash budget (guard layer) — same contract as
        # Engine.pending_limit: None = unbounded; overflow evicts the
        # largest-clock entries and records the evicted ranges for the
        # replica's targeted re-probe (take_evicted_ranges)
        self.pending_limit: Optional[int] = None
        self.evicted_ranges: Dict[int, Tuple[int, int]] = {}
        # packed delete-RANGE cache over self.ds (client, start, end
        # arrays for rows_visible) — tombstones are never expanded to
        # per-clock ids: a few delete-set bytes can declare ranges
        # covering billions of clocks (adversarial matrix). Invalidated
        # on every ds mutation, rebuilt O(ranges) on demand.
        self._ds_pack = None
        # per-apply scratch: segkey -> this batch's admitted rows
        self._new_by_seg: Dict[int, List[int]] = {}
        # the resident device matrix allocates LAZILY on the first
        # device round: construction must never touch the device (a
        # swarm of host-path replicas would otherwise pay two tunnel
        # dispatches each just to exist — measured as the resident
        # mode's whole swarm deficit on bad-weather sessions)
        self._capacity = capacity
        self._mat = None
        self.n_dev = 0
        # snapshot-rehydrated engines (round 21) carry exact winner /
        # order caches but NO device state: their device rounds first
        # try the O(delta) host tail advances, so the recovery path
        # never pays an O(doc) re-splice just to append — the backlog
        # waits for the first round the fast shapes cannot handle
        self._from_snapshot = False
        # pooled resident matrix (round 20): when attached, device
        # rounds DEFER to the shared pool — the server's tick flushes
        # every warm doc's delta in ONE dispatch — and this engine
        # never allocates a private matrix. Registration is host
        # bookkeeping only; a pool-budget refusal later falls back to
        # the private route (correctness never depends on pooling).
        self.pool = pool
        if pool is not None:
            pool.register(self)

    def _ensure_mat(self):
        if self._mat is None:
            jax, jnp = self._jax, self._jnp
            with enable_x64(True):
                m = jnp.zeros(
                    (7, bucket_pow2(self._capacity)), jnp.int64
                )
                self._mat = m.at[3:6, :].set(-1)
        return self._mat

    # -- interning ----------------------------------------------------
    def _intern_clients(self, raw_ids: np.ndarray) -> None:
        new = sorted(set(int(c) for c in raw_ids) - self._dense.keys())
        if not new:
            return
        shifted = bool(self._clients) and new[0] < self._clients[-1]
        old = dict(self._dense) if shifted else None
        clients = sorted(self._clients + new)
        dense = {raw: i for i, raw in enumerate(clients)}
        if old and self.n_dev:
            perm = np.zeros(len(old), np.int32)
            for raw, od in old.items():
                perm[od] = dense[raw]
            with enable_x64(True):
                if self.pool is not None:
                    # pooled: only THIS doc's extent columns relabel
                    # (ids are doc-local in the pooled matrix)
                    self.pool.relabel(self, perm)
                else:
                    self._mat = pk._relabel_mat(
                        self._mat, self._jnp.asarray(perm)
                    )
            # host columns keep RAW ids; only the device matrix embeds
            # dense ids, so no host fixups
        # the table commits only AFTER the device relabel succeeded: a
        # guarded-ladder retry must redo the relabel, not skip it
        # against a matrix still carrying the old dense ids
        self._clients = clients
        self._clients_arr = np.asarray(clients)
        self._dense = dense

    def _dense_of(self, raw: np.ndarray) -> np.ndarray:
        return np.searchsorted(self._clients_arr, raw).astype(np.int64)

    def _pref_of_spec(self, spec: Tuple) -> int:
        ref = self._prefs.get(spec)
        if ref is None:
            ref = len(self._prefs)
            if ref >= (1 << pk._PREF_BITS):
                raise OverflowError("parent-ref space exhausted")
            self._prefs[spec] = ref
            self._pref_spec.append(spec)
            if spec[0] == "item":
                self._pref_item_c.append(spec[1])
                self._pref_item_k.append(spec[2])
            else:
                self._pref_item_c.append(-1)
                self._pref_item_k.append(-1)
        return ref

    def _spec_of_row(self, row: int) -> Optional[Tuple]:
        pref = int(self.cols.col("pref")[row])
        return self._pref_spec[pref] if pref >= 0 else None

    def _kid_of_key(self, name: str) -> int:
        kid = self._keys.get(name)
        if kid is None:
            kid = len(self._keys)
            if kid >= (1 << pk._KID_BITS):
                # a silent overflow would bleed into the pref bits of
                # the composite segkey and merge unrelated segments
                raise OverflowError("map-key id space exhausted")
            self._keys[name] = kid
            self._key_names.append(name)
        return kid

    # -- apply --------------------------------------------------------
    def apply(self, blobs) -> None:
        """Consume a batch of update blobs. The JSON view is marked
        dirty, not rebuilt — read ``.cache`` for the flushed state."""
        if isinstance(blobs, (bytes, bytearray)):
            blobs = [bytes(blobs)]
        self.apply_decoded(
            native.dedup_columns(native.decode_updates_columns_any(blobs))
        )

    def apply_decoded(self, dec) -> None:
        """Consume an already-decoded (deduped) columnar union —
        the seam for callers that decoded once for their own purposes
        (replay_trace's host route) and must not pay the codec
        twice."""
        if self.pool is not None and self.pool.has_pending(self):
            # a deferred pooled round left winners/orders stale; the
            # host paths below may read them — settle first
            self.pool.flush()
        n_raw = len(dec["client"])
        touched: set = set()

        # delete ranges: visibility-only — record which segments they
        # tombstone so their cache entries rebuild. Spans already
        # fully covered by the recorded delete set are REDELIVERY and
        # mark nothing (a duplicate gossip delivery must not re-scan
        # the columns or rebuild every covered segment's cache); fresh
        # spans clamp at each client's admitted watermark — rows
        # cannot exist beyond it, so a hostile range covering clocks
        # that may never exist costs O(ranges), not O(declared
        # length); late rows check visibility against the range set
        # at admission.
        trips = np.asarray(dec["ds"]).reshape(-1, 3)
        if len(trips):
            from crdt_tpu.models.replay import rows_visible

            batch_ds = DeleteSet()
            for c, k, length in trips:
                batch_ds.add(int(c), int(k), int(length))
            spans = []
            for c, s, length in batch_ds.iter_all():
                if self.ds.covers(c, s, length):
                    continue  # redelivered: already recorded
                end = min(s + length, self._next_clock.get(c, 0))
                if end > s:
                    spans.append((c, s, end))
            for c, k, length in trips:
                self.ds.add(int(c), int(k), int(length))
            self._ds_pack = None
            total = sum(e - s for _, s, e in spans)
            if spans and total * 4 > self.cols.n and self.cols.n:
                # bulk range: one vectorized scan over the id columns
                hit = ~rows_visible(
                    self.cols.col("client"), self.cols.col("clock"),
                    np.asarray([c for c, _, _ in spans], np.int64),
                    np.asarray([s for _, s, _ in spans], np.int64),
                    np.asarray([e for _, _, e in spans], np.int64),
                )
                rows_hit = np.flatnonzero(hit)
            else:
                rows_hit = [
                    r for r in (
                        self._id_row.get((c, kk))
                        for c, s, e in spans
                        for kk in range(s, e)
                    ) if r is not None
                ]
            for row in rows_hit:
                sk = self._row_segkey(int(row))
                if sk is not None:
                    touched.add(sk)

        self._new_by_seg = {}
        new_rows = self._admit(dec) if n_raw else None
        # segments delivered before their parent item: retry now that
        # this batch may have supplied the missing ancestors
        if self._rootless:
            for sk in list(self._rootless):
                root = self._root_of(self._seg_spec(sk))
                if root is not None:
                    self._rootless.discard(sk)
                    self._root_segs.setdefault(root, set()).add(sk)
                    touched.add(sk)
        if new_rows is not None and len(new_rows):
            by_seg = self._new_by_seg
            touched.update(by_seg)
            self._device_round(by_seg)
        self._touch_bookkeeping(touched)
        self._dirty.update(
            sk for sk in touched if sk in self._seg_rows
        )

    # -- delta admissibility (the multi-doc server's probe) -----------
    @staticmethod
    def decode_delta(blobs) -> Dict:
        """Decode an update batch into the engine's columnar format
        WITHOUT touching replica state: the multi-doc server's
        admissibility probe decodes once, then feeds the same dec to
        :meth:`apply_decoded` (or discards it and cold-replays)."""
        if isinstance(blobs, (bytes, bytearray)):
            blobs = [bytes(blobs)]
        return native.dedup_columns(
            native.decode_updates_columns_any(list(blobs))
        )

    def delta_admissible(self, dec) -> bool:
        """Would this decoded batch admit WHOLE — no row stashed — so
        the incremental route stays byte-identical to a cold replay
        of the same history? Mirrors :meth:`_admit`'s gate,
        read-only and conservatively:

        - no outstanding stash (pending rows or rootless segments:
          only the full apply pass retries those);
        - every fresh row's clock extends its client's admitted run
          contiguously (offset clocks — a gap the cold replay would
          admit but the engine would stash — refuse);
        - every origin / right / explicit item-parent ref resolves to
          a resident row or another row of this same batch.

        A refusal costs the caller a cold replay, never bytes."""
        if self._pending or self._rootless:
            return False
        n = len(dec["client"])
        if n == 0:
            return True  # delete-only / empty: visibility work only
        client = np.asarray(dec["client"], np.int64)
        clock = np.asarray(dec["clock"], np.int64)
        fresh = np.fromiter(
            (t not in self._id_row
             for t in zip(client.tolist(), clock.tolist())),
            bool, count=n,
        )
        idx = np.flatnonzero(fresh)
        if len(idx) == 0:
            return True  # pure redelivery: dedup drops every row
        cl, ck = client[idx], clock[idx]
        in_batch = set(zip(cl.tolist(), ck.tolist()))
        order = np.lexsort((ck, cl))
        cl_s, ck_s = cl[order], ck[order]
        starts = np.flatnonzero(np.r_[True, cl_s[1:] != cl_s[:-1]])
        ends = np.r_[starts[1:], len(cl_s)]
        for s, e in zip(starts.tolist(), ends.tolist()):
            nxt = self._next_clock.get(int(cl_s[s]), 0)
            # post-dedup clocks are distinct, so run-span equality IS
            # contiguity from the resident watermark
            if int(ck_s[s]) != nxt or \
                    int(ck_s[e - 1]) - nxt != e - s - 1:
                return False
        for c_col, k_col in (
            ("origin_client", "origin_clock"),
            ("right_client", "right_clock"),
            ("parent_client", "parent_clock"),
        ):
            c_a = np.asarray(dec[c_col], np.int64)[idx]
            k_a = np.asarray(dec[k_col], np.int64)[idx]
            for j in np.flatnonzero(c_a >= 0).tolist():
                t = (int(c_a[j]), int(k_a[j]))
                if t not in self._id_row and t not in in_batch:
                    return False
        return True

    def resident_bytes(self) -> int:
        """Budget-accounted footprint of this replica's resident
        state: the device matrix (when materialized) plus the host
        integer column store — the allocations that scale with doc
        size and survive across rounds (content payloads live in the
        caller's blobs either way). The multi-doc resident budget
        (``CRDT_TPU_MT_RESIDENT_BYTES``) sums this per doc. A POOLED
        doc accounts its reserved extent share of the shared matrix
        (8 lanes x extent capacity — the extent reserves eagerly at
        defer time, so the ledger commit after a delta tick sees the
        post-round share)."""
        dev = 0
        if self._mat is not None:
            dev = int(self._mat.shape[0]) * int(self._mat.shape[1]) * 8
        elif self.pool is not None:
            dev = self.pool.doc_device_bytes(self)
        return dev + self.cols._cap * len(_Cols.INT_COLS) * 8

    @staticmethod
    def estimate_resident_bytes(n_rows: int) -> int:
        """Pre-promotion upper bound of :meth:`resident_bytes` for a
        doc of ``n_rows`` ops — the budget gate must refuse BEFORE
        building an over-budget engine, so it works from an estimate:
        the pow2 host column capacity plus a worst-case device matrix
        at the same bucket (host-path docs never allocate it; the
        bound errs toward refusing). The device term uses the POOLED
        layout's 8 lanes — the wider of the two routes — so the
        estimate upper-bounds :meth:`resident_bytes` whichever way
        the doc lands (unit-pinned by tests/test_pooled.py)."""
        cap = 1024
        while cap < max(n_rows, 1):
            cap *= 2
        return cap * len(_Cols.INT_COLS) * 8 + 8 * bucket_pow2(cap) * 8

    # -- local-op fast path -------------------------------------------
    def admit_local(self, recs, ds: Optional[DeleteSet] = None) -> None:
        """Direct admission for locally-born records — the resident
        doc's self-applied ops (crdt.js:294's integrate, local
        direction). The caller anchors every record on resident state
        (origins/rights/parents resident, per-client clocks
        contiguous), so the wire decode, the dedup pass, and the
        vectorized admission gate of :meth:`apply` are all skipped and
        the winner/order caches splice incrementally — O(delta) per op
        instead of a v1 encode/decode round-trip plus an O(segment)
        reorder (VERDICT r3 item 3). Any violated assumption falls
        back to the exact blob path; while stashed or rootless rows
        are outstanding the fast path is skipped entirely (only the
        full pass retries them)."""
        if self.pool is not None and self.pool.has_pending(self):
            # deferred pooled round outstanding: the incremental
            # splices below read winners/orders — settle first
            self.pool.flush()
        if self._pending or self._rootless or not self._can_fast(recs):
            from crdt_tpu.codec import v1 as _v1

            self.apply([_v1.encode_update(list(recs), ds or DeleteSet())])
            return

        touched: set = set()
        # delete ranges: visibility-only. Callers only delete rows that
        # are currently visible (checked against the live delete set
        # before building ``ds``), so these ids are never already in
        # the expanded arrays — the redelivery dedup scan of apply() is
        # unnecessary here.
        if ds is not None and ds.ranges:
            for c, k, length in ds.iter_all():
                self.ds.add(c, k, length)
                for kk in range(k, k + length):
                    row = self._id_row.get((c, kk))
                    if row is not None:
                        sk = self._row_segkey(row)
                        if sk is not None:
                            touched.add(sk)
            self._ds_pack = None

        runs: Dict[int, List[int]] = {}  # segkey -> rows, op order
        for rec in recs:
            spec = (
                ("root", rec.parent_root)
                if rec.parent_root is not None
                else ("item",) + tuple(rec.parent_item)
            )
            pref = self._pref_of_spec(spec)
            kid = self._kid_of_key(rec.key) if rec.key is not None else -1
            oc, ock = rec.origin if rec.origin is not None else (-1, -1)
            rc, rk = rec.right if rec.right is not None else (-1, -1)
            row = self.cols.append_row(
                rec.client, rec.clock, kid, pref, oc, ock, rc, rk,
                rec.kind, rec.type_ref, rec.content,
            )
            self._id_row[(rec.client, rec.clock)] = row
            self._next_clock[rec.client] = rec.clock + 1
            sk = pk.segkey_int(pref, kid)
            seg_rows = self._seg_rows.get(sk)
            if seg_rows is None:
                seg_rows = self._seg_rows[sk] = []
                self._seg_kid[sk] = kid
                root = self._root_of(spec)
                if root is not None:
                    self._root_segs.setdefault(root, set()).add(sk)
                else:  # unreachable for local ops; mirrors _admit
                    self._rootless.add(sk)
            seg_rows.append(row)
            if rc >= 0:
                self._seg_rights[sk] = True
            runs.setdefault(sk, []).append(row)

        # convergence + cache: fast shapes (root-map K_ANY set, root-
        # list tail append) patch the plain-JSON cache directly; every
        # other segment goes through _rebuild_cache. Cache values are
        # the stored contents, same references _build_collection uses.
        from crdt_tpu.core.store import K_ANY as _K_ANY

        # ``touched`` here holds ONLY delete-touched segments (the
        # record loop tracks its segments in ``runs``, not here) — a
        # visibility change always rebuilds fully
        slow: set = set(touched)
        fast_roots: Dict[str, set] = {}
        for sk, new_rows in runs.items():
            kid = self._seg_kid.get(sk, -1)
            if kid >= 0:
                ok = self._splice_map_local(sk, new_rows)
            else:
                ok = self._splice_seq_local(sk, new_rows)
            if not ok or sk in slow:
                slow.add(sk)
                continue
            spec = self._seg_spec(sk)
            root = spec[1] if spec is not None and spec[0] == "root" else None
            if root is None or root == "ix":
                slow.add(sk)  # nested / index: full bookkeeping path
                continue
            kinds = self.cols.col("kind")
            if kid >= 0:
                row = self._win[sk]
                tgt = self._cache.get(root)
                if (
                    row in new_rows
                    and int(kinds[row]) == _K_ANY
                    and isinstance(tgt, dict)
                ):
                    kname = self._key_names[kid]
                    tgt[kname] = self.cols.contents[row]
                    fast_roots.setdefault(root, set()).add(kname)
                else:
                    slow.add(sk)
            else:
                tgt = self._cache.get(root)
                if (
                    ok == "append"
                    and isinstance(tgt, list)
                    and all(int(kinds[r]) == _K_ANY for r in new_rows)
                ):
                    tgt.extend(self.cols.contents[r] for r in new_rows)
                    fast_roots.setdefault(root, set())
                else:
                    slow.add(sk)
        if slow:
            self._touch_bookkeeping(slow)
            self._dirty.update(sk for sk in slow if sk in self._seg_rows)
            roots = set(self.last_touched_roots)
            keys = self.last_touched_keys
        else:
            roots, keys = set(), {}
        for root, ks in fast_roots.items():
            roots.add(root)
            if ks:
                keys.setdefault(root, set()).update(ks)
        self.last_touched_roots = sorted(roots)
        self.last_touched_keys = keys

    def _can_fast(self, recs) -> bool:
        """Cheap preflight for :meth:`admit_local`: contiguous clocks
        and resident (or in-batch) dependencies for every record."""
        nxt: Dict[int, int] = {}
        batch_ids: set = set()
        for rec in recs:
            want = nxt.get(rec.client)
            if want is None:
                want = self._next_clock.get(rec.client, 0)
            if rec.clock != want:
                return False
            nxt[rec.client] = rec.clock + 1
            for dep in rec.dep_ids():
                if dep not in self._id_row and dep not in batch_ids:
                    return False
            batch_ids.add((rec.client, rec.clock))
        return True

    def _anchor_rows(self, row: int):
        """Resolve a row's declared origin/right to resident rows via
        the id index. Returns (left, right, left_declared,
        right_declared); a declared-but-unresolvable reference comes
        back None with its declared flag True (callers decide whether
        that is a fallback condition)."""
        c = self.cols
        o = int(c.col("oc")[row])
        left = (
            self._id_row.get((o, int(c.col("ock")[row])))
            if o >= 0 else None
        )
        r = int(c.col("right_client")[row])
        right = (
            self._id_row.get((r, int(c.col("right_clock")[row])))
            if r >= 0 else None
        )
        return left, right, o >= 0, r >= 0

    def _splice_map_local(self, sk: int, new_rows: List[int]) -> bool:
        """Local map sets share the remote path's O(1) tail advance
        (one rule, one implementation); a bent anchor re-derives the
        chain exactly — _host_order_segment repairs any partial _win
        advance wholesale."""
        if self._advance_map_tail(sk, new_rows):
            return True
        self._host_order_segment(sk)
        return False

    def _is_chained_run(self, new_rows: List[int]) -> bool:
        """Verify the contract both local seq splices rely on: the
        batch is ONE chained run at ONE insertion point — each row
        after the head declares the preceding new row as its origin
        and shares the head's right anchor. A caller that batches two
        independent inserts on the same segment into one call bends
        this; verifying here turns silent misordering into the exact
        fallback (advisor finding, round 4)."""
        if len(new_rows) <= 1:
            return True
        c = self.cols
        cl, ck = c.col("client"), c.col("clock")
        oc, ock = c.col("oc"), c.col("ock")
        rc, rk = c.col("right_client"), c.col("right_clock")
        head = new_rows[0]
        hr = (int(rc[head]), int(rk[head]))
        prev = head
        for row in new_rows[1:]:
            if (int(oc[row]), int(ock[row])) != (int(cl[prev]), int(ck[prev])):
                return False
            if (int(rc[row]), int(rk[row])) != hr:
                return False
            prev = row
        return True

    def _advance_seq_tail(self, sk: int, new_rows: List[int]) -> bool:
        """Pure TAIL-append advance for a sequence segment: a chained
        run anchored on the current order tail with no right anchor —
        O(delta), exact, and side-effect free on refusal (unlike
        :meth:`_splice_seq_local`, which re-derives wholesale when its
        preconditions bend). The rehydrated-engine device rounds use
        this to skip the dispatch entirely for steady tail traffic."""
        if not self._is_chained_run(new_rows):
            return False
        head = new_rows[0]
        left_row, right_row, _, right_decl = self._anchor_rows(head)
        if right_decl or right_row is not None:
            return False
        if sk in self._linked:
            tail = self._lnk_tail.get(sk, -1)
            if (left_row if left_row is not None else -1) != tail:
                return False
            prev = left_row
            for row in new_rows:
                self._link_splice(sk, row, prev)
                prev = row
            self._order_stale.add(sk)
            return True
        order = self._order.get(sk)
        if order is None or \
                len(order) + len(new_rows) != len(self._seg_rows[sk]):
            return False
        if not ((left_row is None and not order)
                or (order and left_row == order[-1])):
            return False
        pos_map = self._order_pos.get(sk)
        if pos_map is not None:
            base = len(order)
            for i, row in enumerate(new_rows):
                pos_map[row] = base + i
        order.extend(new_rows)
        # tail append: existing positions unchanged, no epoch bump
        return True

    def _splice_seq_local(self, sk: int, new_rows: List[int]):
        """One local insert run: chained records sharing an insertion
        point. The caller read ``left``/``right`` as ADJACENT rows of
        the cached full order, so the YATA conflict scan between them
        is empty and the run splices verbatim at that point — exact
        regardless of how the surrounding rows were ordered. Moved
        anchors (contract bent) re-derive exactly. Returns "append" /
        "mid" for a fast splice, False after a full re-derive."""
        if not self._is_chained_run(new_rows):
            self._host_order_segment(sk)
            return False
        if sk in self._linked:
            return self._splice_seq_local_linked(sk, new_rows)
        order = self._order.get(sk)
        if order is None:
            order = []
            self._set_order(sk, order)
        if len(order) + len(new_rows) != len(self._seg_rows[sk]):
            # the cached order does not account for every admitted row
            # of this segment — never splice against a partial view
            self._host_order_segment(sk)
            return False
        head = new_rows[0]
        left_row, right_row, _, right_decl = self._anchor_rows(head)
        if right_decl and right_row is None:
            self._host_order_segment(sk)  # dangling right: full path
            return False
        if right_row is None:
            if (left_row is None and not order) or (
                order and left_row == order[-1]
            ):
                pos_map = self._order_pos.get(sk)
                if pos_map is not None:
                    base = len(order)
                    for i, row in enumerate(new_rows):
                        pos_map[row] = base + i
                order.extend(new_rows)
                # tail append: existing positions unchanged, no bump
                return "append"
        else:
            pos = self.order_position(sk, right_row)
            if pos is not None and (
                (pos == 0 and left_row is None)
                or (pos > 0 and left_row == order[pos - 1])
            ):
                # a mid-insert on the LIST form pays an O(segment)
                # memmove per op; the first one converts the segment
                # to its linked-chain form (one O(segment) pass), so
                # an editing run of mid-inserts is O(1) each after
                # (the keystroke regime — VERDICT r4 item 8)
                if self._build_links(sk, len(new_rows)):
                    return self._splice_seq_local_linked(sk, new_rows)
                order[pos:pos] = new_rows
                self._order_pos.pop(sk, None)  # positions shifted
                self._bump_epoch(sk)
                return "mid"
        self._host_order_segment(sk)
        return False

    def _splice_seq_local_linked(self, sk: int, new_rows: List[int]):
        """The linked-chain variant of the local splice: O(1) pointer
        surgery, same adjacency contract."""
        head = new_rows[0]
        left_row, right_row, _, right_decl = self._anchor_rows(head)
        if right_decl and right_row is None:
            self._host_order_segment(sk)  # dangling right: full path
            return False
        expected = (
            self._lnk_next.get(left_row, -1) if left_row is not None
            else self._lnk_head.get(sk, -1)
        )
        if expected != (right_row if right_row is not None else -1):
            self._host_order_segment(sk)  # anchors moved: re-derive
            return False
        prev = left_row
        for row in new_rows:
            self._link_splice(sk, row, prev)
            prev = row
        self._order_stale.add(sk)
        return "append" if right_row is None else "mid"

    def _row_segkey(self, row: int) -> Optional[int]:
        pref = int(self.cols.col("pref")[row])
        if pref < 0:
            return None
        return int(pk.segkey_of(
            np.int64(pref), np.int64(self.cols.col("kid")[row])
        ))

    # -- admission (vectorized) ---------------------------------------
    def _admit(self, dec) -> np.ndarray:
        """Stable-intern a decoded batch, gate it through the engine's
        admission rule (per-client clock contiguity + origin/right/
        parent presence; failures stash in ``_pending`` and retry every
        apply), and append the admitted rows. Returns the new host row
        indices (np array, possibly empty)."""
        from crdt_tpu.core.store import K_GC

        n = len(dec["client"])
        client = dec["client"].astype(np.int64)
        clock = dec["clock"].astype(np.int64)

        # dedup vs resident (bulk dict probes) — in-batch duplicates
        # were already dropped by native.dedup_columns
        tups = list(zip(client.tolist(), clock.tolist()))
        fresh = np.fromiter(
            (t not in self._id_row for t in tups), bool, count=n
        )
        idx = np.flatnonzero(fresh)
        k = len(idx)
        if k == 0 and not self._pending:
            return idx

        pr = dec["parent_root"][idx].astype(np.int64)
        pc = dec["parent_client"][idx].astype(np.int64)
        pkk = dec["parent_clock"][idx].astype(np.int64)
        bkid = dec["key_id"][idx].astype(np.int64)
        oc = dec["origin_client"][idx].astype(np.int64)
        ock = dec["origin_clock"][idx].astype(np.int64)
        rc = dec["right_client"][idx].astype(np.int64)
        rk = dec["right_clock"][idx].astype(np.int64)
        kind = dec["kind"][idx].astype(np.int64)
        cl = client[idx]
        ck = clock[idx]

        # stable key ids (batch table -> stable table)
        key_map = np.asarray(
            [self._kid_of_key(name) for name in dec["keys"]], np.int64
        )
        kid = np.full(k, -1, np.int64)
        mk_ = bkid >= 0
        if mk_.any():
            kid[mk_] = key_map[bkid[mk_]]

        # explicit parent refs
        root_map = np.asarray(
            [self._pref_of_spec(("root", name)) for name in dec["roots"]],
            np.int64,
        )
        pref = np.full(k, -1, np.int64)
        m_root = pr >= 0
        if m_root.any():
            pref[m_root] = root_map[pr[m_root]]
        m_item = (~m_root) & (pc >= 0)
        if m_item.any():
            pairs = np.stack([pc[m_item], pkk[m_item]], axis=1)
            uniq, inv = np.unique(pairs, axis=0, return_inverse=True)
            refs = np.asarray(
                [
                    self._pref_of_spec(("item", int(a), int(b)))
                    for a, b in uniq
                ],
                np.int64,
            )
            pref[m_item] = refs[inv]

        # merge the pending stash (retry with this batch), dropping
        # stashed ids redelivered in this very batch
        contents = [dec["contents"][i] for i in idx.tolist()]
        tref = dec["type_ref"][idx].astype(np.int64)
        if self._pending:
            fresh_ids = set(zip(cl.tolist(), ck.tolist()))
            pend = [
                (pid, row) for pid, row in self._pending.items()
                if pid not in fresh_ids
            ]
            if pend:
                parr = np.asarray([row[:9] for _, row in pend], np.int64)
                cl = np.concatenate([cl, parr[:, 0]])
                ck = np.concatenate([ck, parr[:, 1]])
                pref = np.concatenate([pref, parr[:, 2]])
                kid = np.concatenate([kid, parr[:, 3]])
                oc = np.concatenate([oc, parr[:, 4]])
                ock = np.concatenate([ock, parr[:, 5]])
                rc = np.concatenate([rc, parr[:, 6]])
                rk = np.concatenate([rk, parr[:, 7]])
                kind = np.concatenate([kind, parr[:, 8]])
                tref = np.concatenate(
                    [tref, np.asarray([row[9] for _, row in pend])]
                )
                contents.extend(row[10] for _, row in pend)
            self._pending = {}
        k = len(cl)
        if k == 0:
            return np.empty(0, np.int64)

        # (client, clock) -> batch index, shared by the implicit-parent
        # resolution and the admission gate's dependency lookups
        btups = {t: j for j, t in enumerate(zip(cl.tolist(), ck.tolist()))}

        # implicit parents/keys: pointer doubling over the
        # origin-else-right graph (in-batch hops; refs that hit the
        # resident union terminate with its pref/kid immediately)
        need = (pref < 0) & (kind != K_GC)
        if need.any():
            ref_c = np.where(oc >= 0, oc, rc)
            ref_k = np.where(oc >= 0, ock, rk)
            has_ref = ref_c >= 0
            ptr = np.arange(k)
            term_pref = pref.copy()
            term_kid = kid.copy()
            rlist = list(zip(ref_c.tolist(), ref_k.tolist()))
            for j in np.flatnonzero(need & has_ref):
                t = rlist[j]
                jj = btups.get(t)
                if jj is not None:
                    ptr[j] = jj
                else:
                    row = self._id_row.get(t)
                    if row is not None:
                        term_pref[j] = self.cols.col("pref")[row]
                        if term_kid[j] < 0:
                            term_kid[j] = self.cols.col("kid")[row]
            rounds = max(1, (max(k, 2) - 1).bit_length() + 1)
            for _ in range(rounds):
                gp = term_pref[ptr]
                gk = term_kid[ptr]
                upd = term_pref < 0
                term_pref = np.where(upd, gp, term_pref)
                term_kid = np.where(upd & (term_kid < 0), gk, term_kid)
                ptr = ptr[ptr]
            pref = np.where(need, term_pref, pref)
            kid = np.where(need & (kid < 0), term_kid, kid)

        # ---- admission gate: the ENGINE's rule ----------------------
        # a row integrates only when its clock is the next for its
        # client (contiguity) and its origin/right/item-parent are all
        # present (resident, or admitted in this same pass). Failures
        # stash in _pending and retry on every later apply.
        sort_ord = np.lexsort((ck, cl))
        cl_s, ck_s = cl[sort_ord], ck[sort_ord]
        run_starts = np.flatnonzero(np.r_[True, cl_s[1:] != cl_s[:-1]])
        run_ends = np.r_[run_starts[1:], k]
        nxt0 = np.asarray([
            self._next_clock.get(int(cl_s[s]), 0) for s in run_starts
        ])

        if self._pref_item_c:
            pic = np.asarray(self._pref_item_c, np.int64)
            pik = np.asarray(self._pref_item_k, np.int64)
            dep_pc = np.where(pref >= 0, pic[np.clip(pref, 0, None)], -1)
            dep_pk = np.where(pref >= 0, pik[np.clip(pref, 0, None)], -1)
        else:
            dep_pc = np.full(k, -1, np.int64)
            dep_pk = np.full(k, -1, np.int64)

        def dep_state(c_arr, k_arr):
            """(in_resident, in_batch_index) per row; -1 = no dep."""
            res = np.zeros(k, bool)
            bidx2 = np.full(k, -1, np.int64)
            for j in np.flatnonzero(c_arr >= 0):
                t = (int(c_arr[j]), int(k_arr[j]))
                if t in self._id_row:
                    res[j] = True
                else:
                    bidx2[j] = btups.get(t, -1)
            return res, bidx2

        deps = [
            dep_state(oc, ock),
            dep_state(rc, rk),
            dep_state(dep_pc, dep_pk),
        ]
        dep_c = [oc, rc, dep_pc]

        admit = np.ones(k, bool)
        while True:
            adm_s = admit[sort_ord]
            ok_s = np.zeros(k, bool)
            for r, (s, e) in enumerate(zip(run_starts, run_ends)):
                ok_s[s:e] = np.logical_and.accumulate(
                    adm_s[s:e]
                    & (ck_s[s:e] - nxt0[r] == np.arange(e - s))
                )
            new_admit = np.zeros(k, bool)
            new_admit[sort_ord] = ok_s
            for (res, bidx2), c_arr in zip(deps, dep_c):
                has = c_arr >= 0
                in_batch_ok = (bidx2 >= 0) & new_admit[
                    np.clip(bidx2, 0, None)
                ]
                new_admit &= ~has | res | in_batch_ok
            if (new_admit == admit).all():
                break
            admit = new_admit

        # stash the blocked rows
        blocked = np.flatnonzero(~admit)
        for j in blocked.tolist():
            self._pending[(int(cl[j]), int(ck[j]))] = (
                int(cl[j]), int(ck[j]), int(pref[j]), int(kid[j]),
                int(oc[j]), int(ock[j]), int(rc[j]), int(rk[j]),
                int(kind[j]), int(tref[j]), contents[j],
            )
        if (
            self.pending_limit is not None
            and len(self._pending) > self.pending_limit
        ):
            self._evict_pending()
        if not admit.any():
            return np.empty(0, np.int64)
        # bump per-client next clocks past the admitted runs
        adm_s = admit[sort_ord]
        for r, (s, e) in enumerate(zip(run_starts, run_ends)):
            cnt = int(adm_s[s:e].sum())
            if cnt:
                self._next_clock[int(cl_s[s])] = int(nxt0[r]) + cnt

        a = np.flatnonzero(admit)
        cl, ck, pref, kid = cl[a], ck[a], pref[a], kid[a]
        oc, ock, rc, rk = oc[a], ock[a], rc[a], rk[a]
        kind, tref = kind[a], tref[a]
        contents = [contents[j] for j in a.tolist()]
        k = len(a)

        rows = np.arange(self.cols.n, self.cols.n + k)
        self._id_row.update(zip(
            zip(cl.tolist(), ck.tolist()), rows.tolist()
        ))
        self.cols.append(
            {
                "client": cl, "clock": ck, "kid": kid, "pref": pref,
                "oc": oc, "ock": ock, "right_client": rc,
                "right_clock": rk, "kind": kind, "type_ref": tref,
            },
            contents,
        )

        # segment bookkeeping, grouped per distinct segkey
        live = (pref >= 0) & (kind != K_GC)
        if live.any():
            sks = pk.segkey_of(pref[live], kid[live])
            live_rows = rows[live]
            order = np.argsort(sks, kind="stable")
            sks_s, rows_s = sks[order], live_rows[order]
            rights_s = (rc[live] >= 0)[order]
            cuts = np.r_[
                0, np.flatnonzero(sks_s[1:] != sks_s[:-1]) + 1, len(sks_s)
            ]
            for a, b in zip(cuts[:-1], cuts[1:]):
                sk = int(sks_s[a])
                grp = rows_s[a:b]
                grp_list = grp.tolist()
                # batch order within the segment (stable sort): the
                # incremental integrate's deferral loop relies on it
                self._seg_rows.setdefault(sk, []).extend(grp_list)
                self._new_by_seg[sk] = grp_list
                if sk not in self._seg_kid:
                    self._seg_kid[sk] = int(
                        self.cols.col("kid")[int(grp[0])]
                    )
                if rights_s[a:b].any():
                    self._seg_rights[sk] = True
                root = self._root_of(self._spec_of_row(int(grp[0])))
                if root is not None:
                    self._root_segs.setdefault(root, set()).add(sk)
                else:
                    self._rootless.add(sk)
        return rows

    def _evict_pending(self) -> None:
        """Shrink the stash to ``pending_limit``: drop the ids deepest
        in their own client's queue (the shared fairness/recovery
        policy — :func:`crdt_tpu.guard.limits.evict_deepest`) and
        record the evicted ranges for the replica's targeted
        re-probe."""
        from crdt_tpu.guard.limits import evict_deepest

        evicted, ranges = evict_deepest(
            list(self._pending), self.pending_limit
        )
        for key in evicted:
            del self._pending[key]
        for c, (lo, hi) in ranges.items():
            plo, phi = self.evicted_ranges.get(c, (lo, hi))
            self.evicted_ranges[c] = (min(plo, lo), max(phi, hi))
        if evicted:
            from crdt_tpu.obs.tracer import get_tracer

            get_tracer().count("engine.pending_evictions", len(evicted))

    def take_evicted_ranges(self) -> Dict[int, Tuple[int, int]]:
        """Drain evicted-range bookkeeping (Engine contract)."""
        ev, self.evicted_ranges = self.evicted_ranges, {}
        return ev

    # -- cache laziness -----------------------------------------------
    @property
    def cache(self) -> dict:
        """The plain-JSON view, flushed on read: rounds only mark
        touched segments dirty, so a replica that is never read pays
        no materialization (crdt.js's `c` equivalent)."""
        if self.pool is not None and self.pool.has_pending(self):
            # a deferred pooled round must settle before the rebuild
            # reads winners/orders
            self.pool.flush()
        if self._dirty:
            dirty, self._dirty = self._dirty, set()
            try:
                self._rebuild_cache(dirty)
            except BaseException:
                # a failed rebuild must not mark the segments clean:
                # the JSON view would stay permanently stale while
                # reporting fresh (advisor finding, round 4)
                self._dirty |= dirty
                raise
        return self._cache

    # -- order access (list, positions, linked chains) ----------------
    def _bump_epoch(self, sk: int) -> None:
        self._order_epoch[sk] = self._order_epoch.get(sk, 0) + 1

    def order_epoch(self, sk: int) -> int:
        """Monotone per-segment counter: unchanged value between two
        reads guarantees document positions and visibility in the
        segment did not move (callers key position caches on it)."""
        return self._order_epoch.get(sk, 0)

    def _set_order(self, sk: int, rows: List[int]) -> None:
        """Every whole-order reassignment goes through here so the
        lazy position map and the linked chain can never serve a
        stale view."""
        self._drop_links(sk)
        self._order[sk] = rows
        self._order_pos.pop(sk, None)
        self._bump_epoch(sk)

    def order_list(self, sk: int) -> List[int]:
        """The segment's document order as a list, materializing from
        the linked chain when the list is stale."""
        if sk in self._order_stale:
            out = []
            nxt = self._lnk_next
            cur = self._lnk_head.get(sk, -1)
            while cur != -1:
                out.append(cur)
                cur = nxt.get(cur, -1)
            self._order[sk] = out
            self._order_pos.pop(sk, None)
            self._order_stale.discard(sk)
        return self._order.get(sk, [])

    def order_position(self, sk: int, row: int) -> Optional[int]:
        """Position of ``row`` in segment ``sk``'s cached order, O(1)
        amortized via the lazy row->position map."""
        pos = self._order_pos.get(sk)
        if pos is None:
            pos = {r: i for i, r in enumerate(self.order_list(sk))}
            self._order_pos[sk] = pos
        return pos.get(row)

    def iter_order(self, sk: int):
        """Forward document-order iteration without materializing a
        stale list (O(1) per step on linked segments)."""
        if sk in self._linked:
            nxt = self._lnk_next
            cur = self._lnk_head.get(sk, -1)
            while cur != -1:
                yield cur
                cur = nxt.get(cur, -1)
        else:
            yield from self._order.get(sk, ())

    def iter_order_reversed(self, sk: int):
        if sk in self._linked:
            prv = self._lnk_prev
            cur = self._lnk_tail.get(sk, -1)
            while cur != -1:
                yield cur
                cur = prv.get(cur, -1)
        else:
            yield from reversed(self._order.get(sk, ()))

    def iter_order_after(self, sk: int, row: int):
        """Forward document-order iteration starting AFTER ``row``
        (O(1) per step on linked segments; empty when the row is
        unknown to the cached order)."""
        if sk in self._linked:
            nxt = self._lnk_next
            cur = nxt.get(row, -1)
            while cur != -1:
                yield cur
                cur = nxt.get(cur, -1)
        else:
            pos = self.order_position(sk, row)
            if pos is None:
                return
            lst = self._order.get(sk, [])
            for i in range(pos + 1, len(lst)):
                yield lst[i]

    def iter_order_before(self, sk: int, row: int):
        """Reverse document-order iteration starting BEFORE ``row``."""
        if sk in self._linked:
            prv = self._lnk_prev
            cur = prv.get(row, -1)
            while cur != -1:
                yield cur
                cur = prv.get(cur, -1)
        else:
            pos = self.order_position(sk, row)
            if pos is None:
                return
            lst = self._order.get(sk, [])
            for i in range(pos - 1, -1, -1):
                yield lst[i]

    def order_next_row(self, sk: int, row: int) -> Optional[int]:
        """The row immediately after ``row`` in full document order
        (None at the tail / when the row is unknown)."""
        if sk in self._linked:
            n = self._lnk_next.get(row, -1)
            return None if n == -1 else n
        rows = self._order.get(sk, [])
        i = self.order_position(sk, row)
        if i is None or i + 1 >= len(rows):
            return None
        return rows[i + 1]

    def _build_links(self, sk: int, n_new: int) -> bool:
        """Thread the linked chain through the current (fresh) order.
        False when the order does not account for every admitted row
        except the ``n_new`` incoming ones — callers then re-derive."""
        order = self._order.get(sk, [])
        if len(order) + n_new != len(self._seg_rows[sk]):
            return False
        nxt, prv = self._lnk_next, self._lnk_prev
        prev = -1
        for r in order:
            if prev == -1:
                self._lnk_head[sk] = r
            else:
                nxt[prev] = r
            prv[r] = prev
            prev = r
        if prev != -1:
            nxt[prev] = -1
            self._lnk_tail[sk] = prev
        self._linked.add(sk)
        return True

    def _drop_links(self, sk: int) -> None:
        if sk not in self._linked:
            return
        nxt, prv = self._lnk_next, self._lnk_prev
        cur = self._lnk_head.pop(sk, -1)
        while cur != -1:
            nn = nxt.pop(cur, -1)
            prv.pop(cur, None)
            cur = nn
        self._lnk_tail.pop(sk, None)
        self._linked.discard(sk)
        self._order_stale.discard(sk)

    def _link_splice(self, sk: int, row: int, left: Optional[int]) -> None:
        """Insert ``row`` immediately after ``left`` (None = head)."""
        nxt, prv = self._lnk_next, self._lnk_prev
        if left is None:
            n = self._lnk_head.get(sk, -1)
            self._lnk_head[sk] = row
            prv[row] = -1
        else:
            n = nxt.get(left, -1)
            nxt[left] = row
            prv[row] = left
        nxt[row] = n
        if n != -1:
            # a TAIL append leaves every existing position and
            # visibility intact — only non-tail splices invalidate
            # cached positions (the edit cursor survives append runs)
            self._bump_epoch(sk)
            prv[n] = row
        else:
            self._lnk_tail[sk] = row

    # -- incremental convergence (the round-4 steady-state core) ------
    def _advance_map_tail(self, sk: int, new_rows: List[int]) -> bool:
        """Map delta whose every row chains onto the then-current
        winner: the tail has no children (or it would not be the
        walk's endpoint), so each row becomes the new tail — O(1),
        any client. Anything else returns False for the full walk."""
        c = self.cols
        oc = c.col("oc")
        ock = c.col("ock")
        cl = c.col("client")
        ck = c.col("clock")
        for row in new_rows:
            prev = self._win.get(sk)
            if prev is not None:
                if (
                    int(oc[row]) == int(cl[prev])
                    and int(ock[row]) == int(ck[prev])
                ):
                    self._win[sk] = row
                    continue
                return False
            if (
                int(oc[row]) < 0
                and len(self._seg_rows[sk]) <= len(new_rows)
            ):
                self._win[sk] = row  # first row of a fresh chain
                continue
            return False
        return True

    def _integrate_remote_seq(self, sk: int, new_rows: List[int]) -> bool:
        """Engine-verbatim YATA conflict scan (crdt.js:294 via
        core/engine.py ``_integrate_into_chain``) splicing a delta
        into this segment's linked chain: O(delta x scan window), not
        O(segment). Preconditions — every new row's declared origin
        and right must resolve to a row of THIS segment (or be an
        in-batch new row, handled by deferral) — keep cross-segment /
        GC / dangling-reference shapes on the full path, whose
        dropping conventions differ. Returns False untouched when any
        precondition fails."""
        c = self.cols
        cl = c.col("client")
        oc = c.col("oc")
        ock = c.col("ock")
        rc = c.col("right_client")
        rk = c.col("right_clock")
        newset = set(new_rows)
        resolved: Dict[int, Tuple[Optional[int], Optional[int]]] = {}
        for row in new_rows:
            left, right, left_decl, right_decl = self._anchor_rows(row)
            if left_decl and (
                left is None
                or (left not in newset and self._row_segkey(left) != sk)
            ):
                return False
            if right_decl and (
                right is None
                or (right not in newset and self._row_segkey(right) != sk)
            ):
                return False
            resolved[row] = (left, right)
        if sk not in self._linked and not self._build_links(
            sk, len(new_rows)
        ):
            return False

        nxt = self._lnk_next
        unplaced = set(new_rows)
        queue = list(new_rows)
        # total scan-step budget: the conflict scan walks the window
        # between a row's anchors, and for a COLD multi-writer backlog
        # (anchors thousands of items stale) that degenerates to the
        # scalar engine's quadratic cost — the exact wholesale reorder
        # handles that shape in one vectorized pass instead. Live
        # steady-state rounds never approach the budget (anchors are
        # near-adjacent when deltas are fresh).
        scan_budget = max(4096, 32 * len(new_rows))
        while queue:
            progress = False
            defer = []
            for row in queue:
                left0, right0 = resolved[row]
                if left0 in unplaced or right0 in unplaced:
                    defer.append(row)
                    continue
                x_client = int(cl[row])
                x_right = (int(rc[row]), int(rk[row]))
                left = left0
                o = (
                    nxt.get(left, -1) if left is not None
                    else self._lnk_head.get(sk, -1)
                )
                conflicting: set = set()
                before: set = set()
                while o != -1 and (right0 is None or o != right0):
                    scan_budget -= 1
                    if scan_budget < 0:
                        self._host_order_segment(sk)
                        return True
                    before.add(o)
                    conflicting.add(o)
                    o_oc = int(oc[o])
                    o_origin_row = (
                        self._id_row.get((o_oc, int(ock[o])))
                        if o_oc >= 0 else None
                    )
                    if o_origin_row == left0:
                        # case 1: same left origin -> client id order
                        if int(cl[o]) < x_client:
                            left = o
                            conflicting.clear()
                        elif (int(rc[o]), int(rk[o])) == x_right:
                            break
                    elif (
                        o_origin_row is not None
                        and o_origin_row in before
                    ):
                        # case 2: o's origin inside the scanned region
                        if o_origin_row not in conflicting:
                            left = o
                            conflicting.clear()
                    else:
                        break
                    o = nxt.get(o, -1)
                self._link_splice(sk, row, left)
                unplaced.discard(row)
                progress = True
            if not progress:
                # in-batch reference cycle: the full path's conventions
                # decide (links now hold a prefix; re-derive wholesale)
                self._host_order_segment(sk)
                return True
            queue = defer
        self._order_stale.add(sk)
        return True

    def _seg_spec(self, sk: int) -> Optional[Tuple]:
        rows = self._seg_rows.get(sk)
        return self._spec_of_row(rows[0]) if rows else None

    def _root_of(self, spec) -> Optional[str]:
        if spec is None:
            return None
        if spec in self._spec_root:
            return self._spec_root[spec]
        seen = []
        seen_set = set()
        cur = spec
        root = None
        while cur is not None and cur not in self._spec_root:
            if cur in seen_set:
                break  # hostile parent-item cycle: no root, no memo
            seen.append(cur)
            seen_set.add(cur)
            if cur[0] == "root":
                root = cur[1]
                break
            row = self._id_row.get((cur[1], cur[2]))
            cur = self._spec_of_row(row) if row is not None else None
        else:
            root = self._spec_root.get(cur)
        if root is not None:
            # an unresolvable chain (parent item not delivered yet)
            # must NOT be memoized: the parent may arrive in a later
            # batch, and _admit retries rootless segments then
            for s in seen:
                self._spec_root[s] = root
        return root

    # -- device round -------------------------------------------------
    def _device_round(self, by_seg: Dict[int, List[int]]) -> None:
        jax, jnp = self._jax, self._jnp
        touched = set(by_seg)

        # split touched: device-convergeable vs right-bearing (host)
        dev_segs = sorted(
            sk for sk in touched
            if sk in self._seg_rows and not self._seg_rights.get(sk)
        )
        if self._from_snapshot and dev_segs:
            # snapshot-rehydrated engine (round 21): the restored
            # winner/order caches are exact, so a tail-shaped delta
            # advances host-side in O(delta) — the alternative is an
            # O(doc) re-splice of the whole column set into a fresh
            # matrix (n_dev=0), which would make every recovery pay
            # the full device promotion just to append. Rows handled
            # here stay in the unspliced backlog; the first round the
            # fast shapes refuse dispatches them all at once.
            still = []
            for sk in dev_segs:
                new = by_seg.get(sk)
                if new:
                    if self._seg_kid.get(sk, -1) >= 0:
                        if self._advance_map_tail(sk, new):
                            continue
                    elif self._advance_seq_tail(sk, new):
                        continue
                still.append(sk)
            dev_segs = still
        host_segs = [
            sk for sk in touched
            if sk in self._seg_rows and self._seg_rights.get(sk)
        ]
        # host/device crossover: small rounds are exact on host against
        # the resident columns (the fixed per-dispatch cost dominates
        # below the threshold; see the class docstring). Host rounds do
        # ZERO device work — their rows accumulate, and the next device
        # round splices the whole unspliced tail (n_dev marks the
        # boundary: admission appends rows in order, so host row ids
        # and device positions stay identical)
        if dev_segs:
            n_sel = sum(len(self._seg_rows[sk]) for sk in dev_segs)
            thr = self.device_min_rows
            if thr is None:
                # AUTO: the shared crossover rule (static floor, then
                # the session-calibrated threshold — VERDICT r3 item 2)
                go_host = self.crossover_use_host(n_sel)
            else:
                go_host = n_sel < thr
            if go_host:
                host_segs.extend(dev_segs)
                dev_segs = []

        if dev_segs and self.pool is not None:
            # pooled route (round 20): the device part of this round
            # DEFERS — the pool's flush splices every warm doc's
            # tail and converges all touched segments in ONE
            # dispatch. Winners/orders of the deferred segments stay
            # stale until that flush; every read path (cache,
            # admit_local, the next apply) force-flushes first.
            if self.pool.defer(self, dev_segs):
                dev_segs = []
            else:
                # pool budget refused the doc's extent
                # (CRDT_TPU_MT_POOL_BYTES): permanent fallback to the
                # private matrix. This round routes host-side (exact)
                # — including anything the pool still held deferred —
                # and the next device round re-splices the whole host
                # column set into a fresh private matrix (n_dev=0).
                pend = self.pool.take_pending(self)
                self.pool.release(self)
                self.pool = None
                self._mat = None
                self.n_dev = 0
                host_segs.extend(
                    (set(dev_segs) | pend) - set(host_segs)
                )
                dev_segs = []

        if dev_segs:
            # stage the UNSPLICED TAIL (this batch + any rows host
            # rounds left behind) as a packed matrix; row 7 carries
            # the touched-segment keys so the whole round is ONE
            # upload + ONE dispatch + ONE fetch (crossover floor)
            rows = np.arange(self.n_dev, self.cols.n)
            k = len(rows)
            oc_tail = self.cols.col("oc")[rows]
            tpad = _octave(len(dev_segs), floor=1 << 10)
            kpad = max(_octave(k, floor=1 << 6), tpad)

            from crdt_tpu.guard.device import dispatch_guarded
            from crdt_tpu.ops.device import xfer_fetch, xfer_put

            n_sel = sum(len(self._seg_rows[sk]) for sk in dev_segs)

            def _dispatch():
                # EVERY device interaction of the round — client
                # interning (which may relabel the resident matrix)
                # and matrix allocation/growth included — runs inside
                # the guarded attempt, so a dying device (or a matrix
                # invalidated by a previous post-donation failure)
                # lands in the ladder instead of escaping as a raw
                # RuntimeError. Idempotent on retry: intern commits
                # only after its relabel succeeds, ensure/grow are
                # no-ops once capacity exists, and the delta block is
                # rebuilt per attempt.
                self._intern_clients(np.concatenate([
                    self.cols.col("client")[rows], oc_tail[oc_tail >= 0],
                ]))
                oc_raw = oc_tail
                # the shared resident-base delta staging (ops/packed):
                # rows without a resolvable parent (incl. GC fillers)
                # stay invalid on device — origin lookups that miss
                # them fall back to root attachment, same convention
                # as the cold path
                delta = pk.stage_resident_delta(
                    self._dense_of(self.cols.col("client")[rows]),
                    self.cols.col("clock")[rows],
                    self.cols.col("pref")[rows],
                    self.cols.col("kid")[rows],
                    np.where(oc_raw >= 0, self._dense_of(
                        np.clip(oc_raw,
                                self._clients[0] if self._clients else 0,
                                None)
                    ), -1),
                    self.cols.col("ock")[rows],
                    dev_segs, kpad,
                )
                self._ensure_mat()
                need = self.n_dev + kpad
                with enable_x64(True):
                    if need > self._mat.shape[1]:
                        self._mat = pk._grow_mat(
                            self._mat, new_cap=bucket_pow2(need)
                        )
                    sel_bucket = min(
                        _octave(n_sel, floor=1 << 13),
                        self._mat.shape[1],
                    )
                    # the round's ONE upload: the delta block only —
                    # the resident matrix is donated in place, so
                    # steady-state bytes-on-link scale with the delta,
                    # never the doc (xfer.h2d_bytes pins this)
                    mat, packed_out = pk._splice_select_converge(
                        self._mat,
                        xfer_put(delta, label="incremental.delta"),
                        jnp.int32(self.n_dev),
                        num_segments=tpad,
                        sel_bucket=sel_bucket, seq_bucket=sel_bucket,
                        mode=pk.kernel_mode_for(sel_bucket),
                        # rounds stay at the sel_bucket bound (None):
                        # the splice path numbers segments ON DEVICE,
                        # and rows whose origins are still in flight
                        # root-attach there — so device segment
                        # populations can exceed any host-side
                        # `_seg_rows` count (fleet swarms with drops/
                        # delays hit this). The round-23 tightened
                        # bound only applies where numbering is
                        # host-side (packed._stage, ops/shard)
                        rank_rounds=None, map_rounds=None,
                    )
                    # the round's ONE fetch
                    return mat, xfer_fetch(
                        packed_out, label="incremental.out"
                    ), sel_bucket

            # device failure ladder (crdt_tpu/guard): retry once, then
            # route the WHOLE round host-side — host segments converge
            # against the resident columns with zero device work, and
            # the unspliced tail simply waits for the next healthy
            # device round (the same contract the crossover uses), so
            # a dying device costs latency, never state. The matrix is
            # only reassigned on success.
            res = dispatch_guarded(
                "incremental.converge", _dispatch, host=lambda: None
            )
            if res is None:
                # ladder exhausted: a post-donation execution failure
                # may have invalidated the resident matrix, so drop it
                # — the next device round re-splices the ENTIRE host
                # column set into a fresh matrix (n_dev=0). A full
                # rebuild once the device heals, never a permanent
                # host-route degrade on a healthy device.
                self._mat = None
                self.n_dev = 0
                host_segs.extend(dev_segs)
                dev_segs = []
        if dev_segs:
            self._mat, h, sel_bucket = res
            pk.count_device_dispatch()
            # advance by the REAL row count: the padded tail is
            # invalid and the next splice overwrites it, keeping
            # device positions identical to host row ids
            self.n_dev += k
            s = tpad
            b = sel_bucket
            win_local = h[:s]
            stream_seg = h[s : s + b]
            stream_row = h[s + b : s + 2 * b]
            sel_rows = h[s + 2 * b : s + 3 * b]
            # map winners: local -> resident row -> segkey
            for w in win_local[win_local >= 0]:
                row = int(sel_rows[w])
                sk = self._row_segkey(row)
                self._win[sk] = row
            # sequence orders: split the stream on segment change
            m = stream_row >= 0
            rows_s, segs_s = stream_row[m], stream_seg[m]
            if len(rows_s):
                res_rows = sel_rows[rows_s]
                cuts = np.r_[
                    0, np.flatnonzero(segs_s[1:] != segs_s[:-1]) + 1,
                    len(segs_s),
                ]
                for a, bnd in zip(cuts[:-1], cuts[1:]):
                    chunk = res_rows[a:bnd].tolist()
                    self._set_order(self._row_segkey(chunk[0]), chunk)
        # host rounds: no device work at all — the unspliced tail
        # waits for the next device round (see the crossover comment).
        # Each segment first tries the INCREMENTAL path (O(delta), the
        # round-4 steady-state fix); shapes outside its preconditions
        # re-derive wholesale, exactly as before.
        for sk in host_segs:
            new = by_seg.get(sk)
            if new:
                if self._seg_kid.get(sk, -1) >= 0:
                    if self._advance_map_tail(sk, new):
                        continue
                else:
                    existing = len(self._seg_rows[sk]) - len(new)
                    # bulk deltas (cold merge, long catch-up) have
                    # anchors stale by construction: the budgeted
                    # conflict scan would exhaust its whole budget and
                    # THEN re-derive (measured: ~0.9s burnt on a 20k
                    # cold text backlog before the identical wholesale
                    # pass ran). When the delta rivals the resident
                    # segment, re-derive directly.
                    if len(new) <= max(256, existing // 2) and \
                            self._integrate_remote_seq(sk, new):
                        continue
            self._host_order_segment(sk)

    def _host_order_segment(self, sk: int) -> None:
        """Exact ordering for one right-bearing segment via the host
        machinery (same split as the cold gather)."""
        from crdt_tpu.core.records import ItemRecord
        from crdt_tpu.core.store import K_GC
        from crdt_tpu.ops.yata import order_sequences

        rows = self._seg_rows[sk]
        if not self._seg_rights.get(sk):
            # right-free segment on the host path (below the device
            # crossover): the exact sibling model — (client asc,
            # clock DESC) under origin trees — in plain Python, with
            # no kernel dispatch and no throwaway engine. This is the
            # keystroke path: a replica's own op or a peer's small
            # delta costs O(segment), not a jit round-trip.
            self._host_order_fast(sk, rows)
            return
        if self._seg_kid.get(sk, -1) >= 0:
            # right-bearing MAP chain: exact tail via chain order
            from crdt_tpu.ops.yata import order_hard_segment

            recs = [self._record_of(r, parent_root="x") for r in rows]
            ordered = order_hard_segment(
                recs, ref_exists=lambda ref: ref in self._id_row
            )
            if ordered:
                self._win[sk] = self._id_row[ordered[-1]]
            return
        spec = self._seg_spec(sk)
        recs = [self._record_of(r) for r in rows]
        sub_ids = {r.id for r in recs}
        stubs = {
            ref
            for r in recs
            for ref in (r.origin, r.right)
            if ref is not None and ref not in sub_ids
            and ref in self._id_row
        }
        recs += [ItemRecord(client=c, clock=k, kind=K_GC) for c, k in stubs]
        orders = order_sequences(recs)
        ids = orders.get(
            spec if spec[0] == "root" else ("item", spec[1], spec[2]), []
        )
        self._set_order(sk, [self._id_row[i] for i in ids])

    def _host_order_fast(self, sk: int, rows: List[int]) -> None:
        """Exact convergence of one RIGHT-FREE segment in plain
        Python: origins resolved within the segment form the tree
        (missing/cross-segment origins attach to the root, the shared
        GC'd-origin convention), siblings order by (client asc, clock
        DESC). Maps take the last-child walk to the chain tail
        (= ``map_winners``); sequences take the DFS pre-order
        (= ``tree_order_ranks`` with the same keys)."""
        c = self.cols
        cl = c.col("client")
        ck = c.col("clock")
        oc = c.col("oc")
        ock = c.col("ock")
        rowset = set(rows)

        def parent_of(r: int):
            o = int(oc[r])
            if o < 0:
                return None
            p = self._id_row.get((o, int(ock[r])))
            return p if p is not None and p in rowset else None

        children: Dict[Optional[int], list] = {}
        for r in rows:
            children.setdefault(parent_of(r), []).append(r)

        if self._seg_kid.get(sk, -1) >= 0:
            # chain tail: repeatedly step to the (max client, min
            # clock) child
            cur: Optional[int] = None
            while True:
                kids = children.get(cur)
                if not kids:
                    break
                cur = max(kids, key=lambda r: (int(cl[r]), -int(ck[r])))
            if cur is not None:
                self._win[sk] = cur
            return
        # sequence DFS pre-order with the sibling key
        for kids in children.values():
            kids.sort(key=lambda r: (int(cl[r]), -int(ck[r])))
        out: List[int] = []
        stack = list(reversed(children.get(None, [])))
        while stack:
            r = stack.pop()
            out.append(r)
            kids = children.get(r)
            if kids:
                stack.extend(reversed(kids))
        # every row sits in exactly one children list, so the DFS
        # visits each reachable row once. Admission leaves pref < 0 on
        # origin-cycle members (they never reach _seg_rows), so
        # normally nothing is unreachable — but if that invariant ever
        # bends, rank the leftovers at the tail DETERMINISTICALLY by
        # (client, clock) — arbitrary residual order could silently
        # diverge from a device-round replica in the same swarm
        # (advisor finding, round 3) — and log that the invariant bent
        if len(out) != len(rows):
            import logging

            emitted = set(out)
            leftovers = sorted(
                (r for r in rows if r not in emitted),
                key=lambda r: (int(cl[r]), int(ck[r])),
            )
            logging.getLogger(__name__).warning(
                "host-order fast path: %d unreachable rows in segment "
                "%d ranked at tail by (client, clock) — cyclic-origin "
                "admission invariant bent", len(leftovers), sk,
            )
            out.extend(leftovers)
        self._set_order(sk, out)

    def _record_of(self, row: int, parent_root: Optional[str] = None):
        from crdt_tpu.core.records import ItemRecord

        c = self.cols
        spec = self._spec_of_row(row)
        oc = int(c.col("oc")[row])
        rc = int(c.col("right_client")[row])
        return ItemRecord(
            client=int(c.col("client")[row]),
            clock=int(c.col("clock")[row]),
            parent_root=(
                parent_root if parent_root is not None
                else (spec[1] if spec and spec[0] == "root" else None)
            ),
            parent_item=(
                (spec[1], spec[2])
                if parent_root is None and spec and spec[0] == "item"
                else None
            ),
            key=(
                None if int(c.col("kid")[row]) < 0
                else self._key_names[int(c.col("kid")[row])]
            ),
            origin=(oc, int(c.col("ock")[row])) if oc >= 0 else None,
            right=(rc, int(c.col("right_clock")[row])) if rc >= 0 else None,
            kind=int(c.col("kind")[row]),
            type_ref=int(c.col("type_ref")[row]),
            content=c.contents[row],
        )

    # -- sync protocol surface ----------------------------------------
    # The live replica (crdt_tpu.api.resident_doc / net.replica in
    # merge_mode="resident") answers ready probes, anti-entropy
    # deficits, and compaction FROM THIS RESIDENT STATE — the scalar
    # engine is never materialized. Semantics mirror Engine exactly:
    # the state vector is the contiguous admitted watermark, diffs
    # carry rows above the requester's watermark plus the full delete
    # set, and _pending rows are excluded (they are not integrated
    # state; the protocol re-supplies them). Match: crdt.js:288,294.

    def state_vector(self):
        from crdt_tpu.core.ids import StateVector

        return StateVector(dict(self._next_clock))

    def records_since(self, sv=None) -> List:
        """Records with clock >= sv[client] (full state when None),
        O(deficit) via the id-row index — admitted runs are contiguous
        per client by the admission rule."""
        if sv is None:
            return [self._record_of(r) for r in range(self.cols.n)]
        out = []
        for client, nxt in self._next_clock.items():
            wm = sv.get(int(client))
            for ck in range(wm, nxt):
                row = self._id_row.get((int(client), ck))
                if row is not None:
                    out.append(self._record_of(row))
        return out

    def to_decoded_columns(self) -> Dict:
        """The full resident union in the decode column schema
        (client-grouped, clock-ascending — the wire's run order), the
        seam for the native ``encode_from_columns`` snapshot path:
        compaction of a resident doc never walks a scalar engine.
        Match: crdt.js:79-98 (what compaction replaces)."""
        c = self.cols
        n = c.n
        order = np.lexsort((c.col("clock"), c.col("client")))
        roots: List[str] = []
        root_idx: Dict[str, int] = {}
        pr = np.full(n, -1, np.int64)
        pc = np.full(n, -1, np.int64)
        pk_ = np.full(n, -1, np.int64)
        pref_col = c.col("pref")
        # pref -> (root index | item id) tables, then one gather
        n_pref = len(self._pref_spec)
        t_root = np.full(n_pref + 1, -1, np.int64)
        t_pc = np.full(n_pref + 1, -1, np.int64)
        t_pk = np.full(n_pref + 1, -1, np.int64)
        for ref, spec in enumerate(self._pref_spec):
            if spec[0] == "root":
                ix = root_idx.get(spec[1])
                if ix is None:
                    ix = root_idx[spec[1]] = len(roots)
                    roots.append(spec[1])
                t_root[ref] = ix
            else:
                t_pc[ref] = spec[1]
                t_pk[ref] = spec[2]
        has = pref_col >= 0
        pr[has] = t_root[pref_col[has]]
        pc[has] = t_pc[pref_col[has]]
        pk_[has] = t_pk[pref_col[has]]
        return {
            "client": c.col("client")[order],
            "clock": c.col("clock")[order],
            "parent_root": pr[order].astype(np.int32),
            "parent_client": pc[order],
            "parent_clock": pk_[order],
            "key_id": c.col("kid")[order].astype(np.int32),
            "origin_client": c.col("oc")[order],
            "origin_clock": c.col("ock")[order],
            "right_client": c.col("right_client")[order],
            "right_clock": c.col("right_clock")[order],
            "kind": c.col("kind")[order].astype(np.int32),
            "type_ref": c.col("type_ref")[order].astype(np.int32),
            "contents": [c.contents[int(r)] for r in order],
            "roots": roots,
            "keys": list(self._key_names),
            "ds": native.ds_to_triples(self.ds),
        }

    def encode_state_as_update(self, sv=None) -> bytes:
        """Diff (or full-state when ``sv`` is None) v1 blob from the
        resident columns. Deficit-sized diffs go through the record
        path (O(deficit)); full state goes through the native
        column encoder in one C pass when the toolchain allows."""
        from crdt_tpu.codec import v1

        if sv is None:
            return native.encode_from_columns_any(
                self.to_decoded_columns(), self.ds
            )
        return v1.encode_update(self.records_since(sv), self.ds)

    def _top_key_of_seg(self, sk: int) -> Optional[str]:
        """Top-level map key holding this segment's subtree (None for
        direct sequence members of a root array) — the per-key
        observer rollup the engine-backed doc computes via
        ``Crdt._classify_row``."""
        spec = self._seg_spec(sk)
        seen = set()
        kid = self._seg_kid.get(sk, -1)
        while spec is not None and spec not in seen:
            seen.add(spec)
            if spec[0] == "root":
                return self._key_names[kid] if kid >= 0 else None
            row = self._id_row.get((spec[1], spec[2]))
            if row is None:
                return None
            kid = int(self.cols.col("kid")[row])
            spec = self._spec_of_row(row)
        return None

    # -- cache --------------------------------------------------------
    def _touch_bookkeeping(self, touched: set) -> None:
        """Observer bookkeeping for a round's touched segments —
        separated from cache materialization so rounds can stay lazy."""
        t_roots: set = set()
        t_keys: Dict[str, set] = {}
        for sk in touched:
            # a touched segment may have changed order OR visibility
            # (delete ranges land here too): position caches must drop
            self._bump_epoch(sk)
            if sk not in self._seg_rows:
                continue
            root = self._root_of(self._seg_spec(sk))
            if root is None:
                continue
            t_roots.add(root)
            key = self._top_key_of_seg(sk)
            if key is not None:
                t_keys.setdefault(root, set()).add(key)
        self.last_touched_roots = sorted(t_roots)
        self.last_touched_keys = t_keys

    def _rebuild_cache(self, touched: set) -> None:
        # root-level map keys patch IN PLACE (a delta touching a few
        # hundred keys of a 25k-key map must not pay a full-collection
        # python rebuild); sequences, nested collections, and roots
        # not yet materialized rebuild whole
        full_roots: set = set()
        patches: List[Tuple[str, int]] = []
        for sk in touched:
            if sk not in self._seg_rows:
                continue
            spec = self._seg_spec(sk)
            root = self._root_of(spec)
            if root is None or root == "ix":
                continue
            if (
                spec == ("root", root)
                and self._seg_kid.get(sk, -1) >= 0
                and isinstance(self._cache.get(root), dict)
            ):
                patches.append((root, sk))
            else:
                full_roots.add(root)
        patches = [(r, sk) for r, sk in patches if r not in full_roots]

        # vectorized visibility for every ordered sequence row of the
        # fully-rebuilt roots (the per-row DeleteSet walk dominates
        # python rebuild time otherwise)
        seq_rows = sorted({
            r
            for root in full_roots
            for sk in self._root_segs.get(root, ())
            for r in self.order_list(sk)
        })
        self._vis = dict(zip(seq_rows, self._visible(seq_rows)))
        for root in full_roots:
            built = self._build_collection_root(root)
            if built == {}:
                # the cold materialize surfaces a map root only while
                # it has a visible winner (ix-registered empties come
                # back through the ix pass below)
                self._cache.pop(root, None)
            else:
                self._cache[root] = built

        c = self.cols
        maybe_empty: set = set()
        for root, sk in patches:
            key = self._key_names[self._seg_kid[sk]]
            tgt = self._cache.setdefault(root, {})
            row = self._win.get(sk)
            if row is None or self.ds.contains(
                int(c.col("client")[row]), int(c.col("clock")[row])
            ):
                tgt.pop(key, None)
                maybe_empty.add(root)  # pop AFTER all patches applied
                continue
            from crdt_tpu.core.store import K_TYPE, TYPE_MAP

            if c.col("kind")[row] == K_TYPE:
                sub = ("item", int(c.col("client")[row]),
                       int(c.col("clock")[row]))
                tgt[key] = self._build_collection(
                    sub, c.col("type_ref")[row] == TYPE_MAP,
                    self._root_segs.get(root, set()), 1,
                )
            else:
                tgt[key] = c.contents[row]
        for root in maybe_empty:
            if self._cache.get(root) == {}:
                self._cache.pop(root, None)  # same rule as above
        # ix-registered collections with no visible content still
        # materialize (empty), exactly like the cold materialize
        for sk in self._root_segs.get("ix", ()):
            row = self._win.get(sk)
            if row is None:
                continue
            name = self._key_names[int(self.cols.col("kid")[row])]
            if name not in self._cache and name != "ix":
                self._cache[name] = (
                    [] if self.cols.contents[row] == "array" else {}
                )

    def _ds_ranges(self):
        """Packed (client, start, end) arrays over the accumulated
        delete set — O(ranges), rebuilt only after a ds mutation."""
        if self._ds_pack is None:
            trip = list(self.ds.iter_all())
            self._ds_pack = (
                np.asarray([c for c, _, _ in trip], np.int64),
                np.asarray([s for _, s, _ in trip], np.int64),
                np.asarray([s + n for _, s, n in trip], np.int64),
            )
        return self._ds_pack

    def _visible(self, rows: List[int]) -> List[bool]:
        if not rows:
            return []
        from crdt_tpu.models.replay import rows_visible

        idx = np.asarray(rows)
        del_c, del_s, del_e = self._ds_ranges()
        return list(rows_visible(
            self.cols.col("client")[idx],
            self.cols.col("clock")[idx],
            del_c,
            del_s,
            del_e,
        ))

    def _build_collection_root(self, root: str):
        spec = ("root", root)
        segs = self._root_segs.get(root, set())
        has_map = any(
            self._seg_spec(sk) == spec and self._seg_kid[sk] >= 0
            for sk in segs
        )
        return self._build_collection(spec, has_map, segs, 0)

    def _build_collection(self, spec, is_map: bool, segs, depth: int):
        from crdt_tpu.core.store import K_TYPE, TYPE_MAP

        if depth > 64:
            return None
        c = self.cols

        def value_of(row):
            if c.col("kind")[row] == K_TYPE:
                sub = ("item", int(c.col("client")[row]),
                       int(c.col("clock")[row]))
                return self._build_collection(
                    sub, c.col("type_ref")[row] == TYPE_MAP, segs,
                    depth + 1,
                )
            return c.contents[row]

        if is_map:
            out = {}
            for sk in segs:
                if self._seg_spec(sk) != spec or self._seg_kid[sk] < 0:
                    continue
                row = self._win.get(sk)
                if row is None:
                    continue
                if self.ds.contains(
                    int(c.col("client")[row]), int(c.col("clock")[row])
                ):
                    continue
                out[self._key_names[self._seg_kid[sk]]] = value_of(row)
            return out
        def vis(r):
            if r in self._vis:
                return self._vis[r]
            return not self.ds.contains(
                int(c.col("client")[r]), int(c.col("clock")[r])
            )

        for sk in segs:
            if self._seg_spec(sk) == spec and self._seg_kid[sk] < 0:
                return [
                    value_of(r)
                    for r in self.order_list(sk)
                    if vis(r)
                ]
        return []
