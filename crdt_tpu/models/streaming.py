"""Overlapped streaming replay — pipeline host phases against the
in-flight device converge.

The one-shot replay (:mod:`crdt_tpu.models.replay`) runs its phases
strictly in series: decode -> stage -> pack -> converge -> gather ->
materialize -> compact. On the tunnelled single-chip platform that
serial shape caps the whole pipeline at an Amdahl ceiling of ~1.6x
regardless of kernel speed (BENCH_r05: host-serial phases bracket a
1.4s converge). This module restructures the SAME computation as a
chunked, double-buffered pipeline — the classic training-stack move
(input-pipeline prefetch + async dispatch):

1. **decode** — the blob stream splits into fixed-size chunks and a
   background thread pool decodes them (`codec.native` per chunk, one
   :func:`crdt_tpu.codec.native.merge_decoded` merge — byte-identical
   to the one-shot decode of the whole stream);
2. **partition** — the union's segments group by their TOP-LEVEL root
   (parent chains climbed host-side, vectorized), so every chunk of
   work owns whole root subtrees and can converge AND materialize
   independently;
3. **converge** — each chunk stages through the packed single-dispatch
   pipeline and enqueues its fused kernel ASYNCHRONOUSLY
   (:func:`crdt_tpu.ops.packed.converge_async`): the upload of chunk
   k+1 rides behind the dispatch of chunk k (double-buffered, bounded
   queue), and winners are fetched only when the consumer needs them;
4. **materialize** — the plain-JSON cache builds INCREMENTALLY per
   chunk (:func:`crdt_tpu.models.replay.assemble_cache`) while later
   chunks are still on the device, so the old serial materialize tail
   amortizes into the overlap window. Snapshot compaction (pure
   decode-side work) runs on the staging lane, inside the same window.

Exactness: every chunk's result is the packed kernel's result for its
segments, and segments never split across chunks, so the merged
winners/orders are the one-shot path's outputs re-ordered. Shapes the
chunked stager cannot prove locally (right-origin segments whose
origin chains leave the segment) are conservatively routed to the
exact host machinery — the same fallback the one-shot gather uses.
Unions the packed layout cannot express at all fall back to the
one-shot path wholesale. Differential-tested byte-identical against
the one-shot oracle in tests/test_streaming.py.

Phase accounting: ``phases`` (when passed) receives per-stage BUSY
seconds summed across lanes, plus ``wall_s``, ``busy_sum_s``, and
``overlap_efficiency`` = (busy - wall) / (busy - max_stage): 0 means
fully serial, 1 means the wall clock collapsed onto the single longest
stage. ``bench.py`` publishes these for the scale run.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from crdt_tpu.codec import native
from crdt_tpu.models import replay as rp
from crdt_tpu.models.replay import ReplayResult
from crdt_tpu.obs.profiling import device_annotation
from crdt_tpu.obs.timeline import get_timeline
from crdt_tpu.obs.tracer import get_tracer

# default pipeline depth targets: enough chunks that decode streams,
# enough convergence shards that fetch/materialize of shard k hides
# behind the dispatch of shard k+1 — but never so many that fixed
# per-dispatch latency dominates (each shard pays one upload + one
# dispatch + one fetch through the tunnel)
_DECODE_CHUNKS = 8
_MAX_SHARDS = 4
_MIN_SHARD_ROWS = 1 << 16


class _Phases:
    """Thread-safe busy-time accumulator (seconds per stage).

    Host stages are charged in per-thread CPU time, not wall time:
    the pipeline's lanes run concurrently, and a stage's wall span
    inflated by GIL/core contention would multiply-count the same
    second into the busy sum (whose contract is to reconstruct the
    SERIAL pipeline's cost). The device lane's occupancy is the one
    wall-clock entry, added explicitly by the consumer."""

    def __init__(self):
        self._lock = threading.Lock()
        self.t: Dict[str, float] = {}

    def add(self, name: str, dt: float) -> None:
        with self._lock:
            self.t[name] = self.t.get(name, 0.0) + dt

    def timed(self, name: str, fn, *a, **kw):
        t0 = time.thread_time()
        out = fn(*a, **kw)
        self.add(name, time.thread_time() - t0)
        return out


_IDLE_PHASES = ("converge_wait",)  # blocked time, not work: reported
                                   # as a diagnostic, excluded from
                                   # the busy sum (the device lane's
                                   # occupancy is charged as
                                   # "converge" instead)


def overlap_stats(phases: Dict[str, float], wall: float) -> Dict:
    """Pipeline accounting over per-stage busy seconds: how much of
    the total work the wall clock actually hid. The sum counts each
    lane's OCCUPANCY — host stages plus the device lane's
    non-overlapping converge span — and excludes blocked-wait
    diagnostics, so it reconstructs what the serial pipeline would
    cost (cross-checked against the one-shot oracle's wall in
    bench.py). ``overlap_efficiency`` is (busy - wall) /
    (busy - max_stage) — the fraction of the maximally-hideable time
    that WAS hidden (1.0 = wall collapsed to the longest stage, 0.0 =
    fully serial); ``wall_vs_phases`` is the raw wall / sum-of-phases
    ratio the acceptance bar reads."""
    phases = {
        k: v for k, v in phases.items() if k not in _IDLE_PHASES
    }
    busy = sum(v for v in phases.values())
    longest = max(phases.values(), default=0.0)
    hideable = busy - longest
    eff = (busy - wall) / hideable if hideable > 1e-9 else (
        1.0 if wall <= busy + 1e-9 else 0.0
    )
    return {
        "busy_sum_s": round(busy, 3),
        "wall_s": round(wall, 3),
        "wall_vs_phases": round(wall / busy, 3) if busy else 1.0,
        "overlap_efficiency": round(min(max(eff, 0.0), 1.0), 3),
        "longest_stage_s": round(longest, 3),
    }


# ---------------------------------------------------------------------------
# decode lane: chunked, thread-pooled
# ---------------------------------------------------------------------------


def stream_decode(blobs: Sequence[bytes], chunk_blobs: int,
                  ph: _Phases) -> Dict:
    """Chunked parallel decode -> the canonical (deduped) union,
    byte-identical to the one-shot ``replay.decode``. Chunk decodes
    run on a small thread pool: the native codec holds the GIL for
    its Python-object work, but chunk k+1's wire parse still overlaps
    chunk k's numpy merge tail, and on free-threaded builds the
    chunks parallelize outright."""
    from concurrent.futures import ThreadPoolExecutor

    blobs = list(blobs)
    chunks = [
        blobs[i:i + chunk_blobs]
        for i in range(0, len(blobs), chunk_blobs)
    ] or [[]]

    def _one(chunk):
        # runs on the pool: the global tracer span here is exactly the
        # concurrent-use case the thread-safe tracer exists for
        with get_tracer().span("decode"):
            return ph.timed(
                "decode", native.decode_updates_columns_any, chunk
            )

    if len(chunks) == 1:
        decs = [_one(chunks[0])]
    else:
        import os

        workers = min(4, max(2, (os.cpu_count() or 2)))
        with ThreadPoolExecutor(max_workers=workers) as ex:
            decs = list(ex.map(_one, chunks))
    return ph.timed(
        "merge", lambda: native.dedup_columns(native.merge_decoded(decs))
    )


# ---------------------------------------------------------------------------
# partition: whole root subtrees per convergence shard
# ---------------------------------------------------------------------------


def partition_shards(cols: Dict[str, np.ndarray], max_shards: int):
    """Group the union's segments by TOP-LEVEL root and greedy-pack
    the roots into at most ``max_shards`` row-balanced shards.

    Returns ``(shard_rows, seg, extra_hard_rows)``:

    - ``shard_rows``: list of union-row index arrays (ascending), one
      per shard, covering every row exactly once. Whole segments — and
      whole root SUBTREES (nested type items and their collections) —
      stay co-located, so each shard converges and materializes
      independently of the others.
    - ``seg``: dense segment id per row (shared diagnostics).
    - ``extra_hard_rows``: representative union rows of right-bearing
      segments whose members' origin chains may LEAVE the segment —
      the shapes whose hardness the one-shot stager proves with
      union-wide walks that a shard cannot run. They are routed to the
      exact host ordering, a conservative superset of the one-shot
      path's hard set.
    """
    n = len(cols["client"])
    if n == 0:
        return [], np.empty(0, np.int64), []
    pir = np.asarray(cols["parent_is_root"], bool)
    pa = np.asarray(cols["parent_a"], np.int64)
    pb = np.asarray(cols["parent_b"], np.int64)
    kid = np.asarray(cols["key_id"], np.int64)

    # dense segment ids over (pir, pa, pb, kid)
    order = np.lexsort((kid, pb, pa, pir))
    same = (
        (pir[order][1:] == pir[order][:-1])
        & (pa[order][1:] == pa[order][:-1])
        & (pb[order][1:] == pb[order][:-1])
        & (kid[order][1:] == kid[order][:-1])
    )
    seg_sorted = np.cumsum(np.r_[True, ~same]) - 1
    seg = np.empty(n, np.int64)
    seg[order] = seg_sorted
    S = int(seg_sorted[-1]) + 1 if n else 0
    rep = np.empty(S, np.int64)
    rep[seg_sorted] = order  # any member row stands for its segment

    # climb each segment's parent chain to its top-level root (log-S
    # pointer-doubling rounds, host-vectorized; shared packed-id
    # index: codec.native.id_index)
    index = native.id_index(cols["client"], cols["clock"])
    rep_pir = pir[rep]
    rep_pa = pa[rep]
    rep_pb = pb[rep]
    prow = native.id_lookup(
        index, np.where(~rep_pir, rep_pa, np.int64(-1)), rep_pb
    )
    # seg -> parent seg; terminal segments self-loop
    terminal = rep_pir | (prow < 0)
    f = np.where(terminal, np.arange(S), seg[np.clip(prow, 0, max(n - 1, 0))])
    for _ in range(max(1, (max(S, 2) - 1).bit_length() + 1)):
        f = f[f]
    # root id of each segment: the terminal ancestor's root (or -1 for
    # dangling/cyclic chains — those collect in shard 0; their specs
    # are non-root and unreachable from any root's nesting)
    top = f
    root_of_seg = np.where(
        rep_pir[top] & terminal[top], rep_pa[top], np.int64(-1)
    )

    # rows per segment / per root, then greedy-pack roots
    seg_rows_count = np.bincount(seg, minlength=S)
    roots_u, root_inv = np.unique(root_of_seg, return_inverse=True)
    root_load = np.bincount(root_inv, weights=seg_rows_count).astype(
        np.int64
    )
    n_shards = max(1, min(max_shards, len(roots_u)))
    bins = np.zeros(len(roots_u), np.int64)
    loads = np.zeros(n_shards, np.int64)
    for r in np.argsort(-root_load, kind="stable"):
        b = int(np.argmin(loads))
        bins[r] = b
        loads[b] += int(root_load[r])
    # dangling bucket (-1) pinned to shard 0 for determinism
    if len(roots_u) and roots_u[0] == -1:
        bins[0] = 0
    shard_of_seg = bins[root_inv]
    shard_of_row = shard_of_seg[seg]
    shard_rows = [
        np.flatnonzero(shard_of_row == b) for b in range(n_shards)
    ]
    shard_rows = [r for r in shard_rows if len(r)]

    # conservative hard set: right-bearing sequence segments with any
    # member whose origin resolves OUTSIDE the segment (the one-shot
    # stager's union-wide subtree walks can cross segments there; a
    # shard-local walk cannot follow them, so the exact host machinery
    # takes those segments in every case)
    extra_hard: List[int] = []
    rc = np.asarray(cols["right_client"], np.int64)
    rb = (rc >= 0) & (kid < 0)
    if rb.any():
        oc = np.asarray(cols["origin_client"], np.int64)
        ock = np.asarray(cols["origin_clock"], np.int64)
        orow = native.id_lookup(index, oc, ock)
        cross = (oc >= 0) & (orow >= 0) & (
            seg[np.clip(orow, 0, max(n - 1, 0))] != seg
        )
        hard_segs = np.intersect1d(
            np.unique(seg[rb]), np.unique(seg[cross])
        )
        extra_hard = [int(rep[s]) for s in hard_segs.tolist()]
    return shard_rows, seg, extra_hard


# ---------------------------------------------------------------------------
# the executor
# ---------------------------------------------------------------------------


def stream_replay(
    blobs: Sequence[bytes],
    *,
    chunk_blobs: Optional[int] = None,
    max_shards: int = _MAX_SHARDS,
    min_shard_rows: int = _MIN_SHARD_ROWS,
    clients: Optional[Sequence[int]] = None,
    phases: Optional[dict] = None,
) -> ReplayResult:
    """Chunked, double-buffered streaming replay: blobs in, converged
    cache + compacted snapshot out — same outputs as
    ``replay_trace(route="device")``, pipelined (see module doc).

    ``chunk_blobs`` sets the decode chunk size (default: ~8 chunks);
    ``max_shards`` bounds the convergence/materialize pipeline depth.
    ``clients`` seeds the resident fallback's client table exactly as
    on the device route (the packed path interns its own equivalent
    table). ``phases``, when given, receives per-stage busy seconds
    plus the overlap accounting of :func:`overlap_stats`."""
    from crdt_tpu.ops import packed

    t_wall0 = time.perf_counter()
    ph = _Phases()
    # tick-timeline hook (round 18): a scale run renders on the same
    # Perfetto timeline as a serve() run — one "stream" tick whose
    # dispatch windows are the per-shard async converges, with the
    # executor's per-stage busy sums as extra lanes at tick end
    tl = get_timeline()
    tl.tick_begin(0, label="stream")
    blobs = list(blobs)
    if chunk_blobs is None:
        chunk_blobs = max(1, -(-len(blobs) // _DECODE_CHUNKS))

    dec = stream_decode(blobs, chunk_blobs, ph)
    cols, ds = ph.timed("columns", rp.stage, dec)
    n = len(cols["client"])

    eff_shards = max(
        1, min(max_shards, n // max(min_shard_rows, 1) or 1)
    )
    shard_rows, _seg, extra_hard = ph.timed(
        "partition", partition_shards, cols, eff_shards
    )

    # crafted rights on MAP rows shift chain tails; repaired per shard
    # so every shard emits only its own segments' tails. The whole-
    # union id set the repair consults is built ONCE here (not per
    # shard) when any such rows exist at all.
    map_bad = np.flatnonzero(
        (np.asarray(cols["right_client"]) >= 0)
        & (np.asarray(cols["key_id"]) >= 0)
    )
    union_ids = None
    if len(map_bad):
        union_ids = set(
            zip(
                np.asarray(cols["client"]).tolist(),
                np.asarray(cols["clock"]).tolist(),
            )
        )

    # ---- staging/dispatch lane (background thread) -------------------
    # bounded queue = the double buffer: at most two plans in flight
    # behind the consumer, so uploads of shard k+1 overlap the dispatch
    # of shard k without unbounded device-memory growth
    q: queue.Queue = queue.Queue(maxsize=2)
    snap_box: dict = {}

    def stager():
        try:
            from crdt_tpu.ops import shard as shard_ops

            for g, rows_g in enumerate(shard_rows):
                sub = {k: v[rows_g] for k, v in cols.items()}
                # multi-chip route (round 13): a big enough stream
                # shard converges sharded over the device mesh in one
                # shard_map program — the pipeline shape (async
                # enqueue, fetch in the consumer) is unchanged
                plan = None
                eng = packed
                if shard_ops.active_for(len(rows_g)):
                    plan = ph.timed("pack", shard_ops.stage, sub)
                    if plan is not None:
                        eng = shard_ops
                if plan is None:
                    # eager per-row shipping is gated on THIS shard's
                    # row count: a sub-threshold shard's extra per-put
                    # fixed latencies outweigh any staging/transfer
                    # overlap (same rationale as replay.converge's
                    # gate). Uploads route through the xfer seam (byte
                    # accounting), and each shard's staged buffers are
                    # DONATED to its dispatch — the double-buffered
                    # queue then recycles the same device memory
                    # across stream shards instead of growing a fresh
                    # allocation per shard.
                    from crdt_tpu.ops.device import xfer_put

                    eager = len(rows_g) >= packed.EAGER_PUT_MIN_ROWS
                    plan = ph.timed(
                        "pack", packed.stage, sub,
                        put=xfer_put if eager else None,
                    )
                if plan is None:
                    q.put(("unstageable", None, None))
                    return
                # per-shard XProf annotation: converge_async's own
                # dispatch annotation nests inside, so device captures
                # attribute each fused kernel to its pipeline shard
                with device_annotation(f"crdt.stream.shard{g}"):
                    handle = eng.converge_async(plan)  # enqueue, no block
                q.put((
                    "shard",
                    ((eng, handle), time.perf_counter()),
                    rows_g,
                ))
            # compact is pure decode-side work: it runs here, inside
            # the window where the consumer is fetching/materializing
            snap_box["snap"] = ph.timed("compact", rp.compact, dec, ds)
            q.put(("done", None, None))
        except BaseException as exc:  # surface in the consumer
            q.put(("error", exc, None))

    worker = threading.Thread(target=stager, daemon=True)
    worker.start()

    # ---- consumer: fetch -> gather -> incremental materialize --------
    cache: dict = {}
    ix_group: Dict[str, int] = {}
    failed: Optional[BaseException] = None
    unstageable = False
    extra_hard_left = list(extra_hard)
    last_fetch_done = 0.0
    try:
        while True:
            kind, payload, rows_g = q.get()
            if kind == "done":
                break
            if kind == "error":
                failed = payload
                break
            if kind == "unstageable":
                unstageable = True
                break
            (eng, handle), t_enq = payload
            tok = tl.dispatch_begin(t=t_enq)
            t0 = time.perf_counter()
            res = eng.converge_fetch(handle)  # the shard's ONE sync
            t1 = time.perf_counter()
            tl.dispatch_end(tok, t0, t1)
            ph.add("converge_wait", t1 - t0)
            # device-lane occupancy: this shard's span, net of any
            # part that overlapped the previous shard's execution
            ph.add("converge", t1 - max(t_enq, last_fetch_done))
            last_fetch_done = t1

            t0 = time.thread_time()
            win_rows, seq_orders = rp._assemble_packed(
                dec, res, row_map=rows_g
            )
            # hard/right shapes are the exception path: each affected
            # shard pays one full-union host pass (the same machinery
            # the one-shot gather uses once); benign firehose unions
            # skip all of this
            hard = [int(rows_g[int(r)]) for r in res.hard_rows]
            if extra_hard_left:
                in_shard = set(rows_g.tolist())
                mine = [r for r in extra_hard_left if r in in_shard]
                extra_hard_left = [
                    r for r in extra_hard_left if r not in in_shard
                ]
                hard.extend(mine)
            if hard:
                affected = {rp.parent_spec(dec, r) for r in hard}
                seq_orders.update(rp._host_seq_orders(dec, affected))
            if len(map_bad):
                shard_bad = map_bad[np.isin(map_bad, rows_g)]
                win_rows = rp._fix_map_chains_with_rights(
                    dec, win_rows, bad_rows=shard_bad,
                    chain_rows=rows_g, union_ids=union_ids,
                )
            win_vis = rp.visible_mask(dec, win_rows, ds)
            ph.add("gather", time.thread_time() - t0)

            part, ix_part = ph.timed(
                "materialize", rp.assemble_cache,
                dec, ds, win_rows, win_vis, seq_orders,
            )
            cache.update(part)
            ix_group.update(ix_part)
    finally:
        # never leave the stager blocked on a full queue (e.g. when
        # the consumer raised mid-shard): drain until it exits
        while worker.is_alive():
            try:
                q.get(timeout=0.05)
            except queue.Empty:
                pass
        worker.join()
    if failed is not None:
        raise failed
    if unstageable:
        # the union exceeds the packed layout's bounds: one-shot
        # fallback through the general path (exact, unpipelined;
        # ``clients`` seeds the resident table exactly as on the
        # device route)
        handle = rp.converge(cols, clients=clients)
        win_rows, win_vis, seq_orders = rp.gather(dec, ds, handle)
        cache = rp.materialize(dec, ds, win_rows, win_vis, seq_orders)
        snap = rp.compact(dec, ds)
        if phases is not None:  # the contract holds on every exit
            wall = time.perf_counter() - t_wall0
            phases.update({k: round(v, 4) for k, v in ph.t.items()})
            phases.update(overlap_stats(ph.t, wall))
            phases["fallback"] = True
        tl.tick_end(extra_busy=_timeline_lanes(ph))
        return ReplayResult(
            cache=cache, snapshot=snap, n_ops=n,
            path="stream-fallback",
        )
    ph.timed("materialize", rp.finish_cache, cache, dec, ix_group)

    wall = time.perf_counter() - t_wall0
    if phases is not None:
        phases.update({k: round(v, 4) for k, v in ph.t.items()})
        phases.update(overlap_stats(ph.t, wall))
    tl.tick_end(extra_busy=_timeline_lanes(ph))
    return ReplayResult(
        cache=cache, snapshot=snap_box["snap"], n_ops=n, path="stream"
    )


def _timeline_lanes(ph: _Phases) -> Dict[str, float]:
    """The executor's host-stage busy sums as timeline lanes. The
    device lane is already covered exactly by the per-shard dispatch
    windows the consumer recorded, so the wall-clock ``converge``
    charge and the blocked-wait diagnostic are excluded (they would
    double-count the device's occupancy into the busy sum)."""
    return {
        k: v for k, v in ph.t.items()
        if k not in ("converge", *_IDLE_PHASES)
    }
