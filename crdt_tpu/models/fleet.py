"""ReplicaFleet — the flagship batched convergence model.

The reference's scale axis is replica parallelism: N peers full-mesh
gossiping updates and converging by CRDT merge (propagate at
/root/reference/crdt.js:385,445,...; merge-on-receipt at crdt.js:294;
the state-vector handshake at crdt.js:237-291). This model is that
entire swarm round as ONE jitted program over a device mesh:

    fleet = ReplicaFleet(n_replicas=1024, ops_per_replica=128)
    out = fleet.step(cols, dels)      # one gossip + merge round

- each replica's pending ops live as [R, N] columnar tensors sharded
  over the mesh's replica axis;
- ``propagate`` = all_gather of the op columns over ICI;
- every peer's ``applyUpdate`` = one batched LWW/YATA convergence over
  the gathered union, computed replicated (the CRDT property: every
  replica merging the same op set reaches the same state);
- the sync handshake = per-replica state vectors + the pairwise
  deficit matrix (the anti-entropy plan).

The driver's ``dryrun_multichip`` and the benchmark both drive this
model; the host-side swarm (crdt_tpu.net) is the trickle path for the
same semantics, this is the firehose path.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from crdt_tpu.parallel.gossip import (
    REPLICA_AXIS,
    make_gossip_step,
    make_hierarchical_gossip_step,
    make_mesh,
    synth_columns,
)
from crdt_tpu.utils.trace import get_tracer

_CLOCK_BITS = 40  # ops/device.py packing: client < 2^22, clock < 2^40


class FleetStep(NamedTuple):
    """Outputs of one gossip+merge round."""

    sv_local: np.ndarray        # [R, C] per-replica state vectors (sharded)
    global_sv: np.ndarray       # [C] merged swarm vector (replicated)
    deficit: np.ndarray         # [R, R] anti-entropy plan (replicated)
    winners: np.ndarray         # [S] converged LWW winner indices
    winner_visible: np.ndarray  # [S] winner not tombstoned
    seq_order: np.ndarray       # [R*N] seq id-sort permutation (union rows)
    seq_seg: np.ndarray         # [R*N] dense sequence id (id-sorted space)
    seq_rank: np.ndarray        # [R*N] YATA document rank (id-sorted space)
    seq_len: np.ndarray         # [S] per-sequence lengths
    map_order: np.ndarray       # [R*N] MAP id-sort perm — winners decode here


class ReplicaFleet:
    """A batch of replicas sharded over a 1-D device mesh.

    Static shapes (XLA traces once): `n_replicas` x `ops_per_replica`
    op columns, `num_clients`-wide state vectors, `num_segments`
    convergence slots. Replicas-per-device = n_replicas / mesh size
    (must divide evenly — pad the replica batch, not the mesh).
    """

    def __init__(
        self,
        n_replicas: int,
        ops_per_replica: int,
        *,
        mesh=None,
        n_devices: Optional[int] = None,
        num_clients: Optional[int] = None,
        num_segments: Optional[int] = None,
    ):
        import jax

        # item ids pack (client, clock) into int64 (ops/device.py); a
        # fleet traced without x64 silently truncates clocks
        jax.config.update("jax_enable_x64", True)
        self.mesh = mesh if mesh is not None else make_mesh(n_devices)
        nd = self.mesh.devices.size
        if n_replicas % nd:
            raise ValueError(
                f"n_replicas={n_replicas} must divide over {nd} devices"
            )
        self.n_replicas = n_replicas
        self.ops_per_replica = ops_per_replica
        self.num_clients = num_clients or n_replicas + 2
        total = n_replicas * ops_per_replica
        self.num_segments = num_segments or (1 << max(9, (total - 1).bit_length()))
        # a 2D (hosts, replicas) mesh runs the two-tier fan-in (ICI
        # within a host, DCN across — make_mesh2d); 1D runs flat gossip
        build = (
            make_hierarchical_gossip_step
            if len(self.mesh.axis_names) == 2
            else make_gossip_step
        )
        self._step = build(
            self.mesh, num_segments=self.num_segments, num_clients=self.num_clients
        )
        self._delta_step = None  # built on first delta_round
        self._delta_budget = None

    @property
    def axis(self) -> str:
        """The REPLICA axis name — the one fleet-shaped [R, N] arrays
        shard over (on a 2D (hosts, replicas) mesh that is the inner
        axis, not the host axis)."""
        names = self.mesh.axis_names
        return names[-1] if names else REPLICA_AXIS

    def synth(
        self,
        *,
        num_maps: int = 4,
        keys_per_map: int = 64,
        num_lists: int = 0,
        seq_fraction: float = 0.5,
        seed: int = 0,
    ):
        """Synthetic concurrent-write workload in this fleet's shape."""
        return synth_columns(
            self.n_replicas,
            self.ops_per_replica,
            num_maps=num_maps,
            keys_per_map=keys_per_map,
            num_lists=num_lists,
            seq_fraction=seq_fraction,
            seed=seed,
        )

    def step(
        self,
        cols: Dict[str, np.ndarray],
        dels: Tuple[np.ndarray, np.ndarray, np.ndarray],
    ) -> FleetStep:
        """One full gossip round: fan-in, converge, handshake. One
        packed upload, one dispatch, one packed fetch — the tunnel
        pays three fixed interaction latencies per round, not ~20."""
        import jax

        from crdt_tpu.parallel.gossip import (
            fleet_out_sizes,
            pack_cols,
            pack_dels,
            unpack_fleet_out,
        )

        from crdt_tpu.ops.device import xfer_fetch, xfer_put

        tracer = get_tracer()
        with tracer.span("fleet.step"):
            # one accounted upload per operand; the packed column
            # block is DONATED to the step (gossip.py), so repeated
            # rounds recycle the same device allocation
            out = self._step(
                xfer_put(pack_cols(cols), label="fleet.cols"),
                xfer_put(pack_dels(dels), label="fleet.dels"),
            )
            jax.block_until_ready(out)
            vec = xfer_fetch(out, label="fleet.out")
        if tracer.enabled:  # the mask reduction isn't free at 100M ops
            tracer.count(
                "fleet.ops_converged", int(np.asarray(cols["valid"]).sum())
            )
        R = self.n_replicas
        N = self.ops_per_replica
        parts = unpack_fleet_out(
            vec, R, N, self.num_clients, self.num_segments
        )
        return FleetStep(**{
            name: parts[name]
            for name, _ in fleet_out_sizes(
                R, N, self.num_clients, self.num_segments
            )
        })

    def delta_round(
        self,
        cols: Dict[str, np.ndarray],
        *,
        budget: int,
    ):
        """One TARGETED anti-entropy round over the fleet's mesh: ship
        only rows above the swarm floor, capped at ``budget`` per
        replica (see crdt_tpu.parallel.delta — ICI bytes scale with
        the deficit, not the resident columns). Requires a 1D mesh.

        Returns ``(svs, deficit, needed_count, delta_cols)`` where
        ``delta_cols`` is the gathered delta union as a column dict.
        """
        from crdt_tpu.parallel.delta import COL_NAMES, make_delta_gossip_step

        if len(self.mesh.axis_names) != 1:
            raise ValueError("delta rounds run on a 1D replica mesh")
        from crdt_tpu.ops.device import xfer_fetch, xfer_put

        if self._delta_step is None or self._delta_budget != budget:
            self._delta_step = make_delta_gossip_step(
                self.mesh, num_clients=self.num_clients, budget=budget
            )
            self._delta_budget = budget
        out = self._delta_step(*(
            xfer_put(cols[k], label="fleet.delta_cols")
            for k in COL_NAMES
        ))
        svs, deficit, needed = (
            xfer_fetch(x, label="fleet.delta_out") for x in out[:3]
        )
        delta_cols = {
            name: xfer_fetch(col, label="fleet.delta_out")
            for name, col in zip(COL_NAMES, out[3:])
        }
        return svs, deficit, needed, delta_cols


# ---------------------------------------------------------------------
# Real-trace ingestion: per-replica v1 wire blobs -> fleet columns.
# This is the seam that makes the fleet a PRODUCT capability rather
# than a synthetic-workload model (VERDICT r4 item 1): the same bytes
# a peer would broadcast (crdt.js:385,445) become one sharded gossip+
# merge round, and the round's outputs assemble back into the exact
# document cache the scalar engine would build.
# ---------------------------------------------------------------------


class FleetTrace(NamedTuple):
    """Per-replica wire blobs staged as fleet-shaped columns.

    - ``cols``: [R, N] kernel columns, client ids DENSELY interned
      (order-preserving, so every client comparison in the kernels —
      LWW tie-breaks, YATA sibling order — is unchanged);
    - ``dels``: replicated delete-range triples, same interned space;
    - ``row_map``: [R, N] -> union decode row (-1 padding) — the
      bridge from kernel outputs back to real contents;
    - ``dec``/``ds``: the union decode + merged delete set (raw id
      space) that :func:`crdt_tpu.models.replay.materialize` consumes;
    - ``clients``: interned-id -> raw-client table (interned id i
      maps to ``clients[i - 1]``);
    - ``num_clients``/``num_segments``: kernel static bounds.
    """

    cols: Dict[str, np.ndarray]
    dels: Tuple[np.ndarray, np.ndarray, np.ndarray]
    row_map: np.ndarray
    dec: Dict
    ds: object
    clients: np.ndarray
    num_clients: int
    num_segments: int

    @property
    def n_replicas(self) -> int:
        return self.row_map.shape[0]

    @property
    def ops_per_replica(self) -> int:
        return self.row_map.shape[1]

    @property
    def n_ops(self) -> int:
        return int((self.row_map >= 0).sum())


def load_trace(
    blobs: Sequence[bytes],
    *,
    replicas_multiple: int = 1,
    ops_bucket: Optional[int] = None,
    dec: Optional[Dict] = None,
) -> FleetTrace:
    """Decode one v1 update blob PER REPLICA into the fleet's sharded
    column layout.

    Each blob is what that replica would ``propagate`` after local
    edits; ops appearing in several blobs (gossip redelivery) are
    fine — the convergence kernels keep the first representative of a
    duplicated id, exactly Yjs's idempotent merge. Like the device
    cold replay, the fleet round expects the union to be causally
    complete (no dangling origins); incomplete backlogs belong to the
    incremental replica, which stashes pendings.

    ``replicas_multiple`` pads the replica count (empty all-invalid
    replicas) so R divides over a mesh of that many devices;
    ``ops_bucket`` pins N (padded per-replica op capacity) so several
    traces can share one compiled step. ``dec`` (optional) reuses a
    caller-decoded union (``replay.decode(blobs)``) instead of
    decoding it again — the sharded-route fallback's seam.

    Known cost: each blob is wire-decoded twice (once in the union
    for one consistent root/key interning, once alone for row
    attribution). Folding attribution into a single decode needs the
    native codec to report per-blob row spans; until then the C
    decoder is cheap enough that staging stays host-bound elsewhere."""
    from crdt_tpu.codec import native
    from crdt_tpu.models import replay
    from crdt_tpu.ops.device import bucket_pow2

    blobs = list(blobs)
    if dec is None:
        dec = replay.decode(blobs)
    kcols = native.kernel_columns(dec)
    ds = native.ds_from_triples(dec["ds"])
    n = len(dec["client"])

    # dense order-preserving client interning comes FIRST: id packing
    # below shifts the client by 40 bits, and a RAW 31-bit (or the
    # codec-admitted 2^62-band) client would alias modulo 2^24 —
    # silently merging distinct clients' rows. Interned ids are dense
    # 1..C, far below the 2^22 packing bound for any real swarm; 0 is
    # the miss value, matching no row (a dangling origin stays
    # dangling on device). A monotone renumbering changes no kernel
    # comparison (LWW tie-breaks, YATA sibling order).
    uniq = np.unique(kcols["client"]) if n else np.zeros(1, np.int64)
    if len(uniq) >= (1 << 22):
        raise ValueError(
            f"{len(uniq)} distinct clients exceeds the id-packing bound"
        )

    def intern(a: np.ndarray) -> np.ndarray:
        a = np.asarray(a, np.int64)
        idx = np.searchsorted(uniq, np.clip(a, uniq[0], None))
        idxc = np.clip(idx, 0, len(uniq) - 1)
        return np.where(
            (a >= 0) & (uniq[idxc] == a), idxc + 1, np.where(a < 0, a, 0)
        )

    union_id = (
        intern(kcols["client"]) << _CLOCK_BITS
    ) | kcols["clock"].astype(np.int64)
    sort_idx = np.argsort(union_id, kind="stable")
    sorted_ids = union_id[sort_idx]

    # per-blob row attribution by id: dedup may have dropped a later
    # copy of an op, so indices can't be taken from concatenation
    # order — every id in any blob resolves into the union by search
    per_rows: List[np.ndarray] = []
    for blob in blobs:
        d = native.decode_updates_columns_any([blob])
        bid = (
            intern(d["client"]) << _CLOCK_BITS
        ) | d["clock"].astype(np.int64)
        if n == 0 or len(bid) == 0:
            per_rows.append(np.empty(0, np.int64))
            continue
        pos = np.clip(np.searchsorted(sorted_ids, bid), 0, n - 1)
        rows = sort_idx[pos]
        hit = union_id[rows] == bid
        per_rows.append(rows[hit].astype(np.int64))

    r_raw = max(len(blobs), 1)
    R = -(-r_raw // replicas_multiple) * replicas_multiple
    N = ops_bucket or bucket_pow2(max(max(
        (len(r) for r in per_rows), default=1), 1))
    if any(len(r) > N for r in per_rows):
        raise ValueError(
            f"ops_bucket={N} below a replica's {max(len(r) for r in per_rows)} rows"
        )
    row_map = np.full((R, N), -1, np.int64)
    for r, rows in enumerate(per_rows):
        row_map[r, : len(rows)] = rows

    flat = row_map.reshape(-1)
    sel = np.clip(flat, 0, None)
    pad = flat < 0

    def take(col: np.ndarray, fill) -> np.ndarray:
        if n == 0:
            return np.full((R, N), fill, dtype=col.dtype)
        out = col[sel].copy()
        out[pad] = fill
        return out.reshape(R, N)

    cols = {
        "client": take(intern(kcols["client"]).astype(np.int32), 0),
        "clock": take(kcols["clock"].astype(np.int64), 0),
        "parent_is_root": take(kcols["parent_is_root"], False),
        "parent_a": take(kcols["parent_a"].astype(np.int64), -2),
        "parent_b": take(kcols["parent_b"].astype(np.int64), -2),
        "key_id": take(kcols["key_id"].astype(np.int32), -1),
        "origin_client": take(
            intern(kcols["origin_client"]).astype(np.int32), -1
        ),
        "origin_clock": take(kcols["origin_clock"].astype(np.int64), -1),
        "valid": take(kcols["valid"], False),
    }

    # replicated delete ranges in the interned space (device-side
    # winner visibility; host materialization reuses the RAW ds)
    triples = [
        (int(c), int(k), int(k + ln)) for c, k, ln in ds.iter_all()
    ]
    D = bucket_pow2(max(len(triples), 16))
    d_client = np.full(D, -1, np.int32)
    d_start = np.full(D, -1, np.int64)
    d_end = np.full(D, -1, np.int64)
    if triples:
        tc = intern(np.asarray([t[0] for t in triples], np.int64))
        d_client[: len(triples)] = tc.astype(np.int32)
        d_start[: len(triples)] = [t[1] for t in triples]
        d_end[: len(triples)] = [t[2] for t in triples]

    # union-tight segment bound (one shared rule with the resident
    # fallback)
    n_segs = replay.segment_bound(kcols)
    return FleetTrace(
        cols=cols,
        dels=(d_client, d_start, d_end),
        row_map=row_map,
        dec=dec,
        ds=ds,
        clients=uniq,
        num_clients=len(uniq) + 2,
        num_segments=bucket_pow2(max(n_segs, 16)),
    )


def fleet_for_trace(
    trace: FleetTrace,
    *,
    mesh=None,
    n_devices: Optional[int] = None,
) -> "ReplicaFleet":
    """A fleet whose static shapes match ``trace`` (one compile serves
    every trace staged with the same buckets)."""
    return ReplicaFleet(
        trace.n_replicas,
        trace.ops_per_replica,
        mesh=mesh,
        n_devices=n_devices,
        num_clients=trace.num_clients,
        num_segments=trace.num_segments,
    )


def gather_fleet(
    trace: FleetTrace, out: FleetStep
) -> Tuple[list, list, dict]:
    """Assemble a fleet round's kernel outputs back into document form:
    winner rows, their visibility, and per-sequence document orders in
    the union decode's row space — the same triple
    :func:`crdt_tpu.models.replay.gather` produces, so materialization
    is shared. Right-origin shapes take the identical exact host
    detours as the resident fallback."""
    from crdt_tpu.models.replay import finish_assembly

    dec, ds = trace.dec, trace.ds
    rm = trace.row_map.reshape(-1)
    win_rows = _winner_rows(
        rm, np.asarray(out.winners), np.asarray(out.map_order)
    )
    seq_orders = _seq_orders_from(
        dec, rm,
        np.asarray(out.seq_order),
        np.asarray(out.seq_seg),
        np.asarray(out.seq_rank),
    )
    return finish_assembly(dec, ds, win_rows, seq_orders)


def _winner_rows(rm: np.ndarray, winners: np.ndarray,
                 map_order: np.ndarray) -> List[int]:
    """Union winner rows from one device's (winners, id-sort perm)."""
    w = winners[winners >= 0]
    rows = rm[map_order[w].astype(np.int64)]
    return rows[rows >= 0].astype(np.int64).tolist()


def _seq_orders_from(dec, rm: np.ndarray, sorder: np.ndarray,
                     sseg: np.ndarray, srank: np.ndarray,
                     into: Optional[dict] = None) -> dict:
    """Vectorized per-sequence document orders (same lexsort +
    run-cuts shape as replay._assemble_packed): ranked positions ->
    union rows grouped by segment, ordered by rank."""
    from crdt_tpu.models.replay import parent_spec

    seq_orders: dict = {} if into is None else into
    pos = np.flatnonzero(srank >= 0)
    if not len(pos):
        return seq_orders
    rows = rm[sorder[pos].astype(np.int64)]
    keep = rows >= 0
    pos, rows = pos[keep], rows[keep]
    if not len(pos):
        return seq_orders
    order2 = np.lexsort((srank[pos], sseg[pos]))
    segs_s = sseg[pos][order2]
    rows_s = rows[order2]
    cuts = np.r_[
        0, np.flatnonzero(segs_s[1:] != segs_s[:-1]) + 1, len(segs_s)
    ]
    for a, b in zip(cuts[:-1], cuts[1:]):
        chunk = rows_s[a:b].astype(np.int64).tolist()
        seq_orders[parent_spec(dec, chunk[0])] = chunk
    return seq_orders


class SegStep(NamedTuple):
    """Outputs of one segment-sharded round (local spaces per device;
    see :func:`crdt_tpu.parallel.gossip.make_segment_sharded_step`).
    ``svs``/``global_sv`` are the trace's host-built handshake vectors
    (pure functions of the staged columns), carried here for API
    parity with :class:`FleetStep`."""

    svs: np.ndarray             # [R, C] per-replica own-op vectors
    global_sv: np.ndarray       # [C]
    deficit: np.ndarray         # [R, R]
    winners: np.ndarray         # [nd, S] local id-sorted winner indices
    winner_visible: np.ndarray  # [nd, S]
    seq_order: np.ndarray       # [nd, N_d] local id-sort permutations
    seq_seg: np.ndarray         # [nd, N_d] per-device dense sequence ids
    seq_rank: np.ndarray        # [nd, N_d]
    seq_len: np.ndarray         # [nd, S]
    map_order: np.ndarray       # [nd, N_d]


class ShardedTrace(NamedTuple):
    """A :class:`FleetTrace` re-partitioned BY SEGMENT over a mesh:
    one device owns every row of each (parent, key) chain and each
    sequence, so convergence divides across devices instead of
    replicating (the scaling mode). ``row_map`` is [nd, N_d] -> union
    decode row."""

    cols: Dict[str, np.ndarray]  # [nd, N_d], incl. "replica"
    dels: Tuple[np.ndarray, np.ndarray, np.ndarray]
    row_map: np.ndarray
    dec: Dict
    ds: object
    n_replicas: int
    num_clients: int
    num_segments: int  # per-device bound
    svs: np.ndarray    # [R, C] host-built per-replica own-op vectors
    global_sv: np.ndarray  # [C]

    @property
    def n_devices(self) -> int:
        return self.row_map.shape[0]

    @property
    def n_ops(self) -> int:
        return int((self.row_map >= 0).sum())


def shard_trace(trace: FleetTrace, n_devices: int) -> ShardedTrace:
    """Partition a replica-sharded trace's union BY SEGMENT into
    ``n_devices`` balanced shards (greedy largest-first by row
    count). Rows keep a ``replica`` attribution column so the SV
    handshake can still produce every replica's own-op vector."""
    from crdt_tpu.ops.device import bucket_pow2

    N = trace.ops_per_replica
    flat_valid = trace.cols["valid"].reshape(-1)
    idx = np.flatnonzero(flat_valid)
    replica = (idx // N).astype(np.int32)
    cf = {k: v.reshape(-1)[idx] for k, v in trace.cols.items()}
    union_rows = trace.row_map.reshape(-1)[idx]

    from crdt_tpu.models.replay import segment_key

    segkey = segment_key(cf["parent_a"], cf["key_id"])
    uniq_sk, seg_inv, seg_counts = np.unique(
        segkey, return_inverse=True, return_counts=True
    )
    # greedy balance: largest segments first, always into the
    # lightest bin (a single huge sequence still bounds the critical
    # path — that is the honest limit of segment parallelism)
    bins = np.zeros(len(uniq_sk), np.int32)
    loads = np.zeros(n_devices, np.int64)
    segs_per = np.zeros(n_devices, np.int64)
    for s in np.argsort(-seg_counts):
        b = int(np.argmin(loads))
        bins[s] = b
        loads[b] += int(seg_counts[s])
        segs_per[b] += 1
    row_bin = bins[seg_inv]

    N_d = bucket_pow2(max(int(loads.max()), 16))
    nd = n_devices
    defaults = {
        "client": 0, "clock": 0, "parent_is_root": False,
        "parent_a": -2, "parent_b": -2, "key_id": -1,
        "origin_client": -1, "origin_clock": -1, "valid": False,
    }
    cols = {
        k: np.full((nd, N_d), fill, dtype=cf[k].dtype)
        for k, fill in defaults.items()
    }
    cols["replica"] = np.zeros((nd, N_d), np.int32)
    row_map = np.full((nd, N_d), -1, np.int64)
    for b in range(nd):
        sel = np.flatnonzero(row_bin == b)
        for k in defaults:
            cols[k][b, : len(sel)] = cf[k][sel]
        cols["replica"][b, : len(sel)] = replica[sel]
        row_map[b, : len(sel)] = union_rows[sel]
    # the handshake's per-replica own-op vectors are a pure O(rows)
    # function of the staged columns — built here once, on host; the
    # mesh keeps only the O(R^2 C) pairwise deficit (the superlinear
    # term), rows sharded
    R = trace.n_replicas
    C = trace.num_clients
    svs = np.zeros((R, C), np.int64)
    if len(idx):
        np.maximum.at(
            svs,
            (replica, cf["client"].astype(np.int64)),
            cf["clock"].astype(np.int64) + 1,
        )
    return ShardedTrace(
        cols=cols,
        dels=trace.dels,
        row_map=row_map,
        dec=trace.dec,
        ds=trace.ds,
        n_replicas=R,
        num_clients=C,
        num_segments=bucket_pow2(max(int(segs_per.max()), 16)),
        svs=svs,
        global_sv=svs.max(axis=0) if R else np.zeros(C, np.int64),
    )


_SEG_COL_ORDER = (  # device-facing; "replica" stays host-side (SV build)
    "client", "clock", "parent_is_root", "parent_a",
    "parent_b", "key_id", "origin_client", "origin_clock", "valid",
)


class SegmentedFleet:
    """The segment-sharded sibling of :class:`ReplicaFleet` — the
    mode where the mesh DIVIDES merge work instead of replicating it.
    Static shapes come from the staged trace; any trace staged with
    the same buckets reuses the compiled step."""

    def __init__(
        self,
        sharded: ShardedTrace,
        *,
        mesh=None,
        n_devices: Optional[int] = None,
    ):
        import jax

        from crdt_tpu.parallel.gossip import make_segment_sharded_step

        jax.config.update("jax_enable_x64", True)
        self.mesh = mesh if mesh is not None else make_mesh(n_devices)
        nd = self.mesh.devices.size
        if sharded.n_devices != nd:
            raise ValueError(
                f"trace sharded for {sharded.n_devices} devices, "
                f"mesh has {nd}"
            )
        self.num_clients = sharded.num_clients
        self.num_segments = sharded.num_segments
        self.n_replicas = sharded.n_replicas
        self._step = make_segment_sharded_step(
            self.mesh,
            num_segments=sharded.num_segments,
            n_replicas=sharded.n_replicas,
        )

    def step(self, sharded: ShardedTrace) -> SegStep:
        """One packed upload per operand, one dispatch, one packed
        fetch (the per-device blocks concatenate into one vector)."""
        import jax

        from crdt_tpu.parallel.gossip import (
            pack_cols,
            pack_dels,
            segment_out_sizes,
        )

        # compiled-step reuse is only sound when the trace fits the
        # bounds this instance compiled with: a larger segment bucket
        # would overflow the kernel's segment table and a replica or
        # mesh mismatch would unpack the output vector at wrong
        # offsets — both return silently wrong winners, the exact
        # hazard fleet_replay guards against for ReplicaFleet reuse
        # (advisor finding, round 5).
        nd_mesh = self.mesh.devices.size
        if (
            sharded.n_devices != nd_mesh
            or sharded.n_replicas != self.n_replicas
            or sharded.num_segments > self.num_segments
        ):
            raise ValueError(
                f"sharded trace (devices={sharded.n_devices}, "
                f"replicas={sharded.n_replicas}, "
                f"segments={sharded.num_segments}) does not fit the "
                f"compiled SegmentedFleet (devices={nd_mesh}, "
                f"replicas={self.n_replicas}, "
                f"segments={self.num_segments})"
            )

        from crdt_tpu.ops.device import xfer_fetch, xfer_put

        tracer = get_tracer()
        nd, N_d = sharded.row_map.shape
        R = self.n_replicas
        blk = -(-R // nd)
        with tracer.span("fleet.seg_step"):
            out = self._step(
                xfer_put(pack_cols(sharded.cols), label="fleet.cols"),
                xfer_put(sharded.svs, label="fleet.svs"),
                xfer_put(pack_dels(sharded.dels), label="fleet.dels"),
            )
            jax.block_until_ready(out)
            vec = xfer_fetch(out, label="fleet.out").reshape(nd, -1)
        sizes = segment_out_sizes(blk, R, N_d, self.num_segments)
        parts: Dict[str, np.ndarray] = {}
        off = 0
        for name, size in sizes:
            parts[name] = vec[:, off: off + size]
            off += size
        deficit = parts["deficit"].reshape(nd * blk, R)[:R]
        return SegStep(
            svs=sharded.svs,
            global_sv=sharded.global_sv,
            deficit=deficit,
            winners=parts["winners"],
            winner_visible=parts["winner_visible"],
            seq_order=parts["seq_order"],
            seq_seg=parts["seq_seg"],
            seq_rank=parts["seq_rank"],
            seq_len=parts["seq_len"],
            map_order=parts["map_order"],
        )


def gather_sharded(
    sharded: ShardedTrace, out: SegStep
) -> Tuple[list, list, dict]:
    """Assemble a segment-sharded round back into document form (the
    per-device blocks are independent segment sets, so assembly is a
    concatenation keyed by (device, local segment))."""
    from crdt_tpu.models.replay import finish_assembly

    dec, ds = sharded.dec, sharded.ds
    nd = sharded.n_devices

    win_rows: List[int] = []
    seq_orders: dict = {}
    for d in range(nd):  # devices hold disjoint segments: no merging
        rm = sharded.row_map[d]
        win_rows.extend(_winner_rows(
            rm, np.asarray(out.winners[d]), np.asarray(out.map_order[d])
        ))
        _seq_orders_from(
            dec, rm,
            np.asarray(out.seq_order[d]),
            np.asarray(out.seq_seg[d]),
            np.asarray(out.seq_rank[d]),
            into=seq_orders,
        )
    return finish_assembly(dec, ds, win_rows, seq_orders)


def fleet_replay(
    blobs: Sequence[bytes],
    *,
    mesh=None,
    n_devices: Optional[int] = None,
    trace: Optional[FleetTrace] = None,
    fleet: Optional["ReplicaFleet"] = None,
    shard: str = "auto",
):
    """One-shot PRODUCT entry: per-replica update blobs in, converged
    cache + compacted snapshot out, convergence computed as ONE
    sharded gossip+merge round over the device mesh. This is
    ``replay_trace(route="fleet")``'s engine — the swarm firehose
    (every peer's pending broadcast merged at once) as opposed to the
    single-chip cold replay's one-union dispatch.

    ``shard`` picks the mesh mapping:

    - ``"auto"`` (default) — ``"sharded"`` when the mesh spans more
      than one device (and no prebuilt ``trace``/``fleet`` pins the
      replicated layout), else ``"replicas"``.
    - ``"sharded"`` — round 13, the scale-out mode: the union's
      staged PACKED layout partitions by whole segments over the
      mesh and converges in ONE ``shard_map`` program
      (:mod:`crdt_tpu.ops.shard` — sortless per-shard converge,
      boundary-only exchange of per-shard state vectors on the
      narrow wire). Byte-identical to the single-chip cold replay.
    - ``"replicas"`` — the reference's full-mesh shape:
      replica-sharded columns, all-gather fan-in, REPLICATED converge
      (every device ends the round holding the whole result).
    - ``"segments"`` — the work-divided mode over the GENERAL
      kernels: the union partitions by segment, each device converges
      only its shard, and only the SV handshake crosses the mesh."""
    from crdt_tpu.models.replay import ReplayResult, compact, materialize

    if mesh is None and fleet is not None:
        mesh = fleet.mesh
    if mesh is None:
        mesh = make_mesh(n_devices)
    auto = shard == "auto"
    if auto:
        # a caller-prebuilt trace/fleet pins the replicated layout
        # (compiled-step reuse is that path's whole point)
        shard = (
            "sharded"
            if mesh.devices.size > 1 and fleet is None and trace is None
            else "replicas"
        )
    shared_dec = None
    if shard == "sharded":
        from crdt_tpu.models import replay as rp
        from crdt_tpu.ops import shard as shard_ops

        # the sharded route needs only the decoded UNION — never the
        # replicated [R, N] fleet layout load_trace builds (interned
        # client tables, row maps, padded columns), which is exactly
        # the staging cost this mapping exists to skip
        dec = trace.dec if trace is not None else rp.decode(blobs)
        # an auto-resolved mapping honors the size gate BEFORE paying
        # the staging pass (the explicit shard="sharded" ask always
        # shards); below the threshold the per-shard fixed costs beat
        # the division, so auto falls back to the replicated round
        splan = None
        if not auto or shard_ops.active_for(
                len(dec["client"]), mesh.devices.size):
            cols, ds = rp.stage(dec)
            splan = shard_ops.stage(cols, n_shards=mesh.devices.size)
        if splan is not None:
            res = shard_ops.converge(splan)
            win_rows, win_vis, seq_orders = rp.gather(
                dec, ds, ("packed", res)
            )
            cache = materialize(dec, ds, win_rows, win_vis, seq_orders)
            return ReplayResult(
                cache=cache,
                snapshot=compact(dec, ds),
                n_ops=len(dec["client"]),
                path="fleet-sharded",
            )
        # too small (auto) or the union cannot take the packed
        # sharded route (bounds): fall through to the replicated
        # mapping, reusing the decoded union; its trace needs R
        # padded to the mesh
        shard = "replicas"
        if trace is None:
            shared_dec = dec
        elif trace.n_replicas % mesh.devices.size:
            trace = None
    if shard == "segments":
        if trace is None:
            trace = load_trace(blobs, replicas_multiple=1)
        sharded = shard_trace(trace, mesh.devices.size)
        seg_fleet = SegmentedFleet(sharded, mesh=mesh)
        out = seg_fleet.step(sharded)
        win_rows, win_vis, seq_orders = gather_sharded(sharded, out)
    elif shard == "replicas":
        if trace is None:
            trace = load_trace(
                blobs, replicas_multiple=mesh.devices.size,
                dec=shared_dec,
            )
        if fleet is None:
            fleet = fleet_for_trace(trace, mesh=mesh)
        elif (
            trace.num_clients > fleet.num_clients
            or trace.num_segments > fleet.num_segments
            or trace.row_map.shape
            != (fleet.n_replicas, fleet.ops_per_replica)
        ):
            # input SHAPES alone can match a compiled step whose
            # client/segment tables are too small — interned ids then
            # fall off the SV table and the anti-entropy plan comes
            # back silently wrong. Reuse requires trace buckets to fit
            # the fleet's compiled bounds.
            raise ValueError(
                f"trace buckets (R,N)={trace.row_map.shape} "
                f"clients={trace.num_clients} "
                f"segments={trace.num_segments} do not fit the reused "
                f"fleet (R,N)=({fleet.n_replicas},"
                f"{fleet.ops_per_replica}) "
                f"clients={fleet.num_clients} "
                f"segments={fleet.num_segments}"
            )
        out = fleet.step(trace.cols, trace.dels)
        win_rows, win_vis, seq_orders = gather_fleet(trace, out)
    else:
        raise ValueError(f"unknown shard mode {shard!r}")
    cache = materialize(trace.dec, trace.ds, win_rows, win_vis, seq_orders)
    return ReplayResult(
        cache=cache,
        snapshot=compact(trace.dec, trace.ds),
        n_ops=trace.n_ops,
        path="fleet",
    )
