"""ReplicaFleet — the flagship batched convergence model.

The reference's scale axis is replica parallelism: N peers full-mesh
gossiping updates and converging by CRDT merge (propagate at
/root/reference/crdt.js:385,445,...; merge-on-receipt at crdt.js:294;
the state-vector handshake at crdt.js:237-291). This model is that
entire swarm round as ONE jitted program over a device mesh:

    fleet = ReplicaFleet(n_replicas=1024, ops_per_replica=128)
    out = fleet.step(cols, dels)      # one gossip + merge round

- each replica's pending ops live as [R, N] columnar tensors sharded
  over the mesh's replica axis;
- ``propagate`` = all_gather of the op columns over ICI;
- every peer's ``applyUpdate`` = one batched LWW/YATA convergence over
  the gathered union, computed replicated (the CRDT property: every
  replica merging the same op set reaches the same state);
- the sync handshake = per-replica state vectors + the pairwise
  deficit matrix (the anti-entropy plan).

The driver's ``dryrun_multichip`` and the benchmark both drive this
model; the host-side swarm (crdt_tpu.net) is the trickle path for the
same semantics, this is the firehose path.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Tuple

import numpy as np

from crdt_tpu.parallel.gossip import (
    REPLICA_AXIS,
    make_gossip_step,
    make_hierarchical_gossip_step,
    make_mesh,
    synth_columns,
)
from crdt_tpu.utils.trace import get_tracer


class FleetStep(NamedTuple):
    """Outputs of one gossip+merge round."""

    sv_local: np.ndarray        # [R, C] per-replica state vectors (sharded)
    global_sv: np.ndarray       # [C] merged swarm vector (replicated)
    deficit: np.ndarray         # [R, R] anti-entropy plan (replicated)
    winners: np.ndarray         # [S] converged LWW winner indices
    winner_visible: np.ndarray  # [S] winner not tombstoned
    seq_order: np.ndarray       # [R*N] id-sort permutation (union rows)
    seq_seg: np.ndarray         # [R*N] dense sequence id (id-sorted space)
    seq_rank: np.ndarray        # [R*N] YATA document rank (id-sorted space)
    seq_len: np.ndarray         # [S] per-sequence lengths


class ReplicaFleet:
    """A batch of replicas sharded over a 1-D device mesh.

    Static shapes (XLA traces once): `n_replicas` x `ops_per_replica`
    op columns, `num_clients`-wide state vectors, `num_segments`
    convergence slots. Replicas-per-device = n_replicas / mesh size
    (must divide evenly — pad the replica batch, not the mesh).
    """

    def __init__(
        self,
        n_replicas: int,
        ops_per_replica: int,
        *,
        mesh=None,
        n_devices: Optional[int] = None,
        num_clients: Optional[int] = None,
        num_segments: Optional[int] = None,
    ):
        import jax

        # item ids pack (client, clock) into int64 (ops/device.py); a
        # fleet traced without x64 silently truncates clocks
        jax.config.update("jax_enable_x64", True)
        self.mesh = mesh if mesh is not None else make_mesh(n_devices)
        nd = self.mesh.devices.size
        if n_replicas % nd:
            raise ValueError(
                f"n_replicas={n_replicas} must divide over {nd} devices"
            )
        self.n_replicas = n_replicas
        self.ops_per_replica = ops_per_replica
        self.num_clients = num_clients or n_replicas + 2
        total = n_replicas * ops_per_replica
        self.num_segments = num_segments or (1 << max(9, (total - 1).bit_length()))
        # a 2D (hosts, replicas) mesh runs the two-tier fan-in (ICI
        # within a host, DCN across — make_mesh2d); 1D runs flat gossip
        build = (
            make_hierarchical_gossip_step
            if len(self.mesh.axis_names) == 2
            else make_gossip_step
        )
        self._step = build(
            self.mesh, num_segments=self.num_segments, num_clients=self.num_clients
        )
        self._delta_step = None  # built on first delta_round
        self._delta_budget = None

    @property
    def axis(self) -> str:
        """The REPLICA axis name — the one fleet-shaped [R, N] arrays
        shard over (on a 2D (hosts, replicas) mesh that is the inner
        axis, not the host axis)."""
        names = self.mesh.axis_names
        return names[-1] if names else REPLICA_AXIS

    def synth(
        self,
        *,
        num_maps: int = 4,
        keys_per_map: int = 64,
        num_lists: int = 0,
        seq_fraction: float = 0.5,
        seed: int = 0,
    ):
        """Synthetic concurrent-write workload in this fleet's shape."""
        return synth_columns(
            self.n_replicas,
            self.ops_per_replica,
            num_maps=num_maps,
            keys_per_map=keys_per_map,
            num_lists=num_lists,
            seq_fraction=seq_fraction,
            seed=seed,
        )

    def step(
        self,
        cols: Dict[str, np.ndarray],
        dels: Tuple[np.ndarray, np.ndarray, np.ndarray],
    ) -> FleetStep:
        """One full gossip round: fan-in, converge, handshake."""
        import jax
        import jax.numpy as jnp

        tracer = get_tracer()
        with tracer.span("fleet.step"):
            out = self._step(
                jnp.asarray(cols["client"]),
                jnp.asarray(cols["clock"]),
                jnp.asarray(cols["parent_is_root"]),
                jnp.asarray(cols["parent_a"]),
                jnp.asarray(cols["parent_b"]),
                jnp.asarray(cols["key_id"]),
                jnp.asarray(cols["origin_client"]),
                jnp.asarray(cols["origin_clock"]),
                jnp.asarray(cols["valid"]),
                jnp.asarray(dels[0]),
                jnp.asarray(dels[1]),
                jnp.asarray(dels[2]),
            )
            jax.block_until_ready(out)
        if tracer.enabled:  # the mask reduction isn't free at 100M ops
            tracer.count(
                "fleet.ops_converged", int(np.asarray(cols["valid"]).sum())
            )
        return FleetStep(*(np.asarray(x) for x in out))

    def delta_round(
        self,
        cols: Dict[str, np.ndarray],
        *,
        budget: int,
    ):
        """One TARGETED anti-entropy round over the fleet's mesh: ship
        only rows above the swarm floor, capped at ``budget`` per
        replica (see crdt_tpu.parallel.delta — ICI bytes scale with
        the deficit, not the resident columns). Requires a 1D mesh.

        Returns ``(svs, deficit, needed_count, delta_cols)`` where
        ``delta_cols`` is the gathered delta union as a column dict.
        """
        import jax.numpy as jnp

        from crdt_tpu.parallel.delta import COL_NAMES, make_delta_gossip_step

        if len(self.mesh.axis_names) != 1:
            raise ValueError("delta rounds run on a 1D replica mesh")
        if self._delta_step is None or self._delta_budget != budget:
            self._delta_step = make_delta_gossip_step(
                self.mesh, num_clients=self.num_clients, budget=budget
            )
            self._delta_budget = budget
        out = self._delta_step(*(jnp.asarray(cols[k]) for k in COL_NAMES))
        svs, deficit, needed = (np.asarray(x) for x in out[:3])
        delta_cols = {
            name: np.asarray(col) for name, col in zip(COL_NAMES, out[3:])
        }
        return svs, deficit, needed, delta_cols
