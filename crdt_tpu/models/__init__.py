from crdt_tpu.models.fleet import FleetStep, ReplicaFleet

__all__ = ["FleetStep", "ReplicaFleet"]
