from crdt_tpu.models.fleet import FleetStep, ReplicaFleet
from crdt_tpu.models.replay import ReplayResult, replay_trace

__all__ = ["FleetStep", "ReplicaFleet", "ReplayResult", "replay_trace"]
