from crdt_tpu.models.fleet import (
    FleetStep,
    FleetTrace,
    ReplicaFleet,
    SegmentedFleet,
    SegStep,
    ShardedTrace,
    fleet_replay,
    load_trace,
    shard_trace,
)
from crdt_tpu.models.incremental import IncrementalReplay
from crdt_tpu.models.multidoc import MultiDocServer, TickReport, cache_digest
from crdt_tpu.models.replay import ReplayResult, replay_trace
from crdt_tpu.models.streaming import stream_replay

__all__ = [
    "FleetStep",
    "FleetTrace",
    "IncrementalReplay",
    "MultiDocServer",
    "TickReport",
    "cache_digest",
    "ReplayResult",
    "ReplicaFleet",
    "SegStep",
    "SegmentedFleet",
    "ShardedTrace",
    "fleet_replay",
    "load_trace",
    "replay_trace",
    "shard_trace",
    "stream_replay",
]
