from crdt_tpu.models.fleet import FleetStep, ReplicaFleet
from crdt_tpu.models.incremental import IncrementalReplay
from crdt_tpu.models.replay import ReplayResult, replay_trace

__all__ = [
    "FleetStep",
    "IncrementalReplay",
    "ReplayResult",
    "ReplicaFleet",
    "replay_trace",
]
