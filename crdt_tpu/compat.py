"""jax API compatibility shims.

The package targets a range of jax releases: top-level ``jax.enable_x64``
and ``jax.shard_map`` exist on newer trains, while older ones only ship
the ``jax.experimental`` spellings. Every internal caller imports the
two names from here so a version bump is a one-file change (and so a
missing symbol fails at import time with one clear site, not as dozens
of scattered AttributeErrors mid-kernel).
"""

from __future__ import annotations

import jax

if hasattr(jax, "enable_x64"):
    enable_x64 = jax.enable_x64
else:  # pre-top-level releases
    from jax.experimental import enable_x64  # noqa: F401

if hasattr(jax, "shard_map") and callable(jax.shard_map):
    _shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_map(f=None, **kw):
    """``jax.shard_map`` with the replication-checker knob translated
    across its rename (``check_vma`` on newer trains, ``check_rep``
    before): callers write the current name, older jax still works."""
    import inspect

    params = inspect.signature(_shard_map).parameters
    if "check_vma" in kw and "check_vma" not in params:
        kw["check_rep"] = kw.pop("check_vma")
    if f is None:
        return _shard_map(**kw)
    return _shard_map(f, **kw)
