from crdt_tpu.utils.trace import Tracer, get_tracer, jax_profile, set_tracer

__all__ = ["Tracer", "get_tracer", "jax_profile", "set_tracer"]
