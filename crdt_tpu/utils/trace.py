"""Tracing / metrics for the merge and sync paths (SURVEY.md §5).

The reference has no observability beyond four console.log lines
around sync (/root/reference/crdt.js:238,247,287,293). The rebuild's
north-star metric is merges/sec and convergence wall-clock, so the
framework carries a lightweight per-phase tracer:

- ``Tracer.span(name)``   context-manager timer; aggregates count /
  total / max per phase (decode, merge, encode, persist, compact, ...)
- ``Tracer.count(name)``  monotonic counters (updates applied, bytes
  broadcast, messages dropped, ...)
- ``Tracer.gauge(name)``  last-value gauges (pending ops, log size)
- ``report()``            one plain dict — JSON-ready

A process-global default tracer is DISABLED by default: every hook in
the hot path is a single attribute check when off. Enable with
``get_tracer().enabled = True`` (or install your own via
:func:`set_tracer`).

For device-side profiling, :func:`jax_profile` wraps
``jax.profiler.trace`` so a convergence dispatch can be captured for
TensorBoard/XProf without importing jax anywhere it isn't already.
"""

from __future__ import annotations

import json
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, Optional


class _Span:
    __slots__ = ("count", "total_s", "max_s")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0

    def add(self, dt: float) -> None:
        self.count += 1
        self.total_s += dt
        if dt > self.max_s:
            self.max_s = dt


class Tracer:
    """Aggregating phase timer + counters. Not thread-safe (the
    framework's host side is single-threaded, poll-driven — same model
    as the reference's node event loop)."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self._spans: Dict[str, _Span] = {}
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}

    # -- phases ----------------------------------------------------------
    @contextmanager
    def span(self, name: str) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._spans.setdefault(name, _Span()).add(time.perf_counter() - t0)

    # -- counters / gauges ----------------------------------------------
    def count(self, name: str, n: int = 1) -> None:
        if self.enabled:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        if self.enabled:
            self._gauges[name] = value

    def counters(self, prefix: str = "") -> Dict[str, int]:
        """Counter snapshot, optionally filtered by name prefix —
        e.g. ``counters("router.relay")`` for the relay path or
        ``counters("replica.probe")`` for the retry schedule (the
        partition-tolerance counters: ``router.dial_retries``,
        ``router.predict_probes``, ``router.relay_*``,
        ``replica.probe_retries``, ``replica.anti_entropy_rounds``)."""
        return {
            k: v for k, v in sorted(self._counters.items())
            if k.startswith(prefix)
        }

    # -- reporting -------------------------------------------------------
    def report(self) -> Dict[str, Any]:
        return {
            "spans": {
                k: {
                    "count": s.count,
                    "total_s": s.total_s,
                    "mean_s": s.total_s / s.count if s.count else 0.0,
                    "max_s": s.max_s,
                }
                for k, s in sorted(self._spans.items())
            },
            "counters": dict(sorted(self._counters.items())),
            "gauges": dict(sorted(self._gauges.items())),
        }

    def to_json(self) -> str:
        return json.dumps(self.report())

    def reset(self) -> None:
        self._spans.clear()
        self._counters.clear()
        self._gauges.clear()


_tracer = Tracer(enabled=False)


def get_tracer() -> Tracer:
    return _tracer


def set_tracer(tracer: Tracer) -> Tracer:
    global _tracer
    _tracer = tracer
    return tracer


@contextmanager
def jax_profile(log_dir: str, *, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a device trace (TensorBoard/XProf format) around a
    block — e.g. one ``converge_maps`` dispatch or a fleet step."""
    import jax

    opts = jax.profiler.ProfileOptions()
    opts.host_tracer_level = host_tracer_level
    jax.profiler.start_trace(log_dir, profiler_options=opts)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
