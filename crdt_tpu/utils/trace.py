"""Tracing / metrics — compatibility surface over :mod:`crdt_tpu.obs`.

Historically this module WAS the tracer (an aggregating count/total/
max phase timer, explicitly not thread-safe). The observability layer
now lives in :mod:`crdt_tpu.obs`: a thread-safe tracer with
log-bucketed latency histograms (p50/p90/p99 per span), the sync
flight recorder, the divergence sentinel, and Prometheus/JSON export.
Every existing import site (``from crdt_tpu.utils.trace import
get_tracer`` ...) keeps working through this shim, and the public
surface here is a strict superset of the old one:

- ``Tracer.span(name)`` — context-manager timer; aggregates count /
  total / max / min and a latency histogram per phase
- ``Tracer.count(name, n)`` / ``gauge(name, v)`` — counters, gauges
- ``Tracer.counters(prefix)`` — filtered counter snapshot
- ``report()`` — one plain dict, JSON-ready (old keys preserved;
  adds ``min_s``/``p50_s``/``p90_s``/``p99_s``/``buckets`` per span)

The process-global default tracer is DISABLED by default: every hook
in the hot path is a single attribute check when off. Enable with
``get_tracer().enabled = True`` (or install your own via
:func:`set_tracer`). Subclassers of the old Tracer: see MIGRATING in
the README.

For device-side profiling, :func:`jax_profile` wraps
``jax.profiler.trace`` so a convergence dispatch can be captured for
TensorBoard/XProf; it degrades with a clear error when jax has no
profiler and never leaks a running profiler on failure
(:mod:`crdt_tpu.obs.profiling`).
"""

from __future__ import annotations

from crdt_tpu.obs.profiling import device_annotation, jax_profile
from crdt_tpu.obs.tracer import Tracer, get_tracer, set_tracer

__all__ = [
    "Tracer",
    "device_annotation",
    "get_tracer",
    "jax_profile",
    "set_tracer",
]
