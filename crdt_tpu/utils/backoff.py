"""Shared retry-timing helpers for the partition-tolerance layer."""

from __future__ import annotations

import random


def jitter(spread: float = 0.25) -> float:
    """Multiplicative jitter factor in [1-spread, 1+spread]: keeps a
    fleet's retry timers from phase-locking into synchronized bursts
    (the thundering-herd failure mode of un-jittered backoff). Used by
    both the router's dial scheduler and the replica's probe /
    anti-entropy cadence — one constant, tuned in one place."""
    return 1.0 + spread * (2.0 * random.random() - 1.0)
