"""ctypes binding for the native transport (crypto + reliable UDP).

The reference's swarm stack bottoms out in `udx-native` (C reliable
streams over UDP) and `sodium-native` (libsodium crypto) underneath
Hyperswarm (SURVEY.md §2.2 native-code census). This module is the
equivalent seam: the C++ transport (native/transport) built as a
shared library on first use and driven through a flat C ABI, exposing

- :func:`keypair` / :class:`SecureBox` — X25519 key agreement +
  XChaCha20-Poly1305 authenticated encryption (the libsodium
  crypto_box primitive family), for the encrypted peer links;
- :class:`UdpEndpoint` — arbitrary-size messages over UDP with
  fragmentation, per-fragment acks, retransmit, reassembly and
  duplicate suppression, pumped by ``poll()`` the way udx rides its
  event loop (no background threads).

RFC test vectors for every crypto primitive live in
tests/test_transport.py.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import List, Optional, Tuple

_REPO_ROOT = Path(__file__).resolve().parent.parent.parent
_SRC = _REPO_ROOT / "native" / "transport" / "transport.cc"
_BUILD_DIR = _REPO_ROOT / "native" / "build"
_SO = _BUILD_DIR / "libtransport.so"

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None

_u8p = ctypes.POINTER(ctypes.c_uint8)


def _build_so() -> None:
    _BUILD_DIR.mkdir(parents=True, exist_ok=True)
    tmp = _SO.with_suffix(f".so.tmp.{os.getpid()}")
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-Wall",
        str(_SRC), "-o", str(tmp),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, _SO)
    except subprocess.CalledProcessError as e:
        stderr = e.stderr.decode(errors="replace") if e.stderr else "(no output)"
        raise RuntimeError(
            f"native transport build failed ({' '.join(cmd)}):\n{stderr}"
        ) from e
    finally:
        if tmp.exists():
            tmp.unlink()


def _load() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
            _build_so()
        lib = ctypes.CDLL(str(_SO))

        lib.ct_hchacha20.argtypes = [_u8p, _u8p, _u8p]
        lib.ct_aead_encrypt.restype = ctypes.c_int
        lib.ct_aead_encrypt.argtypes = [
            _u8p, _u8p, _u8p, ctypes.c_uint32, _u8p, ctypes.c_uint32, _u8p,
        ]
        lib.ct_aead_decrypt.restype = ctypes.c_int
        lib.ct_aead_decrypt.argtypes = lib.ct_aead_encrypt.argtypes
        lib.ct_xaead_encrypt.restype = ctypes.c_int
        lib.ct_xaead_encrypt.argtypes = lib.ct_aead_encrypt.argtypes
        lib.ct_xaead_decrypt.restype = ctypes.c_int
        lib.ct_xaead_decrypt.argtypes = lib.ct_aead_encrypt.argtypes
        lib.ct_x25519.restype = ctypes.c_int
        lib.ct_x25519.argtypes = [_u8p, _u8p, _u8p]
        lib.ct_x25519_base.argtypes = [_u8p, _u8p]
        lib.ct_randombytes.argtypes = [_u8p, ctypes.c_uint32]
        lib.ct_free.argtypes = [_u8p]

        lib.udp_create.restype = ctypes.c_void_p
        lib.udp_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ]
        lib.udp_port.restype = ctypes.c_int
        lib.udp_port.argtypes = [ctypes.c_void_p]
        lib.udp_close.argtypes = [ctypes.c_void_p]
        lib.udp_set_loss.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64,
        ]
        lib.udp_send.restype = ctypes.c_long
        lib.udp_send.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, _u8p,
            ctypes.c_uint32,
        ]
        lib.udp_send_unreliable.restype = ctypes.c_long
        lib.udp_send_unreliable.argtypes = lib.udp_send.argtypes
        lib.udp_poll.restype = ctypes.c_int
        lib.udp_poll.argtypes = [ctypes.c_void_p]
        lib.udp_recv.restype = ctypes.c_int
        lib.udp_recv.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(_u8p), ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.udp_pending.restype = ctypes.c_int
        lib.udp_pending.argtypes = [ctypes.c_void_p]
        lib.udp_failed.restype = ctypes.c_uint64
        lib.udp_failed.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


def _as_u8p(data: bytes):
    return ctypes.cast(ctypes.c_char_p(bytes(data)), _u8p)


def _buf(n: int):
    return (ctypes.c_uint8 * max(n, 1))()


# ---------------------------------------------------------------------------
# crypto surface
# ---------------------------------------------------------------------------


def random_bytes(n: int) -> bytes:
    lib = _load()
    out = _buf(n)
    lib.ct_randombytes(out, n)
    return bytes(out[:n])


def keypair(seed: Optional[bytes] = None) -> Tuple[bytes, bytes]:
    """(public, secret) X25519 keypair; 32-byte seed = secret key."""
    lib = _load()
    sk = bytes(seed) if seed is not None else random_bytes(32)
    if len(sk) != 32:
        raise ValueError("seed must be 32 bytes")
    pub = _buf(32)
    lib.ct_x25519_base(pub, _as_u8p(sk))
    return bytes(pub[:32]), sk


def x25519(secret: bytes, public: bytes) -> bytes:
    """Raw scalar multiplication (RFC 7748). Raises on the all-zero
    output of low-order points, like libsodium."""
    lib = _load()
    out = _buf(32)
    if lib.ct_x25519(out, _as_u8p(secret), _as_u8p(public)):
        raise ValueError("x25519: low-order public key")
    return bytes(out[:32])


def hchacha20(key: bytes, nonce16: bytes) -> bytes:
    lib = _load()
    out = _buf(32)
    lib.ct_hchacha20(out, _as_u8p(key), _as_u8p(nonce16))
    return bytes(out[:32])


def aead_encrypt(key: bytes, nonce12: bytes, plaintext: bytes,
                 aad: bytes = b"") -> bytes:
    """ChaCha20-Poly1305 (RFC 8439): returns ciphertext || 16-byte tag."""
    lib = _load()
    out = _buf(len(plaintext) + 16)
    lib.ct_aead_encrypt(
        _as_u8p(key), _as_u8p(nonce12), _as_u8p(aad), len(aad),
        _as_u8p(plaintext), len(plaintext), out,
    )
    return bytes(out[: len(plaintext) + 16])


def aead_decrypt(key: bytes, nonce12: bytes, ciphertext: bytes,
                 aad: bytes = b"") -> bytes:
    lib = _load()
    if len(ciphertext) < 16:
        raise ValueError("ciphertext too short")
    out = _buf(len(ciphertext) - 16)
    rc = lib.ct_aead_decrypt(
        _as_u8p(key), _as_u8p(nonce12), _as_u8p(aad), len(aad),
        _as_u8p(ciphertext), len(ciphertext), out,
    )
    if rc:
        raise ValueError("aead: authentication failed")
    return bytes(out[: len(ciphertext) - 16])


def xaead_encrypt(key: bytes, nonce24: bytes, plaintext: bytes,
                  aad: bytes = b"") -> bytes:
    """XChaCha20-Poly1305 (24-byte nonce, safe to draw at random)."""
    lib = _load()
    out = _buf(len(plaintext) + 16)
    lib.ct_xaead_encrypt(
        _as_u8p(key), _as_u8p(nonce24), _as_u8p(aad), len(aad),
        _as_u8p(plaintext), len(plaintext), out,
    )
    return bytes(out[: len(plaintext) + 16])


def xaead_decrypt(key: bytes, nonce24: bytes, ciphertext: bytes,
                  aad: bytes = b"") -> bytes:
    lib = _load()
    if len(ciphertext) < 16:
        raise ValueError("ciphertext too short")
    out = _buf(len(ciphertext) - 16)
    rc = lib.ct_xaead_decrypt(
        _as_u8p(key), _as_u8p(nonce24), _as_u8p(aad), len(aad),
        _as_u8p(ciphertext), len(ciphertext), out,
    )
    if rc:
        raise ValueError("aead: authentication failed")
    return bytes(out[: len(ciphertext) - 16])


class SecureBox:
    """Authenticated encryption between two static X25519 identities —
    the libsodium crypto_box construction shape: session key =
    HChaCha20(X25519(my_secret, their_public)), then per-message
    XChaCha20-Poly1305 under a random 24-byte nonce (prepended).

    Both directions derive the same key (ECDH commutes), so one box
    per peer serves send and receive; random extended nonces make
    direction/counter bookkeeping unnecessary.
    """

    def __init__(self, my_secret: bytes, their_public: bytes):
        shared = x25519(my_secret, their_public)
        self.key = hchacha20(shared, bytes(16))

    def encrypt(self, plaintext: bytes, aad: bytes = b"") -> bytes:
        nonce = random_bytes(24)
        return nonce + xaead_encrypt(self.key, nonce, plaintext, aad)

    def decrypt(self, payload: bytes, aad: bytes = b"") -> bytes:
        if len(payload) < 24 + 16:
            raise ValueError("payload too short")
        return xaead_decrypt(self.key, payload[:24], payload[24:], aad)


# ---------------------------------------------------------------------------
# transport surface
# ---------------------------------------------------------------------------


class UdpEndpoint:
    """One bound UDP socket carrying reliable, arbitrary-size messages.

    ``send`` fragments and queues for retransmit until acked; ``poll``
    pumps receive/ack/retransmit (call it regularly — event-loop
    style, the way udx drives its socket from libuv); ``recv`` pops
    fully reassembled inbound messages as (src_ip, src_port, bytes).
    """

    def __init__(self, bind_ip: str = "127.0.0.1", port: int = 0):
        self._lib = _load()
        err = ctypes.create_string_buffer(256)
        self._h = self._lib.udp_create(bind_ip.encode(), port, err, 256)
        if not self._h:
            raise OSError(f"udp_create({bind_ip}:{port}): {err.value.decode()}")
        self.bind_ip = bind_ip
        self.port = int(self._lib.udp_port(self._h))

    @property
    def _handle(self):
        if not self._h:
            raise RuntimeError("endpoint is closed")
        return self._h

    def send(self, ip: str, port: int, data: bytes) -> int:
        mid = self._lib.udp_send(
            self._handle, ip.encode(), port, _as_u8p(data), len(data)
        )
        if mid < 0:
            raise OSError(f"udp_send to {ip}:{port} failed")
        return int(mid)

    def send_unreliable(self, ip: str, port: int, data: bytes) -> int:
        """Fire-and-forget send: framed like :meth:`send` (receivers
        reassemble/dedup identically) but never retransmitted, never
        counted in ``pending``/``failed``. The NAT-traversal probe
        path — callers that need delivery retry at their own layer."""
        mid = self._lib.udp_send_unreliable(
            self._handle, ip.encode(), port, _as_u8p(data), len(data)
        )
        if mid < 0:
            raise OSError(f"udp_send_unreliable to {ip}:{port} failed")
        return int(mid)

    def poll(self) -> int:
        """One pump: drain socket, ack, retransmit. Returns datagrams
        processed."""
        return int(self._lib.udp_poll(self._handle))

    def recv(self) -> Optional[Tuple[str, int, bytes]]:  # crdtlint: taints
        ip = ctypes.create_string_buffer(64)
        port = ctypes.c_int()
        out = _u8p()
        n = ctypes.c_uint32()
        rc = self._lib.udp_recv(
            self._handle, ip, ctypes.byref(port), ctypes.byref(out),
            ctypes.byref(n),
        )
        if rc == 1:
            return None
        try:
            data = ctypes.string_at(out, n.value)
        finally:
            self._lib.ct_free(out)
        return ip.value.decode(), int(port.value), data

    def recv_all(self) -> List[Tuple[str, int, bytes]]:
        out = []
        while (m := self.recv()) is not None:
            out.append(m)
        return out

    @property
    def pending(self) -> int:
        """Outbound messages not yet fully acked."""
        return int(self._lib.udp_pending(self._handle))

    @property
    def failed(self) -> int:
        """Messages dropped after exhausting retransmits."""
        return int(self._lib.udp_failed(self._handle))

    def set_loss(self, permille: int, seed: int = 0) -> None:
        """Test knob: drop this fraction (0-1000) of OUTBOUND datagrams."""
        self._lib.udp_set_loss(self._handle, permille, seed)

    def close(self) -> None:
        if self._h:
            self._lib.udp_close(self._h)
            self._h = None

    def __enter__(self) -> "UdpEndpoint":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
