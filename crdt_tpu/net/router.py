"""Loopback transport implementing the reference's router contract.

The reference consumes `@ypear/router` (Hyperswarm: DHT discovery +
encrypted streams) through a narrow surface (crdt.js:172-317):
``is_ypear_router`` validation, an ``options`` bag shared across ypear
modules, ``update_options`` / ``update_options_cache``, ``start`` /
``started`` / ``peers``, and ``alow(topic, handler)`` returning the
four transport verbs ``(propagate, broadcast, for_peers, to_peer)``
(crdt.js:315-317).

This module provides that exact contract over an in-process fabric so
N replicas run in one process with deterministic, adversarially
schedulable delivery (SURVEY.md §4's loopback pattern) — the testing
and protocol seam. Cross-device replica fan-in rides XLA collectives
instead (crdt_tpu.parallel); a real multi-process shim can implement
this same contract over sockets.

Delivery is queue-based: verbs enqueue onto the shared
:class:`LoopbackNetwork`; nothing is handled until ``run()`` drains
the queue, optionally shuffling / duplicating / dropping messages
under a seeded RNG to emulate the reference's unordered, redundant
gossip fabric (Hyperswarm gives no ordering guarantee across peers;
Yjs idempotence absorbs duplicates — SURVEY.md Q2).
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional, Tuple


class LoopbackNetwork:
    """Shared fabric: topic registry + deterministic delivery queue."""

    def __init__(
        self,
        seed: int = 0,
        *,
        reorder: bool = False,
        duplicate: float = 0.0,
        drop: float = 0.0,
    ):
        self.rng = random.Random(seed)
        self.reorder = reorder
        self.duplicate = duplicate
        self.drop = drop
        # topic -> [(router, handler)]
        self.topics: Dict[str, List[Tuple["LoopbackRouter", Callable]]] = {}
        self.queue: List[Tuple[Callable, dict, str]] = []
        self.delivered = 0
        self.dropped = 0

    def subscribe(self, topic: str, router: "LoopbackRouter", handler: Callable):
        self.topics.setdefault(topic, []).append((router, handler))
        # a joining peer triggers everyone's (re)sync entry point, the
        # way the router drives the injected cache contract
        # (crdt.js:237: `sync(forPeers, topic)`)
        for r, _ in self.topics[topic]:
            r._on_topology_change(topic)

    def unsubscribe(self, topic: str, router: "LoopbackRouter"):
        subs = self.topics.get(topic, [])
        self.topics[topic] = [(r, h) for r, h in subs if r is not router]
        for r, _ in self.topics[topic]:
            r._on_topology_change(topic)

    def subscribers(self, topic: str) -> List["LoopbackRouter"]:
        return [r for r, _ in self.topics.get(topic, [])]

    def enqueue(self, topic: str, to_router: "LoopbackRouter", msg: dict, frm: str):
        for _, handler in [
            (r, h) for r, h in self.topics.get(topic, []) if r is to_router
        ]:
            self.queue.append((handler, dict(msg), frm))

    def run(self, max_rounds: int = 10_000) -> int:
        """Drain the queue (handlers may enqueue more). Returns the
        number of messages delivered."""
        n0 = self.delivered
        rounds = 0
        while self.queue and rounds < max_rounds:
            rounds += 1
            batch, self.queue = self.queue, []
            if self.reorder:
                self.rng.shuffle(batch)
            for handler, msg, frm in batch:
                if self.drop and self.rng.random() < self.drop:
                    self.dropped += 1
                    continue
                copies = 1
                if self.duplicate and self.rng.random() < self.duplicate:
                    copies = 2
                for _ in range(copies):
                    handler(msg, frm)
                    self.delivered += 1
            # end of delivery round: replicas buffering inbound updates
            # (batch_incoming) merge the round's worth in one txn,
            # then get their timer tick (probe retry / anti-entropy —
            # mostly a no-op on this reliable fabric, but the contract
            # matches the UDP router so protocol tests can drive the
            # retry machinery through either transport)
            for topic, subs in list(self.topics.items()):
                for r, _ in subs:
                    contract = r.options.get("cache", {}).get(topic, {})
                    flush = contract.get("flush")
                    if flush is not None:
                        flush()
                    tick = contract.get("tick")
                    if tick is not None:
                        tick()
        if self.queue:
            raise RuntimeError(f"network did not quiesce in {max_rounds} rounds")
        return self.delivered - n0


class LoopbackRouter:
    """One peer's router — the contract surface of `@ypear/router`."""

    is_ypear_router = True  # crdt.js:172's validation flag

    def __init__(
        self,
        network: LoopbackNetwork,
        public_key: str,
        *,
        username: Optional[str] = None,
    ):
        self.network = network
        self.options: Dict[str, Any] = {
            "public_key": public_key,
            "username": username or public_key,
            "cache": {},
        }
        self.started = False
        self._subscribed: List[str] = []

    # -- options bag shared across ypear modules (crdt.js:175-180) -----
    def update_options(self, opts: Dict[str, Any]) -> None:
        self.options.update(opts)

    def update_options_cache(self, per_topic: Dict[str, dict]) -> None:
        # crdt.js:234: inject the per-topic sync contract
        for topic, contract in per_topic.items():
            self.options["cache"].setdefault(topic, {}).update(contract)

    # -- lifecycle (crdt.js:231) ---------------------------------------
    def start(self, network_name: Optional[str] = None) -> None:
        self.options.setdefault("network_name", network_name)
        self.started = True

    @property
    def public_key(self) -> str:
        return self.options["public_key"]

    def peers_on(self, topic: str) -> List[str]:
        return [
            r.public_key
            for r in self.network.subscribers(topic)
            if r is not self
        ]

    @property
    def peers(self) -> List[str]:
        # union over subscribed topics (the reference exposes swarm
        # peers, crdt.js:236)
        out: List[str] = []
        for t in self._subscribed:
            for pk in self.peers_on(t):
                if pk not in out:
                    out.append(pk)
        return out

    # -- the four verbs (crdt.js:315-317) -------------------------------
    def alow(self, topic: str, handler: Callable) -> Tuple[
        Callable, Callable, Callable, Callable
    ]:
        """Subscribe; returns (propagate, broadcast, for_peers, to_peer)."""
        self.network.subscribe(topic, self, handler)
        self._subscribed.append(topic)

        def propagate(msg: dict) -> None:
            for r in self.network.subscribers(topic):
                if r is not self:
                    self.network.enqueue(topic, r, msg, self.public_key)

        broadcast = propagate  # the reference uses them interchangeably

        def for_peers(fn: Callable[[str], None]) -> None:
            for pk in self.peers_on(topic):
                fn(pk)

        def to_peer(public_key: str, msg: dict) -> None:
            for r in self.network.subscribers(topic):
                if r.public_key == public_key:
                    self.network.enqueue(topic, r, msg, self.public_key)
                    return

        return propagate, broadcast, for_peers, to_peer

    def unsubscribe(self, topic: str) -> None:
        self.network.unsubscribe(topic, self)
        if topic in self._subscribed:
            self._subscribed.remove(topic)

    # -- topology hook driving the injected sync contract ---------------
    def _on_topology_change(self, topic: str) -> None:
        contract = self.options["cache"].get(topic)
        if contract and not contract.get("synced") and "sync" in contract:
            contract["sync"]()
