"""Replica = document + sync protocol over a router (crdt.js:166-317).

``ypear_crdt(router, topic=...)`` mirrors the reference factory: it
wires a :class:`crdt_tpu.api.Crdt` document to a router implementing
the contract in :mod:`crdt_tpu.net.router`, registers the per-topic
sync contract (crdt.js:234-277), and dispatches inbound messages the
way the reference's ``onData`` does (crdt.js:279-312):

- ``{message}``            -> observer passthrough (crdt.js:280-284)
- ``{meta:'cleanup'}``     -> peer_close (crdt.js:285)
- ``{meta:'ready', ...}``  -> if synced, act as syncer: encode the diff
                              against the requester's state vector and
                              unicast ``{update, meta:'sync'}``
                              (crdt.js:286-291 — the one true delta in
                              the reference; every update here is one)
- ``{update}``             -> apply, persist, flip ``synced`` on
                              ``meta:'sync'`` (crdt.js:292-311)

Divergences (documented, SURVEY.md §6): broadcasts are per-transaction
deltas, not full state (Q2); a replica whose topic has no peers starts
synced (the reference's heuristic covers only ``-db`` topics and its
50 ms poll loop otherwise hangs a solo first node); collections
created remotely appear in the cache (D3).
"""

from __future__ import annotations

import math
import random
import time
from typing import Any, Callable, Dict, List, Optional

from crdt_tpu.api.doc import Crdt
from crdt_tpu.codec import v1
from crdt_tpu.core.ids import StateVector
from crdt_tpu.obs import propagation
from crdt_tpu.obs.propagation import get_propagation
from crdt_tpu.obs.recorder import get_recorder, update_digest
from crdt_tpu.obs.sentinel import DivergenceSentinel
from crdt_tpu.utils.backoff import jitter
from crdt_tpu.utils.trace import get_tracer


class MemoryPersistence:
    """In-RAM stand-in for the update-log store (stage-6 interface).

    Mirrors the reference keyspace semantics (`doc_<name>_update_<ts>`,
    `_sv`, `_meta` — crdt.js:41-71) with monotonic sequence numbers
    instead of `Date.now()` keys (fix D6) and caller-supplied state
    vectors (fix D5: the reference recomputes SVs on an empty doc and
    stores garbage).
    """

    def __init__(self):
        self._updates: Dict[str, List[bytes]] = {}
        self._sv: Dict[str, bytes] = {}
        self._meta: Dict[str, dict] = {}
        self.closed = False

    def store_update(self, doc_name: str, update: bytes, sv: Optional[bytes] = None):
        self.store_updates(doc_name, [update], sv=sv)

    def store_updates(self, doc_name: str, updates,
                      sv: Optional[bytes] = None):
        """Batched window append — interface parity with
        :class:`crdt_tpu.storage.persistence.LogPersistence`
        (one "batch" per call; in RAM the batch is just a list
        extend)."""
        updates = list(updates)  # survive generator args (see
        #                          LogPersistence.store_updates)
        for u in updates:
            if not isinstance(u, (bytes, bytearray)):
                raise TypeError("update must be bytes")  # crdt.js:29-31
        if not updates:
            return
        self._updates.setdefault(doc_name, []).extend(
            bytes(u) for u in updates
        )
        if sv is not None:
            self._sv[doc_name] = sv
        self._meta[doc_name] = {
            "last_updated": time.time(),
            "size": sum(len(u) for u in self._updates[doc_name]),
            "count": len(self._updates[doc_name]),
        }

    def get_all_updates(self, doc_name: str) -> List[bytes]:
        return list(self._updates.get(doc_name, []))

    def get_state_vector(self, doc_name: str) -> Optional[bytes]:
        return self._sv.get(doc_name)

    def get_meta(self, doc_name: str) -> Optional[dict]:
        return self._meta.get(doc_name)

    def compact(self, doc_name: str, snapshot: bytes, sv: Optional[bytes] = None):
        """Replace the update log with one snapshot update (the
        compaction the reference lacks — SURVEY.md Q3)."""
        self._updates[doc_name] = [bytes(snapshot)]
        if sv is not None:
            self._sv[doc_name] = sv
        self._meta[doc_name] = {
            "last_updated": time.time(),
            "size": len(snapshot),
            "count": 1,
        }

    def open(self):
        self.closed = False

    def close(self):
        self.closed = True


def _prefers_batch_verb(cls) -> bool:
    """Whether a persistence class should take the batched
    ``store_updates`` path. True only when the class defines
    ``store_updates`` at least as deep in the MRO as ``store_update``:
    a subclass that overrides ONLY ``store_update`` (to encrypt,
    mirror, filter — the sole verb that existed before round 9)
    expects to intercept every write, and the inherited batch verb
    would silently bypass it."""
    batch = single = None
    for i, c in enumerate(cls.__mro__):
        if batch is None and "store_updates" in vars(c):
            batch = i
        if single is None and "store_update" in vars(c):
            single = i
    if batch is None:
        return False
    return single is None or batch <= single


def _random_client_id() -> int:
    # Yjs randomizes the client id per doc *instance* — a deterministic
    # identity-derived id is unsafe: a restart without persistence
    # restarts the clock at 0, so new ops fall below peers' watermarks
    # and are silently discarded as stale duplicates, and any id
    # collision between two identities diverges replicas permanently
    return random.getrandbits(31)


class Replica:
    """One peer: document + transport verbs + sync state."""

    def __init__(
        self,
        router,
        topic: str,
        *,
        client_id: Optional[int] = None,
        persistence=None,
        observer_function: Optional[Callable[[dict], None]] = None,
        full_state_updates: bool = False,
        compact_every: Optional[int] = None,
        device_merge: Optional[bool] = None,
        batch_incoming: Optional[bool] = None,
        merge_mode: Optional[str] = None,
        device_min_rows: Optional[int] = None,
        probe_retry_s: float = 0.5,
        probe_retry_max_s: float = 8.0,
        probe_max_retries: int = 10,
        anti_entropy_s: Optional[float] = None,
        anti_entropy_max_s: Optional[float] = None,
        sentinel: Optional[bool] = None,
        on_divergence: Optional[Callable[[dict], None]] = None,
        inbox_max_bytes: Optional[int] = None,
        inbox_max_updates: Optional[int] = None,
        pending_max_records: Optional[int] = None,
        resync_retry_s: float = 0.25,
        resync_max_retries: int = 20,
    ):
        if not getattr(router, "is_ypear_router", False):
            raise TypeError("router is not a ypear router")  # crdt.js:172
        self.router = router
        self.topic = topic
        self.persistence = persistence
        self.observer_function = observer_function
        self.compact_every = compact_every
        self.synced = False
        self.closed = False
        self.peer_state_vectors: Dict[str, StateVector] = {}

        # partition tolerance: ready probes were historically fired
        # ONCE and lost probes were only repaired by topology changes.
        # Now un-synced replicas re-probe on a jittered exponential
        # backoff (bounded — a dead topic must not broadcast forever;
        # any topology change re-arms the schedule), and an optional
        # periodic anti-entropy cadence re-runs the two-way SV
        # exchange so updates lost AFTER sync (where the optimistic
        # SV advancement lies about delivery) are repaired too.
        self.probe_retry_s = probe_retry_s
        self.probe_retry_max_s = probe_retry_max_s
        self.probe_max_retries = probe_max_retries
        self.anti_entropy_s = anti_entropy_s
        self.anti_entropy_max_s = (
            anti_entropy_max_s
            if anti_entropy_max_s is not None
            else (anti_entropy_s or 0.0) * 16
        )
        self._probe_interval = probe_retry_s
        self._probe_retries = 0
        self._next_probe_at: Optional[float] = None
        self._ae_interval = anti_entropy_s or 0.0
        self._next_ae_at: Optional[float] = (
            time.monotonic() + anti_entropy_s if anti_entropy_s else None
        )

        # merge_mode selects the document backend:
        #   "scalar"   — Engine-backed, host integrate loop
        #   "device"   — Engine-backed, TPU-kernel merges (device_merge)
        #   "resident" — no engine at all: HBM-resident columns serve
        #                merges, local ops, AND the sync protocol
        #                (crdt_tpu.api.resident_doc; the north star's
        #                "cache rebuilt from HBM")
        merge_mode_explicit = merge_mode is not None
        if merge_mode is None:
            if device_merge:
                merge_mode = "device"
            else:
                # CRDT_TPU_DEVICE=1 selects RESIDENT, the device-
                # resident product mode: the engine-backed device gate
                # pays a tunnel round-trip per small merge and lost to
                # both other modes at interactive scale in BENCH_r03's
                # swarm run (VERDICT r3 item 4). merge_mode="device"
                # stays available explicitly as a differential oracle.
                import os

                env = os.environ.get("CRDT_TPU_DEVICE", "0") not in (
                    "", "0", "false", "False",
                )
                # an explicit device_merge=False still means scalar
                # even with the env var set (same precedence Crdt uses)
                merge_mode = (
                    "resident" if env and device_merge is None
                    else "scalar"
                )
        if merge_mode not in ("scalar", "device", "resident"):
            raise ValueError(f"unknown merge_mode {merge_mode!r}")
        self.merge_mode = merge_mode

        cid = client_id if client_id is not None else _random_client_id()
        if merge_mode == "resident":
            from crdt_tpu.api.resident_doc import ResidentCrdt

            self.doc = ResidentCrdt(
                cid,
                observer_function=observer_function,
                on_update=self._on_local_update,
                full_state_updates=full_state_updates,
                device_min_rows=device_min_rows,
            )
        else:
            self.doc = Crdt(
                cid,
                observer_function=observer_function,
                on_update=self._on_local_update,
                full_state_updates=full_state_updates,
                # an explicit merge_mode overrides the env-var default
                # (merge_mode="device" must enable device merges even
                # with CRDT_TPU_DEVICE unset, and "scalar" must disable
                # them even with it set)
                device_merge=(
                    merge_mode == "device" if merge_mode_explicit
                    else device_merge
                ),
            )
        # receive-side batching: updates arriving within one router
        # poll round are buffered and applied as ONE merge transaction
        # (one kernel dispatch in device mode) — the north-star gate at
        # the sync handler. Defaults on in device mode; scalar mode
        # keeps per-message application unless asked.
        if batch_incoming is None:
            batch_incoming = self.doc.device_merge
        self.batch_incoming = batch_incoming
        self._inbox: List[tuple] = []  # (update bytes, meta dict)

        # resource guards (crdt_tpu/guard): the inbox byte/count
        # budget sheds the OLDEST buffered updates (re-fetched via the
        # anti-entropy/re-probe path — our SV never advertised them),
        # and the pending-stash cap evicts blocked records whose
        # missing (client, clock) ranges the re-probe machinery below
        # then re-fetches from the blocking peer. None = unbounded
        # (the historical behavior).
        self.inbox_max_bytes = inbox_max_bytes
        self.inbox_max_updates = inbox_max_updates
        self._inbox_bytes = 0
        self.inbox_peak_bytes = 0  # bench/test evidence of boundedness
        if pending_max_records is not None:
            self.doc.engine.pending_limit = pending_max_records
        # bounded-backoff targeted re-probe: armed by sheds/evictions,
        # pumped by tick(); independent of the un-synced probe retry
        # schedule (a replica can be "synced" and still owe itself a
        # re-fetch of evicted state)
        self.resync_retry_s = resync_retry_s
        self.resync_max_retries = resync_max_retries
        self._resync_at: Optional[float] = None
        self._resync_interval = resync_retry_s
        self._resync_retries = 0
        self._resync_needs: Dict[int, int] = {}  # client -> clock owed

        # divergence sentinel (obs.sentinel): snapshot-hash beacons
        # ride the anti-entropy cadence (``sentinel=None`` => beacons
        # enabled exactly when ``anti_entropy_s`` is set). Inbound
        # beacons are ALWAYS checked — a beaconing peer gets fork
        # coverage even from replicas that never beacon themselves.
        self._sentinel_beacons = (
            sentinel if sentinel is not None else anti_entropy_s is not None
        )
        self.sentinel = DivergenceSentinel(
            self.doc, topic=topic, replica=router.public_key,
            on_divergence=on_divergence,
        )
        # per-origin trace-id sequence: sync frames are stamped with
        # (client, seq, monotonic ts) so per-peer propagation and
        # convergence lag become measurable gauges downstream.
        # Round 19: sampled origin frames additionally carry a wire
        # trace context (origin tid + per-leg path records) so the
        # path reconstructs ACROSS processes — see obs/propagation
        self._tid_seq = 0
        self._trace_sample = propagation.sample_rate()
        self._pk8 = str(router.public_key)[:8]

        # load from the update log (crdt.js:193-217): the whole log
        # replays as ONE batched merge (one observer flush; in device
        # mode, one kernel dispatch instead of one per logged update)
        if persistence is not None:
            if getattr(persistence, "closed", False):
                persistence.open()  # restart after self_close
            self.doc.apply_updates(
                persistence.get_all_updates(topic), origin="load"
            )

        if not router.started:
            router.start(router.options.get("network_name"))  # crdt.js:231

        (
            self._propagate,
            self._broadcast,
            self.for_peers,
            self._to_peer,
        ) = router.alow(topic, self._on_data)
        # the per-topic sync contract the router drives (crdt.js:234-277)
        # — registered after `alow` so a topology-triggered sync() never
        # runs before the transport verbs exist
        router.update_options_cache(
            {
                topic: {
                    "synced": False,
                    "sync": self.sync,
                    "peer_state_vectors": self.peer_state_vectors,
                    "update_state_vector": self._update_own_sv,
                    "set_peer_state_vector": self.set_peer_state_vector,
                    "peer_close": self.peer_close,
                    "self_close": self.self_close,
                    # routers call this after each poll/delivery round
                    # so buffered inbound updates land as one merge
                    "flush": self.flush_incoming,
                    # ... and this afterwards: the replica's timer
                    # pump (probe retry/backoff, periodic
                    # anti-entropy) — a lost sync message is now a
                    # delay, not a permanent divergence
                    "tick": self.tick,
                    # async-transport hook (e.g. the UDP router): a
                    # peer subscribing to our topic AFTER construction
                    # triggers a directed anti-entropy probe even when
                    # we are already synced — on a real network peers
                    # appear at any time and both sides must reconcile
                    "peer_joined": self.probe,
                }
            }
        )

        if not router.peers_on(topic):
            # solo first node: nobody can answer a ready probe
            self._set_synced(True)
        else:
            self.sync()

    # ------------------------------------------------------------------
    # sync contract (crdt.js:234-277)
    # ------------------------------------------------------------------
    def sync(self) -> None:
        """Anti-entropy entry point: announce readiness with our SV
        (crdt.js:237-244). Peers answer with a diff update."""
        if self.synced or self.closed:
            return
        if not self.router.peers_on(self.topic):
            # the last peer left before answering: a solo replica is
            # synced by definition (same rule as construction; without
            # it a topic whose synced members all departed would wedge
            # every remaining and future replica forever)
            self._set_synced(True)
            return
        self.probe()

    def probe(self, public_key: Optional[str] = None, *,
              _rearm: bool = True) -> None:
        """Unconditional ready probe (unlike :meth:`sync`, which is a
        no-op once synced): ask one peer — or everyone — for whatever
        we lack. The two-way handshake then reconciles both sides.

        A topology-triggered probe (``public_key`` set: someone
        joined) re-arms the retry schedule from its base interval —
        new peers are new chances to sync, whatever the retry budget
        said before. The resync pump passes ``_rearm=False``: its
        probes ride their OWN backoff and must not refresh the join
        schedule's retry budget on every pump."""
        if self.closed:
            return
        self.flush_incoming()  # advertise the SV incl. buffered updates
        msg = {
            "meta": "ready",
            "public_key": self.router.public_key,
            "state_vector": self.doc.encode_state_vector(),
        }
        rec = get_recorder()
        if rec.enabled:
            rec.record(
                "probe.send", topic=self.topic,
                replica=self.router.public_key, peer=public_key,
            )
        if public_key is not None:
            if _rearm:
                self._probe_retries = 0
                self._probe_interval = self.probe_retry_s
                if not self.synced:
                    # re-arm from the BASE interval even when a
                    # (backed-off) deadline is already pending: the
                    # new peer is a fresh chance to sync and must be
                    # retried promptly
                    self._next_probe_at = (
                        time.monotonic() + self._probe_interval * jitter()
                    )
            self._to_peer(public_key, msg)
        else:
            self._broadcast(msg)
        if _rearm and not self.synced and self._next_probe_at is None:
            self._next_probe_at = (
                time.monotonic() + self._probe_interval * jitter()
            )

    def tick(self, now: Optional[float] = None) -> None:
        """Timer pump, called by routers once per poll/delivery round:
        retries un-synced ready probes (jittered exponential backoff,
        bounded by ``probe_max_retries``) and runs the periodic
        anti-entropy cadence when ``anti_entropy_s`` is set (interval
        backs off while rounds stay idle, resets on any activity)."""
        if self.closed:
            return
        if now is None:
            now = time.monotonic()
        if (
            not self.synced
            and self._next_probe_at is not None
            and now >= self._next_probe_at
        ):
            if self._probe_retries >= self.probe_max_retries:
                self._next_probe_at = None  # bounded; re-armed on join
            else:
                self._probe_retries += 1
                get_tracer().count("replica.probe_retries")
                self._probe_interval = min(
                    self._probe_interval * 2, self.probe_retry_max_s
                )
                self._next_probe_at = (
                    now + self._probe_interval * jitter()
                )
                self.probe()
        if self._resync_at is not None and now >= self._resync_at:
            self._pump_resync(now)
        if self._next_ae_at is not None and now >= self._next_ae_at:
            get_tracer().count("replica.anti_entropy_rounds")
            sent = self.anti_entropy()
            # the SV-records-driven delta above repairs known
            # deficits; the periodic probe below re-exchanges REAL
            # state vectors, repairing deficits the optimistic
            # advancement mis-recorded (a dropped broadcast)
            self.probe()
            if self._sentinel_beacons:
                # the sentinel's snapshot-hash beacon rides the same
                # cadence: silent divergence (equal SVs, unequal
                # state) becomes an observable event at the receivers
                self.beacon()
            if sent:
                self._ae_interval = self.anti_entropy_s
            else:
                self._ae_interval = min(
                    self._ae_interval * 2, self.anti_entropy_max_s
                )
            self._next_ae_at = now + self._ae_interval * jitter()

    # ------------------------------------------------------------------
    # guard layer: shed + targeted re-probe (crdt_tpu/guard)
    # ------------------------------------------------------------------
    def _shed_inbox(self) -> None:
        """Enforce the inbox budget: drop the OLDEST buffered updates
        until within bounds (always keeping the newest — a single
        over-budget update must still make progress). Shed updates
        were never applied, so our advertised SV doesn't cover them
        and any ready-probe answer re-ships them; shedding therefore
        trades latency for bounded memory, never state. Each shed
        re-arms the anti-entropy cadence and the re-probe schedule so
        the re-fetch is immediate, not left to luck."""
        def over(n_left: int, bytes_left: int) -> bool:
            return (
                (self.inbox_max_bytes is not None
                 and bytes_left > self.inbox_max_bytes)
                or (self.inbox_max_updates is not None
                    and n_left > self.inbox_max_updates)
            )

        if not over(len(self._inbox), self._inbox_bytes):
            return
        # one O(shed) slice, not per-item pop(0): a tiny-update flood
        # against a byte budget can hold MANY buffered items, and the
        # guard must stay linear exactly when it is needed
        shed_n = shed_b = 0
        n = len(self._inbox)
        while n - shed_n > 1 and over(n - shed_n, self._inbox_bytes):
            shed_b += len(self._inbox[shed_n][0])
            self._inbox_bytes -= len(self._inbox[shed_n][0])
            shed_n += 1
        if not shed_n:
            return
        self._inbox = self._inbox[shed_n:]
        tracer = get_tracer()
        tracer.count("guard.inbox_shed", shed_n)
        tracer.count("guard.inbox_shed_bytes", shed_b)
        tracer.gauge("guard.inbox_bytes", self._inbox_bytes)
        rec = get_recorder()
        if rec.enabled:
            rec.record(
                "guard.shed", topic=self.topic,
                replica=self.router.public_key, n=shed_n, size=shed_b,
            )
        # immediate AE re-arm: the next tick runs the repair round now
        if self._next_ae_at is not None:
            self._next_ae_at = time.monotonic()
        self._arm_resync()

    def _arm_resync(self, needs: Optional[Dict[int, int]] = None) -> None:
        """Arm (or extend) the bounded-backoff re-probe. ``needs``
        maps client -> highest evicted clock; satisfaction = our SV
        passing that clock. A shed arms with no needs: one prompt
        probe re-fetches whatever was dropped (the answer is an SV
        diff, so it is exact), with the AE cadence as the backstop."""
        if needs:
            for c, hi in needs.items():
                self._resync_needs[c] = max(self._resync_needs.get(c, -1), hi)
        if self._resync_at is None:
            self._resync_interval = self.resync_retry_s
            self._resync_retries = 0
            self._resync_at = (
                time.monotonic() + self._resync_interval * jitter()
            )

    def _resync_target(self) -> Optional[str]:
        """A peer whose recorded SV covers an owed range — the
        BLOCKING peer, probed by unicast; None broadcasts."""
        for c, hi in self._resync_needs.items():
            for pk, sv in self.peer_state_vectors.items():
                if sv.get(c) > hi:
                    return pk
        return None

    def _pump_resync(self, now: float) -> None:
        sv = self.doc.state_vector()
        self._resync_needs = {
            c: hi for c, hi in self._resync_needs.items()
            if sv.get(c) <= hi
        }
        if self._resync_retries >= self.resync_max_retries:
            # bounded: the periodic anti-entropy cadence (and any
            # topology change) remains the backstop
            self._resync_at = None
            return
        self._resync_retries += 1
        get_tracer().count("guard.resync_probes")
        self.probe(self._resync_target(), _rearm=False)
        if self._resync_needs:
            self._resync_interval = min(
                self._resync_interval * 2, self.probe_retry_max_s
            )
            self._resync_at = now + self._resync_interval * jitter()
        else:
            self._resync_at = None  # satisfied (or shed-only: one shot)

    def beacon(self) -> None:
        """Broadcast one divergence-sentinel beacon: our state vector
        plus snapshot/delete-set digests. Receivers whose SV equals
        ours compare digests; a mismatch with equal delete sets is
        silent divergence and raises an observable event (with a
        flight-recorder dump) at the receiver."""
        if self.closed or not self.router.peers_on(self.topic):
            return
        self.flush_incoming()  # digest the state the SV advertises
        self._broadcast({
            "meta": "beacon",
            "public_key": self.router.public_key,
            "state_vector": self.doc.encode_state_vector(),
            **self.sentinel.beacon_payload(),
        })

    def _reset_ae_backoff(self) -> None:
        if self.anti_entropy_s is not None:
            was = self._ae_interval
            self._ae_interval = self.anti_entropy_s
            if was != self._ae_interval and self._next_ae_at is not None:
                self._next_ae_at = min(
                    self._next_ae_at,
                    time.monotonic() + self._ae_interval * jitter(),
                )

    def _set_synced(self, value: bool) -> None:
        self.synced = value
        if value:
            self._next_probe_at = None
            self._probe_retries = 0
            self._probe_interval = self.probe_retry_s
        self.router.options["cache"].setdefault(self.topic, {})["synced"] = value

    def _update_own_sv(self) -> bytes:
        self.flush_incoming()  # the advertised SV covers buffered updates
        return self.doc.encode_state_vector()

    def set_peer_state_vector(self, public_key: str, sv_bytes: bytes) -> None:
        # the router-cache sync-contract hook: peers' SV bytes arrive
        # here too, so the same admission check applies (a hostile SV
        # drops, it does not raise into the caller's loop)
        sv = self._decode_peer_sv(sv_bytes, public_key)
        if sv is not None:
            self.peer_state_vectors[public_key] = sv

    def _decode_peer_sv(self, blob, from_pk: str):
        """Admission check for a peer-supplied state vector (round-17
        wire-taint contract): a hostile SV — client/clock past the
        wire bounds, truncated, trailing garbage, or not bytes at all
        (lib0 `any` payloads can carry str/int/None here, and
        ``bytes(2**40)`` would be the allocation bomb itself) —
        degrades exactly like a malformed update (counted, recorded,
        dropped) instead of raising out of the router's poll loop.
        Returns None on reject; callers skip the protocol action."""
        try:
            if not isinstance(blob, (bytes, bytearray)):
                raise ValueError("state vector is not bytes")
            return v1.decode_state_vector(blob)
        except ValueError:
            get_tracer().count("replica.malformed_updates")
            rec = get_recorder()
            if rec.enabled:
                rec.record(
                    "update.malformed", topic=self.topic,
                    replica=self.router.public_key, peer=from_pk,
                    size=len(blob)
                    if isinstance(blob, (bytes, bytearray)) else 0,
                )
            return None

    def peer_close(self, public_key: str) -> None:
        self.peer_state_vectors.pop(public_key, None)  # crdt.js:266-270

    def self_close(self) -> None:
        """Close persistence and announce cleanup (crdt.js:272-275)."""
        if self.closed:
            return
        self.flush_incoming()  # buffered updates land before the log closes
        self.closed = True
        if self.persistence is not None:
            self.persistence.close()
        self._propagate({"meta": "cleanup", "public_key": self.router.public_key})
        self.router.unsubscribe(self.topic)

    def anti_entropy(self) -> Dict[str, int]:
        """One targeted delta round driven by recorded peer SVs: for
        each peer whose state vector shows a record deficit, unicast
        exactly the records it lacks (the syncer's SV-diff,
        crdt.js:288, generalized to every known peer instead of only
        ready-probe requesters). Returns {peer: bytes_sent}.

        Bytes scale with the DEFICIT, not the doc: a peer missing 3
        ops gets a 3-op update (plus the delete-set tail every diff
        carries, Yjs-style). Peers with no record deficit get nothing
        — tombstone-only surplus still flows through the ready/sync
        handshake, which sends unconditionally. Recorded SVs advance
        optimistically (transports retry until acked; a lost message
        is recovered by the next ready probe). The device-path
        analogue is :mod:`crdt_tpu.parallel.delta`.
        """
        sent: Dict[str, int] = {}
        if self.closed:
            return sent
        self.flush_incoming()  # deficits computed on current state
        mine = self.doc.state_vector()
        rec = get_recorder()
        for pk, sv in list(self.peer_state_vectors.items()):
            if sv.diff_dominates(mine):
                continue  # no record deficit
            update = self.doc.encode_state_as_update(sv)
            # each AE delta is its own origin frame (per-peer diffs
            # differ); the anti_entropy route tag makes repair
            # traffic separable from first-delivery lag downstream
            trace, path = self._trace_fields(update, "anti_entropy")
            self._to_peer(pk, {"update": update, **trace})
            sent[pk] = len(update)
            if rec.enabled:
                rec.record(
                    "ae.delta", topic=self.topic,
                    replica=self.router.public_key, peer=pk,
                    size=len(update), digest=update_digest(update),
                    tid=trace["tid"], path=path,
                )
            self.peer_state_vectors[pk] = sv.merge(mine)
        if sent:
            tracer = get_tracer()
            tracer.count("replica.anti_entropy_bytes", sum(sent.values()))
        return sent

    # ------------------------------------------------------------------
    # local update tail: persist + broadcast (crdt.js:442-446)
    # ------------------------------------------------------------------
    def _trace_fields(self, update: bytes, route: str) -> tuple:
        """The wire trace fields for one ORIGIN frame: the round-18
        trace id + hop count, and (for sampled tids) the round-19
        wire trace context whose first path record tags this frame's
        semantic route (``direct`` broadcasts, ``anti_entropy``
        deltas, ``sync_answer`` diffs — the transport seam may
        retag a direct leg ``predicted``/``relayed``, and forward
        seams append further records). Returns ``(fields, path)`` —
        the dict to splice into the outbound message, plus the
        recorder-shape path (None when the tid was not sampled)."""
        self._tid_seq += 1
        tid = [self.doc.engine.client_id, self._tid_seq,
               time.monotonic()]
        fields: dict = {"tid": tid, "hop": 0}
        path = None
        # contexts ship only while observability is on in THIS
        # process (tracer or recorder): with both off, the origin
        # frame pays nothing beyond the two attribute checks — the
        # same free-when-off contract as every obs hook. Within an
        # observed process the sampling knob scales the tax.
        if (
            (get_tracer().enabled or get_recorder().enabled)
            and propagation.sampled(tid[0], tid[1],
                                    self._trace_sample)
        ):
            ctx = propagation.start_context(
                tid[0], tid[1], self._pk8, route, ts=tid[2]
            )
            tc = propagation.encode_context(ctx)
            fields["tc"] = tc
            path = ctx.path_json()
            get_propagation().record_send(tc, len(update))
        return fields, path

    def _on_local_update(self, update: bytes, meta: dict) -> None:
        self._persist(update)
        if not self.closed:
            # origin trace id: (client, per-origin seq, monotonic ts).
            # Receivers subtract the stamp from their clock to gauge
            # propagation/convergence lag (exact in-process and on a
            # shared clock; cross-host offsets shift it uniformly).
            trace, path = self._trace_fields(update, "direct")
            rec = get_recorder()
            if rec.enabled:
                rec.record(
                    "update.send", topic=self.topic,
                    replica=self.router.public_key, size=len(update),
                    digest=update_digest(update), tid=trace["tid"],
                    hop=0, path=path,
                )
            # hop count: 0 at the origin, so a direct receiver
            # records hop=1. Since round 19 every origin frame —
            # broadcasts here, sync answers and AE deltas at their
            # seams — carries tid/hop plus (sampled) the wire trace
            # context, and the relay forward seam in udp_router
            # actually increments both (closing the round-18
            # caveat): a relayed delivery records hop=2 with the
            # relay's own path record.
            self._propagate({"update": update, **trace, **meta})
            self._advance_topic_peer_svs()
            self._reset_ae_backoff()  # fresh writes: stay chatty

    def _advance_topic_peer_svs(self) -> None:
        """Optimistically advance recorded SVs of peers CURRENTLY on
        the topic — they just received our broadcast (transports retry
        until acked). Keeps ``anti_entropy`` deficit-accurate without
        extra probes; a peer that truly lost the message re-syncs via
        its next ready probe. Peers not subscribed right now (left,
        partitioned) are untouched and stay owed the delta."""
        reached: List[str] = []
        self.for_peers(reached.append)
        if not reached:
            return
        mine = self.doc.state_vector()
        for pk in reached:
            sv = self.peer_state_vectors.get(pk)
            if sv is not None:
                self.peer_state_vectors[pk] = sv.merge(mine)

    def _persist(self, update: bytes) -> None:
        self._persist_many([update])

    def _persist_many(self, updates) -> None:
        """Persist a whole merge window as ONE store batch: the
        batched-incoming path (``flush_incoming``) applies N buffered
        updates in one transaction, so the WAL gets one KV batch —
        N log keys + one SV + one meta — instead of N separate 3-key
        batches (``persist.batches`` vs ``persist.appends`` counters
        record the ratio)."""
        if not updates:
            return
        if self.persistence is None or self.persistence.closed:
            return
        tracer = get_tracer()
        try:
            with tracer.span("replica.persist"):
                sv = self.doc.encode_state_vector()
                if _prefers_batch_verb(type(self.persistence)):
                    self.persistence.store_updates(
                        self.topic, list(updates), sv=sv
                    )
                else:  # no batch verb, or store_update overridden below it
                    for u in updates:
                        self.persistence.store_update(self.topic, u, sv=sv)
        except (OSError, RuntimeError) as e:
            # storage failure policy, last-resort rung: a disk fault
            # must degrade (the doc still holds the state; the WAL is
            # merely behind), never kill the apply path mid-merge.
            # LogPersistence retries + buffers internally and only
            # raises once ITS policy is exhausted or set to "raise";
            # this guard covers third-party backends with no policy.
            tracer.count("persist.errors")
            rec = get_recorder()
            if rec.enabled:
                rec.record(
                    "persist.error", topic=self.topic,
                    replica=self.router.public_key, error=repr(e)[:200],
                )
            return
        for u in updates:
            tracer.count("replica.bytes_persisted", len(u))
        if self.compact_every:
            try:
                meta = self.persistence.get_meta(self.topic)
                if meta and meta.get("count", 0) >= self.compact_every:
                    self.compact()
            except (OSError, RuntimeError):
                # same policy as the store verbs above: a failing
                # compaction trigger (meta read or the compact write)
                # must degrade — skipped now, retried at the next
                # threshold crossing — never kill the apply path
                tracer.count("persist.errors")

    def compact(self) -> None:
        """Squash the update log into one full-state snapshot."""
        if self.persistence is None:
            return
        eng = self.doc.engine
        if eng.pending or eng.pending_deletes.ranges:
            # stashed updates exist only in the raw log; a snapshot of
            # integrated state would drop them across a restart
            return
        with get_tracer().span("replica.compact"):
            self.persistence.compact(
                self.topic,
                self.doc.encode_state_as_update(),
                sv=self.doc.encode_state_vector(),
            )

    # ------------------------------------------------------------------
    # receive path (crdt.js:279-312)
    # ------------------------------------------------------------------
    def _on_data(self, msg: dict, from_pk: str) -> None:
        if self.closed:
            return
        if "message" in msg:
            # free-form payload passthrough (crdt.js:280-284)
            if self.observer_function is not None:
                self.observer_function(msg)
            return
        meta = msg.get("meta")
        if meta == "cleanup":
            self.peer_close(msg.get("public_key", from_pk))
            return
        if meta == "beacon":
            # sentinel check against OUR settled state: buffered
            # updates land first, or a batching window would read as
            # SV lag / a false digest mismatch
            self.flush_incoming()
            rec = get_recorder()
            if rec.enabled:
                rec.record(
                    "beacon.recv", topic=self.topic,
                    replica=self.router.public_key,
                    peer=msg.get("public_key", from_pk),
                    digest=msg.get("digest"),
                )
            # .get(): a key-less beacon is as attacker-shaped as a
            # hostile SV — None rejects through the same admission
            # check instead of a KeyError killing the poll loop
            beacon_sv = self._decode_peer_sv(
                msg.get("state_vector"), from_pk
            )
            if beacon_sv is None:
                return
            self.sentinel.check(
                msg.get("public_key", from_pk),
                beacon_sv,
                msg.get("digest", ""),
                msg.get("ds_digest", ""),
            )
            return
        if meta == "ready":
            # answer with everything we hold: buffered updates must
            # land first or the diff would silently omit them
            self.flush_incoming()
            # act as syncer (crdt.js:286-291). Unlike the reference,
            # unsynced replicas answer too: two unsynced peers exchange
            # what they have and both converge (the reference's
            # synced-only gate deadlocks a topic whose synced members
            # all left). The reply carries our own SV so the requester
            # can return a back-diff — the reference's handshake is
            # one-way and silently strands the requester's surplus
            # state (e.g. ops replayed from its local log).
            requester = msg.get("public_key", from_pk)
            sv = self._decode_peer_sv(msg.get("state_vector"), from_pk)
            if sv is None:
                return
            diff = self.doc.encode_state_as_update(sv)
            # a sync answer is an ORIGIN frame (a fresh diff, not a
            # forward): it gets its own tid + trace context, route
            # tagged sync_answer — the round-18 "unknown" hop class
            # becomes attributable
            trace, path = self._trace_fields(diff, "sync_answer")
            rec = get_recorder()
            if rec.enabled:
                rec.record(
                    "sync.answer", topic=self.topic,
                    replica=self.router.public_key, peer=requester,
                    size=len(diff), digest=update_digest(diff),
                    tid=trace["tid"], path=path,
                )
            self._to_peer(
                requester,
                {
                    "update": diff,
                    "meta": "sync",
                    "state_vector": self.doc.encode_state_vector(),
                    **trace,
                },
            )
            # record the requester's SV ADVANCED by the diff just sent,
            # or every later anti_entropy round would re-unicast the
            # whole document to a peer that already converged
            self.peer_state_vectors[requester] = sv.merge(
                self.doc.state_vector()
            )
            return
        if "update" in msg:
            if self.batch_incoming:
                self._inbox.append((msg["update"], dict(msg), from_pk))
                self._inbox_bytes += len(msg["update"])
                self._shed_inbox()
                # peak measured post-shed: the budget is a real bound
                # (exceeded only by a single over-budget update, which
                # is always kept — see _shed_inbox)
                if self._inbox_bytes > self.inbox_peak_bytes:
                    self.inbox_peak_bytes = self._inbox_bytes
                return
            self._apply_incoming([(msg["update"], dict(msg), from_pk)])

    def flush_incoming(self) -> int:
        """Apply all buffered inbound updates as ONE merge transaction.
        Returns the number of updates applied. No-op when empty; safe
        to call from any router at any time."""
        if not self._inbox:
            return 0
        items, self._inbox = self._inbox, []
        if self._inbox_bytes and (
            self.inbox_max_bytes is not None
            or self.inbox_max_updates is not None
        ):
            # keep the budget gauge honest: a drained inbox is 0
            # bytes, not whatever the last shed left behind
            get_tracer().gauge("guard.inbox_bytes", 0)
        self._inbox_bytes = 0
        self._apply_incoming(items)
        return len(items)

    def _apply_incoming(self, items) -> None:
        tracer = get_tracer()
        rec = get_recorder()
        obs_on = tracer.enabled or rec.enabled
        t_apply = time.monotonic() if obs_on else 0.0
        updates = [u for u, _, _ in items]
        try:
            with tracer.span("replica.apply_update"):
                # two origin-preserving sub-batches: observers filter
                # on origin, so a handshake reply sharing a round with
                # ordinary broadcasts must not relabel them "sync"
                remote = [u for u, m, _ in items if m.get("meta") != "sync"]
                syncs = [u for u, m, _ in items if m.get("meta") == "sync"]
                if remote:
                    self.doc.apply_updates(remote, origin="remote")
                if syncs:
                    self.doc.apply_updates(syncs, origin="sync")
        except ValueError:
            # a malformed blob poisons its whole batch decode; isolate
            # it by RECURSIVE BISECTION so one poisoned blob in an
            # N-update flush costs O(log N) extra merge transactions,
            # not O(N) per-item retries (application is idempotent, so
            # re-applying survivors is safe; replica.isolation_splits
            # pins the cost in the malformed-update tests)
            if len(items) == 1:
                tracer.count("replica.malformed_updates")
                if rec.enabled:
                    rec.record(
                        "update.malformed", topic=self.topic,
                        replica=self.router.public_key,
                        peer=items[0][2], size=len(items[0][0]),
                        digest=update_digest(items[0][0]),
                    )
                return
            tracer.count("replica.isolation_splits")
            mid = len(items) // 2
            self._apply_incoming(items[:mid])
            self._apply_incoming(items[mid:])
            return
        if updates:
            self._reset_ae_backoff()  # remote activity: stay chatty
        # pending-stash evictions (guard layer): the engine recorded
        # the missing (client, clock) ranges; arm the targeted
        # bounded-backoff re-probe that re-fetches the evicted state
        take = getattr(self.doc.engine, "take_evicted_ranges", None)
        ev = take() if take is not None else None
        if ev:
            if rec.enabled:
                rec.record(
                    "guard.evict", topic=self.topic,
                    replica=self.router.public_key,
                    ranges={c: list(r) for c, r in ev.items()},
                )
            self._arm_resync({c: hi for c, (_, hi) in ev.items()})
        if obs_on:
            # observability tail AFTER a successful merge (so the
            # malformed-batch per-item retry above records each
            # surviving item exactly once, and the disabled path
            # pays nothing beyond the two attribute checks):
            # propagation lag = origin stamp -> merge entry,
            # convergence lag = origin stamp -> integrated here
            t_done = time.monotonic()
            for u, m, from_pk in items:
                tid = m.get("tid")
                # hop count (round 18): the frame's hop stamp + this
                # delivery leg. Frames predating the stamp (an older
                # peer) read as one unattributed hop — None, not a
                # guessed 1, so obsq can tell "unknown" from "direct".
                raw_hop = m.get("hop")
                hop = raw_hop + 1 if isinstance(raw_hop, int) else None
                # round 19: a carried trace context decomposes the
                # lag per route-tagged leg (obs/propagation ledger:
                # replica.hop_lag{route=} + birth_to_visibility) and
                # supplies the authoritative hop count / path. A
                # hostile context is counted + recorded and dropped
                # — the update it rode on is untouched.
                ctx = path = None
                tc = m.get("tc")
                if tc is not None:
                    ctx = propagation.decode_or_none(tc)
                    if ctx is None:
                        if rec.enabled:
                            rec.record(
                                "update.bad_context",
                                topic=self.topic,
                                replica=self.router.public_key,
                                peer=from_pk,
                                size=len(tc) if isinstance(
                                    tc, (bytes, bytearray)) else 0,
                            )
                    else:
                        hop = get_propagation().record_receipt(
                            ctx, recv_ts=t_done
                        )
                        path = ctx.path_json()
                # the tid rides the same untrusted frame as tc: a
                # non-numeric (or non-finite) origin stamp must
                # degrade to "no lag observed", never raise out of
                # the flush/poll loop
                if tracer.enabled and isinstance(tid, (list, tuple)) \
                        and len(tid) == 3 \
                        and isinstance(tid[2], (int, float)) \
                        and not isinstance(tid[2], bool) \
                        and math.isfinite(tid[2]):
                    t0 = float(tid[2])
                    lag = t_apply - t0
                    tracer.observe("replica.propagation_lag", lag)
                    tracer.gauge("replica.propagation_lag_s", lag)
                    clag = t_done - t0
                    tracer.observe("replica.convergence_lag", clag)
                    tracer.gauge("replica.convergence_lag_s", clag)
                if rec.enabled:
                    rec.record(
                        "update.recv", topic=self.topic,
                        replica=self.router.public_key, peer=from_pk,
                        size=len(u), digest=update_digest(u), tid=tid,
                        hop=hop, path=path,
                    )
        for u in updates:
            tracer.count("replica.updates_applied")
            tracer.count("replica.bytes_received", len(u))
        # one WAL batch per merge window (the flush_incoming contract),
        # not one append per update
        self._persist_many(updates)
        for _, m, from_pk in items:
            if m.get("meta") == "sync":
                self._set_synced(True)  # crdt.js:306
                if "state_vector" in m:
                    # second leg of the handshake: ship the syncer
                    # whatever we hold beyond its state vector. Sent
                    # unconditionally — an SV-dominance check would
                    # strand tombstone-only surplus, since delete sets
                    # live outside state vectors (diffs always carry
                    # the full delete set, like Yjs)
                    their_sv = self._decode_peer_sv(
                        m["state_vector"], from_pk
                    )
                    if their_sv is None:
                        continue
                    back = self.doc.encode_state_as_update(their_sv)
                    trace, path = self._trace_fields(
                        back, "sync_answer"
                    )
                    if rec.enabled:
                        rec.record(
                            "sync.answer", topic=self.topic,
                            replica=self.router.public_key,
                            peer=from_pk, size=len(back),
                            digest=update_digest(back),
                            tid=trace["tid"], path=path,
                        )
                    self._to_peer(from_pk, {"update": back, **trace})
                    # the syncer now holds everything we do (see the
                    # ready-branch advance)
                    self.peer_state_vectors[from_pk] = their_sv.merge(
                        self.doc.state_vector()
                    )

    # ------------------------------------------------------------------
    # convenience passthroughs to the document API
    # ------------------------------------------------------------------
    @property
    def c(self):
        return self.doc.c

    def __getattr__(self, prop: str) -> Any:
        doc = self.__dict__.get("doc")
        if doc is not None:
            try:
                return getattr(doc, prop)
            except AttributeError:
                pass
        raise AttributeError(prop)

    def send_message(self, payload: Any) -> None:
        """Broadcast a non-CRDT message to peers (observer passthrough)."""
        self._propagate({"message": payload, "public_key": self.router.public_key})


def ypear_crdt(router, **options) -> Replica:
    """Factory mirroring ``ypearCRDT(router, options)`` (crdt.js:166)."""
    topic = options.pop("topic", None)
    if not topic:
        raise ValueError("options.topic is required")
    return Replica(router, topic, **options)
