"""Multi-process router: the ypear router contract over native UDP.

The reference's router is Hyperswarm — DHT topic discovery plus
Noise-encrypted peer streams over udx (SURVEY.md §2.2). This router
implements the same contract surface the CRDT layer consumes
(``is_ypear_router``, ``options``, ``update_options[_cache]``,
``start``/``started``/``peers``, ``alow`` -> the four verbs,
crdt.js:172-317) over the native transport seam
(:mod:`crdt_tpu.net.transport`): reliable-datagram UDP + X25519 /
XChaCha20-Poly1305 encrypted peer links.

Peer discovery is rendezvous-based, the datacenter reduction of
Hyperswarm's DHT (consumed at crdt.js:315): a router constructed with
``bootstrap=[(ip, port), ...]`` dials those known nodes, and any
router running with ``rendezvous=True`` INTRODUCES peers that
announce a shared topic to each other — each side receives the
other's (public key, address) over the established encrypted link and
dials it, after which the ordinary hello/key-exchange/announce/sync
machinery takes over. A swarm therefore forms from one well-known
address, no static peer lists (``add_peer`` remains for fabrics where
peers ARE known addresses). Full DHT walking stays out of scope —
the rendezvous node is the trust anchor the reference's bootstrap DHT
nodes are; a wrong introduction is only a dial to a peer that cannot
complete the key exchange.

NAT traversal (cone NATs) falls out of the introduction mechanics by
construction — the same simultaneous-open recipe Hyperswarm's
holepuncher runs, minus its relay fallback:

- the rendezvous advertises each member's OBSERVED UDP source
  address (``_Peer.addr`` is the packet source, i.e. the NAT's
  public mapping, held open by the member's TTL'd announce refresh);
- one introduction is sent to BOTH sides (:meth:`UdpRouter._introduce`
  tells the newcomer about every holder AND every holder about the
  newcomer), so both ends dial out at once — each outbound hello
  opens its own NAT's mapping toward the other;
- hellos ride the reliable transport (40 ms initial RTO, exponential
  backoff — native/transport/transport.cc), so whichever side's
  first packet loses the race against the other NAT's mapping
  creation is retransmitted straight through once it exists.

Full-cone and (address-)restricted-cone NATs traverse by the intro
mechanics alone. Symmetric NATs (per-destination port mappings) get
the two remaining Hyperswarm capabilities:

- **port prediction**: an introduced dial that does not complete is
  retried on a jittered exponential backoff, and after a few rounds
  the retry sprays unreliable hellos at the advertised port ±
  ``predict_window`` — sequential-allocation symmetric NATs put the
  mapping toward us within a few ports of the mapping the rendezvous
  observed, so a predicted probe (or the peer's probe toward our
  predicted port) lands and the ordinary handshake completes. Probes
  ride :meth:`UdpEndpoint.send_unreliable` (no retransmit state, no
  ``failed`` accounting — most probes are EXPECTED to die).
- **peer relay**: past ``relay_after_s`` the dialer falls back to
  forwarding end-to-end encrypted frames through a mutually reachable
  peer (the introducer first, then other proven peers — deterministic
  election order, rotated on NAK or death). Relays enforce per-source
  byte budgets (token bucket); a saturated or dead relay answers with
  a NAK / goes silent and the sender re-elects or sheds to the sync
  protocol's own retry/anti-entropy cadence. A direct path proven
  LATER (a predicted probe finally landing) upgrades the peer in
  place and the relay leg is dropped.

The mechanism properties are pinned by tests/test_transport.py
(TestIntroductionPunch, TestSymmetricNatTraversal, TestRelayFallback)
over the simulated-NAT loopback fabric in :mod:`crdt_tpu.net.faults`.

Wire protocol (each transport message, after reassembly):
  kind 0x00  plaintext hello       {pk: hex, ack: bool}
  kind 0x01  encrypted envelope    sender_pk(32 raw) || SecureBox
             payload (AAD = sender pk), decrypting to one lib0 `any`:
             {t:'topics', topics:[...]} | {t:'m', topic, msg} |
             {t:'intro', peers:[{pk, ip, port}...]} (rendezvous)

Like the loopback fabric, nothing is delivered until ``poll()`` runs —
single-threaded, event-loop style (udx's own model).
"""

from __future__ import annotations

import logging
import socket
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from crdt_tpu.codec.lib0 import Decoder, Encoder
from crdt_tpu.net.transport import SecureBox, UdpEndpoint, keypair
from crdt_tpu.obs import propagation
from crdt_tpu.obs.recorder import get_recorder
from crdt_tpu.utils.backoff import jitter
from crdt_tpu.utils.trace import get_tracer

_HELLO = 0
_ENVELOPE = 1

_log = logging.getLogger(__name__)

# protocol-level ceiling for a peer's wire-declared announce TTL. The
# clamp must NOT derive from the receiver's local announce_ttl: a
# member legitimately configured with a longer refresh than the
# rendezvous node would be silently clamped below its own schedule and
# age out of introductions while still refreshing on time (advisor
# finding, round 3). One hour bounds how long a crashed peer can pin
# itself into introductions regardless of either side's local config.
_TTL_CAP = 3600.0


def _canon_addr(host: str, port: int) -> Tuple[str, int]:
    """Resolve a configured bootstrap entry to the canonical (ip, port)
    tuple that will appear as a UDP source address. Introducer trust
    compares observed sources against the bootstrap list; a hostname
    entry would never match its numeric source and trust would silently
    never be granted (advisor finding, round 3).

    Resolution is pinned to AF_INET because the native transport's
    sockets are IPv4-only (``native/transport/transport.cc`` binds
    ``AF_INET``) — an AAAA-only answer could never appear as a source
    address on that socket anyway. IPv6 bootstrap entries (literals or
    IPv6-only hostnames) are therefore unsupported; they fail loudly
    here instead of silently never matching (advisor finding, round 4).
    """
    try:
        infos = socket.getaddrinfo(
            host, port, socket.AF_INET, socket.SOCK_DGRAM
        )
        return (infos[0][4][0], int(port))
    except OSError:
        if ":" in host:
            _log.error(
                "bootstrap entry %s:%s looks like an IPv6 literal; the "
                "transport is IPv4-only — this entry can never grant "
                "introducer trust", host, port,
            )
        else:
            _log.warning(
                "bootstrap entry %s:%s did not resolve over IPv4 (the "
                "transport is IPv4-only); introducer trust will never "
                "match this entry until restart", host, port,
            )
        return (host, int(port))


def _pack_any(v: Any) -> bytes:
    enc = Encoder()
    enc.write_any(v)
    return enc.to_bytes()


def _unpack_any(data: bytes) -> Any:
    return Decoder(data).read_any()


class _Peer:
    __slots__ = ("pk_hex", "addr", "topics", "topics_v", "inst", "box",
                 "last_seen", "announce_ttl", "direct", "relay",
                 "relay_idx", "relay_paused_until", "introducer",
                 "predicted")

    def __init__(self, pk_hex: str, addr: Tuple[str, int], inst: str,
                 box: SecureBox, *, direct: bool = True):
        self.pk_hex = pk_hex
        self.addr = addr
        self.topics: Set[str] = set()
        self.topics_v = -1  # last applied announcement version
        self.inst = inst  # incarnation token: resets topics_v on restart
        self.box = box
        self.last_seen = time.monotonic()  # last AUTHENTICATED traffic
        self.announce_ttl = 0.0  # the peer's own wire-declared TTL
        # `direct`: addr is a real datagram source / a proven rebind —
        # usable for direct sends. False for peers seeded from an intro
        # hint or met through a relay: their addr is at best a guess,
        # and traffic routes via `relay` until a probe proves a path
        self.direct = direct
        self.relay: Optional[str] = None  # forwarding peer's pk
        self.relay_idx = 0  # election cursor (rotated on NAK/death)
        self.relay_paused_until = 0.0  # budget-shed cooldown
        self.introducer: Optional[str] = None  # who told us about them
        # the proven direct path landed on a PREDICTED port (not the
        # advertised one): topic frames toward this peer retag their
        # newest trace-context path record `predicted` (obs seam)
        self.predicted = False

    def new_incarnation(self, inst: str) -> None:
        """A restarted process announces from version 1 again; carrying
        the dead incarnation's version watermark would reject every
        announcement of the new one."""
        self.inst = inst
        self.topics_v = -1
        self.topics = set()
        # route attribution resets with the incarnation: the new
        # process proved whatever path it proved, not the old one's
        # predicted mapping
        self.predicted = False


class _Dial:
    """One in-progress introduction dial: retried on a jittered
    exponential backoff, escalating through port prediction to the
    relay fallback, until the peer proves a direct path or the dial
    expires (bounded — a gone-forever peer must not probe forever)."""

    __slots__ = ("pk_hex", "addr", "introducer", "created", "attempts",
                 "interval", "next_due", "give_up_at", "relay_on")

    def __init__(self, pk_hex: str, addr: Tuple[str, int],
                 introducer: Optional[str], *, base_s: float,
                 give_up_s: float):
        self.pk_hex = pk_hex
        self.addr = addr
        self.introducer = introducer
        now = time.monotonic()
        self.created = now
        self.attempts = 0
        self.interval = base_s
        self.next_due = now + base_s
        self.give_up_at = now + give_up_s
        self.relay_on = False


class UdpRouter:
    """One peer's router over a real socket (multi-process capable)."""

    is_ypear_router = True  # crdt.js:172's validation flag

    def __init__(
        self,
        *,
        bind_ip: str = "127.0.0.1",
        port: int = 0,
        seed: Optional[bytes] = None,
        username: Optional[str] = None,
        rendezvous: bool = False,
        bootstrap: Optional[List[Tuple[str, int]]] = None,
        announce_ttl: float = 60.0,
        dial_retry_s: float = 0.5,
        dial_retry_max_s: float = 8.0,
        dial_give_up_s: float = 60.0,
        port_prediction: bool = True,
        predict_after: int = 2,
        predict_window: int = 8,
        relay_after_s: float = 3.0,
        relay_stale_s: float = 30.0,
        relay_budget_bytes: int = 256 * 1024,
        relay_refill_bps: int = 64 * 1024,
        relay_shed_pause_s: float = 1.0,
    ):
        self.endpoint = UdpEndpoint(bind_ip, port)
        pub, sec = keypair(seed)
        self._secret = sec
        pk_hex = pub.hex()
        self.options: Dict[str, Any] = {
            "public_key": pk_hex,
            "username": username or pk_hex[:8],
            "cache": {},
        }
        self.started = False
        self._handlers: Dict[str, Callable] = {}
        self._peers: Dict[str, _Peer] = {}  # pk_hex -> peer
        # announcement version: bumped when OUR topic set changes, so a
        # delayed retransmit of an older announcement can never regress
        # a peer's view of our topics (transport is reliable but not
        # ordered across messages)
        self._topics_v = 0
        # per-process incarnation token, carried in hellos: lets peers
        # distinguish a restart (reset announcement watermark) from a
        # delayed retransmit of an old announcement
        import os as _os

        self._inst = _os.urandom(8).hex()
        # liveness challenges: pk_hex -> (nonce, challenged addr). A
        # hello claiming a known identity from a NEW address — or any
        # hint that the peer's incarnation changed — must prove key
        # possession NOW (decrypt the ping, echo the nonce FROM THAT
        # ADDRESS) before we reroute traffic or reset announcement
        # watermarks. The pong carries the responder's CURRENT inst,
        # and that fresh-nonce-bound value is the only way peer.inst
        # ever changes: trusting the plaintext hello's inst would let
        # a replayed old hello wedge topic membership permanently
        # (set peer.inst to a dead token that no genuine announcement
        # matches)
        self._rebind_nonce: Dict[str, Tuple[str, Tuple[str, int]]] = {}
        # rendezvous discovery (Hyperswarm reduction; module docstring).
        # Announcements carry the announcer's liveness TTL on the wire:
        # bootstrap-joined members refresh their announcement to their
        # RENDEZVOUS peers every ttl/3, and a rendezvous node only
        # introduces holders heard from within each holder's OWN
        # declared TTL — a crashed member ages out instead of being
        # handed to every future joiner as a dead address to dial
        # (reliable-transport retries against it would count as hard
        # failures), and asymmetric TTL configuration cannot silently
        # drop a live member. Introductions are honored only from
        # peers reached at a configured bootstrap address — the stated
        # trust anchor — never from arbitrary swarm members.
        self._rendezvous = rendezvous
        # bootstrap entries are (host, port) with IPv4-only resolution:
        # the native transport's sockets are AF_INET (_canon_addr).
        self._bootstrap = list(bootstrap or [])
        # canonical (ip, port) forms of the bootstrap entries — the set
        # observed UDP sources are compared against for introducer
        # trust. Resolved eagerly; start() re-resolves in case DNS
        # changed between construction and start.
        self._bootstrap_canon: Set[Tuple[str, int]] = {
            _canon_addr(h, p) for h, p in self._bootstrap
        }
        self._announce_ttl = announce_ttl
        self._last_announce = 0.0
        # NAT traversal / partition tolerance (module docstring):
        # dial retry schedule, port prediction, relay fallback
        self._dial_retry_s = dial_retry_s
        self._dial_retry_max_s = dial_retry_max_s
        self._dial_give_up_s = dial_give_up_s
        self._port_prediction = port_prediction
        self._predict_after = predict_after
        self._predict_window = predict_window
        self._relay_after_s = relay_after_s
        self._relay_stale_s = relay_stale_s
        self._relay_budget_bytes = relay_budget_bytes
        self._relay_refill_bps = relay_refill_bps
        self._relay_shed_pause_s = relay_shed_pause_s
        self._dials: Dict[str, _Dial] = {}  # pk_hex -> in-progress dial
        # token buckets for frames WE forward, keyed by source pk
        self._relay_budget: Dict[str, Tuple[float, float]] = {}
        self._last_ping: Dict[str, float] = {}  # keepalive rate limit
        # discovery diagnostics: a wedged swarm (intros never applied,
        # claimants never proving) must be visible, not silent
        self.stats: Dict[str, int] = {
            "intros_applied": 0,
            "intros_buffered": 0,
            "intros_dropped": 0,
            "intros_refused": 0,
            "dial_retries": 0,
            "dials_expired": 0,
            "predict_probes": 0,
            "relay_sends": 0,
            "relay_frames_forwarded": 0,
            "relay_bytes_forwarded": 0,
            "relay_naks": 0,
            "relay_sheds": 0,
            "relay_elections": 0,
            "relay_upgrades": 0,
            "relay_unroutable": 0,
        }
        # introducer trust is granted ONLY by proven key possession at
        # a configured bootstrap address (nonce challenge/pong, the
        # same machinery that guards address rebinds) — a plaintext
        # hello with a spoofed bootstrap source must not mint trust.
        # Intros arriving before the proof completes buffer here and
        # replay on grant (bounded: latest per claimant, few claimants)
        self._rendezvous_pks: Set[str] = set()
        self._pending_intros: Dict[str, Any] = {}

    # -- options bag (crdt.js:175-180) ----------------------------------
    def update_options(self, opts: Dict[str, Any]) -> None:
        self.options.update(opts)

    def update_options_cache(self, per_topic: Dict[str, dict]) -> None:
        for topic, contract in per_topic.items():
            self.options["cache"].setdefault(topic, {}).update(contract)

    # -- lifecycle -------------------------------------------------------
    def start(self, network_name: Optional[str] = None) -> None:
        self.options.setdefault("network_name", network_name)
        self.started = True
        # EVERY configured bootstrap is dialed (not rotated through):
        # a dead rendezvous node then costs only its own unanswered
        # hello, and any live one introduces — the failover the
        # reference gets from Hyperswarm's multi-node DHT bootstrap
        self._bootstrap_canon = {
            _canon_addr(h, p) for h, p in self._bootstrap
        }
        # dial the RESOLVED addresses: the native transport sends to
        # numeric IPs only (a hostname entry would raise at the
        # socket). Per-entry failures are logged and skipped — one
        # unresolved/dead entry must not abort dialing the rest, or
        # multi-bootstrap failover is lost
        for ip, port in sorted(self._bootstrap_canon):
            try:
                self.add_peer(ip, port)
            except OSError as exc:
                _log.warning(
                    "bootstrap %s:%s not dialable (%s); trying others",
                    ip, port, exc,
                )

    def close(self) -> None:
        self.endpoint.close()

    @property
    def public_key(self) -> str:
        return self.options["public_key"]

    @property
    def addr(self) -> Tuple[str, int]:
        return (self.endpoint.bind_ip, self.endpoint.port)

    # -- discovery (bootstrap list; the DHT-walk divergence) -------------
    def add_peer(self, ip: str, port: int) -> None:
        """Dial a known address: plaintext hello carrying our identity;
        the reply completes the key exchange."""
        self._send_hello(ip, port, ack=False)

    def _send_hello(self, ip: str, port: int, *, ack: bool,
                    unreliable: bool = False) -> None:
        payload = bytes([_HELLO]) + _pack_any(
            {"pk": self.public_key, "ack": ack, "inst": self._inst}
        )
        if unreliable:
            # dial retries and prediction probes: most are EXPECTED to
            # die at a closed NAT mapping — no retransmit state, no
            # `failed` accounting; the dial schedule is the retry layer
            send = getattr(self.endpoint, "send_unreliable", None)
            if send is not None:
                send(ip, port, payload)
                return
        self.endpoint.send(ip, port, payload)

    # -- peer/topic views ------------------------------------------------
    @property
    def peers(self) -> List[str]:
        return list(self._peers)

    def peers_on(self, topic: str) -> List[str]:
        return [pk for pk, p in self._peers.items() if topic in p.topics]

    # -- the four verbs (crdt.js:315-317) --------------------------------
    def alow(self, topic: str, handler: Callable) -> Tuple[
        Callable, Callable, Callable, Callable
    ]:
        self._handlers[topic] = handler
        self._topics_v += 1
        self._announce_topics()

        def send_msg(p: _Peer, msg: dict) -> None:
            # transport-route attribution (obs/propagation): a frame
            # whose newest path record says `direct` but which will
            # ride a relay or a prediction-proven mapping retags that
            # record BEFORE sealing — per peer, since the route is
            # per peer. Failures leave the context unchanged;
            # attribution never breaks delivery.
            tc = msg.get("tc")
            if isinstance(tc, (bytes, bytearray)):
                if not p.direct:
                    tc2 = propagation.retag_last_hop(
                        bytes(tc), "relayed"
                    )
                elif p.predicted:
                    tc2 = propagation.retag_last_hop(
                        bytes(tc), "predicted"
                    )
                else:
                    tc2 = tc
                if tc2 is not tc:
                    msg = dict(msg, tc=tc2)
            self._send_envelope(p, {"t": "m", "topic": topic, "msg": msg})

        def propagate(msg: dict) -> None:
            for p in list(self._peers.values()):
                if topic in p.topics:
                    send_msg(p, msg)

        broadcast = propagate  # the reference uses them interchangeably

        def for_peers(fn: Callable[[str], None]) -> None:
            for pk in self.peers_on(topic):
                fn(pk)

        def to_peer(public_key: str, msg: dict) -> None:
            p = self._peers.get(public_key)
            if p is not None and topic in p.topics:
                send_msg(p, msg)

        return propagate, broadcast, for_peers, to_peer

    def unsubscribe(self, topic: str) -> None:
        self._handlers.pop(topic, None)
        self._topics_v += 1
        self._announce_topics()

    # -- wire ------------------------------------------------------------
    def _send_envelope(
        self, peer: _Peer, payload: Any,
        addr: Optional[Tuple[str, int]] = None,
    ) -> None:
        me = bytes.fromhex(self.public_key)
        body = peer.box.encrypt(_pack_any(payload), aad=me)
        if addr is None and not peer.direct:
            # no proven direct path: forward the sealed frame through
            # the elected relay (addr=None in _rebind_nonce marks the
            # relay-routed challenges this peer may owe us)
            self._send_via_relay(peer, me + body)
            return
        ip, port = addr if addr is not None else peer.addr
        self.endpoint.send(ip, port, bytes([_ENVELOPE]) + me + body)

    # -- peer relay (module docstring: the Hyperswarm relay reduction) ---
    def _relay_candidates(self, peer: _Peer) -> List[str]:
        """Deterministic election order: the introducer (connected to
        both sides at introduction time by construction), then proven
        rendezvous peers, then every other direct peer."""
        order: List[str] = []
        seen: Set[str] = set()
        cands: List[str] = []
        if peer.introducer:
            cands.append(peer.introducer)
        cands += sorted(self._rendezvous_pks)
        cands += sorted(self._peers)
        for pk in cands:
            if pk in seen or pk == peer.pk_hex or pk == self.public_key:
                continue
            seen.add(pk)
            p = self._peers.get(pk)
            if p is not None and p.direct:
                order.append(pk)
        return order

    def _relay_for(self, peer: _Peer) -> Optional[_Peer]:
        """Resolve (electing / re-electing as needed) the relay to
        route `peer`'s traffic through. A silent candidate is pinged
        (rate-limited) and skipped while any fresh one exists — a dead
        relay therefore triggers re-election, never a wedge."""
        order = self._relay_candidates(peer)
        if not order:
            return None
        now = time.monotonic()
        fresh = []
        for pk in order:
            p = self._peers[pk]
            if now - p.last_seen <= self._relay_stale_s:
                fresh.append(pk)
            else:
                # nudge: an alive-but-quiet relay pongs, refreshes
                # last_seen, and rejoins the fresh pool
                last = self._last_ping.get(pk, 0.0)
                if now - last > max(self._relay_stale_s / 4, 0.05):
                    self._last_ping[pk] = now
                    self._challenge_liveness(p, p.addr)
        pool = fresh or order
        pk = pool[peer.relay_idx % len(pool)]
        if pk != peer.relay:
            peer.relay = pk
            self.stats["relay_elections"] += 1
            get_tracer().count("router.relay_elections")
        return self._peers[pk]

    def _send_via_relay(self, peer: _Peer, frame: bytes) -> None:
        now = time.monotonic()
        if peer.relay_paused_until > now:
            # relay shed our traffic (budget): drop — the sync layer's
            # retry/anti-entropy cadence recovers the payload later
            self.stats["relay_sheds"] += 1
            get_tracer().count("router.relay_sheds")
            return
        relay = self._relay_for(peer)
        if relay is None:
            self.stats["relay_unroutable"] += 1
            return
        self.stats["relay_sends"] += 1
        tracer = get_tracer()
        tracer.count("router.relay_sends")
        tracer.count("router.relay_send_bytes", len(frame))
        rec = get_recorder()
        if rec.enabled:
            rec.record(
                "relay.send", replica=self.public_key,
                peer=peer.pk_hex, via=relay.pk_hex, size=len(frame),
            )
        self._send_envelope(
            relay, {"t": "relay", "dst": peer.pk_hex, "f": frame}
        )

    def _relay_allow(self, src_pk: str, nbytes: int) -> bool:
        """Token bucket per forwarded-for source: a chatty pair cannot
        monopolize this node's forwarding capacity."""
        now = time.monotonic()
        tokens, last = self._relay_budget.get(
            src_pk, (float(self._relay_budget_bytes), now)
        )
        tokens = min(
            float(self._relay_budget_bytes),
            tokens + (now - last) * self._relay_refill_bps,
        )
        if nbytes > tokens:
            self._relay_budget[src_pk] = (tokens, now)
            return False
        self._relay_budget[src_pk] = (tokens - nbytes, now)
        return True

    def _announce_topics(
        self,
        peer: Optional[_Peer] = None,
        targets: Optional[List[_Peer]] = None,
    ) -> None:
        msg = {
            "t": "topics",
            "v": self._topics_v,
            # incarnation-bound: the static per-pair SecureBox key means
            # a captured announcement from a previous process life would
            # otherwise replay cleanly; a high replayed `v` would set the
            # watermark above the new incarnation's counter and wedge
            # topic membership until v caught up
            "inst": self._inst,
            # our liveness TTL, on the wire: a rendezvous node ages our
            # entry by THIS value, not its local config
            "ttl": self._announce_ttl,
            "topics": sorted(self._handlers),
        }
        if targets is None:
            targets = [peer] if peer is not None else list(self._peers.values())
        for p in targets:
            self._send_envelope(p, msg)
        if peer is None:
            self._last_announce = time.monotonic()

    def _register_peer(
        self, pk_hex: str, addr: Tuple[str, int], inst: str,
        *, direct: bool = True,
    ) -> Optional[_Peer]:
        """Create a peer entry for a previously unknown identity.
        Returns None for keys no secure channel can be built with."""
        try:
            box = SecureBox(self._secret, bytes.fromhex(pk_hex))
        except ValueError:
            return None  # low-order key
        p = _Peer(pk_hex, addr, inst, box, direct=direct)
        self._peers[pk_hex] = p
        return p

    def _challenge_liveness(
        self, peer: _Peer, addr: Optional[Tuple[str, int]]
    ) -> None:
        """A hello is unauthenticated: before rerouting a KNOWN peer's
        traffic to a new address, or believing its incarnation
        changed, ping that address under the peer's key — only the
        real key holder can echo the nonce back, and only from the
        challenged address (the pong's source is checked, so a copied
        pong from elsewhere proves nothing). The pong also reports the
        responder's live inst.

        ``addr=None`` challenges over the RELAY path instead: there is
        no address claim to verify, but the fresh nonce still proves
        the far end holds the key NOW (relayed frames are end-to-end
        sealed), which is what inst adoption needs."""
        import os as _os

        nonce = _os.urandom(16).hex()
        self._rebind_nonce[peer.pk_hex] = (nonce, addr)
        self._send_envelope(peer, {"t": "ping", "n": nonce}, addr=addr)

    def poll(self) -> int:
        """One pump: transport poll + dispatch every complete message.
        Returns the number of router-level messages handled."""
        # announcement refresh (TTL liveness; see __init__): members
        # that joined through a bootstrap keep their topic announcement
        # warm at the RENDEZVOUS peers (so introductions never hand
        # out aged entries) and — since round 19 — at RELAY-ROUTED
        # peers too: a relay-met peer is exactly one whose announce
        # had no reliable path (the one-shot announce rides the relay
        # chain, where an app-level loss is never retransmitted), so
        # a dropped announce must be a delay, not a permanently
        # invisible topic. Refreshing the whole swarm would be O(N^2)
        # steady-state traffic nobody consumes; these two classes are
        # the ones with no other repair path.
        if (
            self._handlers
            and time.monotonic() - self._last_announce
            > self._announce_ttl / 3
        ):
            refresh_targets = [
                p for pk, p in self._peers.items()
                if pk in self._rendezvous_pks or not p.direct
            ]
            if refresh_targets:
                # peer=None path: _announce_topics stamps
                # _last_announce
                self._announce_topics(targets=refresh_targets)
            else:
                # nothing to repair until membership changes (joins
                # announce directly): stamp anyway, or every later
                # poll pays the peer scan with an expired deadline
                self._last_announce = time.monotonic()
        self._service_dials()
        self.endpoint.poll()
        handled = 0
        for src_ip, src_port, data in self.endpoint.recv_all():
            if not data:
                continue
            kind, body = data[0], data[1:]
            if kind == _HELLO:
                self._on_hello(body, (src_ip, src_port))
                handled += 1
            elif kind == _ENVELOPE and len(body) > 32:
                if self._on_envelope(body, (src_ip, src_port)):
                    handled += 1
        # end of poll round: replicas buffering inbound updates
        # (batch_incoming) merge this round's worth in one txn, then
        # get a timer tick (probe retry/backoff, periodic anti-entropy)
        for contract in list(self.options["cache"].values()):
            flush = contract.get("flush")
            if flush is not None:
                flush()
        for contract in list(self.options["cache"].values()):
            tick = contract.get("tick")
            if tick is not None:
                tick()
        return handled

    def _service_dials(self) -> None:
        """Drive every in-progress introduction dial: retry hellos on
        a jittered exponential backoff, escalate to port-prediction
        probes, fall back to a relay, expire bounded."""
        if not self._dials:
            return
        now = time.monotonic()
        tracer = get_tracer()
        for pk, d in list(self._dials.items()):
            peer = self._peers.get(pk)
            if peer is not None and peer.direct:
                del self._dials[pk]  # proven direct path: dial done
                continue
            if now >= d.give_up_at:
                # bounded: stop probing a peer that never answered.
                # An established relay route (peer entry) stays.
                del self._dials[pk]
                self.stats["dials_expired"] += 1
                continue
            if now >= d.next_due:
                d.attempts += 1
                self.stats["dial_retries"] += 1
                tracer.count("router.dial_retries")
                rec = get_recorder()
                if rec.enabled:
                    rec.record(
                        "dial.retry", replica=self.public_key, peer=pk,
                        attempt=d.attempts,
                    )
                ip, port = d.addr
                self._send_hello(ip, port, ack=False, unreliable=True)
                if self._port_prediction and d.attempts >= self._predict_after:
                    # sequential-allocation NATs put the real mapping
                    # near the observed one: spray the neighborhood
                    sent = 0
                    for delta in range(1, self._predict_window + 1):
                        for p in (port + delta, port - delta):
                            if 0 < p < 65536:
                                self._send_hello(
                                    ip, p, ack=False, unreliable=True
                                )
                                sent += 1
                    self.stats["predict_probes"] += sent
                    tracer.count("router.predict_probes", sent)
                d.interval = min(d.interval * 2, self._dial_retry_max_s)
                d.next_due = now + d.interval * jitter()
            if not d.relay_on and now - d.created >= self._relay_after_s:
                if self._activate_relay(d):
                    d.relay_on = True

    def _activate_relay(self, d: _Dial) -> bool:
        """Relay fallback for a dial that direct probing has not
        completed: register the peer (we hold its pk from the intro)
        routed via an elected relay and open the handshake by
        announcing our topics through it."""
        peer = self._peers.get(d.pk_hex)
        if peer is None:
            peer = self._register_peer(d.pk_hex, d.addr, "", direct=False)
            if peer is None:
                return True  # unusable key: stop trying
        if peer.introducer is None:
            peer.introducer = d.introducer
        if self._relay_for(peer) is None:
            return False  # no candidate yet; retry next pass
        get_tracer().count("router.relay_activations")
        self._announce_topics(peer)
        return True

    def _on_hello(self, body: bytes, addr: Tuple[str, int]) -> None:
        try:
            info = _unpack_any(body)
            # normalize case so the envelope lookup (raw.hex(), always
            # lowercase) can never miss a peer registered from a hello
            pk_hex = info["pk"].lower()
            if len(bytes.fromhex(pk_hex)) != 32:
                return  # an X25519 public key is exactly 32 bytes
        except (ValueError, KeyError, TypeError, AttributeError):
            return
        if pk_hex == self.public_key:
            return
        inst = info.get("inst", "")
        peer = self._peers.get(pk_hex)
        if peer is None:
            peer = self._register_peer(pk_hex, addr, inst)
            if peer is None:
                return  # rejected key
        # every continuing path answers a non-ack hello: a restarted
        # peer must be able to learn us, or the encrypted challenges
        # below could never be decrypted
        if not info.get("ack"):
            self._send_hello(addr[0], addr[1], ack=True)
        if peer.addr != addr or not peer.direct:
            # identity known but source moved — or known only through
            # a relay / an intro hint (no proven direct path at all):
            # don't reroute (or upgrade) until this address proves key
            # possession
            self._challenge_liveness(peer, addr)
            return
        if inst != peer.inst:
            # same address, different claimed incarnation: do NOT
            # adopt it from an unauthenticated hello (a replayed old
            # hello would set a dead inst that no genuine announcement
            # matches, wedging topic membership). Challenge instead;
            # the pong reports the live inst
            self._challenge_liveness(peer, peer.addr)
            return
        # introducer trust needs PROOF, not a claimed hello source: a
        # peer presenting from a bootstrap address is challenged there;
        # only the pong (fresh nonce, decrypted under its key, FROM
        # that address) grants it (see the pong branch)
        if (
            addr in self._bootstrap_canon
            and pk_hex not in self._rendezvous_pks
        ):
            self._challenge_liveness(peer, addr)
        # key exchange is done on both ends; tell THIS peer our topics
        # (announcing to everyone here would be O(N^2) per join wave)
        self._announce_topics(peer)

    def _on_envelope(self, body: bytes, addr: Tuple[str, int]) -> bool:
        sender_raw, sealed = body[:32], body[32:]
        pk_hex = sender_raw.hex()
        peer = self._peers.get(pk_hex)
        if peer is None:
            # envelope from an unknown peer (e.g. we restarted): redo
            # the handshake; the CRDT layer's anti-entropy recovers
            # whatever this message carried
            self._send_hello(addr[0], addr[1], ack=False)
            return False
        try:
            payload = _unpack_any(peer.box.decrypt(sealed, aad=sender_raw))
        except ValueError:
            get_tracer().count("router.envelopes_rejected")
            rec = get_recorder()
            if rec.enabled:
                rec.record(
                    "envelope.reject", replica=self.public_key,
                    peer=pk_hex, size=len(sealed),
                )
            return False  # forged or corrupted
        peer.last_seen = time.monotonic()
        return self._dispatch(peer, payload, addr, via=None)

    def _on_relayed_frame(self, frame: bytes, via: str,
                          relay_hop: Optional[tuple] = None) -> bool:
        """A frame forwarded to us by a relay: `frame` is the same
        sealed wire body a direct envelope carries (sender pk || box).
        The relay authenticated nothing about the CONTENT — end-to-end
        AEAD under the sender's static key does. An unknown sender
        reached this way is registered route-via-relay (its address is
        unknown by definition) and greeted with our topic set, which
        is the relayed half of the hello handshake. ``relay_hop`` is
        the relay's (id, monotonic ts) leg attestation from the
        wrapper, merged into topic messages' trace contexts."""
        sender_raw, sealed = frame[:32], frame[32:]
        pk_hex = sender_raw.hex()
        if pk_hex == self.public_key:
            return False
        peer = self._peers.get(pk_hex)
        announce_back = False
        if peer is None:
            peer = self._register_peer(
                pk_hex, ("0.0.0.0", 0), "", direct=False
            )
            if peer is None:
                return False
            peer.relay = via
            peer.introducer = via
            announce_back = True
        try:
            payload = _unpack_any(peer.box.decrypt(sealed, aad=sender_raw))
        except ValueError:
            return False
        peer.last_seen = time.monotonic()
        if announce_back:
            self._announce_topics(peer)
        return self._dispatch(peer, payload, None, via=via,
                              relay_hop=relay_hop)

    @staticmethod
    def _merge_relay_hop(msg: dict, relay_hop: tuple) -> dict:
        """The receiver-side half of the forward-seam hop
        incrementer: fold the relay's attested leg into the message's
        trace fields — the legacy ``hop`` count increments, and the
        trace context (the relay cannot edit it; the frame is sealed
        end-to-end) gains the relay's path record, delta-stamped from
        the relay's forward time. Every failure shape — no trace
        fields, malformed context, hostile attestation types, hop
        bound reached — leaves the message unchanged."""
        import math

        hid, hts = relay_hop
        # finite-only: NaN fails the self-compare, and +/-inf would
        # overflow the microsecond conversion downstream — either
        # way a hostile attestation must degrade to "unattributed",
        # never raise out of the poll loop
        if not isinstance(hid, str) or not isinstance(
            hts, (int, float)
        ) or isinstance(hts, bool) or not math.isfinite(hts):
            return msg
        out = dict(msg)
        if isinstance(out.get("hop"), int):
            out["hop"] = out["hop"] + 1
        tc = out.get("tc")
        if isinstance(tc, (bytes, bytearray)):
            out["tc"] = propagation.append_hop_wire(
                bytes(tc), hid, "relayed", hop_ts=float(hts)
            )
        return out

    def _dispatch(
        self, peer: _Peer, payload: Any,
        addr: Optional[Tuple[str, int]], via: Optional[str],
        relay_hop: Optional[tuple] = None,
    ) -> bool:
        pk_hex = peer.pk_hex
        t = payload.get("t") if isinstance(payload, dict) else None
        if t == "topics":
            if payload.get("inst") != peer.inst:
                if peer.inst == "" and via is not None and isinstance(
                    payload.get("inst"), str
                ):
                    # relay-met peer announcing for the first time: no
                    # recorded incarnation to protect yet — adopt. (A
                    # replayed DEAD-incarnation first announce heals
                    # through the relay-routed nonce challenge the
                    # genuine announce then triggers below.)
                    peer.inst = payload["inst"]
                else:
                    # replayed from a dead incarnation — or our
                    # recorded inst is the stale one (bootstrap raced
                    # a restart, or a spoofed hello poisoned it).
                    # Never adopt an inst from a replayable envelope;
                    # challenge instead: the fresh-nonce pong reports
                    # the live inst, after which the peer's
                    # re-announce applies. Self-healing either way,
                    # wedge-proof both ways. Challenged at the
                    # envelope's source (peer.addr may be a dead pre-
                    # restart socket; the pong's source-binding keeps
                    # a spoofed source harmless) — or, for a
                    # relay-met peer (addr=None), over the relay: no
                    # address claim to verify, but the nonce still
                    # proves key possession NOW.
                    self._challenge_liveness(peer, addr)
                    return True
            v = payload.get("v", 0)
            if v < peer.topics_v:
                return True  # stale retransmit must not regress the set
            peer.topics_v = v
            try:
                ttl = float(payload.get("ttl", 0.0))
            except (TypeError, ValueError):
                ttl = 0.0
            # clamp the declared TTL: an unbounded (or inf) value would
            # pin a crashed peer in introductions forever, and a
            # negative/NaN one would silently exclude a live member
            # (NaN fails every comparison, so it clamps to 0 -> the
            # local default applies). The cap is the PROTOCOL constant
            # _TTL_CAP, not a multiple of the receiver's local refresh
            # default — asymmetric configs stay live (advisor, round 3)
            peer.announce_ttl = ttl if 0.0 < ttl <= _TTL_CAP else (
                _TTL_CAP if ttl > _TTL_CAP else 0.0
            )
            before = set(peer.topics)
            peer.topics = set(payload.get("topics", ()))
            new_topics = peer.topics - before
            for topic in new_topics:
                if topic in self._handlers:
                    self._on_peer_joined_topic(topic, pk_hex)
            if self._rendezvous and new_topics:
                self._introduce(peer, new_topics)
        elif t == "m":
            handler = self._handlers.get(payload.get("topic"))
            if handler is not None:
                msg = payload.get("msg")
                if relay_hop is not None and isinstance(msg, dict):
                    msg = self._merge_relay_hop(msg, relay_hop)
                handler(msg, pk_hex)
        elif t == "intro":
            # rendezvous introduction — honored ONLY from peers whose
            # key possession was nonce-proven at a configured bootstrap
            # address (the trust anchor): an ordinary swarm member —
            # or an attacker spoofing a bootstrap source on a
            # plaintext hello — must not be able to direct us to
            # spray dials at arbitrary third-party addresses. An intro
            # racing its sender's proof buffers (latest per claimant,
            # claimants bounded by the bootstrap list) and replays on
            # grant.
            if pk_hex not in self._rendezvous_pks:
                if peer.addr in self._bootstrap_canon:
                    if len(self._pending_intros) < 8:
                        if pk_hex not in self._pending_intros:
                            self.stats["intros_buffered"] += 1
                        self._pending_intros[pk_hex] = payload
                    else:
                        self.stats["intros_dropped"] += 1
                        _log.warning(
                            "intro from unproven claimant %s dropped: "
                            "pending-intro buffer full (%d claimants "
                            "awaiting liveness proof)",
                            pk_hex[:8], len(self._pending_intros),
                        )
                else:
                    self.stats["intros_refused"] += 1
                    _log.debug(
                        "intro from %s at %s refused: not a configured "
                        "bootstrap address %s",
                        pk_hex[:8], peer.addr,
                        sorted(self._bootstrap_canon),
                    )
                return True
            self.stats["intros_applied"] += 1
            self._apply_intro(payload, introducer=pk_hex)
        elif t == "relay" and via is None:
            # forward a sealed frame between two peers that cannot
            # reach each other. Accepted on DIRECT links only (no
            # multi-hop chains, no forwarding loops), forwarded only
            # to DIRECT peers, and metered per source (token bucket) —
            # a saturated pair is NAK'd and sheds to its own
            # anti-entropy cadence rather than starving the relay.
            dst_pk = payload.get("dst")
            frame = payload.get("f")
            if not isinstance(dst_pk, str) or not isinstance(
                frame, (bytes, bytearray)
            ) or len(frame) <= 32:
                return True
            dstp = self._peers.get(dst_pk)
            if dstp is None or not dstp.direct:
                self.stats["relay_naks"] += 1
                self._send_envelope(
                    peer, {"t": "relay_nak", "dst": dst_pk, "why": "unknown"}
                )
            elif not self._relay_allow(pk_hex, len(frame)):
                self.stats["relay_sheds"] += 1
                get_tracer().count("router.relay_sheds")
                self._send_envelope(
                    peer, {"t": "relay_nak", "dst": dst_pk, "why": "budget"}
                )
            else:
                self.stats["relay_frames_forwarded"] += 1
                self.stats["relay_bytes_forwarded"] += len(frame)
                tracer = get_tracer()
                tracer.count("router.relay_frames_forwarded")
                tracer.count("router.relay_bytes_forwarded", len(frame))
                rec = get_recorder()
                if rec.enabled:
                    rec.record(
                        "relay.forward", replica=self.public_key,
                        peer=dst_pk, src=pk_hex, size=len(frame),
                    )
                # the forward-seam half of the hop incrementer: the
                # inner frame is sealed end-to-end (this relay cannot
                # edit it), so the relay ATTESTS its leg in the
                # wrapper — its identity + monotonic forward stamp —
                # and the receiving router merges that into the
                # decoded trace context (see _merge_relay_hop)
                self._send_envelope(
                    dstp,
                    {"t": "relayed", "src": pk_hex, "f": bytes(frame),
                     "hid": self.public_key[:8],
                     "hts": time.monotonic()},
                )
        elif t == "relayed" and via is None:
            frame = payload.get("f")
            if isinstance(frame, (bytes, bytearray)) and len(frame) > 32:
                self._on_relayed_frame(
                    bytes(frame), via=pk_hex,
                    relay_hop=(payload.get("hid"), payload.get("hts")),
                )
        elif t == "relay_nak":
            dst_pk = payload.get("dst")
            dstp = self._peers.get(dst_pk) if isinstance(dst_pk, str) else None
            if dstp is not None and dstp.relay == pk_hex:
                if payload.get("why") == "budget":
                    # saturation: pause relayed traffic toward this
                    # peer; the sync layer's retry/anti-entropy picks
                    # the payload back up after the pause
                    dstp.relay_paused_until = (
                        time.monotonic() + self._relay_shed_pause_s
                    )
                else:
                    # this relay cannot see the peer: rotate the
                    # election cursor; the next send re-elects
                    dstp.relay_idx += 1
                    dstp.relay = None
        elif t == "ping":
            # liveness challenge: echo the nonce (proving this address
            # — or, relay-routed, this KEY — holds the secret NOW; the
            # nonce is fresh) and report our current incarnation, the
            # only trusted source for it
            self._send_envelope(
                peer,
                {"t": "pong", "n": payload.get("n"), "inst": self._inst},
                addr=addr,
            )
        elif t == "pong":
            pending = self._rebind_nonce.get(pk_hex)
            if pending is None or payload.get("n") != pending[0]:
                return True
            if pending[1] is None:
                # relay-routed challenge: no address was claimed, so
                # none is proven — adopt only the (fresh-nonce-bound)
                # incarnation; routing is untouched
                del self._rebind_nonce[pk_hex]
                live_inst = payload.get("inst", peer.inst)
                if live_inst != peer.inst:
                    peer.new_incarnation(live_inst)
                self._announce_topics(peer)
                return True
            if addr != pending[1]:  # nonce is bound to the challenged
                # address: a pong copied and re-sent from elsewhere
                # must not redirect traffic there
                return True
            del self._rebind_nonce[pk_hex]
            peer.addr = addr  # proven: reroute to the new address
            if not peer.direct or peer.relay is not None:
                # a direct path just beat the relay route (a predicted
                # probe landed, or the peer dialed us): upgrade in
                # place and drop the relay leg
                if peer.relay is not None:
                    self.stats["relay_upgrades"] += 1
                    get_tracer().count("router.relay_upgrades")
                peer.relay = None
            peer.direct = True
            d = self._dials.pop(pk_hex, None)
            # route attribution: the proven address is NOT the
            # advertised one and the prediction spray actually ran —
            # this mapping was found by port prediction, so topic
            # frames toward it carry the `predicted` route tag. A
            # proof AT the advertised address (re-dial, restart)
            # clears it: the tag describes the current path.
            peer.predicted = (
                self._port_prediction
                and d is not None
                and d.attempts >= self._predict_after
                and addr != d.addr
            )
            if addr in self._bootstrap_canon:
                # key possession proven AT a bootstrap address:
                # grant introducer trust and replay any intro that
                # arrived while the proof was in flight
                self._rendezvous_pks.add(pk_hex)
                held = self._pending_intros.pop(pk_hex, None)
                if held is not None:
                    self.stats["intros_applied"] += 1
                    self._apply_intro(held, introducer=pk_hex)
            live_inst = payload.get("inst", peer.inst)
            if live_inst != peer.inst:
                # fresh-nonce-proven incarnation change: reset the
                # announcement watermark and prompt the new
                # incarnation to (re)announce its topics to us;
                # ours go out right below
                peer.new_incarnation(live_inst)
                self._send_hello(addr[0], addr[1], ack=True)
            self._announce_topics(peer)
        return True

    def _apply_intro(self, payload: Any,
                     introducer: Optional[str] = None) -> None:
        """Dial every listed peer we do not already know. The address
        is only a hint — the hello/key-exchange (and, for known
        identities, the liveness challenge) authenticates; a malformed
        or bogus entry must never escape this loop (it would kill the
        router's event loop), so every per-entry failure — wrong-typed
        fields included — just skips the entry. Each dial is tracked
        in ``_dials`` so an unanswered hello escalates through retry /
        prediction / relay instead of being fired once and forgotten
        (the cone-NAT-only gap this closes)."""
        peers_list = payload.get("peers", ())
        if not isinstance(peers_list, (list, tuple)):
            return
        now = time.monotonic()
        for entry in peers_list:
            try:
                pk = entry["pk"].lower()
                ip, port = entry["ip"], int(entry["port"])
                if not isinstance(ip, str):
                    continue
                if pk == self.public_key:
                    continue
                peer = self._peers.get(pk)
                if peer is not None and peer.direct:
                    continue  # already have a proven path
                # unknown peer OR one we only reach via relay (or whose
                # earlier dial expired): a fresh introduction carries a
                # fresh observed address — (re)open the dial so the
                # retry/prediction escalation gets its shot at
                # upgrading the pair to a direct path
                if len(bytes.fromhex(pk)) != 32:
                    continue
                d = self._dials.get(pk)
                if d is None:
                    self._dials[pk] = _Dial(
                        pk, (ip, port), introducer,
                        base_s=self._dial_retry_s,
                        give_up_s=self._dial_give_up_s,
                    )
                else:
                    # refresh the hint and extend the window: the
                    # introducer just vouched the peer is alive
                    d.addr = (ip, port)
                    d.give_up_at = now + self._dial_give_up_s
                if peer is None:
                    self.add_peer(ip, port)
            except (KeyError, TypeError, ValueError,
                    AttributeError, OSError):
                continue

    def _introduce(self, newcomer: _Peer, new_topics: Set[str]) -> None:
        """Rendezvous: tell the newcomer about every other LIVE holder
        of its newly announced topics, and each holder about it — one
        intro envelope per side, holders unioned across topics. Fires
        only on NEWLY announced topics, so refresh re-announcements
        cost nothing; symmetric convergence comes from every
        announcement introducing against the then-current holder set.
        Holders silent past their own wire-declared announce TTL are
        aged out (they are expected to refresh; see __init__)."""
        if not newcomer.direct:
            return  # a relay-met peer has no dialable address to share
        now = time.monotonic()
        holders = {
            pk: p for pk, p in self._peers.items()
            if pk != newcomer.pk_hex
            and p.direct  # never hand out unproven hint addresses
            and now - p.last_seen <= (p.announce_ttl or self._announce_ttl)
            and p.topics & new_topics
        }
        if not holders:
            return
        self._send_envelope(newcomer, {
            "t": "intro",
            "peers": [
                {"pk": p.pk_hex, "ip": p.addr[0], "port": p.addr[1]}
                for p in holders.values()
            ],
        })
        about_new = {
            "t": "intro",
            "peers": [{
                "pk": newcomer.pk_hex,
                "ip": newcomer.addr[0],
                "port": newcomer.addr[1],
            }],
        }
        for p in holders.values():
            self._send_envelope(p, about_new)

    # -- topology hook driving the injected sync contract ----------------
    def _on_peer_joined_topic(self, topic: str, pk_hex: str) -> None:
        contract = self.options["cache"].get(topic)
        if not contract:
            return
        probe = contract.get("peer_joined")
        if probe is not None:
            probe(pk_hex)  # anti-entropy probe regardless of synced
        elif not contract.get("synced") and "sync" in contract:
            contract["sync"]()


def pump(routers: List[UdpRouter], *, quiet_rounds: int = 5,
         timeout_s: float = 10.0, sleep_s: float = 0.002) -> None:
    """Poll a set of in-process routers until the fabric is quiet:
    no router handles a message and no endpoint has unacked sends for
    `quiet_rounds` consecutive sweeps. Raises on timeout (undelivered
    traffic after transport-level retries = a real failure)."""
    deadline = time.monotonic() + timeout_s
    quiet = 0
    failed0 = sum(r.endpoint.failed for r in routers)
    while quiet < quiet_rounds:
        if time.monotonic() > deadline:
            pend = [(r.public_key[:8], r.endpoint.pending) for r in routers]
            raise TimeoutError(f"fabric not quiet: pending={pend}")
        handled = sum(r.poll() for r in routers)
        pending = sum(r.endpoint.pending for r in routers)
        failed = sum(r.endpoint.failed for r in routers)
        if failed > failed0:
            # a message burned every retransmit: the fabric would look
            # quiet, but traffic was lost — that is a failure, not quiet
            raise RuntimeError(f"{failed - failed0} message(s) dropped "
                               "after exhausting transport retries")
        if handled == 0 and pending == 0:
            quiet += 1
        else:
            quiet = 0
        time.sleep(sleep_s)
