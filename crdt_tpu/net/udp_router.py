"""Multi-process router: the ypear router contract over native UDP.

The reference's router is Hyperswarm — DHT topic discovery plus
Noise-encrypted peer streams over udx (SURVEY.md §2.2). This router
implements the same contract surface the CRDT layer consumes
(``is_ypear_router``, ``options``, ``update_options[_cache]``,
``start``/``started``/``peers``, ``alow`` -> the four verbs,
crdt.js:172-317) over the native transport seam
(:mod:`crdt_tpu.net.transport`): reliable-datagram UDP + X25519 /
XChaCha20-Poly1305 encrypted peer links.

Documented divergence: peer discovery is an explicit bootstrap list
(``add_peer``) instead of a global DHT — the rebuild targets
datacenter fabrics where peers are known addresses; DHT walking is
out of scope. Everything after discovery (key exchange, encrypted
links, topic membership, the four verbs, the sync handshake riding
them) matches the reference's shape.

Wire protocol (each transport message, after reassembly):
  kind 0x00  plaintext hello       {pk: hex, ack: bool}
  kind 0x01  encrypted envelope    sender_pk(32 raw) || SecureBox
             payload (AAD = sender pk), decrypting to one lib0 `any`:
             {t:'topics', topics:[...]} | {t:'m', topic, msg}

Like the loopback fabric, nothing is delivered until ``poll()`` runs —
single-threaded, event-loop style (udx's own model).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from crdt_tpu.codec.lib0 import Decoder, Encoder
from crdt_tpu.net.transport import SecureBox, UdpEndpoint, keypair

_HELLO = 0
_ENVELOPE = 1


def _pack_any(v: Any) -> bytes:
    enc = Encoder()
    enc.write_any(v)
    return enc.to_bytes()


def _unpack_any(data: bytes) -> Any:
    return Decoder(data).read_any()


class _Peer:
    __slots__ = ("pk_hex", "pk_raw", "addr", "topics", "box")

    def __init__(self, pk_hex: str, addr: Tuple[str, int], box: SecureBox):
        self.pk_hex = pk_hex
        self.pk_raw = bytes.fromhex(pk_hex)
        self.addr = addr
        self.topics: Set[str] = set()
        self.box = box


class UdpRouter:
    """One peer's router over a real socket (multi-process capable)."""

    is_ypear_router = True  # crdt.js:172's validation flag

    def __init__(
        self,
        *,
        bind_ip: str = "127.0.0.1",
        port: int = 0,
        seed: Optional[bytes] = None,
        username: Optional[str] = None,
    ):
        self.endpoint = UdpEndpoint(bind_ip, port)
        pub, sec = keypair(seed)
        self._secret = sec
        pk_hex = pub.hex()
        self.options: Dict[str, Any] = {
            "public_key": pk_hex,
            "username": username or pk_hex[:8],
            "cache": {},
        }
        self.started = False
        self._handlers: Dict[str, Callable] = {}
        self._peers: Dict[str, _Peer] = {}  # pk_hex -> peer
        self._hello_sent: Set[Tuple[str, int]] = set()

    # -- options bag (crdt.js:175-180) ----------------------------------
    def update_options(self, opts: Dict[str, Any]) -> None:
        self.options.update(opts)

    def update_options_cache(self, per_topic: Dict[str, dict]) -> None:
        for topic, contract in per_topic.items():
            self.options["cache"].setdefault(topic, {}).update(contract)

    # -- lifecycle -------------------------------------------------------
    def start(self, network_name: Optional[str] = None) -> None:
        self.options.setdefault("network_name", network_name)
        self.started = True

    def close(self) -> None:
        self.endpoint.close()

    @property
    def public_key(self) -> str:
        return self.options["public_key"]

    @property
    def addr(self) -> Tuple[str, int]:
        return (self.endpoint.bind_ip, self.endpoint.port)

    # -- discovery (bootstrap list; the DHT-walk divergence) -------------
    def add_peer(self, ip: str, port: int) -> None:
        """Dial a known address: plaintext hello carrying our identity;
        the reply completes the key exchange."""
        self._hello_sent.add((ip, port))
        self._send_hello(ip, port, ack=False)

    def _send_hello(self, ip: str, port: int, *, ack: bool) -> None:
        payload = bytes([_HELLO]) + _pack_any(
            {"pk": self.public_key, "ack": ack}
        )
        self.endpoint.send(ip, port, payload)

    # -- peer/topic views ------------------------------------------------
    @property
    def peers(self) -> List[str]:
        return list(self._peers)

    def peers_on(self, topic: str) -> List[str]:
        return [pk for pk, p in self._peers.items() if topic in p.topics]

    # -- the four verbs (crdt.js:315-317) --------------------------------
    def alow(self, topic: str, handler: Callable) -> Tuple[
        Callable, Callable, Callable, Callable
    ]:
        self._handlers[topic] = handler
        self._announce_topics()

        def propagate(msg: dict) -> None:
            for p in list(self._peers.values()):
                if topic in p.topics:
                    self._send_envelope(p, {"t": "m", "topic": topic, "msg": msg})

        broadcast = propagate  # the reference uses them interchangeably

        def for_peers(fn: Callable[[str], None]) -> None:
            for pk in self.peers_on(topic):
                fn(pk)

        def to_peer(public_key: str, msg: dict) -> None:
            p = self._peers.get(public_key)
            if p is not None and topic in p.topics:
                self._send_envelope(p, {"t": "m", "topic": topic, "msg": msg})

        return propagate, broadcast, for_peers, to_peer

    def unsubscribe(self, topic: str) -> None:
        self._handlers.pop(topic, None)
        self._announce_topics()

    # -- wire ------------------------------------------------------------
    def _send_envelope(self, peer: _Peer, payload: Any) -> None:
        me = bytes.fromhex(self.public_key)
        body = peer.box.encrypt(_pack_any(payload), aad=me)
        self.endpoint.send(peer.addr[0], peer.addr[1], bytes([_ENVELOPE]) + me + body)

    def _announce_topics(self) -> None:
        for p in list(self._peers.values()):
            self._send_envelope(p, {"t": "topics", "topics": sorted(self._handlers)})

    def _ensure_peer(self, pk_hex: str, addr: Tuple[str, int]) -> _Peer:
        p = self._peers.get(pk_hex)
        if p is None:
            p = _Peer(pk_hex, addr, SecureBox(self._secret, bytes.fromhex(pk_hex)))
            self._peers[pk_hex] = p
        else:
            p.addr = addr  # peer may rebind (restart); trust latest source
        return p

    def poll(self) -> int:
        """One pump: transport poll + dispatch every complete message.
        Returns the number of router-level messages handled."""
        self.endpoint.poll()
        handled = 0
        for src_ip, src_port, data in self.endpoint.recv_all():
            if not data:
                continue
            kind, body = data[0], data[1:]
            if kind == _HELLO:
                self._on_hello(body, (src_ip, src_port))
                handled += 1
            elif kind == _ENVELOPE and len(body) > 32:
                if self._on_envelope(body, (src_ip, src_port)):
                    handled += 1
        return handled

    def _on_hello(self, body: bytes, addr: Tuple[str, int]) -> None:
        try:
            info = _unpack_any(body)
            # normalize case so the envelope lookup (raw.hex(), always
            # lowercase) can never miss a peer registered from a hello
            pk_hex = info["pk"].lower()
            if len(bytes.fromhex(pk_hex)) != 32:
                return  # an X25519 public key is exactly 32 bytes
        except (ValueError, KeyError, TypeError, AttributeError):
            return
        if pk_hex == self.public_key:
            return
        self._ensure_peer(pk_hex, addr)
        if not info.get("ack"):
            self._send_hello(addr[0], addr[1], ack=True)
        # key exchange is done on both ends; exchange topic sets
        self._announce_topics()

    def _on_envelope(self, body: bytes, addr: Tuple[str, int]) -> bool:
        sender_raw, sealed = body[:32], body[32:]
        pk_hex = sender_raw.hex()
        peer = self._peers.get(pk_hex)
        if peer is None:
            # envelope from an unknown peer (e.g. we restarted): redo
            # the handshake; the CRDT layer's anti-entropy recovers
            # whatever this message carried
            self._send_hello(addr[0], addr[1], ack=False)
            return False
        try:
            payload = _unpack_any(peer.box.decrypt(sealed, aad=sender_raw))
        except ValueError:
            return False  # forged or corrupted
        t = payload.get("t") if isinstance(payload, dict) else None
        if t == "topics":
            before = set(peer.topics)
            peer.topics = set(payload.get("topics", ()))
            for topic in peer.topics - before:
                if topic in self._handlers:
                    self._on_peer_joined_topic(topic, pk_hex)
        elif t == "m":
            handler = self._handlers.get(payload.get("topic"))
            if handler is not None:
                handler(payload.get("msg"), pk_hex)
        return True

    # -- topology hook driving the injected sync contract ----------------
    def _on_peer_joined_topic(self, topic: str, pk_hex: str) -> None:
        contract = self.options["cache"].get(topic)
        if not contract:
            return
        probe = contract.get("peer_joined")
        if probe is not None:
            probe(pk_hex)  # anti-entropy probe regardless of synced
        elif not contract.get("synced") and "sync" in contract:
            contract["sync"]()


def pump(routers: List[UdpRouter], *, quiet_rounds: int = 5,
         timeout_s: float = 10.0, sleep_s: float = 0.002) -> None:
    """Poll a set of in-process routers until the fabric is quiet:
    no router handles a message and no endpoint has unacked sends for
    `quiet_rounds` consecutive sweeps. Raises on timeout (undelivered
    traffic after transport-level retries = a real failure)."""
    deadline = time.monotonic() + timeout_s
    quiet = 0
    failed0 = sum(r.endpoint.failed for r in routers)
    while quiet < quiet_rounds:
        if time.monotonic() > deadline:
            pend = [(r.public_key[:8], r.endpoint.pending) for r in routers]
            raise TimeoutError(f"fabric not quiet: pending={pend}")
        handled = sum(r.poll() for r in routers)
        pending = sum(r.endpoint.pending for r in routers)
        failed = sum(r.endpoint.failed for r in routers)
        if failed > failed0:
            # a message burned every retransmit: the fabric would look
            # quiet, but traffic was lost — that is a failure, not quiet
            raise RuntimeError(f"{failed - failed0} message(s) dropped "
                               "after exhausting transport retries")
        if handled == 0 and pending == 0:
            quiet += 1
        else:
            quiet = 0
        time.sleep(sleep_s)
