"""Deterministic fault-injection fabric + simulated-NAT loopback net.

The sync protocol's loss-recovery story (retry/backoff on ready
probes, periodic anti-entropy, relay fallback, re-election) was until
now exercised only by ad-hoc per-test plumbing — a loss knob here, a
cleared peer table there. This module makes the adversary a reusable,
SEEDED object so every recovery behavior is pinned by a replayable
schedule instead of one-off setup:

- :class:`FaultSchedule` — per-message fault decisions (drop,
  duplicate, delay/reorder, corrupt, partition) derived from
  ``crc32((seed, src, dst, flow_seq))``: deterministic per flow
  sequence regardless of cross-flow interleaving, so the same seed
  replays the same per-flow fault pattern on every run.
- :class:`FaultyEndpoint` — wraps a transport endpoint (the router
  seam: whole router messages, ABOVE the native reliable layer, so a
  "drop" models an app-level loss the native retransmit cannot see
  and only the protocol's own retries recover).
- :class:`Partition` — blocks cross-group traffic until healed
  (explicitly, or automatically after a fixed number of blocked
  messages — a count, not a timer, so schedules replay).
- :class:`NatFabric` / :class:`SymmetricNat` / :class:`ConeNat` /
  :class:`NattedEndpoint` — a userspace NAT simulation over loopback.
  A real NAT cannot be interposed on 127.0.0.1 sockets, so the fabric
  virtualizes ADDRESSES instead: every participant's endpoint is
  wrapped; sends carry a small virtual (src, dst) header; a natted
  wrapper allocates per-destination external ports (sequential — the
  allocation policy port prediction exploits), registers them with
  the shared fabric, and FILTERS inbound messages exactly the way the
  modeled NAT would (symmetric: accepted only on a mapping opened to
  precisely that remote (ip, port)). Datagrams still ride the real
  native transport between real sockets; what the routers above
  observe — source addresses, reachability, filtering — is the NAT's
  view. Sends to virtual addresses nobody has allocated are dropped
  at the sender (the real network drops them at the NAT), and the
  sender-side mapping is still opened first, exactly like a real
  symmetric NAT processing an outbound packet that dies remotely.

Everything is poll-driven and thread-free, like the endpoints it
wraps. See tests/test_faults.py and tests/test_transport.py
(TestSymmetricNatTraversal) for the schedules these pin.
"""

from __future__ import annotations

import socket as _socket
import time
import zlib
from typing import Dict, List, Optional, Set, Tuple

from crdt_tpu.obs.recorder import get_recorder, update_digest

Addr = Tuple[str, int]


# ---------------------------------------------------------------------------
# seeded fault schedule
# ---------------------------------------------------------------------------


def _hash01(*key) -> float:
    """Stable [0, 1) hash of a tuple of primitives — process-salt-free
    (unlike ``hash``), so schedules replay across runs."""
    return zlib.crc32(repr(key).encode()) / 2**32


class Partition:
    """Blocks messages between two address groups (sets of ports).

    Heals either explicitly (:meth:`heal`) or automatically after
    ``max_blocked`` total messages were suppressed — a message COUNT,
    not a wall-clock timer, so a schedule replays identically however
    fast the fabric is pumped.
    """

    def __init__(self, group_a, group_b, *, max_blocked: Optional[int] = None):
        self.group_a: Set[int] = set(group_a)
        self.group_b: Set[int] = set(group_b)
        self.max_blocked = max_blocked
        self.blocked = 0
        self.healed = False

    def heal(self) -> None:
        self.healed = True

    def blocks(self, src_port: int, dst_port: int) -> bool:
        if self.healed:
            return False
        cross = (
            (src_port in self.group_a and dst_port in self.group_b)
            or (src_port in self.group_b and dst_port in self.group_a)
        )
        if not cross:
            return False
        self.blocked += 1
        if self.max_blocked is not None and self.blocked >= self.max_blocked:
            self.healed = True
        return True


class FaultSchedule:
    """Seeded per-message fault plan, shared by every wrapper in one
    test fabric (each applies it to its own OUTBOUND messages, so
    installing it on all routers covers every direction once)."""

    def __init__(
        self,
        seed: int = 0,
        *,
        drop: float = 0.0,
        duplicate: float = 0.0,
        delay: float = 0.0,
        delay_polls: Tuple[int, int] = (1, 4),
        corrupt: float = 0.0,
        partition: Optional[Partition] = None,
    ):
        self.seed = seed
        self.drop = drop
        self.duplicate = duplicate
        self.delay = delay
        self.delay_polls = delay_polls
        self.corrupt = corrupt
        self.partition = partition

    def decide(self, src: int, dst: int, n: int) -> dict:
        """Fault decision for the n-th message of flow (src, dst)."""
        d = {"drop": False, "dup": False, "delay": 0, "corrupt": False}
        if self.partition is not None and self.partition.blocks(src, dst):
            d["drop"] = True
            d["partitioned"] = True
            return d
        if self.drop and _hash01(self.seed, "drop", src, dst, n) < self.drop:
            d["drop"] = True
            return d
        if self.corrupt and _hash01(self.seed, "corr", src, dst, n) < self.corrupt:
            d["corrupt"] = True
        if self.duplicate and _hash01(self.seed, "dup", src, dst, n) < self.duplicate:
            d["dup"] = True
        if self.delay and _hash01(self.seed, "delay", src, dst, n) < self.delay:
            lo, hi = self.delay_polls
            d["delay"] = lo + int(_hash01(self.seed, "dn", src, dst, n) * (hi - lo + 1))
        return d


class FaultyEndpoint:
    """Endpoint wrapper applying a :class:`FaultSchedule` to outbound
    messages at the ROUTER seam (whole messages, above the native
    reliable layer — faults here model losses the transport's own
    retransmit cannot repair; only protocol retries recover them).

    Delayed messages are held and released by :meth:`poll` — delay
    doubles as reorder, since later messages overtake held ones. Held
    messages count as ``pending`` so quiescence detection does not
    declare a fabric quiet while traffic is still scheduled.
    """

    def __init__(self, inner, schedule: FaultSchedule):
        self._inner = inner
        self.schedule = schedule
        self._polls = 0
        self._flow_seq: Dict[Tuple[int, int], int] = {}
        # [(release_at_poll, ip, port, data, unreliable)]
        self._held: List[tuple] = []
        self.stats: Dict[str, int] = {
            "sent": 0, "dropped": 0, "duplicated": 0, "delayed": 0,
            "corrupted": 0, "partitioned": 0,
        }

    # -- fault application -------------------------------------------------
    def _fault_send(self, ip: str, port: int, data: bytes,
                    unreliable: bool) -> int:
        flow = (self.port, port)
        n = self._flow_seq.get(flow, 0)
        self._flow_seq[flow] = n + 1
        d = self.schedule.decide(flow[0], flow[1], n)
        rec = get_recorder()
        if rec.enabled:
            # one event PER fault kind APPLIED: corrupt co-occurs with
            # delay or dup on one message (drop/partition are
            # exclusive early-outs; a held message is sent once on
            # release, so its dup decision is never applied), and the
            # recorder must agree with the stats counters
            kinds = []
            if d["drop"]:
                kinds = ["partition" if d.get("partitioned") else "drop"]
            else:
                if d["corrupt"]:
                    kinds.append("corrupt")
                if d["delay"]:
                    kinds.append("delay")
                elif d["dup"]:
                    kinds.append("dup")
            for kind in kinds:
                # crdtlint: emits=fault.drop,fault.partition,fault.corrupt,fault.delay,fault.dup
                rec.record(
                    f"fault.{kind}", src=flow[0], dst=flow[1], seq=n,
                    size=len(data), digest=update_digest(data),
                )
        if d["drop"]:
            self.stats["partitioned" if d.get("partitioned") else "dropped"] += 1
            return 0
        if d["corrupt"]:
            # flip one deterministic byte: an encrypted envelope fails
            # authentication at the receiver and is discarded — the
            # recovery path is identical to a drop, but it exercises
            # the decrypt-reject seam too
            if data:
                pos = int(_hash01(self.schedule.seed, "pos", *flow, n) * len(data))
                data = data[:pos] + bytes([data[pos] ^ 0x41]) + data[pos + 1:]
            self.stats["corrupted"] += 1
        if d["delay"]:
            self.stats["delayed"] += 1
            self._held.append((self._polls + d["delay"], ip, port, data, unreliable))
            return 0
        mid = self._raw_send(ip, port, data, unreliable)
        self.stats["sent"] += 1
        if d["dup"]:
            self.stats["duplicated"] += 1
            self._raw_send(ip, port, data, unreliable)
        return mid

    def _raw_send(self, ip: str, port: int, data: bytes,
                  unreliable: bool) -> int:
        if unreliable:
            return self._inner.send_unreliable(ip, port, data)
        return self._inner.send(ip, port, data)

    # -- the endpoint surface ---------------------------------------------
    def send(self, ip: str, port: int, data: bytes) -> int:
        return self._fault_send(ip, port, data, False)

    def send_unreliable(self, ip: str, port: int, data: bytes) -> int:
        return self._fault_send(ip, port, data, True)

    def poll(self) -> int:
        self._polls += 1
        if self._held:
            due = [h for h in self._held if h[0] <= self._polls]
            if due:
                self._held = [h for h in self._held if h[0] > self._polls]
                for _, ip, port, data, unrel in due:
                    self._raw_send(ip, port, data, unrel)
                    self.stats["sent"] += 1
        return self._inner.poll()

    def recv_all(self):
        return self._inner.recv_all()

    def recv(self):
        return self._inner.recv()

    @property
    def pending(self) -> int:
        return self._inner.pending + len(self._held)

    @property
    def failed(self) -> int:
        return self._inner.failed

    @property
    def bind_ip(self) -> str:
        return self._inner.bind_ip

    @property
    def port(self) -> int:
        return self._inner.port

    def close(self) -> None:
        self._inner.close()

    def __getattr__(self, name):
        return getattr(self._inner, name)


def install_faults(router, schedule: FaultSchedule) -> FaultyEndpoint:
    """Wrap ``router.endpoint`` (idempotent layering: applies to
    whatever endpoint the router currently has, raw or NAT-wrapped)."""
    ep = FaultyEndpoint(router.endpoint, schedule)
    router.endpoint = ep
    return ep


# ---------------------------------------------------------------------------
# simulated NATs over loopback
# ---------------------------------------------------------------------------

_VMAGIC = b"\xf7\x43\x56\x31"  # virtual-net header marker ("\xf7CV1")


def _pack_addr(addr: Addr) -> bytes:
    return _socket.inet_aton(addr[0]) + int(addr[1]).to_bytes(2, "big")


def _unpack_addr(b: bytes) -> Addr:
    return (_socket.inet_ntoa(b[:4]), int.from_bytes(b[4:6], "big"))


class NatFabric:
    """Shared bookkeeping for one virtual network: which wrapper owns
    which virtual address. Public wrappers own their real address;
    natted wrappers own each external mapping port they allocate."""

    def __init__(self):
        self._owners: Dict[Addr, "NattedEndpoint"] = {}

    def register(self, vaddr: Addr, wrapper: "NattedEndpoint") -> None:
        self._owners[vaddr] = wrapper

    def resolve(self, vaddr: Addr) -> Optional[Addr]:
        w = self._owners.get(vaddr)
        return w.real_addr if w is not None else None


class SymmetricNat:
    """Per-destination external mappings, sequentially allocated —
    the NAT class that defeats plain hole punching (the mapping the
    rendezvous observed is NOT the mapping used toward a new peer)
    and that port prediction exploits (the new mapping lands on the
    next sequential port). Filtering is address-AND-port-dependent:
    inbound is accepted only on a mapping opened to exactly that
    remote (ip, port)."""

    def __init__(self, base_port: int, ip: str = "127.0.0.1"):
        self.ip = ip
        self._next = base_port
        self.by_dst: Dict[Addr, int] = {}     # remote -> ext port
        self.by_port: Dict[int, Addr] = {}    # ext port -> remote

    def open_mapping(self, dst: Addr) -> int:
        port = self.by_dst.get(dst)
        if port is None:
            port = self._next
            self._next += 1
            self.by_dst[dst] = port
            self.by_port[port] = dst
        return port

    def accept(self, dst_v: Addr, src_v: Addr) -> bool:
        if dst_v[0] != self.ip:
            return False
        remote = self.by_port.get(dst_v[1])
        return remote == src_v


class ConeNat(SymmetricNat):
    """One external mapping for every destination (endpoint-
    independent mapping), with PORT-restricted filtering: inbound is
    accepted only from (ip, port) pairs the host has sent to — the
    strictest cone variant, deliberately, so anything that traverses
    it also traverses the laxer address-restricted and full cones."""

    def open_mapping(self, dst: Addr) -> int:
        if not self.by_port:
            port = self._next
            self._next += 1
            self.by_port[port] = dst  # first remote (unused for filter)
        port = next(iter(self.by_port))
        self.by_dst[dst] = port
        return port

    def accept(self, dst_v: Addr, src_v: Addr) -> bool:
        if dst_v[0] != self.ip or dst_v[1] not in self.by_port:
            return False
        return src_v in self.by_dst  # address-restricted


class NattedEndpoint:
    """Endpoint wrapper placing its router behind a simulated NAT (or,
    with ``nat=None``, making a public host a fabric participant —
    every member of one fabric must be wrapped, since fabric traffic
    carries the virtual-address header).

    Outbound: opens the sender-side mapping (even when the target
    resolves nowhere — real NATs allocate on the outbound packet),
    resolves the virtual destination, and sends the header-framed
    message over the real transport. Inbound: verifies the message was
    addressed to one of our virtual addresses, applies the NAT's
    filter, and presents the sender's VIRTUAL address as the message
    source — which is what the router's observed-address machinery
    (rendezvous introductions, rebind challenges) then sees.
    """

    def __init__(self, inner, fabric: NatFabric,
                 nat: Optional[SymmetricNat] = None):
        self._inner = inner
        self.fabric = fabric
        self.nat = nat
        self.real_addr: Addr = (inner.bind_ip, inner.port)
        self.stats: Dict[str, int] = {
            "blackholed": 0, "filtered": 0, "delivered": 0,
        }
        if nat is None:
            fabric.register(self.real_addr, self)

    def _send(self, ip: str, port: int, data: bytes, unreliable: bool) -> int:
        dst = (ip, port)
        src_v = self.real_addr
        if self.nat is not None:
            ext = self.nat.open_mapping(dst)
            src_v = (self.nat.ip, ext)
            self.fabric.register(src_v, self)
        real = self.fabric.resolve(dst)
        if real is None:
            # nobody owns that virtual address (unallocated predicted
            # port, aged-out mapping): the real network drops this at
            # the NAT — silently, sender-side
            self.stats["blackholed"] += 1
            return 0
        framed = _VMAGIC + _pack_addr(src_v) + _pack_addr(dst) + data
        if unreliable:
            return self._inner.send_unreliable(real[0], real[1], framed)
        return self._inner.send(real[0], real[1], framed)

    def send(self, ip: str, port: int, data: bytes) -> int:
        return self._send(ip, port, data, False)

    def send_unreliable(self, ip: str, port: int, data: bytes) -> int:
        return self._send(ip, port, data, True)

    def recv_all(self):
        out = []
        for ip, port, data in self._inner.recv_all():
            if not data.startswith(_VMAGIC) or len(data) < 16:
                out.append((ip, port, data))  # non-fabric traffic
                continue
            src_v = _unpack_addr(data[4:10])
            dst_v = _unpack_addr(data[10:16])
            payload = data[16:]
            if self.nat is not None:
                if not self.nat.accept(dst_v, src_v):
                    self.stats["filtered"] += 1
                    continue
            elif dst_v != self.real_addr:
                self.stats["filtered"] += 1
                continue
            self.stats["delivered"] += 1
            out.append((src_v[0], src_v[1], payload))
        return out

    def poll(self) -> int:
        return self._inner.poll()

    @property
    def pending(self) -> int:
        return self._inner.pending

    @property
    def failed(self) -> int:
        return self._inner.failed

    @property
    def bind_ip(self) -> str:
        return self._inner.bind_ip

    @property
    def port(self) -> int:
        return self._inner.port

    def close(self) -> None:
        self._inner.close()

    def __getattr__(self, name):
        return getattr(self._inner, name)


def install_nat(router, fabric: NatFabric,
                nat: Optional[SymmetricNat] = None) -> NattedEndpoint:
    """Place a router on a virtual fabric, optionally behind a NAT."""
    ep = NattedEndpoint(router.endpoint, fabric, nat)
    router.endpoint = ep
    return ep


# ---------------------------------------------------------------------------
# seeded state-fork fault (the divergence sentinel's adversary)
# ---------------------------------------------------------------------------


class ForkFault:
    """Seeded fault that FORKS replica state silently — the failure
    class the drop/dup/delay/corrupt schedule above cannot produce
    (those are all eventually repaired by the protocol; CRDT
    convergence guarantees it). A fork models the guarantees-void
    cases: storage bitrot surviving validation, a buggy merge, a
    byzantine peer emitting two different ops under ONE (client,
    clock) id.

    :meth:`inject` applies a conflicting record with the SAME id but
    seed-derived DIFFERENT content to each given replica, bypassing
    the network (nothing is broadcast — the fork is silent). Every
    replica's state vector advances identically, so the sync
    protocol sees two healthy, "converged" peers whose states will
    never agree: later anti-entropy diffs carry each side's forked
    record, and the receiver drops it as an already-known id
    (first-wins dedup). Exactly the condition the divergence
    sentinel's snapshot-hash beacon exists to expose — pinned in
    tests/test_obs.py.
    """

    def __init__(self, seed: int = 0, *, root: str = "kv",
                 key: Optional[str] = None):
        self.seed = seed
        self.root = root
        self.key = key if key is not None else f"fork{seed}"
        # fake origin client well above test/client-id ranges but
        # inside the 31-bit random-id space
        self.client = (1 << 29) + (seed % (1 << 16))

    def inject(self, replicas) -> List[bytes]:
        """Fork the given replicas' states; returns the per-replica
        conflicting blobs (for assertions/postmortems)."""
        from crdt_tpu.codec import v1
        from crdt_tpu.core.ids import DeleteSet
        from crdt_tpu.core.records import ItemRecord

        rec = get_recorder()
        blobs = []
        for i, rep in enumerate(replicas):
            content = f"fork-{self.seed}-{i}-" \
                      f"{int(_hash01(self.seed, 'fork', i) * 1e9)}"
            blob = v1.encode_update(
                [ItemRecord(client=self.client, clock=0,
                            parent_root=self.root, key=self.key,
                            content=content)],
                DeleteSet(),
            )
            rep.doc.apply_updates([blob], origin="fork")
            if rec.enabled:
                rec.record(
                    "fault.fork", replica=rep.router.public_key,
                    topic=rep.topic, digest=update_digest(blob),
                    size=len(blob),
                )
            blobs.append(blob)
        return blobs


# ---------------------------------------------------------------------------
# pumping helpers for faulty fabrics
# ---------------------------------------------------------------------------


def pump_until(routers, cond, *, timeout_s: float = 30.0,
               sleep_s: float = 0.002) -> None:
    """Poll a router set until ``cond()`` holds. Unlike
    :func:`crdt_tpu.net.udp_router.pump`, this neither requires the
    fabric to go quiet (retry timers keep traffic flowing until
    convergence) nor treats burned retransmits as failure (dials at
    blackholed NAT mappings are EXPECTED to die here)."""
    deadline = time.monotonic() + timeout_s
    while not cond():
        if time.monotonic() > deadline:
            raise TimeoutError("condition not reached under faults")
        for r in routers:
            r.poll()
        time.sleep(sleep_s)


# ---------------------------------------------------------------------------
# fleet handoff chaos (round 24)
# ---------------------------------------------------------------------------


class HandoffFaultSchedule:
    """Deterministic fault plan for the fleet fabric
    (``fleet.fabric.MemFabric``): scripted windows keyed on per-link
    FRAME COUNTS (never clocks) plus seeded background drop/dup.

    ``windows`` rows are dicts: ``{"src", "dst", "kinds", "from_n",
    "to_n", "mode"}`` — frames number ``from_n``..``to_n``
    (1-based, inclusive) on link src->dst whose kind is in
    ``kinds`` (empty = all) get ``mode`` ``"drop"`` (the
    partition-during-handoff lever: drop exactly the commit/ack
    exchange) or ``"dup"``. Background ``drop``/``duplicate``
    probabilities hash like :class:`FaultSchedule`.
    """

    def __init__(self, seed: int = 0, *, windows=(),
                 drop: float = 0.0, duplicate: float = 0.0):
        self.seed = seed
        self.windows = [dict(w) for w in windows]
        self.drop = drop
        self.duplicate = duplicate
        self.window_hits = 0

    def decide(self, src: str, dst: str, kind: str, n: int) -> dict:
        d = {"drop": False, "dup": 0}
        for w in self.windows:
            if w.get("src") not in (None, src):
                continue
            if w.get("dst") not in (None, dst):
                continue
            kinds = w.get("kinds") or ()
            if kinds and kind not in kinds:
                continue
            if not int(w.get("from_n", 1)) <= n <= \
                    int(w.get("to_n", 1 << 30)):
                continue
            self.window_hits += 1
            if w.get("mode", "drop") == "drop":
                d["drop"] = True
                return d
            d["dup"] += 1
        if self.drop and \
                _hash01(self.seed, "fdrop", src, dst, n) < self.drop:
            d["drop"] = True
            return d
        if self.duplicate and \
                _hash01(self.seed, "fdup", src, dst, n) < self.duplicate:
            d["dup"] += 1
        return d


class DuplicateAdviceSchedule:
    """Seeded advice-row duplication/replay for the placement loop's
    idempotence proof: ``mangle(poll, rows)`` returns the rows plus
    seeded duplicates of this poll's rows and replays of earlier
    polls' rows (stale seqs) — the consumer must dedup on
    ``(proc, tenant, seq)`` or double-start migrations."""

    def __init__(self, seed: int = 0, *, duplicate: float = 0.5,
                 replay: float = 0.5):
        self.seed = seed
        self.duplicate = duplicate
        self.replay = replay
        self._history: List[dict] = []
        self.injected = 0

    def mangle(self, poll: int, rows) -> List[dict]:
        out = [dict(r) for r in rows]
        for i, r in enumerate(rows):
            if _hash01(self.seed, "adv_dup", poll, i) < self.duplicate:
                out.append(dict(r))
                self.injected += 1
        for i, r in enumerate(self._history):
            if _hash01(self.seed, "adv_rep", poll, i) < self.replay:
                out.append(dict(r))
                self.injected += 1
        self._history.extend(dict(r) for r in rows)
        del self._history[:-64]
        return out
