from crdt_tpu.net.router import LoopbackNetwork, LoopbackRouter
from crdt_tpu.net.replica import MemoryPersistence, Replica, ypear_crdt

__all__ = [
    "LoopbackNetwork",
    "LoopbackRouter",
    "MemoryPersistence",
    "Replica",
    "ypear_crdt",
]
