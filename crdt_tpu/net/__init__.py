from crdt_tpu.net.router import LoopbackNetwork, LoopbackRouter
from crdt_tpu.net.replica import MemoryPersistence, Replica, ypear_crdt
from crdt_tpu.net.udp_router import UdpRouter, pump

__all__ = [
    "LoopbackNetwork",
    "LoopbackRouter",
    "MemoryPersistence",
    "Replica",
    "UdpRouter",
    "pump",
    "ypear_crdt",
]
