from crdt_tpu.net.router import LoopbackNetwork, LoopbackRouter
from crdt_tpu.net.replica import MemoryPersistence, Replica, ypear_crdt
from crdt_tpu.net.udp_router import UdpRouter, pump
from crdt_tpu.net.faults import (
    ConeNat,
    FaultSchedule,
    FaultyEndpoint,
    ForkFault,
    NatFabric,
    Partition,
    SymmetricNat,
    install_faults,
    install_nat,
    pump_until,
)

__all__ = [
    "ConeNat",
    "FaultSchedule",
    "FaultyEndpoint",
    "ForkFault",
    "LoopbackNetwork",
    "LoopbackRouter",
    "MemoryPersistence",
    "NatFabric",
    "Partition",
    "Replica",
    "SymmetricNat",
    "UdpRouter",
    "install_faults",
    "install_nat",
    "pump",
    "pump_until",
    "ypear_crdt",
]
