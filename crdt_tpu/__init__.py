"""crdt_tpu — a TPU-native CRDT framework.

A from-scratch rebuild of the capability surface of ypear/crdt
(reference: /root/reference/crdt.js) designed TPU-first:

- The CRDT engine itself (Yjs semantics: last-writer-wins maps, YATA
  sequence ordering, state vectors, delete sets, v1 binary update codec)
  implemented on a columnar struct-of-arrays op model so the delta-merge
  hot path runs as vectorized JAX/Pallas kernels on TPU
  (reference delegates this to the `yjs` npm dep, package.json:14).
- A replica-sync protocol matching the router-cache contract of
  crdt.js:234-317 (ready/sync anti-entropy handshake, per-peer state
  vectors) with an in-process loopback router for N-replica tests and
  XLA collectives as the on-device gossip fabric.
- A persistence layer matching the LevelDB update-log keyspace of
  crdt.js:5-141, backed by a native C++ ordered-KV store, plus snapshot
  compaction (absent in the reference; SURVEY.md Q3).
- The public batched API of crdt.js:661-702 (map/set/del/array/insert/
  push/unshift/cut/execBatch/observe), with the reference's behavioral
  defects D1-D7 (SURVEY.md §6) fixed.
"""

__version__ = "0.1.0"

from crdt_tpu.core.ids import ID, StateVector, DeleteSet  # noqa: F401


def __getattr__(name):
    # lazy subpackage access without importing jax at package import
    if name in ("ReplicaFleet", "FleetStep", "ReplayResult", "replay_trace"):
        from crdt_tpu import models

        return getattr(models, name)
    if name == "Tracer":
        from crdt_tpu.utils import Tracer

        return Tracer
    raise AttributeError(name)
