"""Interchange records between the codec, engine, and kernels.

An ``ItemRecord`` is one unit-length CRDT item in symbolic form (string
parent/key names, explicit ID tuples) — the currency of the v1 update
codec and of ``Engine.apply_records``. Inside an :class:`ItemStore` the
same item is a row of interned integer columns.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

from crdt_tpu.core.store import K_ANY, NULL


@dataclass
class ItemRecord:
    client: int
    clock: int
    # exactly one of parent_root / parent_item is set; both None only for
    # GC filler records whose position information was collected away
    parent_root: Optional[str] = None
    parent_item: Optional[Tuple[int, int]] = None
    key: Optional[str] = None  # map key; None for sequence items
    origin: Optional[Tuple[int, int]] = None  # YATA left origin
    right: Optional[Tuple[int, int]] = None  # YATA right origin
    kind: int = K_ANY
    type_ref: int = NULL
    content: Any = None

    @property
    def id(self) -> Tuple[int, int]:
        return (self.client, self.clock)

    def dep_ids(self):
        """IDs this record cannot integrate without (origins + item parent)."""
        deps = []
        if self.origin is not None:
            deps.append(self.origin)
        if self.right is not None:
            deps.append(self.right)
        if self.parent_item is not None:
            deps.append(self.parent_item)
        return deps
