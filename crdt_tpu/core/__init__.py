from crdt_tpu.core.ids import ID, StateVector, DeleteSet  # noqa: F401
from crdt_tpu.core.store import ItemStore, ROOT_PARENT, NO_KEY  # noqa: F401
