"""Device-gated remote merge — the TPU hot path of ``Crdt.apply_update``.

The reference merges every incoming update through Yjs's scalar
integrate loop (``Y.applyUpdate``, crdt.js:294). Here the same batch is
split into two phases:

1. **Admit** (host): dedup, per-client clock contiguity, dependency
   checks, pending stash, parent resolution, store append — pure
   bookkeeping, one dict/append pass per record via
   :meth:`Engine._try_admit`. No chain scans.
2. **Rebuild** (device): recompute ALL chain-derived state from the
   columnar store in two kernel dispatches —
   :func:`crdt_tpu.ops.merge.converge_maps` for map (parent, key)
   winners (tree argmax + pointer doubling) and
   :func:`crdt_tpu.ops.yata.tree_order_ranks` for sequence document
   order (DFS ranking via lexsort + Wyllie) — then materialize the
   winners/order back into the engine's chain dicts.

The result is bit-identical engine state to the scalar path
(``Engine.apply_records``): same visible values, same chain order, same
delete set, same pending semantics — asserted by the differential tests
in tests/test_device_merge.py and by running the BASELINE acceptance
configs in both modes.

Buffering is the point: ``Crdt.apply_updates`` admits a whole batch of
updates (a sync backlog, a persistence log replay, a gossip round) and
pays ONE rebuild — the north-star gate ("incoming peer updates buffered
into columnar tensors and applied as one vectorized applyUpdate").
"""

from __future__ import annotations

from crdt_tpu.compat import enable_x64

from typing import Dict, List, Optional, Tuple

import numpy as np

from crdt_tpu.core.ids import DeleteSet
from crdt_tpu.core.records import ItemRecord
from crdt_tpu.core.store import K_GC, NO_KEY, NULL
from crdt_tpu.ops import deleteset as ds_ops
from crdt_tpu.ops.device import _CLOCK_BITS, NULLI, fetch_packed_i32


def apply_records_device(engine, records: List[ItemRecord],
                         delete_set: Optional[DeleteSet] = None) -> None:
    """Device-path equivalent of :meth:`Engine.apply_records`: the
    shared admission loop in admit-only mode, then one kernel-driven
    chain rebuild (begins its own txn, like the scalar path)."""
    engine.apply_batch(records, delete_set, chain_integrate=False)
    if not engine.last_txn_items:
        # no rows admitted (duplicate redelivery, or a delete-only
        # batch): chain-derived state — links, heads, tails, winners —
        # depends only on which rows EXIST, not on deleted flags, so
        # the O(doc) rebuild would reproduce it bit-identically.
        # Deletes were already applied to the flags above.
        return
    rebuild_chains(engine)


# ---------------------------------------------------------------------------
# chain rebuild from the columnar store
# ---------------------------------------------------------------------------


def _origin_rows(client, clock, ocl, ock) -> np.ndarray:
    """Row index of each row's origin (-1 if none/absent), vectorized:
    packed-id sort + binary search instead of n dict lookups."""
    n = len(client)
    pack = (client.astype(np.int64) << _CLOCK_BITS) | clock.astype(np.int64)
    order = np.argsort(pack)
    spack = pack[order]
    opack = np.where(
        ocl >= 0,
        (ocl.astype(np.int64) << _CLOCK_BITS) | ock.astype(np.int64),
        np.int64(-1),
    )
    pos = np.searchsorted(spack, opack)
    posc = np.clip(pos, 0, max(n - 1, 0))
    found = (opack >= 0) & (spack[posc] == opack)
    return np.where(found, order[posc], -1).astype(np.int32)


from crdt_tpu.ops.device import bucket_pow2 as _bucket  # shared policy


def _pad(arr: np.ndarray, size: int, fill) -> np.ndarray:
    out = np.full(size, fill, dtype=arr.dtype)
    out[: len(arr)] = arr
    return out




def _rebuild_state(engine) -> dict:
    """Persistent per-engine rebuild bookkeeping: an interned parent
    spec id per store row, extended incrementally (O(new rows) per
    rebuild). Spec ids let a rebuild select only the rows of AFFECTED
    parents instead of restaging the whole document."""
    st = getattr(engine, "_device_rebuild_state", None)
    if st is None:
        st = {
            "row_spec": np.full(256, -1, np.int64),
            "spec_table": {},
            "specs": [],
            "spec_rows": [],  # spec id -> [store rows], append-only
            "len": 0,
        }
        engine._device_rebuild_state = st
    return st


def rebuild_chains(engine) -> None:
    """Recompute chain-derived structures for every parent touched by
    newly admitted rows: ``_map_tail``/``_map_kids`` + LWW loser
    tombstones from ``converge_maps``; ``_seq_head``/``_next``/
    ``_prev`` sequence links from ``tree_order_ranks``.

    Incremental: only AFFECTED parents (those with new rows since the
    last rebuild) are recomputed — chain state depends solely on which
    rows exist under a parent, so untouched parents' chains stay valid
    verbatim. Host work and kernel dispatch size scale with the
    affected parents' rows, not the document (VERDICT r1 item #8; the
    HBM-resident union for the firehose path is
    :mod:`crdt_tpu.ops.resident`).

    The kernel dispatches run under the guard layer's failure ladder
    (:func:`crdt_tpu.guard.device.dispatch_guarded`): a transient
    device ``RuntimeError`` retries once, a persistent one splits the
    affected parents in half (independent work — an OOM a half-size
    dispatch survives), and a dead device falls back to the exact
    scalar ordering (:func:`_rebuild_host`) — bit-identical state,
    device optional. Each rung is idempotent: the rebuild clears the
    affected chains before recomputing, so a retry after a mid-rebuild
    failure converges to the same state."""
    s = engine.store
    n = s.n
    if n == 0:
        return
    st = _rebuild_state(engine)

    # -- extend per-row spec ids for new rows (O(new)) -----------------
    row_spec = st["row_spec"]
    if len(row_spec) < n:
        grown = np.full(_bucket(n, floor=8), -1, np.int64)
        grown[: len(row_spec)] = row_spec
        st["row_spec"] = row_spec = grown
    affected: set = set()
    specs = st["specs"]
    spec_table = st["spec_table"]
    spec_rows = st["spec_rows"]
    for r in range(st["len"], n):
        if s.kind[r] == K_GC:
            row_spec[r] = -1
            continue
        spec = engine._parent_spec_of_row(r)
        sid = spec_table.get(spec)
        if sid is None:
            sid = len(specs)
            spec_table[spec] = sid
            specs.append(spec)
            spec_rows.append([])
        row_spec[r] = sid
        spec_rows[sid].append(r)
        affected.add(sid)
    st["len"] = n
    if not affected:
        return  # only GC fillers admitted: no chain is touched

    from crdt_tpu.guard.device import dispatch_guarded

    sids = sorted(affected)

    def halves():
        if len(sids) < 2:
            return None
        mid = len(sids) // 2
        lo, hi = sids[:mid], sids[mid:]
        return [
            (lambda: _rebuild_kernel(engine, lo),
             lambda: _rebuild_host(engine, lo)),
            (lambda: _rebuild_kernel(engine, hi),
             lambda: _rebuild_host(engine, hi)),
        ]

    dispatch_guarded(
        "engine.rebuild",
        lambda: _rebuild_kernel(engine, sids),
        split=halves,
        host=lambda: _rebuild_host(engine, sids),
    )


def _clear_specs(engine, sids) -> None:
    """Drop chain-derived state for the given parents (shared by the
    kernel and host rebuild rungs; idempotent, so every ladder retry
    starts from the same cleared baseline)."""
    st = engine._device_rebuild_state
    specs, spec_rows = st["specs"], st["spec_rows"]
    for sid in sids:
        spec = specs[sid]
        engine._seq_head.pop(spec, None)
        engine._seq_tail.pop(spec, None)
        for k in engine._map_kids.pop(spec, {}):
            engine._map_head.pop((spec, k), None)
            engine._map_tail.pop((spec, k), None)
        for r in spec_rows[sid]:
            engine._next.pop(r, None)
            engine._prev.pop(r, None)


def _link(engine, spec, rows_in_order) -> None:
    """Materialize one parent's chain links from an ordered row list."""
    prev = None
    for row in rows_in_order:
        if prev is None:
            engine._seq_head[spec] = row
            engine._prev[row] = NULL
        else:
            engine._next[prev] = row
            engine._prev[row] = prev
        prev = row
    if prev is not None:
        engine._next[prev] = NULL
        engine._seq_tail[spec] = prev


def _rebuild_host(engine, sids) -> None:
    """The ladder's last rung: rebuild the given parents' chains
    entirely on host with the exact scalar ordering
    (``order_hard_segment`` — the same oracle the kernel's hostile-
    shape fallback already uses), so a dead device degrades to a
    slower bit-identical answer instead of an exception mid-merge."""
    from crdt_tpu.ops.yata import order_hard_segment

    st = engine._device_rebuild_state
    specs, spec_rows = st["specs"], st["spec_rows"]
    s = engine.store
    _clear_specs(engine, sids)
    for sid in sids:
        spec = specs[sid]
        by_key: Dict[int, List[int]] = {}
        seq_rows: List[int] = []
        for r in spec_rows[sid]:
            k = int(s.key_id[r])
            if k != NO_KEY:
                by_key.setdefault(k, []).append(r)
            else:
                seq_rows.append(r)
        for k, rws in by_key.items():
            engine._map_kids.setdefault(spec, {})[k] = None
            recs = [engine.record_of_row(r) for r in rws]
            ordered = order_hard_segment(
                recs, ref_exists=lambda ref: s.has(*ref)
            )
            tail = s.find(*ordered[-1]) if ordered else None
            if tail is not None:
                engine._map_tail[(spec, k)] = tail
            for r in rws:
                if r != tail and not s.deleted[r]:
                    # LWW loser tombstones: same post-hoc invariant the
                    # kernel path enforces (Yjs Item.integrate)
                    engine._delete_row(r)
        if seq_rows:
            recs = [engine.record_of_row(r) for r in seq_rows]
            ordered = order_hard_segment(
                recs, ref_exists=lambda ref: s.has(*ref)
            )
            _link(engine, spec, [s.find(c, k) for c, k in ordered])


def _rebuild_kernel(engine, sids) -> None:
    """One kernel-driven rebuild pass over the given parents (the
    ladder's first rung; see :func:`rebuild_chains`)."""
    import jax.numpy as jnp

    from crdt_tpu.ops.merge import converge_maps
    from crdt_tpu.ops.yata import tree_order_ranks

    s = engine.store
    st = engine._device_rebuild_state
    specs, spec_rows = st["specs"], st["spec_rows"]
    row_spec = st["row_spec"]
    affected = set(sids)

    # -- select the affected parents' rows: O(their rows), not O(doc) --
    sel = np.sort(
        np.fromiter(
            (r for sid in affected for r in spec_rows[sid]),
            np.int64,
        )
    )
    m = len(sel)

    # -- clear derived state for affected parents only -----------------
    _clear_specs(engine, sids)

    raw_client = s.client[sel]
    clock = s.clock[sel]
    proot = s.parent_root[sel]
    pcl = s.parent_client[sel]
    pck = s.parent_clock[sel]
    kid = s.key_id[sel].astype(np.int32)
    kind = s.kind[sel]
    raw_ocl = s.origin_client[sel]
    ock = s.origin_clock[sel]
    rcl = s.right_client[sel]
    rck = s.right_clock[sel]

    # Dense, order-preserving client remap: real client ids are random
    # 31-bit values (net/replica.py:_random_client_id), which overflow
    # the kernels' packed (client << 40 | clock) int64 ids — and every
    # YATA/LWW rule only ever COMPARES client ids, so a rank-dense
    # relabeling leaves all outcomes unchanged. An origin whose client
    # is absent from the subset (a GC'd or foreign origin) maps to -1;
    # same-client origins with out-of-subset clocks fail the packed-id
    # search below instead.
    uniq_clients, client = np.unique(raw_client, return_inverse=True)
    client = client.astype(np.int32)
    opos = np.searchsorted(uniq_clients, np.clip(raw_ocl, 0, None))
    opos_c = np.clip(opos, 0, max(len(uniq_clients) - 1, 0))
    o_found = (raw_ocl >= 0) & (uniq_clients[opos_c] == raw_ocl)
    ocl = np.where(o_found, opos_c, -1).astype(np.int32)

    origin_idx = _origin_rows(client, clock, ocl, ock)
    # an origin that names a row OUTSIDE the subset (GC filler, foreign
    # parent) is an ORPHANING origin for sequences: the scalar engine
    # splices such items after a chain-less row, invisible to the head
    # walk. Distinguish it from "no origin at all" (a chain root).
    orphan = (raw_ocl >= 0) & (origin_idx < 0)
    live = kind != K_GC
    is_map = live & (kid != NO_KEY)
    is_seq = live & (kid == NO_KEY)

    pad = _bucket(m)

    # ---- maps: winner (= chain tail) per (parent, key) segment --------
    if is_map.any():
        with enable_x64(True):
            order_k, seg_k, winners, _, _, _ = converge_maps(
                jnp.asarray(_pad(client, pad, 0)),
                jnp.asarray(_pad(clock.astype(np.int64), pad, 0)),
                jnp.asarray(_pad(proot != NULL, pad, False)),
                jnp.asarray(_pad(np.where(proot != NULL, proot, pcl), pad, -2)),
                jnp.asarray(_pad(np.where(proot != NULL, -1, pck), pad, -2)),
                jnp.asarray(_pad(kid, pad, -1)),
                jnp.asarray(_pad(ocl, pad, -1)),
                jnp.asarray(_pad(ock.astype(np.int64), pad, -1)),
                jnp.asarray(np.arange(pad) < m),
                jnp.asarray(np.full(16, -1, np.int32)),
                jnp.asarray(np.full(16, -1, np.int64)),
                jnp.asarray(np.full(16, -1, np.int64)),
                num_segments=pad,
                ds_mode=ds_ops.mask_mode(),  # host static (CL702)
            )
        order_k, seg_sorted, winners = fetch_packed_i32(
            order_k, seg_k, winners
        )
        # kernel outputs live in id-sorted SUBSET space; map back to
        # subset positions, then to store rows via `sel`
        seg_row = np.full(pad, NULLI, np.int32)
        seg_row[order_k] = seg_sorted
        winner_of_seg: Dict[int, int] = {}
        for sid in np.unique(seg_row[:m][is_map]):
            w = winners[sid]
            if w != NULLI:
                winner_of_seg[int(sid)] = int(order_k[w])
        # crafted rights on map rows (honest map sets never carry
        # them) shift chain tails in ways the argmax kernel cannot
        # express; those chains take the exact scalar tail instead
        hard_chains: Dict[Tuple, List[int]] = {}
        for j in np.flatnonzero(is_map & (rcl != NULL)):
            j = int(j)
            hard_chains[(int(row_spec[sel[j]]), int(kid[j]))] = []
        for j in np.flatnonzero(is_map):
            j = int(j)
            row = int(sel[j])
            gsid = int(row_spec[row])
            k = int(kid[j])
            if (gsid, k) in hard_chains:
                hard_chains[(gsid, k)].append(j)
                continue
            sid = int(seg_row[j])
            w = winner_of_seg.get(sid)
            spec = specs[gsid]
            engine._map_kids.setdefault(spec, {})[k] = None
            if w == j:
                engine._map_tail[(spec, k)] = row
            elif not s.deleted[row]:
                # LWW loser: the scalar integrate tombstones every
                # non-tail map entry (crdt.js via yjs Item.integrate);
                # enforcing the same invariant post-hoc yields the
                # identical delete set
                engine._delete_row(row)
        if hard_chains:
            from crdt_tpu.ops.yata import order_hard_segment

            for (gsid, k), js in hard_chains.items():
                spec = specs[gsid]
                engine._map_kids.setdefault(spec, {})[k] = None
                # order_hard_segment rebuilds records without keys;
                # chain order depends only on origins/rights
                recs = [engine.record_of_row(int(sel[j])) for j in js]
                ordered = order_hard_segment(
                    recs, ref_exists=lambda ref: engine.store.has(*ref)
                )
                tail = (
                    engine.store.find(*ordered[-1]) if ordered else None
                )
                if tail is not None:
                    engine._map_tail[(spec, k)] = tail
                for j in js:
                    row = int(sel[j])
                    if row != tail and not s.deleted[row]:
                        engine._delete_row(row)

    # ---- sequences: document order per parent -------------------------
    # subset-local indices throughout; `sel` translates back to rows
    seq_rows = np.flatnonzero(is_seq & ~orphan)
    if len(seq_rows):
        local_seg_of: Dict[int, int] = {}  # global spec id -> dense
        seg = np.full(m, -1, np.int32)
        parent_arr = np.full(m, -1, np.int32)
        key1 = np.zeros(m, np.int64)
        key2 = np.zeros(m, np.int64)
        for j in seq_rows:
            j = int(j)
            gsid = int(row_spec[sel[j]])
            seg[j] = local_seg_of.setdefault(gsid, len(local_seg_of))
            if origin_idx[j] >= 0:
                parent_arr[j] = origin_idx[j]
            # raw client ids are safe here: sibling keys are plain
            # int64 lexsort keys, never packed. Clock is NEGATED:
            # same-client same-origin duplicates order clock-DESC
            # (the integrate break rule; see ops/yata.py)
            key1[j] = raw_client[j]
            key2[j] = -clock[j]

        from crdt_tpu.ops.yata import drop_orphan_subtrees

        seg_all = seg.copy()  # pre-drop assignment (hard fallback)
        seq_list = drop_orphan_subtrees(
            (int(j) for j in seq_rows), seg, parent_arr
        )

        # groups whose sibling order the (client, ~clock) key cannot
        # express: right-origin attachments run the exact group-local
        # scan; segments with rights the sibling model cannot express
        # at all (hostile shapes) fall back to a scalar integrate
        hard_local = _rank_conflict_groups(
            engine, seq_list, seg, parent_arr, key1, key2,
            raw_client, clock, rcl, rck,
        )

        num_segments = _bucket(len(local_seg_of), floor=3)
        with enable_x64(True):
            rank, _ = tree_order_ranks(
                jnp.asarray(_pad(seg, pad, -1)),
                jnp.asarray(_pad(parent_arr, pad, -1)),
                jnp.asarray(_pad(key1, pad, 0)),
                jnp.asarray(_pad(key2, pad, 0)),
                jnp.asarray(np.arange(pad) < m),
                num_segments=num_segments,
            )
        rank = np.asarray(rank)[:m]

        by_seg: Dict[int, List[Tuple[int, int]]] = {}
        for j in seq_list:
            if int(seg[j]) in hard_local:
                continue  # linked by the scalar fallback below
            by_seg.setdefault(int(seg[j]), []).append((int(rank[j]), j))
        inv = {lsid: gsid for gsid, lsid in local_seg_of.items()}

        for lsid, pairs in by_seg.items():
            pairs.sort()
            _link(engine, specs[inv[lsid]],
                  [int(sel[j]) for _, j in pairs])

        if hard_local:
            from crdt_tpu.ops.yata import order_hard_segment

            for lsid in hard_local:
                recs = [
                    engine.record_of_row(int(sel[j]))
                    for j in np.flatnonzero(seg_all == lsid)
                ]
                ordered = order_hard_segment(
                    recs, ref_exists=lambda ref: engine.store.has(*ref)
                )
                _link(
                    engine,
                    specs[inv[lsid]],
                    [engine.store.find(c, k) for c, k in ordered],
                )


def _rank_conflict_groups(
    engine, seq_list, seg, parent_arr, key1, key2, client, clock, rcl, rck
) -> set:
    """Replace (client, ~clock) sibling keys with exact scan ranks for
    groups containing right-origin attachments — the only case where
    the lexicographic key diverges from the Yjs integrate scan
    (attachment-free groups, duplicates included, are exact on the
    device key; see ops/yata.py). Returns the set of local segment ids
    whose rights the sibling model cannot express at all (dangling /
    cross-parent / inside-a-member's-subtree — hostile shapes): those
    sequences need the caller's scalar-integrate fallback."""
    from crdt_tpu.ops.yata import _simulate_group

    groups: Dict[Tuple[int, int], List[int]] = {}
    for i in seq_list:
        groups.setdefault((int(seg[i]), int(parent_arr[i])), []).append(i)
    hard: set = set()
    row_of = None  # (client, clock) -> local idx, built on demand
    for (gseg, _), rows in groups.items():
        if gseg in hard:
            continue
        ids = {(int(client[i]), int(clock[i])) for i in rows}
        out_rights = [
            i for i in rows
            if rcl[i] != NULL and (int(rcl[i]), int(rck[i])) not in ids
        ]
        if out_rights:
            from crdt_tpu.ops.yata import right_walk_is_hard

            if row_of is None:
                row_of = {
                    (int(client[j]), int(clock[j])): j
                    for j in range(len(client))
                    if seg[j] >= 0
                }
            for i in out_rights:
                if right_walk_is_hard(
                    (int(rcl[i]), int(rck[i])),
                    ids,
                    row_of.get,
                    lambda cur: int(seg[cur]),
                    gseg,
                    lambda cur: (int(client[cur]), int(clock[cur])),
                    lambda cur: (
                        int(parent_arr[cur]) if parent_arr[cur] >= 0 else None
                    ),
                    len(client),
                ):
                    hard.add(gseg)
                    break
        if gseg in hard:
            continue
        has_attachment = any(
            rcl[i] != NULL and (int(rcl[i]), int(rck[i])) in ids for i in rows
        )
        if not has_attachment:
            continue  # (client, ~clock) keys are exact (see ops/yata.py)
        sibs = [
            {
                "id": (int(client[i]), int(clock[i])),
                "client": int(client[i]),
                "clock": int(clock[i]),
                "right": (
                    (int(rcl[i]), int(rck[i])) if rcl[i] != NULL else None
                ),
            }
            for i in rows
        ]
        ordered = _simulate_group(sibs, ids)
        member_row = {(int(client[i]), int(clock[i])): i for i in rows}
        for pos, sid in enumerate(ordered):
            key1[member_row[sid]] = pos
            key2[member_row[sid]] = 0
    return hard
