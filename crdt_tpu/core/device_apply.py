"""Device-gated remote merge — the TPU hot path of ``Crdt.apply_update``.

The reference merges every incoming update through Yjs's scalar
integrate loop (``Y.applyUpdate``, crdt.js:294). Here the same batch is
split into two phases:

1. **Admit** (host): dedup, per-client clock contiguity, dependency
   checks, pending stash, parent resolution, store append — pure
   bookkeeping, one dict/append pass per record via
   :meth:`Engine._try_admit`. No chain scans.
2. **Rebuild** (device): recompute ALL chain-derived state from the
   columnar store in two kernel dispatches —
   :func:`crdt_tpu.ops.merge.converge_maps` for map (parent, key)
   winners (tree argmax + pointer doubling) and
   :func:`crdt_tpu.ops.yata.tree_order_ranks` for sequence document
   order (DFS ranking via lexsort + Wyllie) — then materialize the
   winners/order back into the engine's chain dicts.

The result is bit-identical engine state to the scalar path
(``Engine.apply_records``): same visible values, same chain order, same
delete set, same pending semantics — asserted by the differential tests
in tests/test_device_merge.py and by running the BASELINE acceptance
configs in both modes.

Buffering is the point: ``Crdt.apply_updates`` admits a whole batch of
updates (a sync backlog, a persistence log replay, a gossip round) and
pays ONE rebuild — the north-star gate ("incoming peer updates buffered
into columnar tensors and applied as one vectorized applyUpdate").
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from crdt_tpu.core.ids import DeleteSet
from crdt_tpu.core.records import ItemRecord
from crdt_tpu.core.store import K_GC, NO_KEY, NULL
from crdt_tpu.ops.device import _CLOCK_BITS, NULLI


def apply_records_device(engine, records: List[ItemRecord],
                         delete_set: Optional[DeleteSet] = None) -> None:
    """Device-path equivalent of :meth:`Engine.apply_records`: the
    shared admission loop in admit-only mode, then one kernel-driven
    chain rebuild (begins its own txn, like the scalar path)."""
    engine.apply_batch(records, delete_set, chain_integrate=False)
    if not engine.last_txn_items:
        # no rows admitted (duplicate redelivery, or a delete-only
        # batch): chain-derived state — links, heads, tails, winners —
        # depends only on which rows EXIST, not on deleted flags, so
        # the O(doc) rebuild would reproduce it bit-identically.
        # Deletes were already applied to the flags above.
        return
    rebuild_chains(engine)


# ---------------------------------------------------------------------------
# chain rebuild from the columnar store
# ---------------------------------------------------------------------------


def _origin_rows(client, clock, ocl, ock) -> np.ndarray:
    """Row index of each row's origin (-1 if none/absent), vectorized:
    packed-id sort + binary search instead of n dict lookups."""
    n = len(client)
    pack = (client.astype(np.int64) << _CLOCK_BITS) | clock.astype(np.int64)
    order = np.argsort(pack)
    spack = pack[order]
    opack = np.where(
        ocl >= 0,
        (ocl.astype(np.int64) << _CLOCK_BITS) | ock.astype(np.int64),
        np.int64(-1),
    )
    pos = np.searchsorted(spack, opack)
    posc = np.clip(pos, 0, max(n - 1, 0))
    found = (opack >= 0) & (spack[posc] == opack)
    return np.where(found, order[posc], -1).astype(np.int32)


def _bucket(n: int, floor: int = 9) -> int:
    """Power-of-two pad so jit compiles once per bucket."""
    return 1 << max(floor, (max(n, 1) - 1).bit_length())


def _pad(arr: np.ndarray, size: int, fill) -> np.ndarray:
    out = np.full(size, fill, dtype=arr.dtype)
    out[: len(arr)] = arr
    return out


def rebuild_chains(engine) -> None:
    """Recompute every chain-derived structure from the store via the
    device kernels: ``_map_tail``/``_map_kids`` + LWW loser tombstones
    from ``converge_maps``; ``_seq_head``/``_next``/``_prev`` sequence
    links from ``tree_order_ranks``."""
    import jax
    import jax.numpy as jnp

    from crdt_tpu.ops.merge import converge_maps
    from crdt_tpu.ops.yata import tree_order_ranks

    s = engine.store
    n = s.n
    # chain state is derived; everything below rebuilds it from rows
    engine._next.clear()
    engine._prev.clear()
    engine._seq_head.clear()
    engine._seq_tail.clear()
    engine._map_head.clear()
    engine._map_tail.clear()
    engine._map_kids.clear()
    if n == 0:
        return

    raw_client = s.client[:n]
    clock = s.clock[:n]
    proot = s.parent_root[:n]
    pcl = s.parent_client[:n]
    pck = s.parent_clock[:n]
    kid = s.key_id[:n].astype(np.int32)
    kind = s.kind[:n]
    raw_ocl = s.origin_client[:n]
    ock = s.origin_clock[:n]
    rcl = s.right_client[:n]
    rck = s.right_clock[:n]

    # Dense, order-preserving client remap: real client ids are random
    # 31-bit values (net/replica.py:_random_client_id), which overflow
    # the kernels' packed (client << 40 | clock) int64 ids — and every
    # YATA/LWW rule only ever COMPARES client ids, so a rank-dense
    # relabeling leaves all outcomes unchanged. Origin clients always
    # name admitted rows (dependency check), so the same table maps
    # them; -1 stays -1.
    uniq_clients, client = np.unique(raw_client, return_inverse=True)
    client = client.astype(np.int32)
    ocl = np.where(
        raw_ocl >= 0,
        np.searchsorted(uniq_clients, np.clip(raw_ocl, 0, None)),
        -1,
    ).astype(np.int32)

    origin_idx = _origin_rows(client, clock, ocl, ock)
    live = kind != K_GC
    is_map = live & (kid != NO_KEY)
    is_seq = live & (kid == NO_KEY)

    pad = _bucket(n)

    # ---- maps: winner (= chain tail) per (parent, key) segment --------
    if is_map.any():
        with jax.enable_x64(True):
            order_k, seg_k, winners, _, _, _ = converge_maps(
                jnp.asarray(_pad(client, pad, 0)),
                jnp.asarray(_pad(clock.astype(np.int64), pad, 0)),
                jnp.asarray(_pad(proot != NULL, pad, False)),
                jnp.asarray(_pad(np.where(proot != NULL, proot, pcl), pad, -2)),
                jnp.asarray(_pad(np.where(proot != NULL, -1, pck), pad, -2)),
                jnp.asarray(_pad(kid, pad, -1)),
                jnp.asarray(_pad(ocl, pad, -1)),
                jnp.asarray(_pad(ock.astype(np.int64), pad, -1)),
                jnp.asarray(np.arange(pad) < n),
                jnp.asarray(np.full(16, -1, np.int32)),
                jnp.asarray(np.full(16, -1, np.int64)),
                jnp.asarray(np.full(16, -1, np.int64)),
                num_segments=pad,
            )
        order_k = np.asarray(order_k)
        seg_sorted = np.asarray(seg_k)
        winners = np.asarray(winners)
        # kernel outputs live in id-sorted space; map back to rows
        seg_row = np.full(pad, NULLI, np.int32)
        seg_row[order_k] = seg_sorted
        winner_of_seg: Dict[int, int] = {}
        for sid in np.unique(seg_row[:n][is_map]):
            w = winners[sid]
            if w != NULLI:
                winner_of_seg[int(sid)] = int(order_k[w])
        for i in np.flatnonzero(is_map):
            i = int(i)
            sid = int(seg_row[i])
            w = winner_of_seg.get(sid)
            spec = engine._parent_spec_of_row(i)
            k = int(kid[i])
            engine._map_kids.setdefault(spec, {})[k] = None
            if w == i:
                engine._map_tail[(spec, k)] = i
            elif not s.deleted[i]:
                # LWW loser: the scalar integrate tombstones every
                # non-tail map entry (crdt.js via yjs Item.integrate);
                # enforcing the same invariant post-hoc yields the
                # identical delete set
                engine._delete_row(i)

    # ---- sequences: document order per parent -------------------------
    seq_rows = np.flatnonzero(is_seq)
    if len(seq_rows):
        spec_ids: Dict[Tuple, int] = {}
        seg = np.full(n, -1, np.int32)
        parent_arr = np.full(n, -1, np.int32)
        key1 = np.zeros(n, np.int64)
        key2 = np.zeros(n, np.int64)
        for i in seq_rows:
            i = int(i)
            spec = engine._parent_spec_of_row(i)
            seg[i] = spec_ids.setdefault(spec, len(spec_ids))
            if origin_idx[i] >= 0:
                parent_arr[i] = origin_idx[i]
            # raw client ids are safe here: sibling keys are plain
            # int64 lexsort keys, never packed. Clock is NEGATED:
            # same-client same-origin duplicates order clock-DESC
            # (the integrate break rule; see ops/yata.py)
            key1[i] = raw_client[i]
            key2[i] = -clock[i]

        from crdt_tpu.ops.yata import drop_orphan_subtrees

        seq_list = drop_orphan_subtrees(
            (int(i) for i in seq_rows), seg, parent_arr
        )

        # groups whose sibling order the (client, ~clock) key cannot
        # express — right-origin attachments only — run the exact
        # group-local scan on host (see ops/yata.py)
        _rank_conflict_groups(
            engine, seq_list, seg, parent_arr, key1, key2,
            raw_client, clock, rcl, rck,
        )

        num_segments = _bucket(len(spec_ids), floor=3)
        with jax.enable_x64(True):
            rank, _ = tree_order_ranks(
                jnp.asarray(_pad(seg, pad, -1)),
                jnp.asarray(_pad(parent_arr, pad, -1)),
                jnp.asarray(_pad(key1, pad, 0)),
                jnp.asarray(_pad(key2, pad, 0)),
                jnp.asarray(np.arange(pad) < n),
                num_segments=num_segments,
            )
        rank = np.asarray(rank)[:n]

        by_seg: Dict[int, List[Tuple[int, int]]] = {}
        for i in seq_list:
            by_seg.setdefault(int(seg[i]), []).append((int(rank[i]), i))
        inv = {sid: spec for spec, sid in spec_ids.items()}
        for sid, pairs in by_seg.items():
            pairs.sort()
            spec = inv[sid]
            prev = None
            for _, row in pairs:
                if prev is None:
                    engine._seq_head[spec] = row
                    engine._prev[row] = NULL
                else:
                    engine._next[prev] = row
                    engine._prev[row] = prev
                prev = row
            engine._next[prev] = NULL
            engine._seq_tail[spec] = prev


def _rank_conflict_groups(
    engine, seq_list, seg, parent_arr, key1, key2, client, clock, rcl, rck
) -> None:
    """Replace (client, ~clock) sibling keys with exact scan ranks for
    groups containing right-origin attachments — the only case where
    the lexicographic key diverges from the Yjs integrate scan
    (attachment-free groups, duplicates included, are exact on the
    device key; see ops/yata.py)."""
    from crdt_tpu.ops.yata import _simulate_group

    groups: Dict[Tuple[int, int], List[int]] = {}
    for i in seq_list:
        groups.setdefault((int(seg[i]), int(parent_arr[i])), []).append(i)
    for rows in groups.values():
        ids = {(int(client[i]), int(clock[i])) for i in rows}
        has_attachment = any(
            rcl[i] != NULL and (int(rcl[i]), int(rck[i])) in ids for i in rows
        )
        if not has_attachment:
            continue  # (client, ~clock) keys are exact (see ops/yata.py)
        sibs = [
            {
                "id": (int(client[i]), int(clock[i])),
                "client": int(client[i]),
                "clock": int(clock[i]),
                "right": (
                    (int(rcl[i]), int(rck[i])) if rcl[i] != NULL else None
                ),
            }
            for i in rows
        ]
        ordered = _simulate_group(sibs, ids)
        row_of = {(int(client[i]), int(clock[i])): i for i in rows}
        for pos, sid in enumerate(ordered):
            key1[row_of[sid]] = pos
            key2[row_of[sid]] = 0
