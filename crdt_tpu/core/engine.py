"""Host integration engine — the exact-semantics oracle.

This is the scalar reference implementation of the CRDT semantics the
reference library gets from Yjs (``Y.applyUpdate`` at crdt.js:294 is
the hot merge loop; ``Y.Map.set``/``Y.Array.insert`` at crdt.js:375,527
are the local op constructors). Every TPU kernel in ``crdt_tpu.ops`` is
differential-tested against this engine on identical columnar inputs.

Semantics implemented (faithful to the YATA/Yjs behavior):

- Items are unit-length, identified by (client, clock); per-client
  clocks are contiguous. Remote items whose dependencies (origins,
  item parent, or preceding clocks) are unknown wait in a pending set
  — the analogue of Yjs's pending-update stash.
- Sequences (root arrays and nested arrays) are doubly linked chains
  including tombstones. Remote integration runs the YATA conflict
  resolution scan: for a new item with left origin ``o`` and right
  origin ``r``, scan the chain between them; an existing item with the
  same left origin and a smaller client goes before the new item; with
  the same left AND right origin and a larger client the scan stops;
  items whose origin lies strictly inside the scanned region are
  skipped or adopted per the items-before-origin rule.
- Map entries per (parent, key) are chains under the same conflict
  rule (right origin always null). The chain tail is the visible
  entry; when a newly integrated item lands at the tail, its left
  neighbor is tombstoned (Yjs deletes the superseded entry during
  integrate, which keeps delete sets converging under full-state
  exchange).
- Deletions are tombstones recorded in a DeleteSet; remote delete
  sets apply to known items and wait in pending ranges otherwise.
"""

from __future__ import annotations

import copy
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from crdt_tpu.core.ids import DeleteSet, StateVector
from crdt_tpu.core.records import ItemRecord
from crdt_tpu.obs.tracer import get_tracer
from crdt_tpu.core.store import (
    K_ANY,
    K_DELETED,
    K_GC,
    K_TYPE,
    NO_KEY,
    NULL,
    TYPE_ARRAY,
    TYPE_MAP,
    ItemStore,
)

# parent spec: ("root", name_id) or ("item", client, clock)
ParentSpec = Tuple


class Engine:
    def __init__(self, client_id: int):
        self.client_id = int(client_id)
        self.store = ItemStore()
        # linked chains over store rows
        self._next: Dict[int, int] = {}  # row -> row | NULL
        self._prev: Dict[int, int] = {}
        self._seq_head: Dict[ParentSpec, int] = {}  # sequence chains
        self._seq_tail: Dict[ParentSpec, int] = {}
        self._map_head: Dict[Tuple[ParentSpec, int], int] = {}  # key chains
        self._map_tail: Dict[Tuple[ParentSpec, int], int] = {}
        # spec -> ordered set of key ids with chains (dict-as-set), so
        # materializing one map is O(its keys), not O(all map keys)
        self._map_kids: Dict[ParentSpec, Dict[int, None]] = {}
        # pending remote records / deletes waiting on dependencies
        self.pending: List[ItemRecord] = []
        self.pending_deletes = DeleteSet()
        # pending-stash budget (guard layer): None = unbounded (the
        # historical behavior); an int caps len(pending) — overflow
        # evicts the records FURTHEST from integrable (largest clocks
        # per client: their blocker is deepest) and records the
        # evicted (client, clock) ranges so the replica layer can
        # re-probe the blocking peer (crdt_tpu/guard).
        self.pending_limit: Optional[int] = None
        self.evicted_ranges: Dict[int, Tuple[int, int]] = {}
        # per-client next expected clock (contiguity guard)
        self._next_clock: Dict[int, int] = {}
        # root name -> kind hint ("map"/"array") from observed items
        self.root_kinds: Dict[str, str] = {}
        # batch-local bookkeeping for observers/delta tracking
        self.last_txn_items: List[int] = []
        self.last_txn_deletes = DeleteSet()

    # ------------------------------------------------------------------
    # clock / id helpers
    # ------------------------------------------------------------------
    def next_clock(self, client: Optional[int] = None) -> int:
        c = self.client_id if client is None else client
        return self._next_clock.get(c, 0)

    def _alloc_clock(self) -> int:
        c = self._next_clock.get(self.client_id, 0)
        return c

    def state_vector(self) -> StateVector:
        return StateVector(dict(self._next_clock))

    def delete_set(self) -> DeleteSet:
        return self.store.delete_set()

    # ------------------------------------------------------------------
    # parent / chain helpers
    # ------------------------------------------------------------------
    def _parent_spec_of_row(self, row: int) -> ParentSpec:
        s = self.store
        if s.parent_root[row] != NULL:
            return ("root", int(s.parent_root[row]))
        return ("item", int(s.parent_client[row]), int(s.parent_clock[row]))

    def _chain_of_row(self, row: int):
        """Return (head_dict, tail_dict, chain_key) for the row's chain."""
        spec = self._parent_spec_of_row(row)
        kid = int(self.store.key_id[row])
        if kid != NO_KEY:
            return self._map_head, self._map_tail, (spec, kid)
        return self._seq_head, self._seq_tail, spec

    def _root_spec(self, name: str) -> ParentSpec:
        return ("root", self.store.intern_root(name))

    # ------------------------------------------------------------------
    # local operations (construct records, integrate through same path)
    # ------------------------------------------------------------------
    def _local_record(self, **kw) -> ItemRecord:
        rec = ItemRecord(client=self.client_id, clock=self._alloc_clock(), **kw)
        ok = self._try_integrate(rec)
        assert ok, "local op must always be integrable"
        return rec

    def map_set(
        self, map_name: str, key: str, value: Any, *, parent: Optional[ParentSpec] = None
    ) -> ItemRecord:
        """Set key in a (root or nested) map; LWW via key-chain append."""
        spec = parent if parent is not None else self._root_spec(map_name)
        kid = self.store.intern_key(key)
        tail = self._map_tail.get((spec, kid))
        origin = self.store.id_of(tail) if tail is not None else None
        return self._local_record(
            parent_root=map_name if spec[0] == "root" else None,
            parent_item=(spec[1], spec[2]) if spec[0] == "item" else None,
            key=key,
            origin=origin,
            right=None,
            kind=K_ANY,
            content=copy.deepcopy(value),
        )

    def map_set_type(
        self, map_name: str, key: str, type_ref: int = TYPE_ARRAY,
        *, parent: Optional[ParentSpec] = None,
    ) -> ItemRecord:
        """Set key to a fresh nested type (Y.Array inside a map, crdt.js:423)."""
        spec = parent if parent is not None else self._root_spec(map_name)
        kid = self.store.intern_key(key)
        tail = self._map_tail.get((spec, kid))
        origin = self.store.id_of(tail) if tail is not None else None
        return self._local_record(
            parent_root=map_name if spec[0] == "root" else None,
            parent_item=(spec[1], spec[2]) if spec[0] == "item" else None,
            key=key,
            origin=origin,
            right=None,
            kind=K_TYPE,
            type_ref=type_ref,
        )

    def map_delete(self, map_name: str, key: str, *, parent: Optional[ParentSpec] = None) -> bool:
        """Tombstone the visible entry for key. Returns False if absent."""
        spec = parent if parent is not None else self._root_spec(map_name)
        kid = self.store.key_id_of(key)
        if kid is None:
            return False
        tail = self._map_tail.get((spec, kid))
        if tail is None or self.store.deleted[tail]:
            return False
        self._delete_row(tail)
        return True

    def seq_insert(
        self, name: str, index: int, values: List[Any], *, parent: Optional[ParentSpec] = None
    ) -> List[ItemRecord]:
        """Insert values at index into a (root or nested) sequence."""
        spec = parent if parent is not None else self._root_spec(name)
        left = self._visible_left(spec, index)
        out = []
        for v in values:
            right = self._next.get(left, NULL) if left is not None else self._seq_head.get(spec, NULL)
            rec = self._local_record(
                parent_root=name if spec[0] == "root" else None,
                parent_item=(spec[1], spec[2]) if spec[0] == "item" else None,
                key=None,
                origin=self.store.id_of(left) if left is not None else None,
                right=self.store.id_of(right) if right != NULL else None,
                kind=K_ANY,
                content=copy.deepcopy(v),
            )
            out.append(rec)
            left = self.store.find(*rec.id)
        return out

    def seq_insert_type(
        self, name: str, index: int, type_ref: int = TYPE_ARRAY,
        *, parent: Optional[ParentSpec] = None,
    ) -> ItemRecord:
        """Insert a nested type into a sequence (arrays of arrays)."""
        spec = parent if parent is not None else self._root_spec(name)
        left = self._visible_left(spec, index)
        right = self._next.get(left, NULL) if left is not None else self._seq_head.get(spec, NULL)
        return self._local_record(
            parent_root=name if spec[0] == "root" else None,
            parent_item=(spec[1], spec[2]) if spec[0] == "item" else None,
            key=None,
            origin=self.store.id_of(left) if left is not None else None,
            right=self.store.id_of(right) if right != NULL else None,
            kind=K_TYPE,
            type_ref=type_ref,
        )

    def seq_delete(
        self, name: str, index: int, length: int, *, parent: Optional[ParentSpec] = None
    ) -> int:
        """Tombstone `length` visible items from `index`. Returns count."""
        spec = parent if parent is not None else self._root_spec(name)
        row = self._visible_at(spec, index)
        count = 0
        while row is not None and count < length:
            nxt = self._next_visible(row)
            self._delete_row(row)
            count += 1
            row = nxt
        return count

    def _visible_left(self, spec: ParentSpec, index: int) -> Optional[int]:
        """Row of the (index-1)-th visible item, or None for index 0."""
        if index <= 0:
            return None
        row = self._seq_head.get(spec, NULL)
        seen = 0
        while row != NULL:
            if self._is_countable(row):
                seen += 1
                if seen == index:
                    return row
            row = self._next.get(row, NULL)
        raise IndexError(f"index {index} out of range (len={seen})")

    def _visible_at(self, spec: ParentSpec, index: int) -> Optional[int]:
        row = self._seq_head.get(spec, NULL)
        seen = 0
        while row != NULL:
            if self._is_countable(row):
                if seen == index:
                    return row
                seen += 1
            row = self._next.get(row, NULL)
        return None

    def seq_len(self, name: Optional[str] = None, *, parent: Optional[ParentSpec] = None) -> int:
        """Visible length of a sequence — chain count only, no JSON
        materialization (push's append-index lookup)."""
        if parent is not None:
            spec = parent
        else:
            rid = self.store.root_id(name)
            if rid is None:
                return 0
            spec = ("root", rid)
        n = 0
        row = self._seq_head.get(spec, NULL)
        while row != NULL:
            if self._is_countable(row):
                n += 1
            row = self._next.get(row, NULL)
        return n

    def _next_visible(self, row: int) -> Optional[int]:
        r = self._next.get(row, NULL)
        while r != NULL and not self._is_countable(r):
            r = self._next.get(r, NULL)
        return r if r != NULL else None

    def _is_countable(self, row: int) -> bool:
        # ContentFormat is not countable in Yjs (formatting markers carry
        # no sequence position); deleted/GC rows are tombstones
        from crdt_tpu.core.store import K_FORMAT

        return not self.store.deleted[row] and self.store.kind[row] not in (
            K_DELETED,
            K_GC,
            K_FORMAT,
        )

    def _delete_row(self, row: int) -> None:
        if not self.store.deleted[row]:
            self.store.mark_deleted(row)
            self.last_txn_deletes.add(int(self.store.client[row]), int(self.store.clock[row]))

    # ------------------------------------------------------------------
    # remote integration
    # ------------------------------------------------------------------
    def apply_records(
        self, records: List[ItemRecord], delete_set: Optional[DeleteSet] = None
    ) -> None:
        """Integrate a batch of remote records + delete set (applyUpdate)."""
        self.apply_batch(records, delete_set, chain_integrate=True)

    def apply_batch(
        self,
        records: List[ItemRecord],
        delete_set: Optional[DeleteSet] = None,
        *,
        chain_integrate: bool,
    ) -> None:
        """Shared admission loop for both merge paths, O(n + deps):
        records that cannot integrate yet are parked on their first
        missing dependency (a clock gap parks on (client, clock-1);
        a missing origin/right/parent parks on that id) and woken the
        moment it lands — no quadratic re-scan passes over the batch
        (the r1 engine retried the whole remainder per round).
        ``chain_integrate=False`` is the device path's admit-only mode
        (chains are rebuilt by kernels afterwards); one loop keeps both
        modes' admission/pending semantics identical. Ends with the
        delete-set application, like ``Y.applyUpdate``."""
        self.begin_txn()
        if chain_integrate:
            step = self._try_integrate
        else:
            step = lambda rec: self._try_admit(rec)[0]  # noqa: E731
        queue = deque(
            sorted(records + self.pending, key=lambda r: (r.client, r.clock))
        )
        n_prior_pending = len(self.pending)
        self.pending = []
        waiting: Dict[Tuple[int, int], List[ItemRecord]] = {}
        n_integrated = 0
        try:
            while queue:
                rec = queue.popleft()
                if step(rec):
                    n_integrated += 1
                    # anything parked on this id (contiguity waiters key
                    # on (client, clock); dep waiters on the dep id)
                    woken = waiting.pop(rec.id, None)
                    if woken:
                        queue.extend(woken)
                else:
                    blocker = self._blocker_of(rec)
                    if blocker is None:
                        # cannot happen for well-formed records (not-
                        # handled implies a gap or a missing dep)
                        self.pending.append(rec)
                    else:
                        waiting.setdefault(blocker, []).append(rec)
        except BaseException as e:
            # an exception mid-batch must not wipe the stash: the
            # queue, parked waiters, and prior pending (absorbed into
            # the queue) return to pending. The in-flight record is
            # kept only for non-Exception interrupts (KeyboardInterrupt
            # etc. — it was presumably valid); a record that RAISED a
            # regular Exception is malformed and re-queueing it would
            # poison every later batch.
            if not isinstance(e, Exception):
                self.pending.append(rec)
            self.pending.extend(queue)
            for recs in waiting.values():
                self.pending.extend(recs)
            raise
        for recs in waiting.values():
            self.pending.extend(recs)
        if (
            self.pending_limit is not None
            and len(self.pending) > self.pending_limit
        ):
            self._evict_pending()
        if delete_set is not None:
            self._apply_delete_set(delete_set)
        self._retry_pending_deletes()
        tracer = get_tracer()
        if tracer.enabled:
            # one counter flush per batch, never per record: the
            # admission loop itself stays tracer-free. Stashed counts
            # only the NET NEW parked records (prior pending re-rides
            # every batch and must not re-count); the gauge carries
            # the current stash depth
            newly_stashed = len(self.pending) - n_prior_pending
            tracer.count("engine.records_integrated", n_integrated)
            if newly_stashed > 0:
                tracer.count("engine.records_stashed", newly_stashed)
            tracer.gauge("engine.pending", len(self.pending))
            tracer.gauge(
                "engine.pending_delete_ranges",
                sum(len(v) for v in self.pending_deletes.ranges.values()),
            )

    def _evict_pending(self) -> None:
        """Shrink the stash to ``pending_limit`` by dropping the
        records DEEPEST in their own client's queue (the shared
        fairness/recovery policy —
        :func:`crdt_tpu.guard.limits.evict_deepest`). Evicted ids
        merge into ``evicted_ranges`` (client -> (lo, hi)); the
        replica layer drains them via :meth:`take_evicted_ranges` and
        re-probes."""
        from crdt_tpu.guard.limits import evict_deepest

        evicted, ranges = evict_deepest(
            [(r.client, r.clock) for r in self.pending], self.pending_limit
        )
        if not evicted:
            return
        ev = set(evicted)
        n_before = len(self.pending)
        self.pending = [
            r for r in self.pending if (r.client, r.clock) not in ev
        ]
        for c, (lo, hi) in ranges.items():
            plo, phi = self.evicted_ranges.get(c, (lo, hi))
            self.evicted_ranges[c] = (min(plo, lo), max(phi, hi))
        tracer = get_tracer()
        if tracer.enabled:
            tracer.count(
                "engine.pending_evictions", n_before - len(self.pending)
            )

    def take_evicted_ranges(self) -> Dict[int, Tuple[int, int]]:
        """Drain the evicted (client, clock) range bookkeeping — the
        replica layer's cue to issue targeted SV re-probes."""
        ev, self.evicted_ranges = self.evicted_ranges, {}
        return ev

    def _blocker_of(self, rec: ItemRecord) -> Optional[Tuple[int, int]]:
        """The first id this record is waiting on: the previous clock
        of its own client (contiguity), else a missing dependency."""
        nc = self._next_clock.get(rec.client, 0)
        if rec.clock > nc:
            return (rec.client, rec.clock - 1)
        for dep in rec.dep_ids():
            if not self.store.has(*dep):
                return dep
        return None

    def begin_txn(self) -> None:
        self.last_txn_items = []
        self.last_txn_deletes = DeleteSet()

    def _apply_delete_set(self, ds: DeleteSet) -> None:
        self._clamped_delete(ds, self.pending_deletes)

    def _retry_pending_deletes(self) -> None:
        if not self.pending_deletes.ranges:
            return
        pending, self.pending_deletes = self.pending_deletes, DeleteSet()
        self._clamped_delete(pending, self.pending_deletes)

    def _clamped_delete(self, ds: DeleteSet, pend_into: DeleteSet) -> None:
        """Delete every range's integrated clocks; the portion at or
        above the client's contiguity watermark pends as a RANGE, not
        per clock — a hostile (or merely early) range covering clocks
        that may never exist must cost O(ranges), never O(declared
        length) (adversarial matrix, tests/test_yjs_fixtures.py)."""
        for client, clock, length in ds.iter_all():
            end = clock + length
            wm = self._next_clock.get(client, 0)
            for k in range(clock, min(end, wm)):
                row = self.store.find(client, k)
                if row is None:
                    pend_into.add(client, k)
                else:
                    self._delete_row(row)
            if end > wm:
                tail = max(clock, wm)
                pend_into.add(client, tail, end - tail)

    def _try_integrate(self, rec: ItemRecord) -> bool:
        handled, row = self._try_admit(rec)
        if handled and row is not None:
            self._integrate_into_chain(row, rec)
        return handled

    def _try_admit(self, rec: ItemRecord) -> Tuple[bool, Optional[int]]:
        """Admission bookkeeping without chain integration: dedup, clock
        contiguity, dependency check, parent resolution, store append.

        Returns (handled, row): ``handled`` False means the record must
        wait (missing deps / clock gap); ``row`` is the new store row,
        or None when nothing needs chain integration (duplicates, GC
        fillers). The device merge path admits whole batches through
        this and rebuilds chain state with the kernels instead of the
        per-record scan (crdt.js:294's loop, vectorized)."""
        s = self.store
        # duplicate (already integrated) -> drop (idempotent merge)
        if s.has(rec.client, rec.clock):
            return True, None
        # clock contiguity per client
        if rec.clock != self._next_clock.get(rec.client, 0):
            if rec.clock < self._next_clock.get(rec.client, 0):
                return True, None  # stale duplicate below watermark
            return False, None
        # dependencies known?
        for dep in rec.dep_ids():
            if not s.has(*dep):
                return False, None
        if rec.kind == K_GC:
            # positional info is gone; record clock coverage only
            row = s.add_item(
                rec.client, rec.clock, kind=K_GC, content=None, deleted=True
            )
            self._next_clock[rec.client] = rec.clock + 1
            self.last_txn_items.append(row)
            return True, None
        # resolve parent
        if rec.parent_root is not None:
            spec: ParentSpec = ("root", s.intern_root(rec.parent_root))
            self.root_kinds.setdefault(
                rec.parent_root, "map" if rec.key is not None else "array"
            )
        elif rec.parent_item is not None:
            spec = ("item", rec.parent_item[0], rec.parent_item[1])
        else:
            # parent implied by origin's parent (Yjs omits parent info when
            # an origin is present)
            oid = rec.origin if rec.origin is not None else rec.right
            assert oid is not None, "record without parent or origin"
            orow = s.find(*oid)
            spec = self._parent_spec_of_row(orow)
            if rec.key is None and s.key_id[orow] != NO_KEY:
                rec.key = s.keys[int(s.key_id[orow])]
        row = s.add_item(
            rec.client,
            rec.clock,
            parent_root=spec[1] if spec[0] == "root" else NULL,
            parent_id=(spec[1], spec[2]) if spec[0] == "item" else (NULL, NULL),
            key_id=s.intern_key(rec.key) if rec.key is not None else NO_KEY,
            origin=rec.origin or (NULL, NULL),
            right=rec.right or (NULL, NULL),
            kind=rec.kind,
            type_ref=rec.type_ref if rec.type_ref is not None else NULL,
            content=rec.content,
            deleted=rec.kind in (K_DELETED, K_GC),
        )
        self._next_clock[rec.client] = rec.clock + 1
        self.last_txn_items.append(row)
        return True, row

    def _integrate_into_chain(self, row: int, rec: ItemRecord) -> None:
        """YATA conflict resolution: faithful port of the integrate scan."""
        s = self.store
        heads, tails, ckey = self._chain_of_row(row)
        head = heads.get(ckey, NULL)

        origin_row = s.find(*rec.origin) if rec.origin is not None else None
        left = origin_row
        right = s.find(*rec.right) if rec.right is not None else None

        o = self._next.get(left, NULL) if left is not None else head
        conflicting: set = set()
        items_before_origin: set = set()
        while o != NULL and (right is None or o != right):
            items_before_origin.add(o)
            conflicting.add(o)
            o_origin = (int(s.origin_client[o]), int(s.origin_clock[o]))
            o_origin_row = (
                s.find(*o_origin) if o_origin != (NULL, NULL) else None
            )
            if o_origin_row == origin_row:
                # case 1: same left origin as ours -> order by client id
                if int(s.client[o]) < rec.client:
                    left = o
                    conflicting.clear()
                else:
                    o_right = (int(s.right_client[o]), int(s.right_clock[o]))
                    my_right = rec.right if rec.right is not None else (NULL, NULL)
                    if o_right == my_right:
                        break
            elif o_origin_row is not None and o_origin_row in items_before_origin:
                # case 2: o's origin is inside the scanned region
                if o_origin_row not in conflicting:
                    left = o
                    conflicting.clear()
            else:
                break
            o = self._next.get(o, NULL)

        # splice after `left` (or at head)
        if left is not None:
            nxt = self._next.get(left, NULL)
            self._next[left] = row
            self._prev[row] = left
        else:
            nxt = head
            heads[ckey] = row
            self._prev[row] = NULL
        self._next[row] = nxt
        if nxt != NULL:
            self._prev[nxt] = row
        else:
            tails[ckey] = row

        # map-entry bookkeeping (Yjs Item.integrate): an item landing at
        # the chain tail becomes the visible entry and tombstones its
        # left neighbor; an item landing with a right neighbor lost the
        # race and is tombstoned itself. Both sides of a concurrent set
        # therefore derive the same delete set from the same op set.
        if int(s.key_id[row]) != NO_KEY:
            self._map_kids.setdefault(ckey[0], {})[ckey[1]] = None
            if self._next[row] == NULL:
                if left is not None and not s.deleted[left]:
                    self._delete_row(left)
            else:
                self._delete_row(row)

    # ------------------------------------------------------------------
    # materialization
    # ------------------------------------------------------------------
    def _value_of_row(self, row: int) -> Any:
        s = self.store
        if s.kind[row] == K_TYPE:
            spec = ("item", int(s.client[row]), int(s.clock[row]))
            if s.type_ref[row] == TYPE_MAP:
                return self._map_json(spec)
            return self._seq_json(spec)
        return s.content[row]

    def _map_json(self, spec: ParentSpec) -> Dict[str, Any]:
        out = {}
        for kid in self._map_kids.get(spec, ()):
            tail = self._map_tail.get((spec, kid))
            if tail is not None and not self.store.deleted[tail]:
                out[self.store.keys[kid]] = self._value_of_row(tail)
        return out

    def _seq_json(self, spec: ParentSpec) -> List[Any]:
        out = []
        row = self._seq_head.get(spec, NULL)
        while row != NULL:
            if self._is_countable(row):
                out.append(self._value_of_row(row))
            row = self._next.get(row, NULL)
        return out

    def map_json(self, name: str) -> Dict[str, Any]:
        rid = self.store.root_id(name)
        if rid is None:
            return {}
        return self._map_json(("root", rid))

    def seq_json(self, name: str) -> List[Any]:
        rid = self.store.root_id(name)
        if rid is None:
            return []
        return self._seq_json(("root", rid))

    def map_get(self, name: str, key: str) -> Any:
        """Visible value for key, or None (the `get` the README promised
        but the reference never shipped — SURVEY.md D7)."""
        rid = self.store.root_id(name)
        kid = self.store.key_id_of(key)
        if rid is None or kid is None:
            return None
        tail = self._map_tail.get((("root", rid), kid))
        if tail is None or self.store.deleted[tail]:
            return None
        return self._value_of_row(tail)

    def map_has(self, name: str, key: str) -> bool:
        """Whether the key has a VISIBLE entry — distinguishes a stored
        None value from an absent/tombstoned key (map_get can't)."""
        rid = self.store.root_id(name)
        kid = self.store.key_id_of(key)
        if rid is None or kid is None:
            return False
        tail = self._map_tail.get((("root", rid), kid))
        return tail is not None and not bool(self.store.deleted[tail])

    def map_entry_spec(self, name: str, key: str) -> Optional[ParentSpec]:
        """Parent spec of the visible nested type under (name, key)."""
        rid = self.store.root_id(name)
        kid = self.store.key_id_of(key)
        if rid is None or kid is None:
            return None
        tail = self._map_tail.get((("root", rid), kid))
        if tail is None or self.store.deleted[tail]:
            return None
        if self.store.kind[tail] != K_TYPE:
            return None
        return ("item", int(self.store.client[tail]), int(self.store.clock[tail]))

    def _public_parent(self, spec: ParentSpec) -> Tuple:
        """Interned parent spec -> the symbolic parent key used by the
        kernel wrappers: ("root", name) or ("item", client, clock)."""
        if spec[0] == "root":
            return ("root", self.store.root_names[spec[1]])
        return ("item", spec[1], spec[2])

    def seq_order_table(self) -> Dict[Tuple, List[Tuple[int, int]]]:
        """{parent: [item ids in chain order, tombstones included]} for
        every sequence — the oracle view the YATA kernel is tested
        against."""
        out: Dict[Tuple, List[Tuple[int, int]]] = {}
        for spec, head in self._seq_head.items():
            parent = self._public_parent(spec)
            ids = []
            row = head
            while row != NULL:
                ids.append(self.store.id_of(row))
                row = self._next.get(row, NULL)
            out[parent] = ids
        return out

    def map_winner_table(self) -> Dict[Tuple, Tuple[Tuple[int, int], bool]]:
        """{(parent, key): (winner id, visible)} over every map chain —
        the oracle view the LWW kernel is differential-tested against.
        Parent is ("root", name) or ("item", client, clock)."""
        out: Dict[Tuple, Tuple[Tuple[int, int], bool]] = {}
        for (spec, kid), tail in self._map_tail.items():
            parent = self._public_parent(spec)
            out[(parent, self.store.keys[kid])] = (
                self.store.id_of(tail),
                not bool(self.store.deleted[tail]),
            )
        return out

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for name, kind in self.root_kinds.items():
            out[name] = self.map_json(name) if kind == "map" else self.seq_json(name)
        return out

    # ------------------------------------------------------------------
    # export for codec / kernels
    # ------------------------------------------------------------------
    def record_of_row(self, row: int) -> ItemRecord:
        """Symbolic record for one store row."""
        s = self.store
        parent_root = (
            s.root_names[int(s.parent_root[row])]
            if s.parent_root[row] != NULL
            else None
        )
        parent_item = (
            (int(s.parent_client[row]), int(s.parent_clock[row]))
            if s.parent_root[row] == NULL and s.parent_client[row] != NULL
            else None
        )
        origin = (
            (int(s.origin_client[row]), int(s.origin_clock[row]))
            if s.origin_client[row] != NULL
            else None
        )
        right = (
            (int(s.right_client[row]), int(s.right_clock[row]))
            if s.right_client[row] != NULL
            else None
        )
        key = s.keys[int(s.key_id[row])] if s.key_id[row] != NO_KEY else None
        return ItemRecord(
            client=int(s.client[row]),
            clock=int(s.clock[row]),
            parent_root=parent_root,
            parent_item=parent_item,
            key=key,
            origin=origin,
            right=right,
            kind=int(s.kind[row]),
            type_ref=int(s.type_ref[row]),
            content=s.content[row],
        )

    def records_for_rows(self, rows) -> List[ItemRecord]:
        """Records for specific rows, (client, clock)-sorted — O(len)
        txn-delta extraction (vs records_since's full-store scan)."""
        out = [self.record_of_row(row) for row in rows]
        out.sort(key=lambda r: (r.client, r.clock))
        return out

    def to_decoded_columns(self, ds: Optional[DeleteSet] = None) -> dict:
        """The whole store in the decode column schema (client-grouped,
        clock-ascending — the wire's run order): the seam for the
        native ``encode_from_columns`` snapshot path. The store is
        already SoA numpy, so a full-state encode is one lexsort + one
        C pass instead of an O(doc) ``record_of_row`` walk — the same
        unification the resident replay has
        (``IncrementalReplay.to_decoded_columns``). ``ds`` lets the
        caller reuse an already-computed delete set (building one is
        an O(store) scan). Match: north star 'snapshot rebuild through
        the same kernel'; /root/reference/crdt.js:79-98."""
        import numpy as np

        from crdt_tpu.codec.native import ds_to_triples

        s = self.store
        n = s.n
        order = np.lexsort((s.clock[:n], s.client[:n]))
        cols = {
            name: getattr(s, name)[:n][order]
            for name in (
                "client", "clock", "parent_client", "parent_clock",
                "origin_client", "origin_clock", "right_client",
                "right_clock",
            )
        }
        cols.update(
            parent_root=s.parent_root[:n][order].astype(np.int32),
            key_id=s.key_id[:n][order].astype(np.int32),
            kind=s.kind[:n][order].astype(np.int32),
            type_ref=s.type_ref[:n][order].astype(np.int32),
            contents=[s.content[int(r)] for r in order],
            roots=list(s.root_names),
            keys=list(s.keys),
            ds=ds_to_triples(ds if ds is not None else self.delete_set()),
        )
        return cols

    def records_since(self, sv: Optional[StateVector] = None) -> List[ItemRecord]:
        """All records with clock >= sv[client] (full state when sv None).

        O(delta) via the store's per-client clock-sorted row index: a
        ready-probe on a large doc touches only the rows the requester
        lacks, not the whole store (the reference's syncer re-encodes a
        full diff per probe, crdt.js:288)."""
        from bisect import bisect_left

        s = self.store
        if sv is None:
            out = [self.record_of_row(row) for row in range(s.n)]
        else:
            out = []
            for client, rows in s.client_rows.items():
                wm = sv.get(client)
                if not wm:
                    out.extend(self.record_of_row(r) for r in rows)
                    continue
                # rows are clock-ascending per client
                start = bisect_left(rows, wm, key=lambda r: int(s.clock[r]))
                out.extend(self.record_of_row(r) for r in rows[start:])
        out.sort(key=lambda r: (r.client, r.clock))
        return out
