"""Columnar (struct-of-arrays) item store.

The reference's CRDT state lives inside Yjs's linked-list-of-Items heap
(`Y.Doc`, crdt.js:221). Rebuilding TPU-first, the equivalent state is a
struct-of-arrays table of unit items — one row per (client, clock) — so
merge work (dedup against state vectors, LWW winner selection, YATA
ordering, delete-set application, cache gathers) is vectorizable over
rows. Strings/values live in a host-side content table; device kernels
see only integer columns.

Schema per row (all unit-length items; Yjs runs are split on ingest and
re-coalesced on encode):

  client, clock        : item ID
  parent_root          : interned root-collection name id, or -1
  parent_client/clock  : parent item ID when nested (parent_root == -1)
  key_id               : interned map key id, -1 for sequence items
  origin_client/clock  : YATA left origin ID, (-1,-1) if none
  right_client/clock   : YATA right origin ID, (-1,-1) if none
  kind                 : content kind (ANY/TYPE/DELETED/JSON/BINARY/STRING/GC)
  type_ref             : for kind==TYPE: 0=YArray, 1=YMap (Yjs typeRefs)
  deleted              : tombstone flag
  content[row]         : host Python value (ANY/JSON payload, str char, bytes)
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from crdt_tpu.core.ids import DeleteSet, StateVector

# content kinds (host-side; NOT the same numbering as wire content refs)
K_GC = 0
K_DELETED = 1
K_JSON = 2
K_BINARY = 3
K_STRING = 4
K_ANY = 5
K_TYPE = 6
# YText/subdoc payloads: carried for codec fidelity, not materialized
K_EMBED = 7
K_FORMAT = 8
K_DOC = 9

# Yjs type refs used by ContentType
TYPE_ARRAY = 0
TYPE_MAP = 1

ROOT_PARENT = -1
NO_KEY = -1
NULL = -1

_INT_COLS = (
    "client",
    "clock",
    "parent_root",
    "parent_client",
    "parent_clock",
    "key_id",
    "origin_client",
    "origin_clock",
    "right_client",
    "right_clock",
    "kind",
    "type_ref",
    "deleted",
)


class ItemStore:
    """Growable SoA table of unit items plus name/key interning."""

    def __init__(self, capacity: int = 1024):
        self._cap = max(capacity, 16)
        self.n = 0
        for col in _INT_COLS:
            setattr(self, col, np.full(self._cap, NULL, dtype=np.int64))
        self.content: List[Any] = []
        # interning tables; shared namespace semantics follow Yjs root types
        self.root_names: List[str] = []
        self._root_ids: Dict[str, int] = {}
        self.keys: List[str] = []
        self._key_ids: Dict[str, int] = {}
        self._id_index: Dict[Tuple[int, int], int] = {}
        # client -> rows in clock-ascending order (integration adds each
        # client's items with monotonically increasing clocks), so an
        # SV-diff can binary-search per client instead of scanning the
        # whole store (the reference recomputes full-doc diffs per sync,
        # crdt.js:288; at 100k items that is the difference between an
        # O(delta) and an O(doc) ready-probe)
        self.client_rows: Dict[int, List[int]] = {}

    # -- interning ---------------------------------------------------------
    def intern_root(self, name: str) -> int:
        rid = self._root_ids.get(name)
        if rid is None:
            rid = len(self.root_names)
            self.root_names.append(name)
            self._root_ids[name] = rid
        return rid

    def intern_key(self, key: str) -> int:
        kid = self._key_ids.get(key)
        if kid is None:
            kid = len(self.keys)
            self.keys.append(key)
            self._key_ids[key] = kid
        return kid

    def root_id(self, name: str) -> Optional[int]:
        return self._root_ids.get(name)

    def key_id_of(self, key: str) -> Optional[int]:
        return self._key_ids.get(key)

    # -- rows --------------------------------------------------------------
    def _grow(self) -> None:
        new_cap = self._cap * 2
        for col in _INT_COLS:
            arr = getattr(self, col)
            new = np.full(new_cap, NULL, dtype=np.int64)
            new[: self.n] = arr[: self.n]
            setattr(self, col, new)
        self._cap = new_cap

    def add_item(
        self,
        client: int,
        clock: int,
        *,
        parent_root: int = NULL,
        parent_id: Tuple[int, int] = (NULL, NULL),
        key_id: int = NO_KEY,
        origin: Tuple[int, int] = (NULL, NULL),
        right: Tuple[int, int] = (NULL, NULL),
        kind: int = K_ANY,
        type_ref: int = NULL,
        content: Any = None,
        deleted: bool = False,
    ) -> int:
        if (client, clock) in self._id_index:
            raise ValueError(f"duplicate item id ({client},{clock})")
        if self.n == self._cap:
            self._grow()
        i = self.n
        self.n += 1
        self.client[i] = client
        self.clock[i] = clock
        self.parent_root[i] = parent_root
        self.parent_client[i], self.parent_clock[i] = parent_id
        self.key_id[i] = key_id
        self.origin_client[i], self.origin_clock[i] = origin
        self.right_client[i], self.right_clock[i] = right
        self.kind[i] = kind
        self.type_ref[i] = type_ref
        self.deleted[i] = 1 if (deleted or kind in (K_DELETED, K_GC)) else 0
        self.content.append(content)
        self._id_index[(client, clock)] = i
        self.client_rows.setdefault(client, []).append(i)
        return i

    def find(self, client: int, clock: int) -> Optional[int]:
        return self._id_index.get((client, clock))

    def has(self, client: int, clock: int) -> bool:
        return (client, clock) in self._id_index

    def id_of(self, row: int) -> Tuple[int, int]:
        return (int(self.client[row]), int(self.clock[row]))

    def mark_deleted(self, row: int) -> None:
        self.deleted[row] = 1

    # -- aggregates --------------------------------------------------------
    def state_vector(self) -> StateVector:
        """Contiguous-prefix state vector: {client: k} claims clocks [0, k).

        Only the gap-free prefix per client is reported, so a store that
        received out-of-order clocks never claims knowledge it lacks
        (integration layers keep clocks contiguous via pending queues;
        this aggregate stays honest regardless). One vectorized pass.
        """
        sv = StateVector()
        if not self.n:
            return sv
        clients = self.client[: self.n]
        clocks = self.clock[: self.n]
        order = np.lexsort((clocks, clients))
        sc, sk = clients[order], clocks[order]
        starts = np.flatnonzero(np.r_[True, sc[1:] != sc[:-1]])
        ends = np.r_[starts[1:], len(sc)]
        # within each client segment, prefix length = #leading i with clock==i
        contiguous = sk == (np.arange(len(sk)) - np.repeat(starts, ends - starts))
        for s, e in zip(starts, ends):
            seg = contiguous[s:e]
            k = int(np.argmin(seg)) if not seg.all() else e - s
            if k:
                sv.clocks[int(sc[s])] = k
        return sv

    def delete_set(self) -> DeleteSet:
        """Vectorized: sort deleted (client, clock) rows, emit run ranges."""
        ds = DeleteSet()
        rows = np.flatnonzero(self.deleted[: self.n])
        if not len(rows):
            return ds
        clients = self.client[rows]
        clocks = self.clock[rows]
        order = np.lexsort((clocks, clients))
        sc, sk = clients[order], clocks[order]
        breaks = np.r_[True, (sc[1:] != sc[:-1]) | (sk[1:] != sk[:-1] + 1)]
        starts = np.flatnonzero(breaks)
        ends = np.r_[starts[1:], len(sc)]
        for s, e in zip(starts, ends):
            ds.ranges.setdefault(int(sc[s]), []).append(
                (int(sk[s]), int(sk[e - 1]) + 1)
            )
        return ds

    def columns(self) -> Dict[str, np.ndarray]:
        """Dense copies of the integer columns (length n) for device use."""
        return {col: getattr(self, col)[: self.n].copy() for col in _INT_COLS}

    def __len__(self) -> int:
        return self.n

    def __repr__(self) -> str:
        return (
            f"ItemStore(n={self.n}, roots={len(self.root_names)}, "
            f"keys={len(self.keys)})"
        )
