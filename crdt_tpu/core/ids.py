"""Identifier, state-vector, and delete-set primitives.

The reference delegates these concepts to Yjs (used via
``Y.encodeStateVector`` / delete sets inside updates, crdt.js:59,239,258).
Here they are first-class host types with exact semantics:

- ``ID``: (client, clock). ``clock`` is the per-client item counter —
  the n-th item created by a client has clock n (unit-length items).
- ``StateVector``: client -> next expected clock (== number of clocks
  seen from that client). Yjs semantics: a state vector of {c: k} means
  clocks [0, k) from client c are known.
- ``DeleteSet``: client -> sorted, merged list of [clock, clock+len)
  ranges of deleted items.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Tuple

NULL_ID = (-1, -1)


@dataclass(frozen=True, order=True)
class ID:
    client: int
    clock: int

    def as_tuple(self) -> Tuple[int, int]:
        return (self.client, self.clock)


class StateVector:
    """client -> next clock. Missing client == 0 clocks known."""

    __slots__ = ("clocks",)

    def __init__(self, clocks: Dict[int, int] | None = None):
        self.clocks: Dict[int, int] = dict(clocks or {})

    def get(self, client: int) -> int:
        return self.clocks.get(client, 0)

    def observe(self, client: int, clock: int, length: int = 1) -> None:
        """Record that clocks [clock, clock+length) from `client` are known."""
        end = clock + length
        if end > self.clocks.get(client, 0):
            self.clocks[client] = end

    def covers(self, client: int, clock: int) -> bool:
        return clock < self.clocks.get(client, 0)

    def merge(self, other: "StateVector") -> "StateVector":
        out = StateVector(self.clocks)
        for c, k in other.clocks.items():
            if k > out.clocks.get(c, 0):
                out.clocks[c] = k
        return out

    def diff_dominates(self, other: "StateVector") -> bool:
        """True if self >= other componentwise."""
        return all(self.get(c) >= k for c, k in other.clocks.items())

    def copy(self) -> "StateVector":
        return StateVector(self.clocks)

    def __eq__(self, other) -> bool:
        if not isinstance(other, StateVector):
            return NotImplemented
        a = {c: k for c, k in self.clocks.items() if k > 0}
        b = {c: k for c, k in other.clocks.items() if k > 0}
        return a == b

    def __repr__(self) -> str:
        return f"StateVector({self.clocks!r})"


def _merge_ranges(ranges: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    """Sort and coalesce half-open [start, end) ranges."""
    if not ranges:
        return []
    ranges = sorted(ranges)
    out = [ranges[0]]
    for s, e in ranges[1:]:
        ls, le = out[-1]
        if s <= le:
            out[-1] = (ls, max(le, e))
        else:
            out.append((s, e))
    return out


@dataclass
class DeleteSet:
    """client -> sorted half-open [start, end) deleted-clock ranges.

    Ranges are coalesced lazily: ``add`` marks the set dirty and every
    reader normalizes first, so the sorted-disjoint invariant queries
    rely on always holds.
    """

    ranges: Dict[int, List[Tuple[int, int]]] = field(default_factory=dict)
    _dirty: bool = False

    def add(self, client: int, clock: int, length: int = 1) -> None:
        self.ranges.setdefault(client, []).append((clock, clock + length))
        self._dirty = True

    def normalize(self) -> None:
        if not self._dirty:
            # still drop empty clients inserted externally
            for c in [c for c, r in self.ranges.items() if not r]:
                del self.ranges[c]
            return
        for c in list(self.ranges):
            merged = _merge_ranges(self.ranges[c])
            if merged:
                self.ranges[c] = merged
            else:
                del self.ranges[c]
        self._dirty = False

    def covers(self, client: int, clock: int, length: int = 1) -> bool:
        """True when [clock, clock+length) lies inside ONE recorded
        range (ranges are normalized disjoint, so full coverage
        requires a single containing range)."""
        if self._dirty:
            self.normalize()
        rs = self.ranges.get(client)
        if not rs:
            return False
        end = clock + length
        lo, hi = 0, len(rs)
        while lo < hi:
            mid = (lo + hi) // 2
            s, e = rs[mid]
            if clock < s:
                hi = mid
            elif clock >= e:
                lo = mid + 1
            else:
                return end <= e
        return False

    def contains(self, client: int, clock: int) -> bool:
        return self.covers(client, clock, 1)

    def merge(self, other: "DeleteSet") -> "DeleteSet":
        out = DeleteSet({c: list(r) for c, r in self.ranges.items()})
        for c, rs in other.ranges.items():
            out.ranges.setdefault(c, []).extend(rs)
        out._dirty = True
        out.normalize()
        return out

    def iter_all(self) -> Iterator[Tuple[int, int, int]]:
        """Yield (client, clock, length) for every range, clients sorted."""
        if self._dirty:
            self.normalize()
        for c in sorted(self.ranges):
            for s, e in self.ranges[c]:
                yield (c, s, e - s)

    def copy(self) -> "DeleteSet":
        out = DeleteSet({c: list(r) for c, r in self.ranges.items()})
        out._dirty = self._dirty
        return out

    def __eq__(self, other) -> bool:
        if not isinstance(other, DeleteSet):
            return NotImplemented
        a, b = self.copy(), other.copy()
        a.normalize()
        b.normalize()
        return a.ranges == b.ranges
