"""Durable update log with the reference's keyspace semantics.

Mirrors `CRDTPersistence` (/root/reference/crdt.js:5-141) over the
native kvlog store instead of LevelDB:

  doc_<name>_update_<seq>  append-only update log   (crdt.js:41-42,61)
  doc_<name>_sv            latest state vector      (crdt.js:62)
  doc_<name>_meta          JSON {last_updated,size} (crdt.js:63-70)

All three written in one atomic batch per update, like the reference's
3-key LevelDB batch (crdt.js:60-71). Documented fixes (SURVEY.md §6):

- D5: the stored state vector is the caller's *accumulated* vector —
  the reference recomputes it by diffing an empty doc and stores
  garbage (crdt.js:54-59).
- D6: log keys are zero-padded monotonic sequence numbers, not
  `Date.now()` — two updates in the same millisecond no longer
  overwrite each other (crdt.js:41-42).
- Q3: `compact()` exists — squashes the log to a single snapshot
  update so startup replay is O(state), not O(history). The reference
  replays its entire unbounded log (crdt.js:79-98).

Updates are validated by decoding before hitting the log (the
reference applies each update to a throwaway Y.Doc for the same
purpose, crdt.js:33-40).
"""

from __future__ import annotations

import json
import time
from typing import List, Optional

from crdt_tpu.obs.tracer import get_tracer
from crdt_tpu.storage.kv import Batch, KvLog


def _esc(doc: str) -> str:
    # doc names are caller-chosen: a raw name containing "_update_"
    # would collide with another doc's log prefix (e.g. doc "a" vs doc
    # "a_update_0"). Percent-escape "_" so the literal separators below
    # are the only underscores in any key.
    return doc.replace("%", "%25").replace("_", "%5f")


def _update_key(doc: str, seq: int) -> bytes:
    # 20 digits: lexicographic order == numeric order for any int64
    return f"doc_{_esc(doc)}_update_{seq:020d}".encode()


def _update_prefix(doc: str) -> bytes:
    return f"doc_{_esc(doc)}_update_".encode()


def _sv_key(doc: str) -> bytes:
    return f"doc_{_esc(doc)}_sv".encode()


def _meta_key(doc: str) -> bytes:
    return f"doc_{_esc(doc)}_meta".encode()


class LogPersistence:
    """Drop-in for :class:`crdt_tpu.net.replica.MemoryPersistence`,
    backed by the native store. One kvlog file may hold many docs (the
    reference opens one LevelDB per path; the keyspace is already
    doc-prefixed so sharing is safe and cheaper)."""

    def __init__(self, path: str, *, validate: bool = True):
        self.path = str(path)
        self.validate = validate
        self._kv: Optional[KvLog] = KvLog(self.path)
        self._next_seq: dict = {}

    # -- lifecycle ---------------------------------------------------------
    @property
    def closed(self) -> bool:
        return self._kv is None

    def open(self) -> None:
        if self._kv is None:
            self._kv = KvLog(self.path)
            self._next_seq.clear()

    def close(self) -> None:
        if self._kv is not None:
            self._kv.sync()
            self._kv.close()
            self._kv = None

    def _require(self) -> KvLog:
        if self._kv is None:
            raise RuntimeError("persistence is closed")
        return self._kv

    def _seq_for(self, doc: str) -> int:
        seq = self._next_seq.get(doc)
        if seq is None:
            # resume after the highest logged sequence (scan once)
            seq = 0
            last = None
            for k, _ in self._require().scan_prefix(_update_prefix(doc)):
                last = k
            if last is not None:
                seq = int(last.rsplit(b"_", 1)[1]) + 1
        self._next_seq[doc] = seq + 1
        return seq

    # -- the CRDTPersistence surface --------------------------------------
    def store_update(self, doc_name: str, update: bytes, sv: Optional[bytes] = None) -> None:
        self.store_updates(doc_name, [update], sv=sv)

    def store_updates(self, doc_name: str, updates: List[bytes],
                      sv: Optional[bytes] = None) -> None:
        """Append a WINDOW of updates as ONE atomic KV batch — N log
        keys, one state vector, one meta write, one fsync-able log
        append. This is the batched-incoming path's WAL shape
        (``Replica.flush_incoming`` applies a whole inbox as one merge
        transaction; before this, each update still paid its own
        3-key batch + meta read-modify-write). Counters distinguish
        units from windows: ``persist.appends`` counts updates,
        ``persist.batches`` counts KV batches."""
        # materialize FIRST: a generator argument must survive the
        # validation pass (iterating it twice would silently store
        # nothing while still advancing the SV)
        updates = list(updates)
        for u in updates:
            if not isinstance(u, (bytes, bytearray)):
                raise TypeError("update must be bytes")  # crdt.js:29-31
        updates = [bytes(u) for u in updates]
        if not updates:
            return
        if self.validate:
            from crdt_tpu.codec import v1

            for u in updates:
                v1.decode_update(u)  # raises on malformed input
        kv = self._require()
        tracer = get_tracer()
        with tracer.span("persist"):
            batch = Batch()
            for u in updates:
                batch.put(_update_key(doc_name, self._seq_for(doc_name)), u)
            if sv is not None:
                batch.put(_sv_key(doc_name), bytes(sv))
            meta = self.get_meta(doc_name) or {"size": 0, "count": 0}
            batch.put(
                _meta_key(doc_name),
                json.dumps(
                    {
                        "last_updated": time.time(),
                        "size": meta["size"] + sum(map(len, updates)),
                        "count": meta["count"] + len(updates),
                    }
                ).encode(),
            )
            kv.write(batch)
        tracer.count("persist.appends", len(updates))
        tracer.count("persist.batches")
        tracer.count("persist.bytes_appended", sum(map(len, updates)))

    def get_all_updates(self, doc_name: str) -> List[bytes]:
        return [v for _, v in self._require().scan_prefix(_update_prefix(doc_name))]

    def get_state_vector(self, doc_name: str) -> Optional[bytes]:
        return self._require().get(_sv_key(doc_name))

    def get_meta(self, doc_name: str) -> Optional[dict]:
        raw = self._require().get(_meta_key(doc_name))
        return json.loads(raw) if raw is not None else None

    def compact(self, doc_name: str, snapshot: bytes, sv: Optional[bytes] = None) -> None:
        """Replace the doc's update log with one snapshot update, then
        drop dead log history from disk."""
        kv = self._require()
        tracer = get_tracer()
        with tracer.span("persist.compact"):
            batch = Batch()
            for k in kv.keys(_update_prefix(doc_name)):
                batch.delete(k)
            batch.put(_update_key(doc_name, 0), bytes(snapshot))
            if sv is not None:
                batch.put(_sv_key(doc_name), bytes(sv))
            batch.put(
                _meta_key(doc_name),
                json.dumps(
                    {"last_updated": time.time(), "size": len(snapshot), "count": 1}
                ).encode(),
            )
            kv.write(batch)
            self._next_seq[doc_name] = 1
            # reclaim disk only when dead history dominates: kv.compact()
            # rewrites the WHOLE shared store, so an unconditional call
            # would make N docs' auto-compaction O(store) each — amortize
            # against live size instead (LevelDB's own trigger is
            # similarly ratio-based)
            if kv.log_size > 4 * max(kv.live_size, 1):
                kv.compact()
        tracer.count("persist.compactions")
        tracer.gauge("persist.log_size_bytes", kv.log_size)

    # -- maintenance -------------------------------------------------------
    def sync(self) -> None:
        self._require().sync()

    @property
    def log_size(self) -> int:
        return self._require().log_size
