"""Durable update log with the reference's keyspace semantics.

Mirrors `CRDTPersistence` (/root/reference/crdt.js:5-141) over the
native kvlog store instead of LevelDB:

  doc_<name>_update_<seq>  append-only update log   (crdt.js:41-42,61)
  doc_<name>_sv            latest state vector      (crdt.js:62)
  doc_<name>_meta          JSON {last_updated,size} (crdt.js:63-70)

All three written in one atomic batch per update, like the reference's
3-key LevelDB batch (crdt.js:60-71). Documented fixes (SURVEY.md §6):

- D5: the stored state vector is the caller's *accumulated* vector —
  the reference recomputes it by diffing an empty doc and stores
  garbage (crdt.js:54-59).
- D6: log keys are zero-padded monotonic sequence numbers, not
  `Date.now()` — two updates in the same millisecond no longer
  overwrite each other (crdt.js:41-42).
- Q3: `compact()` exists — squashes the log to a single snapshot
  update so startup replay is O(state), not O(history). The reference
  replays its entire unbounded log (crdt.js:79-98).

Updates are validated by decoding before hitting the log (the
reference applies each update to a throwaway Y.Doc for the same
purpose, crdt.js:33-40).
"""

from __future__ import annotations

import json
import time
from typing import List, Optional

from crdt_tpu.obs.tracer import get_tracer
from crdt_tpu.storage.kv import Batch, KvLog


def _esc(doc: str) -> str:
    # doc names are caller-chosen: a raw name containing "_update_"
    # would collide with another doc's log prefix (e.g. doc "a" vs doc
    # "a_update_0"). Percent-escape "_" so the literal separators below
    # are the only underscores in any key.
    return doc.replace("%", "%25").replace("_", "%5f")


def _update_key(doc: str, seq: int) -> bytes:
    # 20 digits: lexicographic order == numeric order for any int64
    return f"doc_{_esc(doc)}_update_{seq:020d}".encode()


def _update_prefix(doc: str) -> bytes:
    return f"doc_{_esc(doc)}_update_".encode()


def _sv_key(doc: str) -> bytes:
    return f"doc_{_esc(doc)}_sv".encode()


def _meta_key(doc: str) -> bytes:
    return f"doc_{_esc(doc)}_meta".encode()


# Process-wide degraded registry: the ``persist.degraded`` gauge counts
# currently-degraded (store, doc) windows across EVERY LogPersistence
# in the process (they share one tracer), so one store's recovery can
# never mask another store's still-active degradation. 0 = all clear.
_DEGRADED: set = set()


def _set_degraded(store, doc_name: str, on: bool) -> None:
    key = (id(store), doc_name)
    if on:
        _DEGRADED.add(key)
    else:
        _DEGRADED.discard(key)
    get_tracer().gauge("persist.degraded", len(_DEGRADED))


class LogPersistence:
    """Drop-in for :class:`crdt_tpu.net.replica.MemoryPersistence`,
    backed by the native store. One kvlog file may hold many docs (the
    reference opens one LevelDB per path; the keyspace is already
    doc-prefixed so sharing is safe and cheaper).

    Failure policy (crdt_tpu/guard): a failed KV batch retries with
    backoff (``retries`` x ``retry_backoff_s``, ``persist.retries``
    counter), then — under the default ``failure_policy="degrade"`` —
    the window lands in a BOUNDED in-memory overflow buffer
    (``overflow_max_bytes``, enforced across every doc the store
    buffers; the ``persist.degraded`` gauge counts currently-degraded
    (store, doc) windows process-wide, 0 = all clear) instead of
    raising into the apply path. The buffer drains into the
    next successful write (one batch, followed by ``sync()``;
    ``persist.recovered_updates``), and reads (``get_all_updates`` /
    ``get_state_vector``) see buffered state meanwhile, so replicas
    syncing FROM persistence never observe the outage. Past the bound
    the OLDEST buffered updates drop (``persist.dropped_updates`` —
    visible, bounded, and only lossy if the process dies while the
    disk is still down). ``failure_policy="raise"`` restores the
    historical propagate-everything behavior.

    ``kv_wrapper`` is the fault-injection seam: a callable applied to
    every freshly opened :class:`KvLog` (e.g. ``lambda kv:
    FaultyKv(kv, schedule)`` — :mod:`crdt_tpu.guard.faults`), so
    seeded ENOSPC/EIO/torn-batch/crash schedules survive close/open
    cycles."""

    def __init__(self, path: str, *, validate: bool = True,
                 retries: int = 2, retry_backoff_s: float = 0.01,
                 failure_policy: str = "degrade",
                 overflow_max_bytes: int = 4 << 20,
                 kv_wrapper=None):
        if failure_policy not in ("degrade", "raise"):
            raise ValueError(f"unknown failure_policy {failure_policy!r}")
        self.path = str(path)
        self.validate = validate
        self.retries = retries
        self.retry_backoff_s = retry_backoff_s
        self.failure_policy = failure_policy
        self.overflow_max_bytes = overflow_max_bytes
        self._kv_wrapper = kv_wrapper
        self._kv: Optional[KvLog] = None
        self._next_seq: dict = {}
        self._overflow: dict = {}      # doc -> [update bytes]
        self._overflow_sv: dict = {}   # doc -> latest sv bytes
        self._overflow_bytes = 0
        self._kv = self._make_kv()

    def _make_kv(self):
        kv = KvLog(self.path)
        return self._kv_wrapper(kv) if self._kv_wrapper else kv

    # -- lifecycle ---------------------------------------------------------
    @property
    def closed(self) -> bool:
        return self._kv is None

    def open(self) -> None:
        if self._kv is None:
            self._kv = self._make_kv()
            # _next_seq is derived from the log scan on every open:
            # a cached value can be stale after a crashed compact
            # (satellite fix, round 10 — see _seq_for/compact)
            self._next_seq.clear()

    def close(self) -> None:
        if self._kv is not None:
            # best-effort write-back of degraded-mode buffers: the
            # process is exiting, so a still-failing disk drops them
            # (counted — the honest semantics of degraded mode)
            for doc in list(self._overflow):
                try:
                    self._flush_overflow(doc)
                except OSError:
                    lost = self._overflow.pop(doc, [])
                    self._overflow_sv.pop(doc, None)
                    _set_degraded(self, doc, False)
                    get_tracer().count(
                        "persist.dropped_updates", len(lost)
                    )
            self._overflow_bytes = 0
            try:
                self._kv.sync()
            except OSError:
                pass  # nothing more to do on a dead disk at close
            self._kv.close()
            self._kv = None

    def __del__(self):
        # a degraded store dropped without close() must not pin the
        # process-wide gauge forever (and its registry keys embed
        # id(self), which the allocator may reuse after this dealloc)
        try:
            for key in [k for k in _DEGRADED if k[0] == id(self)]:
                _set_degraded(self, key[1], False)
        except Exception:
            pass  # interpreter shutdown: globals may already be gone

    def _require(self) -> KvLog:
        if self._kv is None:
            raise RuntimeError("persistence is closed")
        return self._kv

    def _seq_for(self, doc: str) -> int:
        seq = self._next_seq.get(doc)
        if seq is None:
            # resume after the highest logged sequence (scan once)
            seq = 0
            last = None
            for k, _ in self._require().scan_prefix(_update_prefix(doc)):
                last = k
            if last is not None:
                seq = int(last.rsplit(b"_", 1)[1]) + 1
        self._next_seq[doc] = seq + 1
        return seq

    # -- the CRDTPersistence surface --------------------------------------
    def store_update(self, doc_name: str, update: bytes, sv: Optional[bytes] = None) -> None:
        self.store_updates(doc_name, [update], sv=sv)

    def store_updates(self, doc_name: str, updates: List[bytes],
                      sv: Optional[bytes] = None) -> None:
        """Append a WINDOW of updates as ONE atomic KV batch — N log
        keys, one state vector, one meta write, one fsync-able log
        append. This is the batched-incoming path's WAL shape
        (``Replica.flush_incoming`` applies a whole inbox as one merge
        transaction; before this, each update still paid its own
        3-key batch + meta read-modify-write). Counters distinguish
        units from windows: ``persist.appends`` counts updates,
        ``persist.batches`` counts KV batches."""
        # materialize FIRST: a generator argument must survive the
        # validation pass (iterating it twice would silently store
        # nothing while still advancing the SV)
        updates = list(updates)
        for u in updates:
            if not isinstance(u, (bytes, bytearray)):
                raise TypeError("update must be bytes")  # crdt.js:29-31
        updates = [bytes(u) for u in updates]
        if not updates and not self._overflow.get(doc_name):
            return
        if self.validate:
            from crdt_tpu.codec import v1

            for u in updates:
                v1.decode_update(u)  # raises on malformed input
        self._require()
        tracer = get_tracer()
        # drain any degraded-mode buffer FIRST (same batch): recovery
        # is automatic on the first write the disk accepts
        drain = self._overflow.pop(doc_name, [])
        if drain:
            self._overflow_bytes -= sum(map(len, drain))
            if sv is None:
                sv = self._overflow_sv.get(doc_name)
        window = drain + updates
        with tracer.span("persist"):
            try:
                self._write_with_retry(doc_name, window, sv)
            except OSError:
                if self.failure_policy == "raise":
                    # restore the drained buffer: raising must not
                    # silently discard previously accepted updates
                    if drain:
                        self._overflow[doc_name] = (
                            drain + self._overflow.get(doc_name, [])
                        )
                        self._overflow_bytes += sum(map(len, drain))
                    raise
                self._degrade(doc_name, window, sv)
                return
        if drain:
            # recovered: the buffered window is durable — make it so
            # on disk too before declaring the degradation over
            self._require().sync()
            self._overflow_sv.pop(doc_name, None)
            tracer.count("persist.recovered_updates", len(drain))
        _set_degraded(self, doc_name, False)
        tracer.count("persist.appends", len(window))
        tracer.count("persist.batches")
        tracer.count("persist.bytes_appended", sum(map(len, window)))

    def _write_batch(self, doc_name: str, updates: List[bytes],
                     sv: Optional[bytes]) -> None:
        kv = self._require()
        batch = Batch()
        for u in updates:
            batch.put(_update_key(doc_name, self._seq_for(doc_name)), u)
        if sv is not None:
            batch.put(_sv_key(doc_name), bytes(sv))
        meta = self.get_meta(doc_name) or {"size": 0, "count": 0}
        batch.put(
            _meta_key(doc_name),
            json.dumps(
                {
                    "last_updated": time.time(),
                    "size": meta["size"] + sum(map(len, updates)),
                    "count": meta["count"] + len(updates),
                }
            ).encode(),
        )
        kv.write(batch)

    def _write_with_retry(self, doc_name: str, updates: List[bytes],
                          sv: Optional[bytes]) -> None:
        """One window write with bounded-backoff retries. On any
        failure the cached ``_next_seq`` is invalidated so the next
        attempt re-derives it from the log scan — a torn batch on a
        non-atomic store may have landed a prefix of the keys."""
        from crdt_tpu.guard.faults import retry_with_backoff

        def attempt():
            try:
                self._write_batch(doc_name, updates, sv)
            except OSError:
                self._next_seq.pop(doc_name, None)
                raise

        retry_with_backoff(
            attempt, retries=self.retries,
            backoff_s=self.retry_backoff_s, counter="persist.retries",
        )

    def _degrade(self, doc_name: str, updates: List[bytes],
                 sv: Optional[bytes]) -> None:
        """Disk still failing after retries: buffer the window in RAM
        (bounded — oldest drop past ``overflow_max_bytes``), flip the
        ``persist.degraded`` gauge, and let the next successful write
        (or ``flush_degraded``) drain it back."""
        tracer = get_tracer()
        buf = self._overflow.setdefault(doc_name, [])
        buf.extend(updates)
        self._overflow_bytes += sum(map(len, updates))
        if sv is not None:
            self._overflow_sv[doc_name] = bytes(sv)
        _set_degraded(self, doc_name, True)
        # the bound is GLOBAL across every doc this store buffers:
        # drop the oldest update of the largest buffered doc, always
        # keeping the newest update of the window degrading right now
        # (a single over-budget update must still make progress)
        sizes = {d: sum(map(len, b)) for d, b in self._overflow.items()}
        dropped_n = 0
        while self._overflow_bytes > self.overflow_max_bytes:
            victim = max(
                (d for d in self._overflow
                 if d != doc_name or len(self._overflow[d]) > 1),
                key=lambda d: sizes[d], default=None,
            )
            if victim is None:
                break  # only the current window's newest remains
            vbuf = self._overflow[victim]
            dropped = vbuf.pop(0)
            self._overflow_bytes -= len(dropped)
            sizes[victim] -= len(dropped)
            dropped_n += 1
            if not vbuf:
                del self._overflow[victim]
                del sizes[victim]
                self._overflow_sv.pop(victim, None)
                _set_degraded(self, victim, False)
        if dropped_n:
            tracer.count("persist.dropped_updates", dropped_n)
        tracer.count("persist.degraded_writes")
        tracer.gauge("persist.overflow_bytes", self._overflow_bytes)

    def flush_degraded(self) -> bool:
        """Explicitly retry the degraded-mode write-back for every
        buffered doc (the drain also rides every ordinary write).
        Returns True when no buffer remains."""
        for doc in list(self._overflow):
            try:
                self._flush_overflow(doc)
            except OSError:
                return False
        return not self._overflow

    def _flush_overflow(self, doc_name: str) -> None:
        drain = self._overflow.pop(doc_name, [])
        if not drain:
            return
        self._overflow_bytes -= sum(map(len, drain))
        try:
            self._write_with_retry(
                doc_name, drain, self._overflow_sv.get(doc_name)
            )
        except OSError:
            self._overflow[doc_name] = (
                drain + self._overflow.get(doc_name, [])
            )
            self._overflow_bytes += sum(map(len, drain))
            raise
        self._require().sync()
        self._overflow_sv.pop(doc_name, None)
        tracer = get_tracer()
        tracer.count("persist.recovered_updates", len(drain))
        _set_degraded(self, doc_name, False)

    def get_all_updates(self, doc_name: str) -> List[bytes]:
        # degraded-mode buffers append after the log: readers (replica
        # restarts-within-process, peers syncing from persistence) see
        # accepted updates whether or not the disk took them yet
        logged = [
            v for _, v in self._require().scan_prefix(_update_prefix(doc_name))
        ]
        return logged + list(self._overflow.get(doc_name, []))

    def get_updates_since(self, doc_name: str, seq: int) -> List[bytes]:
        """The WAL tail a snapshot at coverage ``seq`` still needs:
        logged updates with sequence number STRICTLY greater than
        ``seq`` (the snapshot rider lands at the compaction blob's
        own seq, so the blob is never replayed on top of itself),
        plus any degraded-mode overflow (accepted but not yet on
        disk — always newer than any durable snapshot)."""
        tail = [
            v for k, v in
            self._require().scan_prefix(_update_prefix(doc_name))
            if int(k.rsplit(b"_", 1)[1]) > seq
        ]
        return tail + list(self._overflow.get(doc_name, []))

    def get_state_vector(self, doc_name: str) -> Optional[bytes]:
        ov = self._overflow_sv.get(doc_name)
        if ov is not None and self._overflow.get(doc_name):
            return ov
        return self._require().get(_sv_key(doc_name))

    def get_meta(self, doc_name: str) -> Optional[dict]:
        raw = self._require().get(_meta_key(doc_name))
        return json.loads(raw) if raw is not None else None

    def compact(self, doc_name: str, snapshot: bytes, sv: Optional[bytes] = None) -> None:
        """Replace the doc's update log with one snapshot update, then
        drop dead log history from disk.

        Crash-safe at every intermediate write, even on a store WITHOUT
        atomic batches (the torn-batch adversary in
        :mod:`crdt_tpu.guard.faults`): the snapshot is PUT at a fresh
        sequence number BEFORE the old log keys are deleted, so any
        prefix of the batch leaves either the full old log, old log +
        snapshot (idempotent replay), or a partial old log + snapshot
        (the snapshot dominates) — never an empty log. The native
        store's batch is atomic anyway; the ordering is the defense in
        depth the crash-point matrix pins. A compaction failure
        degrades (the un-compacted log is perfectly valid; retried at
        the next threshold crossing) and invalidates the cached
        ``_next_seq`` so sequence numbers re-derive from the log scan
        — a stale cache after a torn compact could otherwise overwrite
        a live key (satellite fix, round 10)."""
        kv = self._require()
        tracer = get_tracer()
        with tracer.span("persist.compact"):
            old_keys = kv.keys(_update_prefix(doc_name))
            batch = Batch()
            batch.put(
                _update_key(doc_name, self._seq_for(doc_name)),
                bytes(snapshot),
            )
            for k in old_keys:
                batch.delete(k)
            if sv is not None:
                batch.put(_sv_key(doc_name), bytes(sv))
            batch.put(
                _meta_key(doc_name),
                json.dumps(
                    {"last_updated": time.time(), "size": len(snapshot), "count": 1}
                ).encode(),
            )
            try:
                from crdt_tpu.guard.faults import retry_with_backoff

                retry_with_backoff(
                    lambda: kv.write(batch), retries=self.retries,
                    backoff_s=self.retry_backoff_s,
                    counter="persist.retries",
                )
            except OSError:
                self._next_seq.pop(doc_name, None)
                tracer.count("persist.compact_errors")
                if self.failure_policy == "raise":
                    raise
                return
            # compaction squashed everything the overflow buffer held
            # (the snapshot is full state): the buffer is now redundant
            if self._overflow.pop(doc_name, None) is not None:
                self._overflow_sv.pop(doc_name, None)
                self._overflow_bytes = sum(
                    sum(map(len, v)) for v in self._overflow.values()
                )
                _set_degraded(self, doc_name, False)
            # reclaim disk only when dead history dominates: kv.compact()
            # rewrites the WHOLE shared store, so an unconditional call
            # would make N docs' auto-compaction O(store) each — amortize
            # against live size instead (LevelDB's own trigger is
            # similarly ratio-based)
            if kv.log_size > 4 * max(kv.live_size, 1):
                kv.compact()
        tracer.count("persist.compactions")
        tracer.gauge("persist.log_size_bytes", kv.log_size)

    # -- maintenance -------------------------------------------------------
    def sync(self) -> None:
        self._require().sync()

    @property
    def log_size(self) -> int:
        return self._require().log_size
