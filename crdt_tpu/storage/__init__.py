"""Persistence layer — native ordered-KV update log (SURVEY.md §7 stage 6)."""

from crdt_tpu.storage.kv import KvLog
from crdt_tpu.storage.persistence import LogPersistence

__all__ = ["KvLog", "LogPersistence"]
