"""Device-layout snapshots: crash-proof instant recovery (round 21).

ROADMAP item 4. Cold replay of a large doc pays decode + staging +
converge + materialize over the FULL history; an incremental round
costs ~0.36 s. Restarts, new-replica joins, resident evictions and
live doc migrations are all cold starts. The fix is a checksummed
snapshot of the resident engine whose load path is *validate + copy*:
the host columns land via one ``np.frombuffer`` per section (already
in the device staging layout, so the first warm round's H2D put ships
them unchanged), and the interner/segment bookkeeping is rebuilt with
the same O(n) pass ``_admit`` runs per batch.

On-disk format (one file per doc generation, little-endian):

  ``MAGIC(8) | u32 header_len | header | u32 crc32(header) | payload``

The header is lib0-encoded: version, row count, coverage seq, state
digest, then a section table (name, enc, byte length, crc32 each).
Sections reuse the round-12 staged-encoding vocabulary — per-section
``encs`` of ``'i16'`` (:func:`packed._narrow_ident`), ``'hilo'``
(:func:`packed._split_hi_lo`, exact for any int32) or raw ``'i64'``
(segkeys carry the map-flag bit 62) — plus ``'aux'`` sections for
the python-object state (keys, parent specs, contents, cache). An
aux payload leads with a flag byte: 1 = UTF-8 JSON, chosen when an
encode-time round-trip is type-faithful (decode is one C-speed
``json.loads``); 0 = element-wise lib0, the fallback for values
JSON would coerce (bytes, tuples, NaN, non-string dict keys).

Crash safety is the WAL compaction contract (round 10) extended to
files: the writer is *temp file -> fsync -> rename -> dir fsync ->
unlink older -> dir fsync* (put-at-fresh-seq BEFORE old state dies),
every fs primitive goes through a seam :class:`guard.faults.FaultyFs`
can kill (the ALICE matrix in ``tests/test_snapshot.py`` crashes at
EVERY op), and the loader treats ANY damage — bad magic, version
skew, CRC mismatch, truncation, a torn rename's leftover ``.tmp`` —
as ``ValueError``, counts ``snap.fallbacks{reason=}``, tries the next
older generation, and finally lets the caller fall back to WAL
replay, which converges byte-identically.

The ``seq`` a snapshot carries is a *coverage cursor* in the writer's
own domain: the WAL rider stores the compaction seq (tail = WAL
entries with seq strictly greater), the server's eviction/checkpoint
writers store the covered ``len(st.blobs)`` prefix.

Knobs: ``CRDT_TPU_SNAP_DIR`` (store root; enables the server seams),
``CRDT_TPU_SNAP_BYTES`` (total store budget; writes that would
overflow it are skipped, counted, and never fatal).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from crdt_tpu.codec import native
from crdt_tpu.codec.lib0 import Decoder, Encoder
from crdt_tpu.core.ids import DeleteSet
from crdt_tpu.obs.tracer import get_tracer
from crdt_tpu.ops import packed as pk

MAGIC = b"CTPUSNP1"
VERSION = 2

# the ten host metadata columns, snapshotted in _Cols.INT_COLS order
_COL_NAMES = (
    "client", "clock", "kid", "pref", "oc", "ock",
    "right_client", "right_clock", "kind", "type_ref",
)

# every section the format knows, in file order. Adding a section is
# a VERSION bump; unknown names on decode are a hard reject (a spliced
# header must not smuggle payload past the allocator fences).
_SECTION_NAMES = tuple("col_" + c for c in _COL_NAMES) + (
    "sv", "ds", "orders_idx", "orders_rows", "win_keys", "win_rows",
    "rights", "keys", "prefs", "contents", "cache",
)
_AUX_SECTIONS = frozenset({"keys", "prefs", "contents", "cache"})
# segkey-bearing sections carry the map-flag bit 62: never narrowed
_FORCE_I64 = frozenset({"orders_idx", "win_keys", "rights"})


def _crc(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def _encode_ints(name: str, arr: np.ndarray) -> Tuple[str, bytes]:
    """One numeric section -> (enc kind, payload bytes). The round-12
    narrow ladder: int16 identity when the values fit, exact hi/lo
    int16 pair when int32-representable, raw int64 otherwise."""
    arr = np.ascontiguousarray(arr, np.int64)
    if name not in _FORCE_I64:
        narrow = pk._narrow_ident(arr)
        if narrow is not None:
            return "i16", narrow.astype("<i2").tobytes()
        if len(arr) == 0 or (
            int(arr.min()) >= -(1 << 31) and int(arr.max()) < (1 << 31)
        ):
            hi, lo = pk._split_hi_lo(arr)
            return "hilo", np.concatenate([hi, lo]).astype(
                "<i2").tobytes()
    return "i64", arr.astype("<i8").tobytes()


def _decode_ints(name: str, enc: str, data: bytes) -> np.ndarray:
    if enc == "i16":
        return np.frombuffer(data, "<i2").astype(np.int64)
    if enc == "hilo":
        if len(data) % 4:
            raise ValueError(f"snapshot: torn hilo section {name!r}")
        arr16 = np.frombuffer(data, "<i2").astype(np.int64)
        half = len(arr16) // 2
        hi, lo = arr16[:half], arr16[half:]
        return (hi << 16) | ((lo + 0x8000) & 0xFFFF)
    if enc == "i64":
        if len(data) % 8:
            raise ValueError(f"snapshot: torn i64 section {name!r}")
        return np.frombuffer(data, "<i8").astype(np.int64)
    raise ValueError(f"snapshot: unknown encoding {enc!r}")


def _faithful(a, b) -> bool:
    """Type-faithful structural equality: ``bool`` is not ``int``,
    ``tuple`` is not ``list``, and the check recurses through
    containers. This is the encode-time gate for the JSON aux rung —
    any value JSON would coerce disqualifies the whole section."""
    if type(a) is not type(b):
        return False
    if isinstance(a, list):
        return len(a) == len(b) and all(
            _faithful(x, y) for x, y in zip(a, b))
    if isinstance(a, dict):
        return a.keys() == b.keys() and all(
            _faithful(a[k], b[k]) for k in a)
    return a == b


def _json_rung(v) -> Optional[bytes]:
    """UTF-8 JSON bytes for *v* — or ``None`` when a decode
    round-trip does not reproduce it with :func:`_faithful` equality
    (bytes, tuples, NaN, non-string dict keys all fall through to
    the element-wise lib0 rung). Verification runs once at encode
    time so the hot load path can trust a flag-1 section blindly:
    ``json.loads`` is a C loop, the lib0 decode is a Python one."""
    try:
        blob = json.dumps(
            v, ensure_ascii=False, separators=(",", ":"),
            allow_nan=False).encode("utf-8")
        back = json.loads(blob.decode("utf-8"))
    except (TypeError, ValueError, RecursionError):
        return None
    return blob if _faithful(back, v) else None


def _json_list(body: bytes, what: str) -> list:
    """Parse a flag-1 aux body as a JSON array. Damage of any shape
    (bad UTF-8, torn JSON, a non-array top level) is ``ValueError``
    with the stable ``snapshot:`` prefix, nothing else."""
    try:
        # crdtlint: sanitizes — json.loads validates the full body;
        # the per-element fences below are the allocator guards
        vals = json.loads(body.decode("utf-8"))
    except Exception as exc:
        raise ValueError(
            f"snapshot: {what} json damage ({exc})") from exc
    if not isinstance(vals, list):
        raise ValueError(f"snapshot: {what} is not a list")
    return vals


class _Snap:
    """A decoded snapshot — validated columns + python-object state,
    ready for :func:`rehydrate`. Pure data, no engine references."""

    __slots__ = ("n", "seq", "cols", "contents", "keys", "prefs",
                 "sv", "ds", "orders", "wins", "rights", "cache",
                 "digest")

    def __init__(self):
        self.n = 0
        self.seq = 0
        self.cols: Dict[str, np.ndarray] = {}
        self.contents: List = []
        self.keys: List[str] = []
        self.prefs: List[Tuple] = []
        self.sv: Dict[int, int] = {}
        self.ds = DeleteSet()
        self.orders: Dict[int, List[int]] = {}
        self.wins: Dict[int, int] = {}
        self.rights: set = set()
        self.cache: dict = {}
        self.digest = b""


# ---------------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------------


def encode_engine(eng, *, seq: int = 0) -> bytes:
    """Serialize a settled ``IncrementalReplay`` engine. Refuses an
    engine with stashed or rootless state (exactly the refusal
    ``delta_admissible`` applies: such state is not a converged doc).
    The caller settles any pooled rounds first — reading
    ``eng.cache`` flushes the pool and is also what materializes the
    cache section, so the load path never pays a rebuild."""
    if eng._pending or eng._rootless:
        raise ValueError(
            "snapshot: engine has pending/rootless state")
    cache = eng.cache  # flushes the pool; stored verbatim below
    c = eng.cols
    n = c.n

    sections: List[Tuple[str, str, bytes]] = []
    for name in _COL_NAMES:
        enc, data = _encode_ints("col_" + name, c.col(name))
        sections.append(("col_" + name, enc, data))

    sv_flat: List[int] = []
    for client in sorted(eng._next_clock):
        sv_flat.extend((client, eng._next_clock[client]))
    enc, data = _encode_ints("sv", np.asarray(sv_flat, np.int64))
    sections.append(("sv", enc, data))

    ds_tri = native.ds_to_triples(eng.ds)
    enc, data = _encode_ints("ds", ds_tri)
    sections.append(("ds", enc, data))

    # seq segments: flat (segkey, len) index + concatenated rows, in
    # sorted-segkey order so encode is deterministic
    oidx: List[int] = []
    orows: List[int] = []
    for sk in sorted(eng._order):
        rows = eng.order_list(sk)  # materializes any stale links
        oidx.extend((sk, len(rows)))
        orows.extend(rows)
    enc, data = _encode_ints("orders_idx", np.asarray(oidx, np.int64))
    sections.append(("orders_idx", enc, data))
    enc, data = _encode_ints(
        "orders_rows", np.asarray(orows, np.int64))
    sections.append(("orders_rows", enc, data))

    wkeys = sorted(eng._win)
    enc, data = _encode_ints("win_keys", np.asarray(wkeys, np.int64))
    sections.append(("win_keys", enc, data))
    enc, data = _encode_ints("win_rows", np.asarray(
        [eng._win[sk] for sk in wkeys], np.int64))
    sections.append(("win_rows", enc, data))

    rights = sorted(sk for sk, v in eng._seg_rights.items() if v)
    enc, data = _encode_ints("rights", np.asarray(rights, np.int64))
    sections.append(("rights", enc, data))

    # aux sections carry a leading flag byte: 1 = UTF-8 JSON (the
    # fast rung — decode is a single C-speed ``json.loads``), 0 =
    # element-wise lib0. The JSON rung is only taken when the
    # encode-time round-trip is type-faithful, so flag 1 never lies.
    key_list = list(eng._key_names)
    blob = _json_rung(key_list)
    if blob is None:
        e = Encoder()
        e.write_var_uint(len(key_list))
        for name in key_list:
            e.write_var_string(name)
        sections.append(("keys", "aux", b"\x00" + e.to_bytes()))
    else:
        sections.append(("keys", "aux", b"\x01" + blob))

    blob = _json_rung([
        ["root", spec[1]] if spec[0] == "root"
        else ["item", int(spec[1]), int(spec[2])]
        for spec in eng._pref_spec])
    if blob is None:
        e = Encoder()
        e.write_var_uint(len(eng._pref_spec))
        for spec in eng._pref_spec:
            if spec[0] == "root":
                e.write_uint8(0)
                e.write_var_string(spec[1])
            else:
                e.write_uint8(1)
                e.write_var_int(int(spec[1]))
                e.write_var_int(int(spec[2]))
        sections.append(("prefs", "aux", b"\x00" + e.to_bytes()))
    else:
        sections.append(("prefs", "aux", b"\x01" + blob))

    blob = _json_rung(c.contents)
    if blob is None:
        e = Encoder()
        e.write_var_uint(n)
        for v in c.contents:
            e.write_any(v)
        sections.append(("contents", "aux", b"\x00" + e.to_bytes()))
    else:
        sections.append(("contents", "aux", b"\x01" + blob))

    blob = _json_rung(cache)
    if blob is None:
        e = Encoder()
        e.write_any(cache)
        sections.append(("cache", "aux", b"\x00" + e.to_bytes()))
    else:
        sections.append(("cache", "aux", b"\x01" + blob))

    by_name = {name: data for name, _, data in sections}
    digest = hashlib.sha1(
        by_name["sv"] + by_name["ds"]).digest()[:8]

    h = Encoder()
    h.write_var_uint(VERSION)
    h.write_var_uint(n)
    h.write_var_uint(seq)
    h.write_var_uint8_array(digest)
    h.write_var_uint(len(sections))
    for name, enc_kind, data in sections:
        h.write_var_string(name)
        h.write_var_string(enc_kind)
        h.write_var_uint(len(data))
        h.write_var_uint(_crc(data))
    header = h.to_bytes()

    parts = [MAGIC, len(header).to_bytes(4, "little"), header,
             _crc(header).to_bytes(4, "little")]
    parts.extend(data for _, _, data in sections)
    return b"".join(parts)


# ---------------------------------------------------------------------------
# decode (the recovery ladder's first rung: ANY damage -> ValueError)
# ---------------------------------------------------------------------------


def decode_payload(payload: bytes) -> _Snap:
    """Validate + parse a snapshot blob. Every reject is a
    ``ValueError`` with a stable reason prefix and ZERO partial
    state — the loader allocates nothing until the header and every
    section CRC check out (CL10xx wire-taint / CL11xx allocation
    scopes: all counts and lengths are fenced against the actual
    byte budget before any list/array is sized from them)."""
    if len(payload) < len(MAGIC) + 8:
        raise ValueError("snapshot: truncated header")
    if payload[:len(MAGIC)] != MAGIC:
        raise ValueError("snapshot: bad magic")
    off = len(MAGIC)
    hlen = int.from_bytes(payload[off:off + 4], "little")
    off += 4
    # crdtlint: sanitizes — hlen fenced against the real byte budget
    if hlen < 0 or off + hlen + 4 > len(payload):
        raise ValueError("snapshot: truncated header")
    header = payload[off:off + hlen]
    off += hlen
    want = int.from_bytes(payload[off:off + 4], "little")
    off += 4
    if _crc(header) != want:
        raise ValueError("snapshot: header crc mismatch")

    d = Decoder(header)
    try:
        version = d.read_var_uint()
        if version != VERSION:
            raise ValueError(
                f"snapshot: version skew (got {version})")
        n = d.read_var_uint()
        seq = d.read_var_uint()
        digest = bytes(d.read_var_uint8_array())
        nsec = d.read_var_uint()
        if nsec != len(_SECTION_NAMES):
            raise ValueError("snapshot: bad section count")
        table = []
        for _ in range(nsec):
            name = d.read_var_string()
            enc = d.read_var_string()
            size = d.read_var_uint()
            crc = d.read_var_uint()
            table.append((name, enc, size, crc))
    except ValueError:
        raise
    except Exception as exc:  # lib0 cursor errors are also damage
        raise ValueError(f"snapshot: header parse ({exc})") from exc

    if tuple(t[0] for t in table) != _SECTION_NAMES:
        raise ValueError("snapshot: bad section table")
    total = sum(t[2] for t in table)
    if off + total != len(payload):
        raise ValueError("snapshot: truncated payload")

    raw: Dict[str, bytes] = {}
    encs: Dict[str, str] = {}
    for name, enc, size, crc in table:
        # crdtlint: sanitizes — per-section re-fence (the sum check
        # above already pins the total to the real byte budget)
        if size < 0 or off + size > len(payload):
            raise ValueError("snapshot: truncated payload")
        data = payload[off:off + size]
        off += size
        if _crc(data) != crc:
            raise ValueError(f"snapshot: crc mismatch in {name!r}")
        if (name in _AUX_SECTIONS) != (enc == "aux"):
            raise ValueError(f"snapshot: bad encoding for {name!r}")
        raw[name] = data
        encs[name] = enc

    if hashlib.sha1(raw["sv"] + raw["ds"]).digest()[:8] != digest:
        raise ValueError("snapshot: state digest mismatch")

    snap = _Snap()
    snap.n, snap.seq, snap.digest = n, seq, digest

    for cname in _COL_NAMES:
        arr = _decode_ints(
            "col_" + cname, encs["col_" + cname], raw["col_" + cname])
        if len(arr) != n:
            raise ValueError(
                f"snapshot: column {cname!r} length mismatch")
        snap.cols[cname] = arr

    sv = _decode_ints("sv", encs["sv"], raw["sv"])
    if len(sv) % 2:
        raise ValueError("snapshot: torn sv section")
    snap.sv = {int(c): int(k) for c, k in zip(sv[0::2], sv[1::2])}
    if any(k < 0 for k in snap.sv.values()):
        raise ValueError("snapshot: negative sv clock")

    ds = _decode_ints("ds", encs["ds"], raw["ds"])
    if len(ds) % 3:
        raise ValueError("snapshot: torn ds section")
    if len(ds) and int(ds[2::3].min()) <= 0:
        raise ValueError("snapshot: non-positive ds run")
    snap.ds = native.ds_from_triples(ds)

    oidx = _decode_ints("orders_idx", encs["orders_idx"],
                        raw["orders_idx"])
    orows = _decode_ints("orders_rows", encs["orders_rows"],
                         raw["orders_rows"])
    if len(oidx) % 2:
        raise ValueError("snapshot: torn orders index")
    if np.any(orows < 0) or np.any(orows >= max(n, 1)):
        raise ValueError("snapshot: order row out of range")
    pos = 0
    rows_list = orows.tolist()
    for sk, cnt in zip(oidx[0::2].tolist(), oidx[1::2].tolist()):
        # crdtlint: sanitizes — cnt fenced against the decoded rows
        if cnt < 0 or pos + cnt > len(rows_list):
            raise ValueError("snapshot: order count out of range")
        if sk in snap.orders:
            raise ValueError("snapshot: duplicate order segment")
        snap.orders[sk] = rows_list[pos:pos + cnt]
        pos += cnt
    if pos != len(rows_list):
        raise ValueError("snapshot: dangling order rows")

    wkeys = _decode_ints("win_keys", encs["win_keys"], raw["win_keys"])
    wrows = _decode_ints("win_rows", encs["win_rows"], raw["win_rows"])
    if len(wkeys) != len(wrows):
        raise ValueError("snapshot: torn winner section")
    if len(wrows) and (int(wrows.min()) < 0 or int(wrows.max()) >= n):
        raise ValueError("snapshot: winner row out of range")
    snap.wins = dict(zip(wkeys.tolist(), wrows.tolist()))

    snap.rights = set(_decode_ints(
        "rights", encs["rights"], raw["rights"]).tolist())

    # every aux section begins with a flag byte (1 = JSON, 0 = lib0)
    for name in _AUX_SECTIONS:
        if not raw[name]:
            raise ValueError(f"snapshot: empty {name} section")
        if raw[name][0] not in (0, 1):
            raise ValueError(f"snapshot: bad {name} aux flag")

    try:
        if raw["keys"][0] == 1:
            snap.keys = _json_list(raw["keys"][1:], "keys")
            if not all(isinstance(s, str) for s in snap.keys):
                raise ValueError("snapshot: bad key name")
        else:
            d = Decoder(raw["keys"][1:])
            cnt = d.read_var_uint()
            # crdtlint: sanitizes — a name is >=1 byte on the wire
            if cnt > d.remaining():
                raise ValueError("snapshot: keys count out of range")
            snap.keys = [d.read_var_string() for _ in range(cnt)]

        if raw["prefs"][0] == 1:
            for spec in _json_list(raw["prefs"][1:], "prefs"):
                if (isinstance(spec, list) and len(spec) == 2
                        and spec[0] == "root"
                        and isinstance(spec[1], str)):
                    snap.prefs.append(("root", spec[1]))
                elif (isinstance(spec, list) and len(spec) == 3
                        and spec[0] == "item"
                        and isinstance(spec[1], int)
                        and isinstance(spec[2], int)
                        and not isinstance(spec[1], bool)
                        and not isinstance(spec[2], bool)):
                    snap.prefs.append(("item", spec[1], spec[2]))
                else:
                    raise ValueError("snapshot: bad pref spec")
        else:
            d = Decoder(raw["prefs"][1:])
            cnt = d.read_var_uint()
            # crdtlint: sanitizes — a spec is >=2 bytes on the wire
            if cnt * 2 > d.remaining():
                raise ValueError("snapshot: prefs count out of range")
            for _ in range(cnt):
                tag = d.read_uint8()
                if tag == 0:
                    snap.prefs.append(("root", d.read_var_string()))
                elif tag == 1:
                    snap.prefs.append(
                        ("item", d.read_var_int(), d.read_var_int()))
                else:
                    raise ValueError("snapshot: bad pref tag")

        if raw["contents"][0] == 1:
            snap.contents = _json_list(raw["contents"][1:], "contents")
            if len(snap.contents) != n:
                raise ValueError("snapshot: contents count mismatch")
        else:
            d = Decoder(raw["contents"][1:])
            cnt = d.read_var_uint()
            if cnt != n:
                raise ValueError("snapshot: contents count mismatch")
            snap.contents = [d.read_any() for _ in range(cnt)]
            if d.remaining():
                raise ValueError("snapshot: trailing content bytes")

        if raw["cache"][0] == 1:
            try:
                cache = json.loads(raw["cache"][1:].decode("utf-8"))
            except Exception as exc:
                raise ValueError(
                    f"snapshot: cache json damage ({exc})") from exc
        else:
            d = Decoder(raw["cache"][1:])
            cache = d.read_any()
        if not isinstance(cache, dict):
            raise ValueError("snapshot: cache is not a mapping")
        snap.cache = cache
    except ValueError:
        raise
    except Exception as exc:
        raise ValueError(f"snapshot: aux parse ({exc})") from exc

    # cross-section fences the rebuild relies on
    if len(snap.contents) != n:
        raise ValueError("snapshot: contents length mismatch")
    prefc = snap.cols["pref"]
    if len(prefc) and int(prefc.max()) >= len(snap.prefs):
        raise ValueError("snapshot: pref ref out of range")
    kidc = snap.cols["kid"]
    if len(kidc) and int(kidc.max()) >= len(snap.keys):
        raise ValueError("snapshot: key ref out of range")
    return snap


# ---------------------------------------------------------------------------
# rehydrate
# ---------------------------------------------------------------------------


def rehydrate(snap: _Snap, *, pool=None,
              device_min_rows: Optional[int] = None):
    """A live ``IncrementalReplay`` from a decoded snapshot — the
    restore path the round-15 promotion seam calls instead of the
    full-history engine build. Columns land by copy; the interners
    replay in stored order (the pref/kid numbering is embedded in
    every segkey, so order is identity); the per-segment bookkeeping
    is rebuilt with the same grouped pass ``_admit`` runs. The device
    matrix stays lazy: the first warm round stages it exactly as a
    freshly promoted engine would."""
    from crdt_tpu.core.store import K_GC
    from crdt_tpu.models.incremental import IncrementalReplay

    n = snap.n
    eng = IncrementalReplay(
        capacity=max(n, 1), device_min_rows=device_min_rows,
        pool=pool)
    c = eng.cols
    while c._cap < n:
        c._cap *= 2
    for name in _COL_NAMES:
        col = np.zeros(c._cap, np.int64)
        col[:n] = snap.cols[name]
        c._a[name] = col
    c.contents = list(snap.contents)
    c.n = n

    eng.ds = snap.ds
    eng._next_clock = dict(snap.sv)
    for name in snap.keys:
        eng._kid_of_key(name)
    for spec in snap.prefs:
        eng._pref_of_spec(spec)
    cl = snap.cols["client"]
    ck = snap.cols["clock"]
    eng._id_row = dict(zip(
        zip(cl.tolist(), ck.tolist()), range(n)))

    # segment bookkeeping: the _admit grouped pass over ALL rows
    pref = snap.cols["pref"]
    kind = snap.cols["kind"]
    kid = snap.cols["kid"]
    live = (pref >= 0) & (kind != K_GC)
    if live.any():
        rows = np.arange(n)
        sks = pk.segkey_of(pref[live], kid[live])
        live_rows = rows[live]
        order = np.argsort(sks, kind="stable")
        sks_s, rows_s = sks[order], live_rows[order]
        cuts = np.r_[
            0, np.flatnonzero(sks_s[1:] != sks_s[:-1]) + 1, len(sks_s)
        ]
        for a, b in zip(cuts[:-1], cuts[1:]):
            sk = int(sks_s[a])
            grp = rows_s[a:b]
            eng._seg_rows[sk] = grp.tolist()
            eng._seg_kid[sk] = int(kid[int(grp[0])])
            if sk in snap.rights:
                eng._seg_rights[sk] = True
            root = eng._root_of(eng._spec_of_row(int(grp[0])))
            if root is not None:
                eng._root_segs.setdefault(root, set()).add(sk)
            else:
                eng._rootless.add(sk)
    if eng._rootless:
        # a converged doc never has rootless segments; a snapshot
        # that decodes into one was forged or corrupted below the
        # CRC floor — reject rather than serve a diverged doc
        raise ValueError("snapshot: rootless segment after rebuild")

    for sk, rows_l in snap.orders.items():
        if sk not in eng._seg_rows:
            raise ValueError("snapshot: order for unknown segment")
        eng._order[sk] = list(rows_l)
    for sk, row in snap.wins.items():
        if sk not in eng._seg_rows:
            raise ValueError("snapshot: winner for unknown segment")
        eng._win[sk] = row

    eng._cache = dict(snap.cache)
    eng._dirty = set()
    # the restored winner/order caches are exact: device rounds may
    # advance tail-shaped deltas host-side in O(delta) instead of
    # paying the O(doc) first-round re-splice (the recovery path's
    # whole point — see IncrementalReplay._device_round)
    eng._from_snapshot = True
    return eng


# ---------------------------------------------------------------------------
# the store (atomic generations on a real or fault-injected fs)
# ---------------------------------------------------------------------------


class Fs:
    """The snapshot writer's fs primitives, one virtual op each —
    the seam :class:`crdt_tpu.guard.faults.FaultyFs` wraps to
    enumerate the ALICE crash matrix. Reads never fault."""

    def write(self, path: str, data: bytes) -> None:
        with open(path, "wb") as f:
            f.write(data)

    def fsync(self, path: str) -> None:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def rename(self, src: str, dst: str) -> None:
        os.replace(src, dst)

    def fsync_dir(self, path: str) -> None:
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(fd)
        except OSError:
            pass  # some filesystems refuse directory fsync
        finally:
            os.close(fd)

    def unlink(self, path: str) -> None:
        os.unlink(path)

    # -- read side (never fault-injected) --

    def read(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def listdir(self, path: str) -> List[str]:
        try:
            return os.listdir(path)
        except FileNotFoundError:
            return []

    def size(self, path: str) -> int:
        try:
            return os.path.getsize(path)
        except OSError:
            return 0


def _esc(doc: str) -> str:
    """Filesystem-safe doc name (percent-escape, collision-free)."""
    out = []
    for ch in str(doc):
        if ch.isalnum() or ch in "._":
            out.append(ch)
        else:
            out.append("%%%02x" % ord(ch))
    return "".join(out)


class SnapshotStore:
    """Snapshot generations under one directory, named
    ``<doc>-<seq:020d>.snap``. Writes are crash-atomic (tmp, fsync,
    rename, dir fsync; older generations die only AFTER the new one
    is durable). Loads walk generations newest-first through the
    recovery ladder: damage is counted per reason and skipped, never
    raised to the serving path."""

    def __init__(self, root: str, *, max_bytes: Optional[int] = None,
                 fs: Optional[Fs] = None):
        self.root = str(root)
        if max_bytes is None:
            env = os.environ.get("CRDT_TPU_SNAP_BYTES", "")
            max_bytes = int(env) if env else None
        self.max_bytes = max_bytes
        self.fs = fs if fs is not None else Fs()
        os.makedirs(self.root, exist_ok=True)

    # -- naming --

    def _files_of(self, doc) -> List[Tuple[int, str]]:
        """(seq, filename) generations of ``doc``, newest first.
        ``.tmp`` leftovers of a torn rename never match."""
        pref = _esc(doc) + "-"
        out = []
        for name in self.fs.listdir(self.root):
            if not (name.startswith(pref) and name.endswith(".snap")):
                continue
            stem = name[len(pref):-len(".snap")]
            if not stem.isdigit():
                continue
            out.append((int(stem), name))
        out.sort(reverse=True)
        return out

    def total_bytes(self) -> int:
        return sum(
            self.fs.size(os.path.join(self.root, name))
            for name in self.fs.listdir(self.root)
            if name.endswith(".snap"))

    # -- write --

    def write(self, doc, payload: bytes, seq: int) -> bool:
        """Land one generation atomically. Returns False (counted,
        never raised) when the store budget refuses or the disk
        errors — the caller keeps serving from the WAL and may retry
        at the next compaction. ``SimulatedCrash`` (a BaseException)
        propagates: the ALICE harness kills the writer mid-sequence
        and reopens."""
        tracer = get_tracer()
        if self.max_bytes is not None:
            mine = sum(
                self.fs.size(os.path.join(self.root, name))
                for _, name in self._files_of(doc))
            if self.total_bytes() - mine + len(payload) \
                    > self.max_bytes:
                if tracer.enabled:
                    tracer.count("snap.write_errors",
                                 labels={"reason": "budget"})
                return False
        final = os.path.join(
            self.root, "%s-%020d.snap" % (_esc(doc), seq))
        tmp = final + ".tmp"
        t0 = time.perf_counter()
        try:
            self.fs.write(tmp, payload)
            self.fs.fsync(tmp)
            self.fs.rename(tmp, final)
            self.fs.fsync_dir(self.root)
            # the new generation is durable: now (and only now) the
            # old ones may die — the round-10 put-before-delete order
            for _, name in self._files_of(doc):
                path = os.path.join(self.root, name)
                if path != final:
                    self.fs.unlink(path)
            self.fs.fsync_dir(self.root)
        except OSError:
            try:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            except OSError:
                pass
            if tracer.enabled:
                tracer.count("snap.write_errors",
                             labels={"reason": "io"})
            return False
        if tracer.enabled:
            tracer.count("snap.writes")
            tracer.count("snap.bytes", len(payload))
            tracer.gauge(
                "snap.write_ms",
                (time.perf_counter() - t0) * 1000.0)
        return True

    # -- load (the recovery ladder) --

    def load_latest(self, doc) -> Optional[Tuple[_Snap, int]]:
        """Newest valid generation, or None. Each damaged generation
        is counted ``snap.fallbacks{reason=}`` and skipped; the
        final None sends the caller down the WAL-replay rung."""
        tracer = get_tracer()
        for seq, name in self._files_of(doc):
            path = os.path.join(self.root, name)
            t0 = time.perf_counter()
            try:
                payload = self.fs.read(path)
            except OSError:
                if tracer.enabled:
                    tracer.count("snap.fallbacks",
                                 labels={"reason": "io"})
                continue
            try:
                snap = decode_payload(payload)
            except ValueError as exc:
                if tracer.enabled:
                    tracer.count("snap.fallbacks",
                                 labels={"reason": _reason(exc)})
                continue
            if snap.seq != seq:
                if tracer.enabled:
                    tracer.count("snap.fallbacks",
                                 labels={"reason": "seq_skew"})
                continue
            if tracer.enabled:
                tracer.count("snap.loads")
                tracer.gauge(
                    "snap.load_ms",
                    (time.perf_counter() - t0) * 1000.0)
            return snap, seq
        return None

    def drop(self, doc) -> None:
        """Best-effort removal of every generation of ``doc``."""
        for _, name in self._files_of(doc):
            try:
                self.fs.unlink(os.path.join(self.root, name))
            except OSError:
                pass

    # -- sidecars (server checkpoint manifests + history blobs) --

    def put_blob(self, name: str, data: bytes) -> bool:
        """An atomically-written sidecar file (same tmp/fsync/rename
        ladder, no generation bookkeeping)."""
        final = os.path.join(self.root, _esc(name) + ".blob")
        tmp = final + ".tmp"
        try:
            self.fs.write(tmp, data)
            self.fs.fsync(tmp)
            self.fs.rename(tmp, final)
            self.fs.fsync_dir(self.root)
        except OSError:
            if get_tracer().enabled:
                get_tracer().count("snap.write_errors",
                                   labels={"reason": "io"})
            return False
        return True

    def get_blob(self, name: str) -> Optional[bytes]:
        path = os.path.join(self.root, _esc(name) + ".blob")
        try:
            return self.fs.read(path)
        except OSError:
            return None


def _reason(exc: ValueError) -> str:
    """Stable low-cardinality fallback label from a reject message."""
    msg = str(exc)
    for key in ("magic", "version", "crc", "truncated", "digest"):
        if key in msg:
            return key
    return "invalid"


def store_from_env() -> Optional[SnapshotStore]:
    """The ambient store ``CRDT_TPU_SNAP_DIR`` names, or None."""
    root = os.environ.get("CRDT_TPU_SNAP_DIR", "")
    return SnapshotStore(root) if root else None


# ---------------------------------------------------------------------------
# the WAL compaction rider
# ---------------------------------------------------------------------------


def compact_with_snapshot(lp, doc, eng, store: SnapshotStore) -> bool:
    """Compact ``doc``'s WAL through ``lp`` AND land a snapshot of
    the settled engine at the SAME coverage seq, snapshot first:

      1. peek the seq the compaction blob will occupy,
      2. write the snapshot file (atomic; failure degrades to a
         plain compact — the WAL stays the source of truth),
      3. run the stock crash-safe ``LogPersistence.compact``.

    Every crash window is covered: before (2) nothing changed; after
    (2) but before (3) the snapshot covers every live WAL update and
    the tail query (seq strictly greater) returns nothing stale;
    crashes inside (3) are round 10's proven ladder. The caller must
    hold off concurrent appends for the doc (same contract as
    ``compact`` itself)."""
    from crdt_tpu.codec.v1 import encode_state_vector

    sv = encode_state_vector(eng.state_vector())
    blob = eng.encode_state_as_update()
    # peek-without-consuming: _seq_for advances the cursor; putting
    # it back makes the compaction land at the SAME seq the snapshot
    # claims, so the compact blob itself is never replayed as tail
    seq = lp._seq_for(doc)
    lp._next_seq[doc] = seq
    wrote = store.write(doc, encode_engine(eng, seq=seq), seq)
    lp.compact(doc, blob, sv)
    return wrote
