"""ctypes binding for the native kvlog store.

The reference's storage engine is `leveldown`, a C++ LevelDB binding
reached through the `level` JS wrapper (/root/reference/crdt.js:18-20).
This module is the equivalent seam: the C++ store (native/kvlog) built
as a shared library on first use, driven through a flat C ABI (the
image has no pybind11; ctypes is the binding layer).

Capability parity with the surface the reference exercises:
``get`` (crdt.js:47), atomic multi-key ``batch`` (crdt.js:60-71),
ordered prefix scans (`createReadStream` gt/lt, crdt.js:111-130),
``close`` (crdt.js:134) — plus ``compact`` and torn-tail crash
recovery, which LevelDB has and the reference's usage relies on
implicitly (every update is persisted before/around broadcast,
SURVEY.md §5 durability).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Iterator, List, Optional, Tuple

_REPO_ROOT = Path(__file__).resolve().parent.parent.parent
_SRC = _REPO_ROOT / "native" / "kvlog" / "kvlog.cc"
_BUILD_DIR = _REPO_ROOT / "native" / "build"
_SO = _BUILD_DIR / "libkvlog.so"

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def _build_so() -> None:
    _BUILD_DIR.mkdir(parents=True, exist_ok=True)
    # pid-suffixed tmp: two processes racing the first build each write
    # their own file; the os.replace is what's atomic
    tmp = _SO.with_suffix(f".so.tmp.{os.getpid()}")
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-Wall",
        str(_SRC), "-o", str(tmp),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, _SO)
    finally:
        if tmp.exists():
            tmp.unlink()


def _load() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
            _build_so()
        lib = ctypes.CDLL(str(_SO))
        c_u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.kv_open.restype = ctypes.c_void_p
        lib.kv_open.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        lib.kv_close.argtypes = [ctypes.c_void_p]
        lib.kv_put.restype = ctypes.c_int
        lib.kv_put.argtypes = [ctypes.c_void_p, c_u8p, ctypes.c_uint32, c_u8p, ctypes.c_uint32]
        lib.kv_del.restype = ctypes.c_int
        lib.kv_del.argtypes = [ctypes.c_void_p, c_u8p, ctypes.c_uint32]
        lib.kv_batch.restype = ctypes.c_int
        lib.kv_batch.argtypes = [ctypes.c_void_p, c_u8p, ctypes.c_uint32]
        lib.kv_get.restype = ctypes.c_int
        lib.kv_get.argtypes = [
            ctypes.c_void_p, c_u8p, ctypes.c_uint32,
            ctypes.POINTER(c_u8p), ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.kv_free.argtypes = [c_u8p]
        lib.kv_scan.restype = ctypes.c_void_p
        lib.kv_scan.argtypes = [ctypes.c_void_p, c_u8p, ctypes.c_uint32, c_u8p, ctypes.c_uint32]
        lib.kv_iter_next.restype = ctypes.c_int
        lib.kv_iter_next.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(c_u8p), ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(c_u8p), ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.kv_iter_close.argtypes = [ctypes.c_void_p]
        lib.kv_sync.restype = ctypes.c_int
        lib.kv_sync.argtypes = [ctypes.c_void_p]
        lib.kv_compact.restype = ctypes.c_int
        lib.kv_compact.argtypes = [ctypes.c_void_p]
        lib.kv_count.restype = ctypes.c_uint64
        lib.kv_count.argtypes = [ctypes.c_void_p]
        lib.kv_log_size.restype = ctypes.c_uint64
        lib.kv_log_size.argtypes = [ctypes.c_void_p]
        lib.kv_live_size.restype = ctypes.c_uint64
        lib.kv_live_size.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


def _as_u8p(data: bytes):
    return ctypes.cast(ctypes.c_char_p(data), ctypes.POINTER(ctypes.c_uint8))


def _take_bytes(lib, ptr, length: int) -> bytes:
    try:
        return ctypes.string_at(ptr, length)
    finally:
        lib.kv_free(ptr)


class Batch:
    """Atomic write batch — the `db.batch([...])` the reference uses
    for its update+sv+meta triple (crdt.js:60-71). Ops are buffered in
    the wire payload format and committed as ONE WAL record."""

    def __init__(self):
        self._buf = bytearray()
        self.count = 0

    def put(self, key: bytes, value: bytes) -> "Batch":
        self._buf.append(0)
        self._buf += len(key).to_bytes(4, "little")
        self._buf += len(value).to_bytes(4, "little")
        self._buf += key
        self._buf += value
        self.count += 1
        return self

    def delete(self, key: bytes) -> "Batch":
        self._buf.append(1)
        self._buf += len(key).to_bytes(4, "little")
        self._buf += (0).to_bytes(4, "little")
        self._buf += key
        self.count += 1
        return self

    def payload(self) -> bytes:
        return bytes(self._buf)

    def ops(self):
        """Decode the buffered ops back out: yields ("put", key,
        value) / ("del", key, None). The wire layout (op byte, klen
        u32le, vlen u32le, key, value) lives HERE only — fault
        injectors (crdt_tpu.guard.faults.FaultyKv) replay batches op
        by op through this iterator, so a format change cannot
        silently desynchronize the crash-point harness."""
        buf, i = self._buf, 0
        while i < len(buf):
            op = buf[i]
            klen = int.from_bytes(buf[i + 1:i + 5], "little")
            vlen = int.from_bytes(buf[i + 5:i + 9], "little")
            key = bytes(buf[i + 9:i + 9 + klen])
            val = bytes(buf[i + 9 + klen:i + 9 + klen + vlen])
            i += 9 + klen + vlen
            yield ("put", key, val) if op == 0 else ("del", key, None)


class KvLog:
    """One open store (= one log file). Not multi-process safe — same
    single-owner contract as a LevelDB directory."""

    def __init__(self, path: str):
        self._lib = _load()
        Path(path).parent.mkdir(parents=True, exist_ok=True)  # crdt.js:12-16
        err = ctypes.create_string_buffer(256)
        self._h = self._lib.kv_open(str(path).encode(), err, 256)
        if not self._h:
            raise OSError(f"kv_open({path}): {err.value.decode()}")
        self.path = str(path)

    @property
    def _handle(self):
        # close() nulls the handle; passing NULL to the C ABI would
        # segfault the interpreter instead of raising
        if not self._h:
            raise RuntimeError(f"store {self.path} is closed")
        return self._h

    # -- point ops ---------------------------------------------------------
    def put(self, key: bytes, value: bytes) -> None:
        if self._lib.kv_put(self._handle, _as_u8p(key), len(key), _as_u8p(value), len(value)):
            raise OSError("kv_put failed")

    def get(self, key: bytes) -> Optional[bytes]:  # crdtlint: taints
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = ctypes.c_uint32()
        rc = self._lib.kv_get(self._handle, _as_u8p(key), len(key), ctypes.byref(out), ctypes.byref(n))
        if rc == 1:
            return None
        if rc != 0:
            raise OSError("kv_get failed")
        return _take_bytes(self._lib, out, n.value)

    def delete(self, key: bytes) -> None:
        if self._lib.kv_del(self._handle, _as_u8p(key), len(key)):
            raise OSError("kv_del failed")

    def write(self, batch: Batch) -> None:
        payload = batch.payload()
        rc = self._lib.kv_batch(self._handle, _as_u8p(payload), len(payload))
        if rc == -2:
            raise ValueError("malformed batch payload")
        if rc != 0:
            raise OSError("kv_batch failed")

    # -- scans -------------------------------------------------------------
    # stored bytes were written by a peer (or survived a torn tail);
    # readers re-fence them like wire input
    # crdtlint: taints
    def scan(self, start: bytes = b"", end: bytes = b"") -> Iterator[Tuple[bytes, bytes]]:
        """Ordered iteration over [start, end); empty end = to the last
        key. Snapshot semantics (writes during iteration don't appear):
        the native iterator snapshots here, eagerly, not on first
        ``next()`` of the returned generator."""
        it = self._lib.kv_scan(self._handle, _as_u8p(start), len(start), _as_u8p(end), len(end))
        if not it:
            raise OSError("kv_scan failed")
        return self._drain_iter(it)

    def _drain_iter(self, it) -> Iterator[Tuple[bytes, bytes]]:
        try:
            while True:
                kp = ctypes.POINTER(ctypes.c_uint8)()
                vp = ctypes.POINTER(ctypes.c_uint8)()
                kn = ctypes.c_uint32()
                vn = ctypes.c_uint32()
                rc = self._lib.kv_iter_next(
                    it, ctypes.byref(kp), ctypes.byref(kn), ctypes.byref(vp), ctypes.byref(vn)
                )
                if rc == 1:
                    return
                if rc != 0:
                    raise OSError("kv_iter_next failed")
                yield (
                    _take_bytes(self._lib, kp, kn.value),
                    _take_bytes(self._lib, vp, vn.value),
                )
        finally:
            self._lib.kv_iter_close(it)

    def scan_prefix(self, prefix: bytes) -> Iterator[Tuple[bytes, bytes]]:  # crdtlint: taints
        """The reference's gt/lt prefix range (crdt.js:115-118)."""
        return self.scan(prefix, prefix + b"\xff")

    def keys(self, prefix: bytes = b"") -> List[bytes]:  # crdtlint: taints
        return [k for k, _ in self.scan_prefix(prefix)] if prefix else [
            k for k, _ in self.scan()
        ]

    # -- maintenance -------------------------------------------------------
    def sync(self) -> None:
        if self._lib.kv_sync(self._handle):
            raise OSError("kv_sync failed")

    def compact(self) -> None:
        if self._lib.kv_compact(self._handle):
            raise OSError("kv_compact failed")

    def close(self) -> None:
        if self._h:
            self._lib.kv_close(self._h)
            self._h = None

    def __len__(self) -> int:
        return int(self._lib.kv_count(self._handle))

    @property
    def log_size(self) -> int:
        """Bytes in the on-disk log (history included)."""
        return int(self._lib.kv_log_size(self._handle))

    @property
    def live_size(self) -> int:
        """Bytes of live key+value data (what compaction keeps)."""
        return int(self._lib.kv_live_size(self._handle))

    def __enter__(self) -> "KvLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
