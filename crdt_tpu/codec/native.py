"""Native v1 codec binding — decode-to-columns / encode-from-columns.

Builds ``native/codec/v1codec.cc`` as a CPython extension on first use
(g++, Python + numpy headers; no pip) and exposes the two hot-path
entry points the end-to-end pipeline needs:

- :func:`decode_updates_columns` — one C pass over a batch of v1 blobs
  producing interned numpy columns + a contents list (the Python
  path's ``decode_update`` + ``resolve_parents`` +
  ``records_to_columns`` collapsed).
- :func:`encode_from_columns` — byte-identical to
  ``crdt_tpu.codec.v1.encode_update`` on the same logical rows.

Everything degrades gracefully: :func:`available` is False when the
toolchain is missing, and callers fall back to the pure-Python codec
(which remains the semantic reference, pinned by the wire fixtures in
tests/test_yjs_fixtures.py).
"""

from __future__ import annotations

import os
import subprocess
import sysconfig
import threading
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from crdt_tpu.core.ids import DeleteSet
from crdt_tpu.core.records import ItemRecord

_REPO_ROOT = Path(__file__).resolve().parent.parent.parent
_SRC = _REPO_ROOT / "native" / "codec" / "v1codec.cc"
_BUILD_DIR = _REPO_ROOT / "native" / "build"
_SO = _BUILD_DIR / "_v1codec.so"

_lock = threading.Lock()
_mod = None
_build_error: Optional[str] = None


def _build() -> None:
    _BUILD_DIR.mkdir(parents=True, exist_ok=True)
    tmp = _SO.with_suffix(f".so.tmp.{os.getpid()}")
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-Wall",
        f"-I{sysconfig.get_paths()['include']}",
        f"-I{np.get_include()}",
        str(_SRC), "-o", str(tmp),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, _SO)
    except subprocess.CalledProcessError as e:
        stderr = e.stderr.decode(errors="replace") if e.stderr else "(no output)"
        raise RuntimeError(
            f"native codec build failed ({' '.join(cmd)}):\n{stderr}"
        ) from e
    finally:
        if tmp.exists():
            tmp.unlink()


def _load():
    global _mod, _build_error
    with _lock:
        if _mod is not None:
            return _mod
        if _build_error is not None:
            raise RuntimeError(_build_error)
        try:
            if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
                _build()
            import importlib.util

            spec = importlib.util.spec_from_file_location("_v1codec", _SO)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
        except Exception as e:  # remember: don't retry a broken toolchain
            _build_error = f"native codec unavailable: {e}"
            raise RuntimeError(_build_error) from e
        _mod = mod
        return mod


def available() -> bool:
    try:
        _load()
        return True
    except RuntimeError:
        return False


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------


def decode_updates_columns(blobs: Sequence[bytes]) -> Dict:
    """Batch-decode v1 blobs into one columnar union (see module doc).

    Returns a dict of numpy columns (client/clock/parent_root/
    parent_client/parent_clock/key_id/origin_client/origin_clock/
    right_client/right_clock/kind/type_ref), a ``contents`` list, the
    interning tables ``roots``/``keys``, and ``ds`` — flat
    (client, clock, length) triples.
    """
    # bytes() normalization: the C pass takes exact bytes; callers may
    # hand bytearray/memoryview (the Python fallback accepts them too)
    return _load().decode_updates([bytes(b) for b in blobs])


def ds_from_triples(triples: np.ndarray) -> DeleteSet:
    ds = DeleteSet()
    t = np.asarray(triples).reshape(-1, 3)
    for c, s, length in t:
        ds.add(int(c), int(s), int(length))
    return ds


def kernel_columns(dec: Dict) -> Dict[str, np.ndarray]:
    """Kernel-facing columns (crdt_tpu.ops.merge layout) from a decode.

    Matches ``records_to_columns`` exactly, including the -2 sentinel
    for rows with NO parent at all (unresolvable origins) — the kernels
    segment on parent_a/parent_b, so the sentinel must agree."""
    pr = dec["parent_root"]
    pc, pk = dec["parent_client"], dec["parent_clock"]
    root = pr >= 0
    item = (~root) & (pc >= 0)
    return {
        "client": dec["client"],
        "clock": dec["clock"],
        "parent_is_root": root,
        "parent_a": np.where(
            root, pr.astype(np.int64), np.where(item, pc, np.int64(-2))
        ),
        "parent_b": np.where(
            root, np.int64(-1), np.where(item, pk, np.int64(-2))
        ),
        "key_id": dec["key_id"],
        "origin_client": dec["origin_client"],
        "origin_clock": dec["origin_clock"],
        # right origins ride along so staging can order attachment
        # groups (mid-inserts/prepends) without a records detour; the
        # general kernels ignore them
        "right_client": dec["right_client"],
        "right_clock": dec["right_clock"],
        "valid": np.ones(len(dec["client"]), bool),
    }


def decoded_to_records(
    dec: Dict, rows: Optional[Sequence[int]] = None
) -> Tuple[List[ItemRecord], DeleteSet]:
    """Reconstruct symbolic records (parent-resolved) — the bridge to
    the scalar engine and the differential tests. ``rows`` restricts
    the output to a row subset (full delete set either way)."""
    roots, keys = dec["roots"], dec["keys"]
    out: List[ItemRecord] = []
    n = len(dec["client"])
    client = dec["client"]
    clock = dec["clock"]
    pr = dec["parent_root"]
    pc, pk = dec["parent_client"], dec["parent_clock"]
    kid = dec["key_id"]
    oc, ok = dec["origin_client"], dec["origin_clock"]
    rc, rk = dec["right_client"], dec["right_clock"]
    kind, tref = dec["kind"], dec["type_ref"]
    contents = dec["contents"]
    for i in (range(n) if rows is None else rows):
        i = int(i)
        out.append(ItemRecord(
            client=int(client[i]),
            clock=int(clock[i]),
            parent_root=roots[pr[i]] if pr[i] >= 0 else None,
            parent_item=(int(pc[i]), int(pk[i])) if pc[i] >= 0 else None,
            key=keys[kid[i]] if kid[i] >= 0 else None,
            origin=(int(oc[i]), int(ok[i])) if oc[i] >= 0 else None,
            right=(int(rc[i]), int(rk[i])) if rc[i] >= 0 else None,
            kind=int(kind[i]),
            type_ref=int(tref[i]),
            content=contents[i],
        ))
    return out, ds_from_triples(dec["ds"])


def _decode_py(blobs: Sequence[bytes]) -> Dict:
    """Pure-Python fallback producing the same columnar dict (same
    first-appearance interning order as the C pass)."""
    from crdt_tpu.codec import v1
    from crdt_tpu.ops.merge import resolve_parents

    records: List[ItemRecord] = []
    triples: List[int] = []
    for blob in blobs:
        recs, d = v1.decode_update(blob)
        records.extend(recs)
        for c, s, length in d.iter_all():
            triples.extend((c, s, length))
    records = resolve_parents(records)
    n = len(records)
    dec: Dict = {
        "client": np.empty(n, np.int64),
        "clock": np.empty(n, np.int64),
        "parent_root": np.full(n, -1, np.int32),
        "parent_client": np.full(n, -1, np.int64),
        "parent_clock": np.full(n, -1, np.int64),
        "key_id": np.full(n, -1, np.int32),
        "origin_client": np.full(n, -1, np.int64),
        "origin_clock": np.full(n, -1, np.int64),
        "right_client": np.full(n, -1, np.int64),
        "right_clock": np.full(n, -1, np.int64),
        "kind": np.empty(n, np.int32),
        "type_ref": np.full(n, -1, np.int32),
        "contents": [r.content for r in records],
        "ds": np.asarray(triples, np.int64),
    }
    roots: Dict[str, int] = {}
    keys: Dict[str, int] = {}
    for i, r in enumerate(records):
        dec["client"][i] = r.client
        dec["clock"][i] = r.clock
        if r.parent_root is not None:
            dec["parent_root"][i] = roots.setdefault(r.parent_root, len(roots))
        if r.parent_item is not None:
            dec["parent_client"][i], dec["parent_clock"][i] = r.parent_item
        if r.key is not None:
            dec["key_id"][i] = keys.setdefault(r.key, len(keys))
        if r.origin is not None:
            dec["origin_client"][i], dec["origin_clock"][i] = r.origin
        if r.right is not None:
            dec["right_client"][i], dec["right_clock"][i] = r.right
        dec["kind"][i] = r.kind
        dec["type_ref"][i] = r.type_ref
    dec["roots"] = list(roots)
    dec["keys"] = list(keys)
    return dec


def decode_updates_columns_any(blobs: Sequence[bytes]) -> Dict:
    """Native decode when the toolchain allows, Python otherwise."""
    if available():
        return decode_updates_columns(blobs)
    return _decode_py(blobs)


_COLUMN_KEYS = (
    "client", "clock", "parent_root", "parent_client", "parent_clock",
    "key_id", "origin_client", "origin_clock", "right_client",
    "right_clock", "kind", "type_ref",
)


def merge_decoded(chunks: Sequence[Dict]) -> Dict:
    """Concatenate per-chunk decoded column dicts into ONE union,
    exactly as if the chunks' blobs had gone through a single
    :func:`decode_updates_columns_any` pass: the ``roots``/``keys``
    interning tables merge in first-appearance order and every chunk's
    index columns remap onto the merged tables. This is the seam the
    streaming executor's background decode workers feed — each worker
    decodes its blob chunk independently, and the merge is pure numpy.

    Like the single-pass decode, the result is NOT deduped; callers
    that need the canonical union apply :func:`dedup_columns` (one
    pass over the merged columns, identical to the one-shot path)."""
    chunks = [c for c in chunks]
    if len(chunks) == 1:
        return chunks[0]
    if not chunks:
        return decode_updates_columns_any([])
    roots: Dict[str, int] = {}
    keys: Dict[str, int] = {}
    parts: Dict[str, List[np.ndarray]] = {k: [] for k in _COLUMN_KEYS}
    contents: List = []
    ds_parts: List[np.ndarray] = []
    for c in chunks:
        root_map = np.asarray(
            [roots.setdefault(r, len(roots)) for r in c["roots"]],
            np.int64,
        )
        key_map = np.asarray(
            [keys.setdefault(k, len(keys)) for k in c["keys"]],
            np.int64,
        )
        for name in _COLUMN_KEYS:
            col = c[name]
            if name == "parent_root" and len(root_map):
                col = np.where(
                    col >= 0, root_map[np.clip(col, 0, None)], col
                ).astype(col.dtype)
            elif name == "key_id" and len(key_map):
                col = np.where(
                    col >= 0, key_map[np.clip(col, 0, None)], col
                ).astype(col.dtype)
            parts[name].append(col)
        contents.extend(c["contents"])
        ds_parts.append(np.asarray(c["ds"], np.int64).reshape(-1))
    out = {k: np.concatenate(parts[k]) for k in _COLUMN_KEYS}
    out["contents"] = contents
    out["ds"] = np.concatenate(ds_parts) if ds_parts else np.empty(
        0, np.int64
    )
    out["roots"] = list(roots)
    out["keys"] = list(keys)
    _resolve_parents_merged(out)
    return out


def id_index(client, clock) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Dense-rank-packed (client, clock) row index for vectorized id
    lookups: clients rank densely, clocks ride the low 41 bits (the
    wire bound is 2^40, so the packed key is collision-free for any
    decodable union). Returns ``(uniq_clients, keys_sorted,
    rows_sorted)`` for :func:`id_lookup`; duplicate ids resolve to
    their FIRST-appearing row (the decoder's emplace convention).
    Shared by the cross-chunk parent resolution below and the
    streaming executor's partition climb — one home for the bit
    layout."""
    client = np.asarray(client, np.int64)
    clock = np.asarray(clock, np.int64)
    uniq = np.unique(client)
    if not len(uniq):
        return uniq, np.empty(0, np.int64), np.empty(0, np.int64)
    keys = (np.searchsorted(uniq, client).astype(np.int64) << 41) | clock
    order = np.lexsort((np.arange(len(keys)), keys))
    return uniq, keys[order], order


def id_lookup(index, qc, qk) -> np.ndarray:
    """Row of each queried (qc, qk) id under an :func:`id_index`
    (-1 where absent; duplicate ids give the first-appearing row)."""
    uniq, keys_sorted, rows_sorted = index
    qc = np.asarray(qc, np.int64)
    qk = np.asarray(qk, np.int64)
    if not len(keys_sorted):
        return np.full(len(qc), -1, np.int64)
    qrank = np.searchsorted(uniq, np.clip(qc, uniq[0], None))
    found_c = (
        (qc >= 0) & (qrank < len(uniq))
        & (uniq[np.clip(qrank, 0, len(uniq) - 1)] == qc)
    )
    qkey = np.where(found_c, (qrank << 41) | qk, np.int64(-1))
    pos = np.searchsorted(keys_sorted, qkey)
    posc = np.clip(pos, 0, len(keys_sorted) - 1)
    hit = (qkey >= 0) & (keys_sorted[posc] == qkey)
    return np.where(hit, rows_sorted[posc], np.int64(-1))


def _resolve_parents_merged(dec: Dict) -> None:
    """Cross-chunk implicit-parent resolution, in place.

    Each chunk's decode already resolved origin-else-right chains that
    stay INSIDE the chunk; rows whose chains cross a chunk boundary
    come out parentless. This pass re-walks exactly those rows over
    the merged union — numpy pointer doubling, O(log chain) rounds —
    with the single-pass decoder's semantics: first-occurrence id
    index, walk to the first ancestor carrying an explicit parent,
    copy its parent columns (and key when the row has none), leave
    cycles and dangling references unresolved."""
    from crdt_tpu.core.store import K_GC

    pr, pc, pk = dec["parent_root"], dec["parent_client"], dec["parent_clock"]
    kid, kind = dec["key_id"], dec["kind"]
    n = len(pr)
    need = (pr < 0) & (pc < 0) & (kind != K_GC)
    if not need.any():
        return
    oc, ock = dec["origin_client"], dec["origin_clock"]
    rc, rk = dec["right_client"], dec["right_clock"]
    ref_c = np.where(oc >= 0, oc, rc).astype(np.int64)
    ref_k = np.where(oc >= 0, ock, rk).astype(np.int64)

    # first-occurrence id index (duplicates may still be present at
    # this point — dedup runs after, exactly like the one-shot path)
    index = id_index(dec["client"], dec["clock"])
    ref_row = id_lookup(index, ref_c, ref_k)

    # pointer doubling to each row's first explicitly-parented
    # ancestor; node n is the dead-end sink
    has_explicit = (pr >= 0) | (pc >= 0)
    f = np.where(
        has_explicit, np.arange(n, dtype=np.int64),
        np.where(ref_row >= 0, ref_row, np.int64(n)),
    )
    f = np.r_[f, np.int64(n)]  # sink self-loop
    for _ in range(max(1, (max(n, 2) - 1).bit_length() + 1)):
        f = f[f]
    term = f[:n]
    ok = need & (term < n) & has_explicit[np.clip(term, 0, n - 1)]
    rows = np.flatnonzero(ok)
    t = term[rows]
    pr[rows] = pr[t]
    pc[rows] = pc[t]
    pk[rows] = pk[t]
    fill_key = ok & (kid < 0)
    rows_k = np.flatnonzero(fill_key)
    kid[rows_k] = kid[term[rows_k]]


def dedup_columns(dec: Dict) -> Dict:
    """Drop duplicate-id rows (first occurrence wins), returning a
    canonical union. Redelivered blobs — at-least-once transports,
    overlapping log segments — produce duplicate ids that the kernels
    dedup on-device but that would corrupt a host re-ENCODE (both
    encoders' run/skip bookkeeping assumes unique, forward-moving
    clocks per client)."""
    n = len(dec["client"])
    if n == 0:
        return dec
    # lexsort, NOT a packed (client << 40 | clock) key: real client ids
    # are 31-bit and would alias modulo 2^24 in the shifted int64,
    # silently merging distinct clients' rows
    order = np.lexsort((dec["clock"], dec["client"]))
    sc = dec["client"][order]
    sk = dec["clock"][order]
    first = np.zeros(n, bool)
    first[order[np.r_[True, (sc[1:] != sc[:-1]) | (sk[1:] != sk[:-1])]]] = True
    if first.all():
        return dec
    idx = np.flatnonzero(first)  # original order preserved
    out = {k: dec[k][idx] for k in _COLUMN_KEYS}
    contents = dec["contents"]
    out["contents"] = [contents[i] for i in idx]
    out["ds"] = dec["ds"]
    out["roots"] = dec["roots"]
    out["keys"] = dec["keys"]
    return out


# ---------------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------------


def ds_to_triples(ds: Optional[DeleteSet]) -> np.ndarray:
    """Flat (client, start, len) triples in the encoder's canonical
    order: clients descending, ranges ascending within a client."""
    if ds is None:
        return np.empty(0, np.int64)
    ds = ds.copy()
    ds.normalize()
    out: List[int] = []
    for client in sorted(ds.ranges, reverse=True):
        for s, e in ds.ranges[client]:
            out.extend((client, s, e - s))
    return np.asarray(out, np.int64)


def encode_from_columns_any(dec: Dict, ds: Optional[DeleteSet] = None) -> bytes:
    """Native encode when available; Python fallback otherwise."""
    if available():
        return encode_from_columns(dec, ds)
    from crdt_tpu.codec import v1

    records, dec_ds = decoded_to_records(dec)
    return v1.encode_update(records, ds if ds is not None else dec_ds)


def encode_from_columns(dec: Dict, ds: Optional[DeleteSet] = None) -> bytes:
    """One v1 blob from a decoded (or equivalently-shaped) column set.
    ``ds`` defaults to the decode's own delete set."""
    triples = (
        ds_to_triples(ds)
        if ds is not None
        else ds_to_triples(ds_from_triples(dec["ds"]))
    )
    m = _load()
    return m.encode_update(
        np.ascontiguousarray(dec["client"], np.int64),
        np.ascontiguousarray(dec["clock"], np.int64),
        np.ascontiguousarray(dec["parent_root"], np.int32),
        np.ascontiguousarray(dec["parent_client"], np.int64),
        np.ascontiguousarray(dec["parent_clock"], np.int64),
        np.ascontiguousarray(dec["key_id"], np.int32),
        np.ascontiguousarray(dec["origin_client"], np.int64),
        np.ascontiguousarray(dec["origin_clock"], np.int64),
        np.ascontiguousarray(dec["right_client"], np.int64),
        np.ascontiguousarray(dec["right_clock"], np.int64),
        np.ascontiguousarray(dec["kind"], np.int32),
        np.ascontiguousarray(dec["type_ref"], np.int32),
        list(dec["contents"]),
        list(dec["roots"]),
        list(dec["keys"]),
        triples,
    )
