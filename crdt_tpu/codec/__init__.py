from crdt_tpu.codec.lib0 import Decoder, Encoder  # noqa: F401
