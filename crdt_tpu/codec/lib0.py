"""lib0-compatible binary primitives (varint / string / any encoding).

The Yjs v1 update format (consumed by the reference through
``Y.encodeStateAsUpdate`` / ``Y.applyUpdate``, crdt.js:56,294) is built
on the lib0 encoding library. This module reimplements the wire-level
primitives from the published format description so our updates stay
byte-compatible with Yjs v1:

- varUint: little-endian base-128, 7 payload bits per byte, high bit
  set on all but the last byte.
- varInt: first byte carries sign (0x40) and 6 payload bits; later
  bytes carry 7 bits; 0x80 is the continue bit throughout.
- varString: varUint byte-length prefix + UTF-8 bytes.
- varUint8Array: varUint length prefix + raw bytes.
- any: one type byte (127=undefined, 126=null, 125=varInt, 124=f32,
  123=f64, 122=i64, 121=false, 120=true, 119=string, 118=object,
  117=array, 116=Uint8Array) followed by the payload.
"""

from __future__ import annotations

import math
import struct
from typing import Any, List


class Undefined:
    """Sentinel distinguishing JS `undefined` from `null` (Python None)."""

    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "undefined"


UNDEFINED = Undefined()


class Encoder:
    __slots__ = ("_parts",)

    def __init__(self):
        self._parts: List[bytes] = []

    def to_bytes(self) -> bytes:
        return b"".join(self._parts)

    def write_uint8(self, n: int) -> None:
        self._parts.append(bytes((n & 0xFF,)))

    def write_var_uint(self, n: int) -> None:
        if n < 0:
            raise ValueError(f"varUint must be >= 0, got {n}")
        out = bytearray()
        while True:
            b = n & 0x7F
            n >>= 7
            if n:
                out.append(0x80 | b)
            else:
                out.append(b)
                break
        self._parts.append(bytes(out))

    def write_var_int(self, n: int) -> None:
        is_neg = n < 0
        if is_neg:
            n = -n
        # first byte: continue(0x80) | sign(0x40) | 6 bits
        first = (0x40 if is_neg else 0) | (n & 0x3F)
        n >>= 6
        out = bytearray()
        if n:
            out.append(0x80 | first)
            while True:
                b = n & 0x7F
                n >>= 7
                if n:
                    out.append(0x80 | b)
                else:
                    out.append(b)
                    break
        else:
            out.append(first)
        self._parts.append(bytes(out))

    def write_var_string(self, s: str) -> None:
        data = s.encode("utf-8")
        self.write_var_uint(len(data))
        self._parts.append(data)

    def write_var_uint8_array(self, data: bytes) -> None:
        self.write_var_uint(len(data))
        self._parts.append(bytes(data))

    def write_bytes(self, data: bytes) -> None:
        self._parts.append(bytes(data))

    def write_float32(self, x: float) -> None:
        self._parts.append(struct.pack(">f", x))

    def write_float64(self, x: float) -> None:
        self._parts.append(struct.pack(">d", x))

    def write_int64(self, n: int) -> None:
        self._parts.append(struct.pack(">q", n))

    def write_any(self, v: Any) -> None:
        if v is UNDEFINED:
            self.write_uint8(127)
        elif v is None:
            self.write_uint8(126)
        elif isinstance(v, bool):  # must precede int check
            self.write_uint8(120 if v else 121)
        elif isinstance(v, int):
            # lib0 uses varInt for every JS safe integer; type 122
            # (fixed int64 BigInt) only beyond Number.MAX_SAFE_INTEGER
            if -(2**53) < v < 2**53:
                self.write_uint8(125)
                self.write_var_int(v)
            elif -(2**63) <= v < 2**63:
                self.write_uint8(122)
                self.write_int64(v)
            else:
                # lib0 bigint is a fixed 8-byte field; larger cannot be represented
                raise TypeError(f"integer {v} out of lib0 bigint (int64) range")
        elif isinstance(v, float):
            if math.isfinite(v):
                # use f32 when exactly representable; values at/above
                # the f32 rounding boundary are legal f64 payloads and
                # must not OverflowError out of the probe
                try:
                    f32 = struct.unpack(">f", struct.pack(">f", v))[0]
                except (OverflowError, struct.error):
                    f32 = None
                if f32 == v:
                    self.write_uint8(124)
                    self.write_float32(v)
                    return
            self.write_uint8(123)
            self.write_float64(v)
        elif isinstance(v, str):
            self.write_uint8(119)
            self.write_var_string(v)
        elif isinstance(v, dict):
            self.write_uint8(118)
            self.write_var_uint(len(v))
            for k, val in v.items():
                self.write_var_string(str(k))
                self.write_any(val)
        elif isinstance(v, (list, tuple)):
            self.write_uint8(117)
            self.write_var_uint(len(v))
            for item in v:
                self.write_any(item)
        elif isinstance(v, (bytes, bytearray)):
            self.write_uint8(116)
            self.write_var_uint8_array(bytes(v))
        else:
            raise TypeError(f"cannot encode value of type {type(v)!r} as lib0 any")


class Decoder:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes):
        self.data = bytes(data)
        self.pos = 0

    def has_content(self) -> bool:
        return self.pos < len(self.data)

    def remaining(self) -> int:
        """Bytes left to read — the buffer-anchored bound defensive
        decoders (state vectors, trace contexts) fence declared
        counts against before trusting them."""
        return len(self.data) - self.pos

    def read_uint8(self) -> int:
        if self.pos >= len(self.data):
            raise ValueError("unexpected end of lib0 buffer")
        b = self.data[self.pos]
        self.pos += 1
        return b

    def read_var_uint(self) -> int:
        n = 0
        shift = 0
        while True:
            b = self.read_uint8()
            n |= (b & 0x7F) << shift
            if not (b & 0x80):
                # uint64-representability, mirroring the native
                # reader's overflow rejection at EVERY varuint
                # position (flag/count positions included): a value
                # only python's bigints can hold would make a
                # python-decoding and a native-decoding replica
                # disagree on the same blob
                if n >= (1 << 64):
                    raise ValueError("varUint exceeds uint64")
                return n
            shift += 7
            if shift > 70:
                raise ValueError("varUint too long")

    def read_var_int(self) -> int:
        b = self.read_uint8()
        sign = -1 if b & 0x40 else 1
        n = b & 0x3F
        shift = 6
        while b & 0x80:
            b = self.read_uint8()
            n |= (b & 0x7F) << shift
            shift += 7
            if shift > 70:
                raise ValueError("varInt too long")
        # int64-representability bound, shared with the native codec:
        # magnitudes in [2^63, 2^64) wrap negative through its int64
        # cast, so a python-decoding and a native-decoding replica
        # would silently diverge on the same blob (honest lib0 writers
        # emit JS safe integers, < 2^53)
        if n >= (1 << 63):
            raise ValueError("varInt magnitude exceeds int64")
        return sign * n

    def read_var_string(self) -> str:
        return self.read_bytes(self.read_var_uint()).decode("utf-8")

    def read_var_uint8_array(self) -> bytes:
        return self.read_bytes(self.read_var_uint())

    def read_bytes(self, n: int) -> bytes:  # crdtlint: sanitizes
        # the pre-check fences the SIGN too: a negative count would
        # pass the tail check, return a truncated slice, and silently
        # REWIND the cursor (pos += n), letting a decoder re-read
        # bytes forever (round-17 decode-allocation contract)
        if n < 0:
            raise ValueError("negative lib0 byte count")
        if self.pos + n > len(self.data):
            raise ValueError("unexpected end of lib0 buffer")
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def read_float32(self) -> float:
        return struct.unpack(">f", self.read_bytes(4))[0]

    def read_float64(self) -> float:
        return struct.unpack(">d", self.read_bytes(8))[0]

    def read_int64(self) -> int:
        return struct.unpack(">q", self.read_bytes(8))[0]

    def read_any(self) -> Any:
        t = self.read_uint8()
        if t == 127:
            return UNDEFINED
        if t == 126:
            return None
        if t == 125:
            return self.read_var_int()
        if t == 124:
            return self.read_float32()
        if t == 123:
            return self.read_float64()
        if t == 122:
            return self.read_int64()
        if t == 121:
            return False
        if t == 120:
            return True
        if t == 119:
            return self.read_var_string()
        if t == 118:
            n = self.read_var_uint()
            return {self.read_var_string(): self.read_any() for _ in range(n)}
        if t == 117:
            n = self.read_var_uint()
            return [self.read_any() for _ in range(n)]
        if t == 116:
            return self.read_var_uint8_array()
        raise ValueError(f"unknown lib0 any type byte {t}")
