"""Yjs v1 binary update codec.

The reference moves document state exclusively as v1 update blobs
(``Y.encodeStateAsUpdate`` / ``Y.applyUpdate`` / ``Y.encodeStateVector``
at crdt.js:56,59,294); this module provides the byte-compatible codec
over our unit-item records so the framework can interoperate with
Yjs-wire peers and replay captured traces.

Wire layout (v1):

  update        := clientStructs deleteSet
  clientStructs := numClients:varUint
                   { numStructs:varUint client:varUint clock:varUint
                     struct* }*
  struct        := info:uint8 payload
      info bits: 5-bit content ref | 0x80 origin present |
                 0x40 rightOrigin present | 0x20 parentSub present
      refs: 0 GC, 1 Deleted, 2 JSON, 3 Binary, 4 String, 5 Embed,
            6 Format, 7 Type, 8 Any, 9 Doc, 10 Skip
      If neither origin nor rightOrigin is present the parent is
      written: varUint(1)+varString(rootName) or varUint(0)+ID, then
      the optional parentSub string. Otherwise the parent is derived
      from the origin item at integration time.
  deleteSet     := numClients:varUint
                   { client:varUint numRanges:varUint
                     { clock:varUint len:varUint }* }*

Runs: a wire struct may span several clocks (ContentAny with n
elements, ContentString with n UTF-16 code units, Deleted/GC/Skip with
a length). Decode splits runs into unit records (part j's origin is
(client, clock+j-1), all parts share the struct's rightOrigin — the
exact shape Yjs produces when splitting items). Encode re-coalesces
maximal runs, so round-trips are compact.
"""

from __future__ import annotations

import json
from typing import Any, List, Optional, Sequence, Tuple

from crdt_tpu.codec.lib0 import UNDEFINED, Decoder, Encoder
from crdt_tpu.core.ids import DeleteSet, StateVector
from crdt_tpu.core.records import ItemRecord
from crdt_tpu.core.store import (
    K_ANY,
    K_BINARY,
    K_DELETED,
    K_DOC,
    K_EMBED,
    K_FORMAT,
    K_GC,
    K_JSON,
    K_STRING,
    K_TYPE,
    NULL,
)

# wire content refs
# Wire sanity bound shared with the kernels' 40-bit clock packing
# (ops/device pack_id): any struct clock, run end, origin clock, or
# delete-range end at or beyond this is hostile — honest clocks count
# ops actually created. Bounding here keeps run expansion and every
# downstream clock computation finite (adversarial matrix,
# tests/test_yjs_fixtures.py).
_MAX_CLOCK = 1 << 40

# client-id fields get a looser bound: honest Yjs clients are random
# 32-bit ints; anything at or beyond 2^62 is hostile, and values in
# [2^63, 2^64) would wrap negative through an int64 cast in the native
# codec — 2^64-1 would even collide with its -1 "absent" sentinel.
# Both codecs reject the whole band so a hostile blob cannot make a
# python-decoding replica and a native-decoding replica disagree.
_MAX_ID = 1 << 62

REF_GC = 0
REF_DELETED = 1
REF_JSON = 2
REF_BINARY = 3
REF_STRING = 4
REF_EMBED = 5
REF_FORMAT = 6
REF_TYPE = 7
REF_ANY = 8
REF_DOC = 9
REF_SKIP = 10

_KIND_TO_REF = {
    K_GC: REF_GC,
    K_DELETED: REF_DELETED,
    K_JSON: REF_JSON,
    K_BINARY: REF_BINARY,
    K_STRING: REF_STRING,
    K_EMBED: REF_EMBED,
    K_FORMAT: REF_FORMAT,
    K_TYPE: REF_TYPE,
    K_ANY: REF_ANY,
    K_DOC: REF_DOC,
}


def _utf16_units(s: str) -> List[str]:
    """Split into UTF-16 code units (Yjs clock lengths are JS string
    lengths); surrogate halves survive via surrogatepass."""
    units = []
    for ch in s:
        b = ch.encode("utf-16-be", "surrogatepass")
        for i in range(0, len(b), 2):
            units.append(b[i : i + 2].decode("utf-16-be", "surrogatepass"))
    return units


def _join_utf16(units: Sequence[str]) -> str:
    b = b"".join(u.encode("utf-16-be", "surrogatepass") for u in units)
    return b.decode("utf-16-be", "surrogatepass")


# ---------------------------------------------------------------------------
# state vector
# ---------------------------------------------------------------------------

def encode_state_vector(sv: StateVector) -> bytes:
    e = Encoder()
    clocks = {c: k for c, k in sv.clocks.items() if k > 0}
    e.write_var_uint(len(clocks))
    for client in sorted(clocks, reverse=True):
        e.write_var_uint(client)
        e.write_var_uint(clocks[client])
    return e.to_bytes()


def decode_state_vector(data: bytes) -> StateVector:
    # round-17 wire-taint fix (crdtlint CL1001): state vectors arrive
    # off the wire in sync probes/beacons too — client and clock ride
    # the SAME bounds as update structs (_MAX_ID / _MAX_CLOCK).
    # Before this fence, a hostile SV with a 2^63 clock decoded fine
    # and overflowed int64 in device staging (statevec deficits,
    # shard boundary exchange) instead of failing closed here.
    d = Decoder(data)
    n = d.read_var_uint()
    sv = StateVector()
    for _ in range(n):
        client = _read_client_id(d)
        clock = _read_clock_val(d)
        if clock > 0:
            sv.clocks[client] = clock
    if d.has_content():
        raise ValueError("trailing bytes after state vector")
    return sv


# ---------------------------------------------------------------------------
# bounded wire reads (shared rejection semantics with the native
# codec's Reader::field — see _MAX_ID / _MAX_CLOCK)
# ---------------------------------------------------------------------------

def _read_client_id(d: Decoder) -> int:  # crdtlint: sanitizes
    v = d.read_var_uint()
    if v >= _MAX_ID:
        raise ValueError("client id exceeds wire bound")
    return v


def _read_clock_val(d: Decoder) -> int:  # crdtlint: sanitizes
    v = d.read_var_uint()
    if v >= _MAX_CLOCK:
        raise ValueError("clock exceeds wire bound")
    return v


def _read_id(d: Decoder) -> tuple:
    return (_read_client_id(d), _read_clock_val(d))


# ---------------------------------------------------------------------------
# delete set
# ---------------------------------------------------------------------------

def _write_delete_set(e: Encoder, ds: Optional[DeleteSet]) -> None:
    if ds is None:
        e.write_var_uint(0)
        return
    ds = ds.copy()
    ds.normalize()
    clients = sorted(ds.ranges, reverse=True)
    e.write_var_uint(len(clients))
    for client in clients:
        rs = ds.ranges[client]
        e.write_var_uint(client)
        e.write_var_uint(len(rs))
        for s, end in rs:
            e.write_var_uint(s)
            e.write_var_uint(end - s)


def _read_delete_set(d: Decoder) -> DeleteSet:
    ds = DeleteSet()
    for _ in range(d.read_var_uint()):
        client = _read_client_id(d)
        for _ in range(d.read_var_uint()):
            clock = d.read_var_uint()
            length = d.read_var_uint()
            if clock + length >= _MAX_CLOCK:
                raise ValueError("delete range exceeds wire clock bound")
            if length:
                ds.add(client, clock, length)
    ds.normalize()
    return ds


# ---------------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------------

def _coalesce(recs: List[ItemRecord]) -> List[List[ItemRecord]]:
    """Group a client's clock-sorted unit records into maximal wire runs."""
    runs: List[List[ItemRecord]] = []
    for rec in recs:
        if runs:
            run = runs[-1]
            prev = run[-1]
            # parent matches if explicitly equal, or absent entirely (then
            # it is derived from the origin chain at integration, which
            # inside a run always points at the previous part)
            same_parent = (
                rec.parent_root is None
                and rec.parent_item is None
                and rec.key is None
            ) or (
                rec.parent_root == prev.parent_root
                and rec.parent_item == prev.parent_item
                and rec.key == prev.key
            )
            chained = (
                rec.clock == prev.clock + 1
                and rec.origin == (prev.client, prev.clock)
                and rec.right == run[0].right
            )
            # GC/Skip runs only need clock adjacency
            plain = rec.kind in (K_GC,) and prev.kind == rec.kind and rec.clock == prev.clock + 1
            mergeable_kind = rec.kind == prev.kind and rec.kind in (
                K_ANY,
                K_JSON,
                K_STRING,
                K_DELETED,
            )
            if plain or (mergeable_kind and same_parent and chained):
                run.append(rec)
                continue
        runs.append([rec])
    return runs


def _write_item_content(e: Encoder, run: List[ItemRecord]) -> None:
    kind = run[0].kind
    if kind == K_DELETED:
        e.write_var_uint(len(run))
    elif kind == K_JSON:
        e.write_var_uint(len(run))
        for r in run:
            if r.content is UNDEFINED:
                e.write_var_string("undefined")
            else:
                e.write_var_string(json.dumps(r.content))
    elif kind == K_BINARY:
        e.write_var_uint8_array(bytes(run[0].content))
    elif kind == K_STRING:
        e.write_var_string(_join_utf16([r.content for r in run]))
    elif kind == K_EMBED:
        e.write_var_string(json.dumps(run[0].content))
    elif kind == K_FORMAT:
        k, v = run[0].content
        e.write_var_string(k)
        e.write_var_string(json.dumps(v))
    elif kind == K_TYPE:
        e.write_var_uint(int(run[0].type_ref))
    elif kind == K_ANY:
        e.write_var_uint(len(run))
        for r in run:
            e.write_any(r.content)
    elif kind == K_DOC:
        guid, opts = run[0].content
        e.write_var_string(guid)
        e.write_any(opts)
    else:
        raise ValueError(f"cannot encode content kind {kind}")


def encode_update(
    records: Sequence[ItemRecord], delete_set: Optional[DeleteSet] = None
) -> bytes:
    """Encode unit records + delete set as a v1 update blob."""
    by_client: dict = {}
    for r in records:
        by_client.setdefault(r.client, []).append(r)
    for recs in by_client.values():
        recs.sort(key=lambda r: r.clock)

    e = Encoder()
    e.write_var_uint(len(by_client))
    for client in sorted(by_client, reverse=True):
        recs = by_client[client]
        runs = _coalesce(recs)
        # inject Skip runs for clock gaps (diff updates above a state
        # vector are contiguous, but be defensive like Yjs is)
        withskips: List[Tuple[str, Any]] = []
        prev_end = None
        for run in runs:
            start = run[0].clock
            if prev_end is not None and start > prev_end:
                withskips.append(("skip", (prev_end, start - prev_end)))
            withskips.append(("run", run))
            prev_end = run[-1].clock + 1
        e.write_var_uint(len(withskips))
        e.write_var_uint(client)
        first = withskips[0]
        e.write_var_uint(
            first[1][0].clock if first[0] == "run" else first[1][0]
        )
        for tag, payload in withskips:
            if tag == "skip":
                _, length = payload
                e.write_uint8(REF_SKIP)
                e.write_var_uint(length)
                continue
            run = payload
            head = run[0]
            if head.kind == K_GC:
                e.write_uint8(REF_GC)
                e.write_var_uint(len(run))
                continue
            ref = _KIND_TO_REF[head.kind]
            has_origin = head.origin is not None
            has_right = head.right is not None
            write_parent = not has_origin and not has_right
            has_sub = write_parent and head.key is not None
            info = (
                ref
                | (0x80 if has_origin else 0)
                | (0x40 if has_right else 0)
                | (0x20 if has_sub else 0)
            )
            e.write_uint8(info)
            if has_origin:
                e.write_var_uint(head.origin[0])
                e.write_var_uint(head.origin[1])
            if has_right:
                e.write_var_uint(head.right[0])
                e.write_var_uint(head.right[1])
            if write_parent:
                if head.parent_root is not None:
                    e.write_var_uint(1)
                    e.write_var_string(head.parent_root)
                else:
                    assert head.parent_item is not None, (
                        "record needs parent_root, parent_item, or an origin"
                    )
                    e.write_var_uint(0)
                    e.write_var_uint(head.parent_item[0])
                    e.write_var_uint(head.parent_item[1])
                if has_sub:
                    e.write_var_string(head.key)
            _write_item_content(e, run)
    _write_delete_set(e, delete_set)
    return e.to_bytes()


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------

def _split_units(
    client: int,
    clock: int,
    *,
    parent_root: Optional[str],
    parent_item: Optional[Tuple[int, int]],
    key: Optional[str],
    origin: Optional[Tuple[int, int]],
    right: Optional[Tuple[int, int]],
    kind: int,
    type_ref: int = NULL,
    contents: Optional[List[Any]] = None,
    length: int = 1,
) -> List[ItemRecord]:
    n = len(contents) if contents is not None else length
    out = []
    for j in range(n):
        out.append(
            ItemRecord(
                client=client,
                clock=clock + j,
                parent_root=parent_root if j == 0 else None,
                parent_item=parent_item if j == 0 else None,
                key=key if j == 0 else None,
                origin=origin if j == 0 else (client, clock + j - 1),
                right=right,
                kind=kind,
                type_ref=type_ref,
                content=contents[j] if contents is not None else None,
            )
        )
    # parts after the first derive parent from their origin (previous
    # part); keep key on the first part only, like a Yjs split does
    return out


def decode_update(data: bytes) -> Tuple[List[ItemRecord], DeleteSet]:
    d = Decoder(data)
    records: List[ItemRecord] = []
    # expansion budget: GC/Deleted runs decode to unit records, so a
    # few declared bytes must never buy unbounded allocation. Honest
    # compacted histories stay far under 4096 units per blob byte;
    # hostile declarations fail fast instead of hanging the decoder.
    budget = max(1 << 20, 4096 * len(data))
    num_clients = d.read_var_uint()
    for _ in range(num_clients):
        num_structs = d.read_var_uint()
        client = _read_client_id(d)
        clock = _read_clock_val(d)
        for _ in range(num_structs):
            info = d.read_uint8()
            ref = info & 0x1F
            if ref == REF_SKIP:
                clock += d.read_var_uint()
                if clock >= _MAX_CLOCK:
                    raise ValueError("skip run exceeds wire clock bound")
                continue
            if ref == REF_GC:
                length = d.read_var_uint()
                if clock + length >= _MAX_CLOCK:
                    raise ValueError("gc run exceeds wire clock bound")
                if len(records) + length > budget:
                    raise ValueError("expansion budget exceeded")
                records.extend(
                    _split_units(
                        client,
                        clock,
                        parent_root=None,
                        parent_item=None,
                        key=None,
                        origin=None,
                        right=None,
                        kind=K_GC,
                        length=length,
                    )
                )
                clock += length
                continue
            origin = None
            right = None
            parent_root = None
            parent_item = None
            key = None
            if info & 0x80:
                origin = _read_id(d)
            if info & 0x40:
                right = _read_id(d)
            if not (info & 0xC0):
                if d.read_var_uint() == 1:
                    parent_root = d.read_var_string()
                else:
                    parent_item = _read_id(d)
                if info & 0x20:
                    key = d.read_var_string()
            common = dict(
                parent_root=parent_root,
                parent_item=parent_item,
                key=key,
                origin=origin,
                right=right,
            )
            if ref == REF_DELETED:
                length = d.read_var_uint()
                if clock + length >= _MAX_CLOCK:
                    raise ValueError("deleted run exceeds wire clock bound")
                if len(records) + length > budget:
                    raise ValueError("expansion budget exceeded")
                recs = _split_units(
                    client, clock, kind=K_DELETED, length=length, **common
                )
            elif ref == REF_JSON:
                n = d.read_var_uint()
                vals = []
                for _ in range(n):
                    s = d.read_var_string()
                    vals.append(UNDEFINED if s == "undefined" else json.loads(s))
                recs = _split_units(
                    client, clock, kind=K_JSON, contents=vals, **common
                )
            elif ref == REF_BINARY:
                recs = _split_units(
                    client,
                    clock,
                    kind=K_BINARY,
                    contents=[d.read_var_uint8_array()],
                    **common,
                )
            elif ref == REF_STRING:
                units = _utf16_units(d.read_var_string())
                recs = _split_units(
                    client, clock, kind=K_STRING, contents=units, **common
                )
            elif ref == REF_EMBED:
                recs = _split_units(
                    client,
                    clock,
                    kind=K_EMBED,
                    contents=[json.loads(d.read_var_string())],
                    **common,
                )
            elif ref == REF_FORMAT:
                k = d.read_var_string()
                v = json.loads(d.read_var_string())
                recs = _split_units(
                    client, clock, kind=K_FORMAT, contents=[(k, v)], **common
                )
            elif ref == REF_TYPE:
                tref = d.read_var_uint()
                if tref >= (1 << 31):
                    raise ValueError("type ref exceeds wire bound")
                recs = _split_units(
                    client, clock, kind=K_TYPE, type_ref=tref, length=1, **common
                )
            elif ref == REF_ANY:
                n = d.read_var_uint()
                vals = [d.read_any() for _ in range(n)]
                recs = _split_units(
                    client, clock, kind=K_ANY, contents=vals, **common
                )
            elif ref == REF_DOC:
                guid = d.read_var_string()
                opts = d.read_any()
                recs = _split_units(
                    client, clock, kind=K_DOC, contents=[(guid, opts)], **common
                )
            else:
                raise ValueError(f"unknown struct ref {ref}")
            records.extend(recs)
            clock += len(recs)
    ds = _read_delete_set(d)
    if d.has_content():
        raise ValueError("trailing bytes after v1 update")
    return records, ds


# ---------------------------------------------------------------------------
# engine glue — the Y.* surface the reference calls
# ---------------------------------------------------------------------------

def encode_state_as_update(engine, sv: Optional[StateVector] = None) -> bytes:
    """``Y.encodeStateAsUpdate(doc[, sv])`` (crdt.js:56,288,347): items
    above the target state vector plus the full delete set.

    Full-state encodes (``sv`` None or empty — compaction snapshots,
    and the syncer's answer to a FRESH requester, whose decoded state
    vector is empty) go through the native column encoder in one C
    pass over the store's SoA columns; byte-identity with the Python
    record path is pinned by tests/test_native_codec.py. Real diffs
    stay on the O(deficit) record path."""
    if sv is None or not sv.clocks:
        from crdt_tpu.codec import native

        if native.available():
            ds = engine.delete_set()
            return native.encode_from_columns(
                engine.to_decoded_columns(ds), ds
            )
    return encode_update(engine.records_since(sv), engine.delete_set())


def apply_update(engine, data: bytes) -> None:
    """``Y.applyUpdate(doc, update)`` (crdt.js:294)."""
    records, ds = decode_update(data)
    engine.apply_records(records, ds)


def encode_state_vector_of(engine) -> bytes:
    return encode_state_vector(engine.state_vector())
