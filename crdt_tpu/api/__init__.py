from crdt_tpu.api.doc import Crdt, ReservedNameError, WrongKindError

__all__ = ["Crdt", "ReservedNameError", "WrongKindError"]
