from crdt_tpu.api.doc import Crdt, ReservedNameError, WrongKindError
from crdt_tpu.api.resident_doc import ResidentCrdt

__all__ = ["Crdt", "ResidentCrdt", "ReservedNameError", "WrongKindError"]
