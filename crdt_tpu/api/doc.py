"""Document/API layer — the reference's public surface (L4+L5).

Reproduces the op layer and friendly API of the reference
(`/root/reference/crdt.js:325-702`): named map/array collections over
one shared document, a plain-JSON read cache ``c`` with attribute
fallthrough (the reference's Proxy, crdt.js:688-693), a batch queue
drained by ``exec_batch`` in a single transaction (crdt.js:325-355),
an index map ``ix`` registering collection kinds (crdt.js:201,205),
and per-collection observers (crdt.js:620-657).

Documented divergences from the reference (SURVEY.md §6 — all defects
fixed rather than replicated):

- D1: non-batch ``unshift``/``cut`` actually mutate (the reference's
  else-branch skips ``operation()``, crdt.js:583-588,609-614).
- D2: nested-array validation works (the reference calls the
  nonexistent ``Array.prototype.contains``, crdt.js:411).
- D3: collections created remotely appear in the cache (the reference
  iterates its own stale index, crdt.js:297-305).
- D4: ``exec_batch`` on an empty queue returns instead of hanging
  (crdt.js:330-331).
- D7: ``get`` exists (README.md:83 promises it, the code lacks it);
  ``insert`` takes ``(name, index, value)`` in the README's order
  (the code's is val-then-index, crdt.js:521).
- Q1: observers fire on local mutations too, tagged with ``origin``
  (the reference only fires on remote updates, crdt.js:308-310).
- Q2: updates emitted per op are true deltas (new items + delete-set
  delta of the transaction); ``full_state_updates=True`` restores the
  reference's full-state-per-op broadcast behavior (crdt.js:443).
"""

from __future__ import annotations

import copy
from types import MappingProxyType
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from crdt_tpu.codec import v1
from crdt_tpu.core.engine import Engine, ParentSpec  # noqa: F401 — ParentSpec is part of the Doc API surface
from crdt_tpu.core.ids import DeleteSet, StateVector
from crdt_tpu.core.store import NULL, TYPE_ARRAY

# names the reference refuses to use as collection names (crdt.js:320,365)
RESERVED_NAMES = ("ix", "doc")

ARRAY_METHODS = ("insert", "push", "unshift", "cut")


class ReservedNameError(ValueError):
    pass


class WrongKindError(TypeError):
    pass


def _as_list(value: Any) -> list:
    """Scalar -> single-element list (the reference's push wrap,
    crdt.js:554); lists pass through."""
    return value if isinstance(value, list) else [value]


class _Observer:
    __slots__ = ("name", "key", "func")

    def __init__(self, name: str, key: Optional[str], func: Callable):
        self.name = name
        self.key = key
        self.func = func


class DocOpsMixin:
    """Backend-independent op plumbing shared by the engine-backed
    :class:`Crdt` and the resident-backed
    :class:`crdt_tpu.api.resident_doc.ResidentCrdt`: the reserved-name
    guard, the observer registry, the txn-exception choreography, and
    the batch queue. Subclasses supply ``_begin_txn()`` and
    ``_finish_txn(origin, meta=None, propagate=True,
    want_update=False)`` plus ``_batched`` / ``_observers`` lists."""

    def _check_name(self, name: str) -> None:
        if not isinstance(name, str) or not name:
            raise ValueError("collection name must be a non-empty string")
        if name in RESERVED_NAMES:
            raise ReservedNameError(
                f"'{name}' is reserved (crdt.js:320,365)"
            )

    # ---- op plumbing (the per-op tail, crdt.js:440-447) --------------
    def _run_op(self, batch: bool, operation: Callable[[], Any]) -> Any:
        if batch:
            self._batched.append(operation)
            return None
        self._begin_txn()
        try:
            result = operation()
        except BaseException:
            # a throwing op still commits what it integrated (Yjs txn
            # semantics): the records exist with allocated clocks, so
            # not broadcasting them would wedge every peer on a
            # per-client clock gap forever — but the op's own error
            # must win over any broadcast-tail error
            try:
                self._finish_txn(origin="local")
            except Exception:
                pass
            raise
        self._finish_txn(origin="local")
        return result

    # ---- batch queue (crdt.js:325-355) -------------------------------
    def exec_batch(self, propagate: bool = True) -> Optional[bytes]:
        """Drain queued ops in one transaction → one update (one
        broadcast). Empty queue returns None (D4: the reference hangs).

        ``propagate=False`` mirrors ``throughDatabase``
        (crdt.js:350-353): the update is returned without invoking
        ``on_update``.
        """
        if not self._batched:
            return None
        ops, self._batched = self._batched, []
        self._begin_txn()
        try:
            for op in ops:
                op()
        except BaseException:
            # partial batches commit what ran before the throw (see
            # _run_op: unbroadcast records would wedge peers)
            try:
                self._finish_txn(
                    "local",
                    meta={"meta": "batch"},
                    propagate=propagate,
                    want_update=True,
                )
            except Exception:
                pass
            raise
        return self._finish_txn(
            "local",
            meta={"meta": "batch"},
            propagate=propagate,
            want_update=True,
        )

    @property
    def pending_batch_size(self) -> int:
        return len(self._batched)

    # ---- observers (crdt.js:620-657) ---------------------------------
    def observe(self, name: str, func: Callable, key: Optional[str] = None):
        self._observers.append(_Observer(name, key, func))
        return func

    def unobserve(self, func: Callable) -> bool:
        before = len(self._observers)
        self._observers = [o for o in self._observers if o.func is not func]
        return len(self._observers) < before


class Crdt(DocOpsMixin):
    """One replica's document + API.

    Transport and persistence attach through two hooks:

    - ``on_update(update_bytes, meta)`` — called after every non-batch
      op and every ``exec_batch`` with the encoded v1 update (the
      reference's persist+propagate tail, crdt.js:442-446).
    - ``observer_function(event)`` — the reference's coarse observer
      (crdt.js:308-310), fired with a dict carrying the frozen cache.
    """

    def __init__(
        self,
        client_id: int,
        *,
        observer_function: Optional[Callable[[dict], None]] = None,
        on_update: Optional[Callable[[bytes, dict], None]] = None,
        full_state_updates: bool = False,
        device_merge: Optional[bool] = None,
    ):
        self.engine = Engine(client_id)
        self.observer_function = observer_function
        self.on_update = on_update
        self.full_state_updates = full_state_updates
        # CRDT_TPU_DEVICE is a PRODUCT-level knob consumed by the
        # replica layer, where it selects merge_mode="resident"
        # (net/replica.py; VERDICT r3 item 4). The standalone Crdt
        # keeps the engine device gate strictly explicit — one env
        # var must not mean different things at different layers.
        self.device_merge = bool(device_merge)
        self._c: Dict[str, Any] = {}
        self._batched: List[Callable[[], Any]] = []
        self._observers: List[_Observer] = []
        self._known_len = 0  # root_kinds size at last D3 backfill

    # ------------------------------------------------------------------
    # cache / reads (the reference's Proxy + frozen `c`, crdt.js:661-702)
    # ------------------------------------------------------------------
    @property
    def c(self):
        """Read-only snapshot cache (``Object.freeze({...c})``)."""
        return MappingProxyType(self._c)

    def __getattr__(self, prop: str) -> Any:
        # Proxy fallthrough: unknown property reads hit the cache
        # (crdt.js:691: `return target.c[prop]`)
        try:
            return self.__dict__["_c"][prop]
        except KeyError:
            raise AttributeError(prop) from None

    def __getitem__(self, prop: str) -> Any:
        return self._c[prop]

    def __contains__(self, prop: str) -> bool:
        return prop in self._c

    def __repr__(self) -> str:
        # the reference's custom inspect prints the cache (crdt.js:696)
        return f"Crdt(client={self.engine.client_id}, c={self._c!r})"

    def get(self, name: str, key: Optional[str] = None) -> Any:
        """Visible value — the method README.md:83 documents but the
        reference never shipped (D7)."""
        if key is None:
            return copy.deepcopy(self._c.get(name))
        return copy.deepcopy(self.engine.map_get(name, key))

    def state_vector(self) -> StateVector:
        return self.engine.state_vector()

    def encode_state_vector(self) -> bytes:
        return v1.encode_state_vector_of(self.engine)

    def encode_state_as_update(self, sv: Optional[StateVector] = None) -> bytes:
        return v1.encode_state_as_update(self.engine, sv)

    # ------------------------------------------------------------------
    # guards
    # ------------------------------------------------------------------
    def _kind_of(self, name: str) -> Optional[str]:
        kind = self.engine.map_get("ix", name)
        if kind is not None:
            return kind
        return self.engine.root_kinds.get(name)

    def _check_kind(self, name: str, want: str) -> None:
        kind = self._kind_of(name)
        if kind is not None and kind != want:
            raise WrongKindError(f"'{name}' is a {kind}, not a {want}")

    # ------------------------------------------------------------------
    # op plumbing (the per-op tail, crdt.js:440-447; _run_op and the
    # batch queue live in DocOpsMixin)
    # ------------------------------------------------------------------
    def _begin_txn(self) -> None:
        self.engine.begin_txn()

    def _finish_txn(
        self,
        origin: str,
        meta: Optional[dict] = None,
        propagate: bool = True,
        want_update: bool = False,
    ) -> Optional[bytes]:
        eng = self.engine
        # last_txn_items lists exactly this txn's rows: O(txn), not the
        # O(doc) scan records_since would do
        new_records = eng.records_for_rows(eng.last_txn_items)
        txn_deletes = eng.last_txn_deletes
        touched, touched_keys = self._touched_roots()
        self._refresh_cache(touched, touched_keys)
        update = None
        emitting = propagate and self.on_update is not None and origin == "local"
        if (new_records or txn_deletes.ranges) and (emitting or want_update):
            if self.full_state_updates:
                update = v1.encode_state_as_update(eng)  # Q2 compat mode
            else:
                update = v1.encode_update(new_records, txn_deletes)
            # broadcast BEFORE observers: a throwing observer must not
            # abort the emission, or peers wedge on the clock gap
            if emitting:
                self.on_update(update, meta or {})
        self._fire_observers(touched, touched_keys, origin)
        return update

    def _touched_roots(self) -> Tuple[List[str], Dict[str, set]]:
        """Roots touched by the last txn, plus per-root changed top-level
        keys (the key of the item directly under the root — nested
        edits roll up to the map key holding the nested type)."""
        eng = self.engine
        s = eng.store
        roots: set = set()
        keys: Dict[str, set] = {}
        rows = list(eng.last_txn_items)
        for client, clock, length in eng.last_txn_deletes.iter_all():
            for k in range(clock, clock + length):
                row = s.find(client, k)
                if row is not None:
                    rows.append(row)
        for row in rows:
            root, key = self._classify_row(row)
            if root is not None:
                roots.add(root)
                if key is not None:
                    keys.setdefault(root, set()).add(key)
        return sorted(roots), keys

    def _classify_row(self, row: int) -> Tuple[Optional[str], Optional[str]]:
        """(root name, top-level map key) of a row, walking up nested
        parents; key is None for sequence members of a root array."""
        from crdt_tpu.core.store import NO_KEY

        s = self.engine.store
        seen = set()
        while row is not None and row not in seen:
            seen.add(row)
            if s.parent_root[row] != NULL:
                root = s.root_names[int(s.parent_root[row])]
                kid = int(s.key_id[row])
                return root, (s.keys[kid] if kid != NO_KEY else None)
            if s.parent_client[row] == NULL:
                return None, None  # GC filler — no positional info
            row = s.find(int(s.parent_client[row]), int(s.parent_clock[row]))
        return None, None

    def _refresh_cache(
        self,
        roots: Sequence[str],
        touched_keys: Optional[Dict[str, set]] = None,
    ) -> None:
        eng = self.engine
        for name in roots:
            if name == "ix":
                continue
            kind = self._kind_of(name)
            # deep-copied: cache values must not alias live store
            # content, or `crdt.c['m']['k'].append(...)` would mutate
            # CRDT state without an op and diverge replicas
            if kind == "array":
                self._c[name] = copy.deepcopy(eng.seq_json(name))
            elif kind == "map":
                keys = (touched_keys or {}).get(name)
                cur = self._c.get(name)
                if keys is None or None in keys or not isinstance(cur, dict):
                    # unknown per-key delta (or first materialization):
                    # full rebuild
                    self._c[name] = copy.deepcopy(eng.map_json(name))
                    continue
                # per-key incremental refresh: O(changed keys), not
                # O(map) — r1 deep-copied whole collections per txn.
                # Rebound (not mutated): stored observer events hold
                # the previous snapshot dict. Like the reference's
                # SHALLOW Object.freeze({...c}) (crdt.js:668-670),
                # snapshots are isolated from CRDT-driven change, not
                # from callers mutating nested values — cache values
                # are read-only by contract (and unchanged keys were
                # always shared across snapshots for untouched roots)
                new = dict(cur)
                for k in keys:
                    if eng.map_has(name, k):
                        new[k] = copy.deepcopy(eng.map_get(name, k))
                    else:
                        new.pop(k, None)
                self._c[name] = new
        # D3 fix: collections created remotely get cache entries too.
        # New collections only appear when the txn touched the index
        # map or integrated items under a new root, so the O(known)
        # backfill is skipped on hot single-collection txns.
        if "ix" in roots or len(eng.root_kinds) != self._known_len:
            self._known_len = len(eng.root_kinds)
            known = set(eng.map_json("ix").keys()) | set(eng.root_kinds.keys())
            known.discard("ix")
            for name in known:
                if name not in self._c:
                    kind = self._kind_of(name)
                    self._c[name] = copy.deepcopy(
                        eng.seq_json(name) if kind == "array" else eng.map_json(name)
                    )

    def _fire_observers(
        self,
        touched: Sequence[str],
        touched_keys: Dict[str, set],
        origin: str,
    ) -> None:
        if not touched:
            return  # no-op txns (incl. failed ops) emit no events
        event = {
            "origin": origin,
            "touched": list(touched),
            # snapshot, not a live view: later txns rebind cache
            # entries and must not retroactively mutate stored events
            # (the reference freezes a copy too: Object.freeze({...c}),
            # crdt.js:668-670)
            "c": MappingProxyType(dict(self._c)),
        }
        if self.observer_function is not None:
            # Q1 fix: fires on local mutations too, origin-tagged
            self.observer_function(event)
        for ob in self._observers:
            if ob.name in touched:
                if ob.key is not None:
                    # per-key observers fire only when their key changed
                    # (the reference attaches to h[name][key],
                    # crdt.js:622-638)
                    if ob.key not in touched_keys.get(ob.name, ()):
                        continue
                    # deep-copied: observers must not be able to mutate
                    # live store content (see _refresh_cache)
                    value = copy.deepcopy(self.engine.map_get(ob.name, ob.key))
                    ob.func({**event, "name": ob.name, "key": ob.key, "value": value})
                else:
                    # deep-copied like the key path: observers must not
                    # mutate the cached snapshot. (event["c"] itself is
                    # the shallow-frozen view, matching the reference's
                    # Object.freeze({...c}) — crdt.js:668-670.)
                    value = copy.deepcopy(self._c.get(ob.name))
                    ob.func({**event, "name": ob.name, "value": value})

    # ------------------------------------------------------------------
    # collection creation (crdt.js:363-390, 485-512)
    # ------------------------------------------------------------------
    def map(self, name: str, batch: bool = False):
        self._check_name(name)

        def operation():
            # kind check at execution time: a queued or remote op may
            # have registered the name since this op was queued
            self._check_kind(name, "map")
            if self.engine.map_get("ix", name) is None:
                self.engine.map_set("ix", name, "map")
                self.engine.root_kinds[name] = "map"
                self._c.setdefault(name, {})
            return name

        return self._run_op(batch, operation)

    def array(self, name: str, batch: bool = False):
        self._check_name(name)

        def operation():
            self._check_kind(name, "array")
            if self.engine.map_get("ix", name) is None:
                self.engine.map_set("ix", name, "array")
                self.engine.root_kinds[name] = "array"
                self._c.setdefault(name, [])
            return name

        return self._run_op(batch, operation)

    # ------------------------------------------------------------------
    # map ops (crdt.js:400-477)
    # ------------------------------------------------------------------
    def set(
        self,
        name: str,
        key: str,
        value: Any = None,
        *,
        array_method: Optional[str] = None,
        index: Optional[int] = None,
        length: Optional[int] = None,
        batch: bool = False,
    ) -> Any:
        """Set ``key`` in map ``name``; with ``array_method`` operate on a
        nested array stored under the key (crdt.js:422-432).

        Nested mode (D2 fixed — the reference's validation throws):
        ``array_method`` ∈ insert/push/unshift/cut; ``index``/``length``
        qualify insert and cut.
        """
        self._check_name(name)
        if not isinstance(key, str) or not key:
            raise ValueError("key must be a non-empty string")
        if array_method is not None and array_method not in ARRAY_METHODS:
            raise ValueError(f"array_method must be one of {ARRAY_METHODS}")
        if array_method == "insert" and index is None:
            raise ValueError("insert requires index")
        if array_method == "cut" and index is None:
            raise ValueError("cut requires index")

        def operation():
            eng = self.engine
            self._check_kind(name, "map")  # execution-time (see map())
            if eng.map_get("ix", name) is None:
                eng.map_set("ix", name, "map")  # auto-create (crdt.js:418-421)
                eng.root_kinds[name] = "map"
            if array_method is None:
                eng.map_set(name, key, value)
                return value
            spec = eng.map_entry_spec(name, key)
            if spec is None:
                rec = eng.map_set_type(name, key, TYPE_ARRAY)
                spec = ("item", rec.client, rec.clock)
            if array_method == "insert":
                eng.seq_insert(name, index, _as_list(value), parent=spec)
            elif array_method == "push":
                n = eng.seq_len(parent=spec)
                eng.seq_insert(name, n, _as_list(value), parent=spec)
            elif array_method == "unshift":
                eng.seq_insert(name, 0, _as_list(value), parent=spec)
            else:  # cut
                eng.seq_delete(
                    name,
                    index,
                    length if length is not None else 1,
                    parent=spec,
                )
            return copy.deepcopy(eng.map_get(name, key))

        return self._run_op(batch, operation)

    def delete(self, name: str, key: str, batch: bool = False) -> Any:
        """Delete ``key`` from map ``name`` (the reference's ``del``,
        crdt.js:459-477; ``del`` is a Python keyword)."""
        self._check_name(name)

        def operation():
            self._check_kind(name, "map")
            return self.engine.map_delete(name, key)

        return self._run_op(batch, operation)

    # the reference's name, for API parity in dynamic call sites
    del_ = delete

    # ------------------------------------------------------------------
    # array ops (crdt.js:485-617)
    # ------------------------------------------------------------------
    def _seq_op(self, name: str, batch: bool, body: Callable[[], Any]) -> Any:
        self._check_name(name)

        def operation():
            eng = self.engine
            self._check_kind(name, "array")  # execution-time (see map())
            if eng.map_get("ix", name) is None:
                eng.map_set("ix", name, "array")
                eng.root_kinds[name] = "array"
            return body()

        return self._run_op(batch, operation)

    def insert(self, name: str, index: int, value: Any, batch: bool = False):
        """Insert at index — README.md:87 argument order (D7; the
        reference code's is val-then-index, crdt.js:521)."""
        vals = _as_list(value)
        return self._seq_op(
            name, batch, lambda: self.engine.seq_insert(name, index, vals) and None
        )

    def push(self, name: str, value: Any, batch: bool = False):
        vals = _as_list(value)

        def body():
            n = self.engine.seq_len(name)
            self.engine.seq_insert(name, n, vals)

        return self._seq_op(name, batch, body)

    def unshift(self, name: str, value: Any, batch: bool = False):
        # D1 fix: the reference's non-batch unshift never mutates
        vals = _as_list(value)
        return self._seq_op(
            name, batch, lambda: self.engine.seq_insert(name, 0, vals) and None
        )

    def cut(self, name: str, index: int, length: int = 1, batch: bool = False):
        # D1 fix: the reference's non-batch cut never mutates
        return self._seq_op(
            name, batch, lambda: self.engine.seq_delete(name, index, length)
        )

    # ------------------------------------------------------------------
    # remote updates (crdt.js:292-311)
    # ------------------------------------------------------------------
    def apply_update(self, data: bytes, origin: str = "remote") -> None:
        self.apply_updates([data], origin)

    def apply_updates(self, datas: Sequence[bytes], origin: str = "remote") -> None:
        """Apply a batch of encoded updates as ONE merge transaction.

        This is the buffering gate of the north star: a sync backlog,
        a persistence log replay, or a gossip round's worth of updates
        decodes into one record union and pays one integration pass —
        and in device mode (``device_merge=True``) that pass runs on
        the TPU kernels
        (admit on host, chain rebuild via converge_maps +
        tree_order_ranks; see crdt_tpu.core.device_apply), replacing
        the reference's per-update scalar loop (crdt.js:294).
        """
        if not datas:
            return
        all_records, all_ds = self._decode_batch(datas)
        if self.device_merge:
            from crdt_tpu.core.device_apply import apply_records_device

            apply_records_device(self.engine, all_records, all_ds)
        else:
            self.engine.apply_records(all_records, all_ds)  # own txn
        touched, touched_keys = self._touched_roots()
        self._refresh_cache(touched, touched_keys)  # + D3 backfill
        self._fire_observers(touched, touched_keys, origin)

    @staticmethod
    def _decode_batch(datas: Sequence[bytes]):
        """Batch-decode updates, through the native C codec when the
        toolchain allows (one C pass for the whole backlog — the
        lib0/struct parsing that otherwise dominates log replays and
        sync bursts), falling back to the pure-Python codec."""
        try:
            from crdt_tpu.codec import native

            if native.available():
                # ValueError (malformed update) propagates: same
                # contract as the fallback below
                return native.decoded_to_records(
                    native.decode_updates_columns(datas)
                )
        except RuntimeError:
            pass  # toolchain raced away mid-call: fall back
        all_records: List[Any] = []
        all_ds = DeleteSet()
        for data in datas:
            records, ds = v1.decode_update(data)
            all_records.extend(records)
            for c, clk, length in ds.iter_all():
                all_ds.add(c, clk, length)
        return all_records, all_ds

