"""Resident-document API — the op layer over HBM-resident state.

``merge_mode="resident"`` replicas never materialize the scalar
engine: the document lives in :class:`crdt_tpu.models.incremental.
IncrementalReplay` (host admission columns + the HBM-resident device
matrix + per-segment winner/order caches), and this class puts the
reference's public surface (crdt.js:325-702 — the same one
:class:`crdt_tpu.api.doc.Crdt` reproduces engine-backed) on top of it.

The design collapses the local/remote asymmetry: **local ops ARE
updates**. Every mutation builds :class:`ItemRecord`s anchored on the
resident state (map chain tails from the winner cache, sequence
left/right anchors from the order cache — the same anchors
``Engine.map_set`` / ``Engine.seq_insert`` derive, with multi-value
inserts chained through fresh ids; see ``_seq_insert`` for the
placement-equivalence argument), encodes them as a v1 blob,
self-applies it through the SAME admission + convergence path remote
updates take, and hands the blob to the transport. One code path integrates everything (crdt.js:294's
``applyUpdate``, unified for both directions), so a resident replica
converges with engine-backed peers by construction — pinned by the
acceptance configs running all three merge modes in tests/test_net.py.

Per-round convergence cost follows the replay's host/device crossover
(``device_min_rows``): keystroke-sized deltas — including every local
op — converge on host against the resident columns; firehose rounds
go through the device kernels. Sync protocol answers (state vector,
ready-probe diffs, anti-entropy deficits, compaction snapshots) come
from the resident columns via ``IncrementalReplay``'s protocol
surface; see that module for the Engine-equivalence argument.
"""

from __future__ import annotations

import copy
from types import MappingProxyType
from typing import Any, Callable, Dict, List, Optional, Tuple

from crdt_tpu.api.doc import (
    ARRAY_METHODS,
    DocOpsMixin,
    WrongKindError,
    _as_list,
    _Observer,
)
from crdt_tpu.codec import v1
from crdt_tpu.core.ids import DeleteSet, StateVector
from crdt_tpu.core.records import ItemRecord
from crdt_tpu.core.store import (
    K_ANY,
    K_DELETED,
    K_FORMAT,
    K_GC,
    K_TYPE,
    NULL,
    TYPE_ARRAY,
)
from crdt_tpu.models.incremental import IncrementalReplay
from crdt_tpu.ops import packed as pk


class _ResidentEngineShim:
    """The few ``doc.engine`` attributes the replica layer reads,
    answered from resident state (``Replica.compact``'s pending
    guard). Delete ranges are never pending here — the resident store
    records the full delete set immediately and every snapshot carries
    it — so only stashed rows gate compaction."""

    def __init__(self, replay: IncrementalReplay, client_id: int):
        self._replay = replay
        self.client_id = client_id
        self.pending_deletes = DeleteSet()

    @property
    def pending(self):
        return self._replay._pending

    # guard layer: the pending-budget contract (Engine parity) — the
    # replica layer sets the cap and drains evicted ranges through
    # ``doc.engine`` without caring which backend answers
    @property
    def pending_limit(self):
        return self._replay.pending_limit

    @pending_limit.setter
    def pending_limit(self, value) -> None:
        self._replay.pending_limit = value

    def take_evicted_ranges(self):
        return self._replay.take_evicted_ranges()

    def delete_set(self) -> DeleteSet:
        # the divergence sentinel's tombstone guard reads the full
        # recorded delete set (resident state records it immediately)
        return self._replay.ds


class ResidentCrdt(DocOpsMixin):
    """Drop-in :class:`crdt_tpu.api.doc.Crdt` replacement backed by
    resident state. Constructor contract matches (the replica layer
    builds either without caring which); the name guard, observer
    registry, txn choreography, and batch queue come from the shared
    :class:`DocOpsMixin`."""

    def __init__(
        self,
        client_id: int,
        *,
        observer_function: Optional[Callable[[dict], None]] = None,
        on_update: Optional[Callable[[bytes, dict], None]] = None,
        full_state_updates: bool = False,
        device_merge: Optional[bool] = None,  # accepted for signature parity
        device_min_rows: Optional[int] = None,
        capacity: int = 1 << 14,
    ):
        self._replay = IncrementalReplay(
            capacity=capacity, device_min_rows=device_min_rows
        )
        self.client_id = client_id
        self.engine = _ResidentEngineShim(self._replay, client_id)
        self.observer_function = observer_function
        self.on_update = on_update
        self.full_state_updates = full_state_updates
        self.device_merge = True  # resident IS the device-resident mode
        self.root_kinds: Dict[str, str] = {}
        self._observers: List[_Observer] = []
        self._batched: List[Callable[[], Any]] = []
        # per-txn accumulators (one broadcast per op / per exec_batch)
        self._txn_records: List[ItemRecord] = []
        self._txn_ds = DeleteSet()
        self._txn_roots: set = set()
        self._txn_keys: Dict[str, set] = {}
        # per-sequence edit cursor: spec -> (k, row, epoch) where
        # ``row`` is the k-th visible item (1-based) as of the
        # segment's order ``epoch``. Indexed edits resolve their
        # anchors by walking FROM the cursor (O(|index - k|)) instead
        # of from the head (O(index)) — interactive editing is
        # position-local, so mid-document typing stays ~O(1) in doc
        # size (VERDICT r4 item 8). Any non-local mutation bumps the
        # epoch and the cursor falls back to one full scan.
        self._seq_cursor: Dict[Tuple, Tuple[int, int, int]] = {}

    # ------------------------------------------------------------------
    # cache / reads (same contract as Crdt)
    # ------------------------------------------------------------------
    @property
    def c(self):
        return MappingProxyType(self._replay.cache)

    def __getattr__(self, prop: str) -> Any:
        try:
            return self.__dict__["_replay"].cache[prop]
        except KeyError:
            raise AttributeError(prop) from None

    def __getitem__(self, prop: str) -> Any:
        return self._replay.cache[prop]

    def __contains__(self, prop: str) -> bool:
        return prop in self._replay.cache

    def __repr__(self) -> str:
        return f"ResidentCrdt(client={self.client_id}, c={self._replay.cache!r})"

    def get(self, name: str, key: Optional[str] = None) -> Any:
        if key is None:
            return copy.deepcopy(self._replay.cache.get(name))
        coll = self._replay.cache.get(name)
        if isinstance(coll, dict):
            return copy.deepcopy(coll.get(key))
        return None

    # ------------------------------------------------------------------
    # sync surface (served from resident state)
    # ------------------------------------------------------------------
    def state_vector(self) -> StateVector:
        return self._replay.state_vector()

    def encode_state_vector(self) -> bytes:
        return v1.encode_state_vector(self._replay.state_vector())

    def encode_state_as_update(self, sv: Optional[StateVector] = None) -> bytes:
        return self._replay.encode_state_as_update(sv)

    # ------------------------------------------------------------------
    # resident-state lookups (the Engine anchor equivalents)
    # ------------------------------------------------------------------
    def _sk(self, spec: Tuple, key: Optional[str]) -> Optional[int]:
        """Segkey of (parent spec, map key | sequence) without creating
        interner entries."""
        r = self._replay
        pref = r._prefs.get(spec)
        if pref is None:
            return None
        if key is None:
            kid = -1
        else:
            kid = r._keys.get(key)
            if kid is None:
                return None
        import numpy as np

        return int(pk.segkey_of(np.int64(pref), np.int64(kid)))

    def _row_deleted(self, row: int) -> bool:
        r = self._replay
        return r.ds.contains(
            int(r.cols.col("client")[row]), int(r.cols.col("clock")[row])
        )

    def _row_id(self, row: int) -> Tuple[int, int]:
        r = self._replay
        return (
            int(r.cols.col("client")[row]),
            int(r.cols.col("clock")[row]),
        )

    def _tail_row(self, spec: Tuple, key: str) -> Optional[int]:
        sk = self._sk(spec, key)
        return None if sk is None else self._replay._win.get(sk)

    def _order_rows(self, spec: Tuple) -> List[int]:
        sk = self._sk(spec, None)
        return [] if sk is None else self._replay.order_list(sk)

    def _iter_rows(self, spec: Tuple):
        """Forward document-order iteration — O(1) per step on linked
        segments, no stale-list materialization."""
        sk = self._sk(spec, None)
        if sk is not None:
            yield from self._replay.iter_order(sk)

    def _countable(self, row: int) -> bool:
        kind = int(self._replay.cols.col("kind")[row])
        if kind in (K_DELETED, K_GC, K_FORMAT):
            return False
        return not self._row_deleted(row)

    def _visible_left(self, spec: Tuple, index: int) -> Optional[int]:
        """Row of the (index-1)-th visible item (Engine._visible_left).

        Resolution is cursor-local: the last indexed edit's anchor
        position is cached per sequence (epoch-validated against the
        replay's order epoch), so a run of nearby edits walks
        O(position delta) links instead of O(index) from the head."""
        if index <= 0:
            return None
        sk = self._sk(spec, None)
        r = self._replay
        if sk is not None:
            cur = self._seq_cursor.get(spec)
            if cur is not None:
                ck, crow, epoch = cur
                if epoch == r.order_epoch(sk):
                    row = self._walk_from_cursor(sk, ck, crow, index)
                    if row is not None:
                        self._seq_cursor[spec] = (
                            index, row, r.order_epoch(sk)
                        )
                        return row
        seen = 0
        for row in self._iter_rows(spec):
            if self._countable(row):
                seen += 1
                if seen == index:
                    if sk is not None:
                        self._seq_cursor[spec] = (
                            index, row, r.order_epoch(sk)
                        )
                    return row
        raise IndexError(f"index {index} out of range (len={seen})")

    def _walk_from_cursor(
        self, sk: int, ck: int, crow: int, index: int
    ) -> Optional[int]:
        """The index-th visible row, walking from the validated cursor
        (crow = ck-th visible). Returns None when the backward walk
        cannot satisfy the cursor's own claim (callers re-scan);
        raises IndexError when the document really is too short."""
        r = self._replay
        if index == ck:
            return crow
        if index > ck:
            seen = ck
            for row in r.iter_order_after(sk, crow):
                if self._countable(row):
                    seen += 1
                    if seen == index:
                        return row
            raise IndexError(
                f"index {index} out of range (len={seen})"
            )
        need = ck - index
        for prev in r.iter_order_before(sk, crow):
            if self._countable(prev):
                need -= 1
                if need == 0:
                    return prev
        return None

    def _right_of(self, spec: Tuple, left: Optional[int]) -> Optional[int]:
        """The item immediately after ``left`` in FULL order, tombstones
        included (Engine's ``_next``) — or the head when left is None.
        O(1) on linked segments (advisor, round 3)."""
        sk = self._sk(spec, None)
        if sk is None:
            return None
        if left is None:
            for row in self._replay.iter_order(sk):
                return row
            return None
        return self._replay.order_next_row(sk, left)

    def _append_anchor(self, spec: Tuple) -> Optional[int]:
        """Last countable row — the left anchor of an append — found by
        scanning from the TAIL (O(trailing tombstones), usually O(1),
        vs the head scan's O(document); advisor finding, round 3)."""
        sk = self._sk(spec, None)
        if sk is None:
            return None
        for row in self._replay.iter_order_reversed(sk):
            if self._countable(row):
                return row
        return None

    # ------------------------------------------------------------------
    # record building: each primitive allocates clocks, SELF-APPLIES
    # through the replay (one blob), and accumulates for the broadcast
    # ------------------------------------------------------------------
    def _alloc_clock(self) -> int:
        return self._replay._next_clock.get(self.client_id, 0)

    def _apply_own(self, recs: List[ItemRecord],
                   ds: Optional[DeleteSet] = None) -> None:
        r = self._replay
        # direct admission: no per-op v1 encode/decode round-trip —
        # the broadcast blob is built once per txn in _finish_txn
        # (VERDICT r3 item 3); admit_local itself falls back to the
        # exact blob path when its preflight fails
        r.admit_local(recs, ds)
        for rec in recs:
            if (rec.client, rec.clock) not in r._id_row:
                raise AssertionError("local op must always be integrable")
        self._txn_records.extend(recs)
        if ds is not None:
            for c, k, n in ds.iter_all():
                self._txn_ds.add(c, k, n)
        self._txn_roots.update(r.last_touched_roots)
        for root, keys in r.last_touched_keys.items():
            self._txn_keys.setdefault(root, set()).update(keys)

    def _parent_kw(self, name: str, spec: Tuple) -> dict:
        if spec[0] == "root":
            return {"parent_root": name, "parent_item": None}
        return {"parent_root": None, "parent_item": (spec[1], spec[2])}

    def _map_set(self, name: str, spec: Tuple, key: str, value: Any,
                 *, kind: int = K_ANY,
                 type_ref: int = TYPE_ARRAY) -> ItemRecord:
        tail = self._tail_row(spec, key)
        origin = self._row_id(tail) if tail is not None else None
        rec = ItemRecord(
            client=self.client_id,
            clock=self._alloc_clock(),
            key=key,
            origin=origin,
            right=None,
            kind=kind,
            type_ref=type_ref if kind == K_TYPE else NULL,
            content=copy.deepcopy(value) if kind != K_TYPE else None,
            **self._parent_kw(name, spec),
        )
        self._apply_own([rec])
        return rec

    def _map_delete(self, spec: Tuple, key: str) -> bool:
        tail = self._tail_row(spec, key)
        if tail is None or self._row_deleted(tail):
            return False
        ds = DeleteSet()
        ds.add(*self._row_id(tail))
        self._apply_own([], ds)
        return True

    def _seq_insert(self, name: str, spec: Tuple, index: Optional[int],
                    values: List[Any]) -> None:
        """All values of one insert go out as ONE chained record run in
        ONE blob/apply: value k's origin is value k-1's id and every
        record shares the insertion point's right anchor. This is
        exact — a brand-new id cannot be any concurrent item's origin,
        so each chained record integrates immediately after its
        predecessor with no conflict scan the intermediate state could
        influence (the engine's per-value ``_next`` walk reduces to the
        same placement). ``index=None`` means append: the left anchor
        comes from a tail scan instead of a head walk (O(1) for the
        keystroke path instead of O(document))."""
        if index is None:
            left = self._append_anchor(spec)
        else:
            left = self._visible_left(spec, index)
        right = self._right_of(spec, left)
        right_id = self._row_id(right) if right is not None else None
        origin = self._row_id(left) if left is not None else None
        clock = self._alloc_clock()
        recs = []
        for v in values:
            rec = ItemRecord(
                client=self.client_id,
                clock=clock,
                key=None,
                origin=origin,
                right=right_id,
                kind=K_ANY,
                content=copy.deepcopy(v),
                **self._parent_kw(name, spec),
            )
            recs.append(rec)
            origin = (rec.client, rec.clock)
            clock += 1
        if recs:
            self._apply_own(recs)
            if index is not None:
                # the run's last row is now the (index+V)-th visible
                # item: seed the cursor there so the next nearby edit
                # walks O(delta) instead of O(index)
                sk = self._sk(spec, None)
                last = self._replay._id_row.get(
                    (recs[-1].client, recs[-1].clock)
                )
                if sk is not None and last is not None:
                    self._seq_cursor[spec] = (
                        index + len(recs), last,
                        self._replay.order_epoch(sk),
                    )

    def _seq_delete(self, spec: Tuple, index: int, length: int) -> int:
        targets = []
        seen = 0
        try:
            anchor = (
                self._visible_left(spec, index) if index > 0 else None
            )
        except IndexError:
            return 0  # cut past the visible tail deletes nothing
        if anchor is not None:
            sk = self._sk(spec, None)
            it = self._replay.iter_order_after(sk, anchor)
            seen = index
        else:
            it = self._iter_rows(spec)
        for row in it:
            if not self._countable(row):
                continue
            if seen >= index:
                targets.append(row)
                if len(targets) == length:
                    break
            seen += 1
        if not targets:
            return 0
        ds = DeleteSet()
        for row in targets:
            ds.add(*self._row_id(row))
        self._apply_own([], ds)
        if anchor is not None:
            # the delete bumped the epoch, but every deleted row sits
            # strictly AFTER the anchor — its visible rank is intact,
            # so reseed the cursor instead of forcing the next edit
            # (type-backspace-type is the common keystroke mix) back
            # to a full head scan
            sk = self._sk(spec, None)
            if sk is not None:
                self._seq_cursor[spec] = (
                    index, anchor, self._replay.order_epoch(sk)
                )
        return len(targets)

    # ------------------------------------------------------------------
    # txn plumbing (the per-op broadcast tail, crdt.js:440-447;
    # _run_op and the batch queue live in DocOpsMixin)
    # ------------------------------------------------------------------
    def _begin_txn(self) -> None:
        self._txn_records = []
        self._txn_ds = DeleteSet()
        self._txn_roots = set()
        self._txn_keys = {}

    def _finish_txn(
        self,
        origin: str,
        meta: Optional[dict] = None,
        propagate: bool = True,
        want_update: bool = False,
    ) -> Optional[bytes]:
        update = None
        emitting = (
            propagate and self.on_update is not None and origin == "local"
        )
        if (self._txn_records or self._txn_ds.ranges) and (
            emitting or want_update
        ):
            if self.full_state_updates:
                update = self.encode_state_as_update()
            else:
                update = v1.encode_update(self._txn_records, self._txn_ds)
            if emitting:
                self.on_update(update, meta or {})
        self._fire_observers(
            sorted(self._txn_roots), self._txn_keys, origin
        )
        return update

    def _fire_observers(self, touched, touched_keys, origin) -> None:
        if not touched:
            return
        if self.observer_function is None and not self._observers:
            # no listeners: do not force the lazy cache to materialize
            # (the firehose steady state depends on this)
            return
        cache = self._replay.cache
        event = {
            "origin": origin,
            "touched": list(touched),
            "c": MappingProxyType(dict(cache)),
        }
        if self.observer_function is not None:
            self.observer_function(event)
        for ob in self._observers:
            if ob.name not in touched:
                continue
            if ob.key is not None:
                if ob.key not in touched_keys.get(ob.name, ()):
                    continue
                coll = cache.get(ob.name)
                value = (
                    copy.deepcopy(coll.get(ob.key))
                    if isinstance(coll, dict) else None
                )
                ob.func(
                    {**event, "name": ob.name, "key": ob.key, "value": value}
                )
            else:
                value = copy.deepcopy(cache.get(ob.name))
                ob.func({**event, "name": ob.name, "value": value})

    # ------------------------------------------------------------------
    # guards (name guard shared via DocOpsMixin)
    # ------------------------------------------------------------------
    def _ix_value(self, name: str) -> Optional[str]:
        tail = self._tail_row(("root", "ix"), name)
        if tail is None or self._row_deleted(tail):
            return None
        return self._replay.cols.contents[tail]

    def _kind_of(self, name: str) -> Optional[str]:
        kind = self._ix_value(name)
        if kind is not None:
            return kind
        return self.root_kinds.get(name)

    def _check_kind(self, name: str, want: str) -> None:
        kind = self._kind_of(name)
        if kind is not None and kind != want:
            raise WrongKindError(f"'{name}' is a {kind}, not a {want}")

    def _register(self, name: str, kind: str) -> None:
        if self._ix_value(name) is None:
            self._map_set("ix", ("root", "ix"), name, kind)
            self.root_kinds[name] = kind

    # ------------------------------------------------------------------
    # collection creation + map ops (crdt.js:363-477)
    # ------------------------------------------------------------------
    def map(self, name: str, batch: bool = False):
        self._check_name(name)

        def operation():
            self._check_kind(name, "map")
            self._register(name, "map")
            return name

        return self._run_op(batch, operation)

    def array(self, name: str, batch: bool = False):
        self._check_name(name)

        def operation():
            self._check_kind(name, "array")
            self._register(name, "array")
            return name

        return self._run_op(batch, operation)

    def set(
        self,
        name: str,
        key: str,
        value: Any = None,
        *,
        array_method: Optional[str] = None,
        index: Optional[int] = None,
        length: Optional[int] = None,
        batch: bool = False,
    ) -> Any:
        self._check_name(name)
        if not isinstance(key, str) or not key:
            raise ValueError("key must be a non-empty string")
        if array_method is not None and array_method not in ARRAY_METHODS:
            raise ValueError(f"array_method must be one of {ARRAY_METHODS}")
        if array_method == "insert" and index is None:
            raise ValueError("insert requires index")
        if array_method == "cut" and index is None:
            raise ValueError("cut requires index")

        def operation():
            self._check_kind(name, "map")
            self._register(name, "map")
            root = ("root", name)
            if array_method is None:
                self._map_set(name, root, key, value)
                return value
            # nested array under the key (crdt.js:422-432)
            spec = None
            tail = self._tail_row(root, key)
            if (
                tail is not None
                and not self._row_deleted(tail)
                and int(self._replay.cols.col("kind")[tail]) == K_TYPE
            ):
                spec = ("item",) + self._row_id(tail)
            if spec is None:
                rec = self._map_set(
                    name, root, key, None, kind=K_TYPE, type_ref=TYPE_ARRAY
                )
                spec = ("item", rec.client, rec.clock)
            if array_method == "insert":
                self._seq_insert(name, spec, index, _as_list(value))
            elif array_method == "push":
                self._seq_insert(name, spec, None, _as_list(value))
            elif array_method == "unshift":
                self._seq_insert(name, spec, 0, _as_list(value))
            else:  # cut
                self._seq_delete(
                    spec, index, length if length is not None else 1
                )
            coll = self._replay.cache.get(name)
            return (
                copy.deepcopy(coll.get(key))
                if isinstance(coll, dict) else None
            )

        return self._run_op(batch, operation)

    def delete(self, name: str, key: str, batch: bool = False) -> Any:
        self._check_name(name)

        def operation():
            self._check_kind(name, "map")
            return self._map_delete(("root", name), key)

        return self._run_op(batch, operation)

    del_ = delete

    # ------------------------------------------------------------------
    # array ops (crdt.js:485-617)
    # ------------------------------------------------------------------
    def _seq_op(self, name: str, batch: bool, body: Callable[[], Any]) -> Any:
        self._check_name(name)

        def operation():
            self._check_kind(name, "array")
            self._register(name, "array")
            return body()

        return self._run_op(batch, operation)

    def insert(self, name: str, index: int, value: Any, batch: bool = False):
        vals = _as_list(value)
        return self._seq_op(
            name, batch,
            lambda: self._seq_insert(name, ("root", name), index, vals),
        )

    def push(self, name: str, value: Any, batch: bool = False):
        vals = _as_list(value)
        return self._seq_op(
            name, batch,
            lambda: self._seq_insert(name, ("root", name), None, vals),
        )

    def unshift(self, name: str, value: Any, batch: bool = False):
        vals = _as_list(value)
        return self._seq_op(
            name, batch,
            lambda: self._seq_insert(name, ("root", name), 0, vals),
        )

    def cut(self, name: str, index: int, length: int = 1, batch: bool = False):
        return self._seq_op(
            name, batch,
            lambda: self._seq_delete(("root", name), index, length),
        )

    # ------------------------------------------------------------------
    # remote updates (crdt.js:292-311) — the same path local ops take
    # ------------------------------------------------------------------
    def apply_update(self, data: bytes, origin: str = "remote") -> None:
        self.apply_updates([data], origin)

    def apply_updates(self, datas, origin: str = "remote") -> None:
        if not datas:
            return
        r = self._replay
        r.apply(list(datas))
        self._fire_observers(
            r.last_touched_roots, r.last_touched_keys, origin
        )

