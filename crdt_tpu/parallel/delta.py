"""Targeted anti-entropy on the device mesh — deltas, not full state.

The full-gossip step (:mod:`crdt_tpu.parallel.gossip`) all-gathers every
replica's complete op columns each round — the reference's Q2 defect
(full-state broadcasts, crdt.js:443) reproduced on-device as a compat
mode. This module is the fix, driven by the state-vector machinery:

- :func:`make_delta_gossip_step` — the ``propagate`` analogue. Replicas
  all-gather their SVs (tiny: [R, C] int64), derive the swarm floor
  (componentwise MIN — clocks every replica already holds), and
  all-gather only rows ABOVE the floor, packed into a static
  ``budget``-sized buffer per replica. ICI bytes scale with the
  deficit, not the doc: cost drops from O(R·N_doc) to
  O(R·C + R·budget) per round.
- :func:`make_ring_delta_step` — the ``toPeer`` analogue
  (crdt.js:290): each replica learns its ring successor's SV via
  ``ppermute``, selects exactly the rows that successor lacks, and
  ``ppermute``s them point-to-point over ICI. R-1 rounds converge a
  ring the way repeated ``toPeer`` unicasts do.

Static-shape discipline: the per-round ``budget`` caps how many rows a
replica may ship; ``needed_count`` in the outputs reports the true
deficit so the caller can loop rounds (or raise the budget bucket)
until it reaches zero. Host-path analogue: ``Replica.anti_entropy``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from crdt_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P

from crdt_tpu.ops import statevec

COL_NAMES = (
    "client",
    "clock",
    "parent_is_root",
    "parent_a",
    "parent_b",
    "key_id",
    "origin_client",
    "origin_clock",
    "valid",
)


def _pack_rows(cols, needed, budget: int):
    """Select `needed` rows into the first `budget` slots (per replica
    row). Rows beyond the budget (or not needed) come back invalid."""

    def pack_one(row_cols, needed_row):
        order = jnp.argsort(~needed_row, stable=True)  # needed first
        take = order[:budget]
        n_needed = needed_row.sum()
        in_budget = jnp.arange(budget) < n_needed
        out = [c[take] for c in row_cols[:-1]]
        out.append(row_cols[-1][take] & in_budget)  # valid col masked
        return tuple(out), n_needed

    return jax.vmap(pack_one)(cols, needed)


def make_delta_gossip_step(mesh, num_clients: int, budget: int):
    """Deficit-driven gossip: all-gather ONLY rows above the swarm
    floor. Returns a jitted step over [R, N] sharded columns yielding

    - ``svs``          [R, C] every replica's state vector
    - ``deficit``      [R, R] pairwise anti-entropy plan
    - ``needed_count`` [R] rows each replica had to ship (caller
      checks <= budget; loop more rounds otherwise)
    - ``delta_*``      [R * budget] the gathered delta union columns
      (feed to converge_maps / converge_sequences, or integrate into
      resident state)
    """
    axis = mesh.axis_names[0]
    # host-resolved kernel static at factory build: the traced step
    # must not read CRDT_TPU_PALLAS (crdtlint CL702)
    sv_deficit_mode = statevec.deficit_mode()

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis, None),) * 9,
        out_specs=(P(), P(), P(axis)) + (P(),) * 9,
        check_vma=False,
    )
    def step(*cols):
        client, clock = cols[0], cols[1]
        valid = cols[8]
        sv_local = jax.vmap(
            lambda c, k, v: statevec.build(c, k, v, num_clients)
        )(client, clock, valid)
        svs = jax.lax.all_gather(sv_local, axis).reshape(-1, num_clients)
        deficit = statevec.missing_static(svs, sv_deficit_mode)

        # swarm floor: clocks EVERY replica holds; only rows above it
        # can be missing anywhere
        floor = jnp.min(svs, axis=0)
        needed = jax.vmap(
            lambda c, k, v: statevec.diff_mask(c, k, v, floor)
        )(client, clock, valid)

        packed, n_needed = _pack_rows(cols, needed, budget)
        union = tuple(
            jax.lax.all_gather(c, axis).reshape(-1, *c.shape[2:]).reshape(-1)
            for c in packed
        )
        return (svs, deficit, n_needed) + union

    # per-round column uploads donated (freshly built by the caller
    # each round — ReplicaFleet.delta_round); backends without
    # donation skip the reuse (one UserWarning per compiled shape,
    # filtered in the test config and bench)
    return jax.jit(step, donate_argnums=tuple(range(9)))


def make_ring_delta_step(mesh, num_clients: int, budget: int):
    """Point-to-point delta exchange (the ``toPeer`` analogue): every
    replica ships its ring successor exactly the rows that successor
    lacks, via ``ppermute`` over ICI. Requires one replica per device
    (device-level point-to-point). Returns a jitted step yielding

    - ``sent_count`` [R] rows shipped to the successor
    - ``recv_*``     [R, budget] columns received from the predecessor
    """
    axis = mesh.axis_names[0]
    nd = mesh.devices.size
    fwd = [(i, (i + 1) % nd) for i in range(nd)]
    bwd = [(i, (i - 1) % nd) for i in range(nd)]

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis, None),) * 9,
        out_specs=(P(axis),) + (P(axis, None),) * 9,
        check_vma=False,
    )
    def step(*cols):
        client, clock = cols[0], cols[1]
        valid = cols[8]
        sv_local = jax.vmap(
            lambda c, k, v: statevec.build(c, k, v, num_clients)
        )(client, clock, valid)
        # learn the SUCCESSOR's SV: it travels backwards around the ring
        succ_sv = jax.lax.ppermute(sv_local, axis, perm=bwd)
        needed = jax.vmap(
            lambda c, k, v, sv: statevec.diff_mask(c, k, v, sv)
        )(client, clock, valid, succ_sv)
        packed, n_needed = _pack_rows(cols, needed, budget)
        # ship the packed rows forward to the successor
        recv = tuple(jax.lax.ppermute(c, axis, perm=fwd) for c in packed)
        return (n_needed,) + recv

    return jax.jit(step, donate_argnums=tuple(range(9)))


def synth_resident_columns(
    n_replicas: int,
    shared_ops: int,
    fresh_ops: int,
    *,
    num_maps: int = 4,
    keys_per_map: int = 32,
    seed: int = 0,
):
    """Anti-entropy workload: every replica already holds a shared
    history (`shared_ops` rows by client 1, fully replicated) plus its
    own `fresh_ops` unshared writes — the state after a settled swarm
    takes new local edits. The deficit is exactly the fresh rows."""
    rng = np.random.default_rng(seed)
    R, N = n_replicas, shared_ops + fresh_ops
    cols = {
        "client": np.empty((R, N), np.int32),
        "clock": np.empty((R, N), np.int64),
        "parent_is_root": np.ones((R, N), bool),
        "parent_a": rng.integers(0, num_maps, (R, N)).astype(np.int64),
        "parent_b": np.full((R, N), -1, np.int64),
        "key_id": rng.integers(0, keys_per_map, (R, N)).astype(np.int32),
        "origin_client": np.full((R, N), -1, np.int32),
        "origin_clock": np.full((R, N), -1, np.int64),
        "valid": np.ones((R, N), bool),
    }
    # shared history: identical rows on every replica (client 1)
    cols["client"][:, :shared_ops] = 1
    cols["clock"][:, :shared_ops] = np.arange(shared_ops)
    shared_pa = rng.integers(0, num_maps, shared_ops)
    shared_key = rng.integers(0, keys_per_map, shared_ops)
    cols["parent_a"][:, :shared_ops] = shared_pa
    cols["key_id"][:, :shared_ops] = shared_key
    # fresh per-replica rows (client r+2 so client 1 stays the history)
    for r in range(R):
        cols["client"][r, shared_ops:] = r + 2
        cols["clock"][r, shared_ops:] = np.arange(fresh_ops)
    return cols
