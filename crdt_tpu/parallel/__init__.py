from crdt_tpu.parallel.gossip import make_gossip_step, make_mesh

__all__ = ["make_gossip_step", "make_mesh"]
