"""Replica-parallel gossip over a device mesh — ICI as the swarm fabric.

The reference's one parallelism axis is replica parallelism: N peers
full-mesh-gossiping updates over Hyperswarm and converging by CRDT
merge (SURVEY.md §2.2: `propagate` at crdt.js:385,445,...; the
ready/sync handshake at crdt.js:237-291). On TPU that maps to:

- replicas = a sharded batch dimension over a 1D ``Mesh`` axis;
- ``propagate`` (full-mesh gossip) = ``all_gather`` of the replicas'
  op columns over ICI;
- the merge every peer performs on receipt (``Y.applyUpdate``,
  crdt.js:294) = one vectorized ``converge_maps`` over the gathered
  union, computed replicated on every device — exactly the CRDT
  model, where each replica merges the same op set and reaches the
  same state;
- the state-vector handshake = per-replica SV build (scatter-max) +
  all-gather + the pairwise ``missing`` deficit matrix, replacing the
  reference's one-peer-at-a-time `encodeStateVector` exchange.

No tensor/pipeline/expert axes are invented: the reference has no
model compute to shard (SURVEY.md §2.2 parallelism census); the honest
scale story is replicas × ops, and ops scale inside each device's
static-shape columns.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from crdt_tpu.compat import shard_map

from crdt_tpu.ops import deleteset as ds_ops
from crdt_tpu.ops import statevec
from crdt_tpu.ops.merge import converge_maps
from crdt_tpu.ops.yata import converge_sequences

REPLICA_AXIS = "replicas"

# one packed int64 tensor per direction: through a tunnelled platform
# every host<->device interaction pays a fixed latency (25-110ms
# measured), so a round that ships nine column arrays and fetches ten
# outputs is floored by ~20 interactions regardless of bytes. The
# fleet steps therefore take ONE [9, R, N] int64 input (cast/packed on
# host) and return ONE flat int64 vector (static offsets) — the same
# discipline ops/packed.py uses for the single-chip cold replay.
COL_PACK_ORDER = (
    "client", "clock", "parent_is_root", "parent_a", "parent_b",
    "key_id", "origin_client", "origin_clock", "valid",
)


def pack_cols(cols) -> np.ndarray:
    """[9, R, N] int64 from the fleet column dict (host-side)."""
    return np.stack(
        [np.asarray(cols[k]).astype(np.int64) for k in COL_PACK_ORDER]
    )


def pack_dels(dels) -> np.ndarray:
    """[3, D] int64 from the delete triples (host-side)."""
    return np.stack([np.asarray(d).astype(np.int64) for d in dels])


def _unpack_cols(packed):
    """Device-side: the nine typed columns from one int64 block."""
    client = packed[0].astype(jnp.int32)
    clock = packed[1]
    pir = packed[2] != 0
    pa = packed[3]
    pb = packed[4]
    kid = packed[5].astype(jnp.int32)
    oc = packed[6].astype(jnp.int32)
    ock = packed[7]
    valid = packed[8] != 0
    return client, clock, pir, pa, pb, kid, oc, ock, valid


def fleet_out_sizes(R: int, N: int, C: int, S: int):
    """Static (name, size) layout of the replicated steps' one packed
    output vector."""
    RN = R * N
    return (
        ("sv_local", R * C),
        ("global_sv", C),
        ("deficit", R * R),
        ("winners", S),
        ("winner_visible", S),
        ("seq_order", RN),
        ("seq_seg", RN),
        ("seq_rank", RN),
        ("seq_len", S),
        ("map_order", RN),
    )


def unpack_fleet_out(vec: np.ndarray, R: int, N: int, C: int, S: int):
    """Host-side: named arrays (original shapes) from the one fetch."""
    out = {}
    off = 0
    for name, size in fleet_out_sizes(R, N, C, S):
        out[name] = vec[off: off + size]
        off += size
    out["sv_local"] = out["sv_local"].reshape(R, C)
    out["deficit"] = out["deficit"].reshape(R, R)
    return out


def make_mesh(n_devices: Optional[int] = None, axis: str = REPLICA_AXIS) -> Mesh:
    """1D replica mesh over the first `n_devices` devices (all when
    None). Multi-host meshes work the same way: jax.devices() spans
    hosts and the collectives ride ICI within a slice / DCN across."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


HOST_AXIS = "hosts"


def make_mesh2d(n_hosts: int, devices_per_host: int) -> Mesh:
    """2D (hosts, replicas) mesh — the multi-host topology. Collectives
    over the inner axis ride ICI within each host's slice; collectives
    over the outer axis cross DCN. On a single-process test rig the
    same mesh shape runs on virtual devices; on a real multi-host pod
    jax.devices() spans processes and the axis split maps onto the
    physical fabric."""
    devs = jax.devices()
    need = n_hosts * devices_per_host
    if len(devs) < need:
        raise ValueError(f"need {need} devices, have {len(devs)}")
    if jax.process_count() > 1 and devices_per_host != jax.local_device_count():
        # the inner axis must stay inside one process, or every "ICI"
        # collective silently crosses DCN and the two-tier rationale
        # inverts
        raise ValueError(
            f"devices_per_host={devices_per_host} must equal "
            f"local_device_count()={jax.local_device_count()} on a "
            "multi-process pod"
        )
    return Mesh(
        np.asarray(devs[:need]).reshape(n_hosts, devices_per_host),
        (HOST_AXIS, REPLICA_AXIS),
    )


def make_gossip_step(mesh: Mesh, num_segments: int, num_clients: int):
    """Build the jitted full gossip+merge step for `mesh`.

    Step input: ONE packed [9, R, N] int64 block (:func:`pack_cols`;
    R sharded over the replica axis) holding the op columns of each
    replica's pending update batch, plus one replicated [3, D] delete
    block (:func:`pack_dels`). Output: ONE flat int64 vector
    (replicated; :func:`unpack_fleet_out` slices it) holding

    - ``sv_local``  [R, C] per-replica state vectors
    - ``global_sv`` [C] merged swarm state vector
    - ``deficit``   [R, R] pairwise missing-clock totals
      — the anti-entropy plan: entry (i, j) > 0 means i must send to j
    - ``winners``/``winner_visible`` [S] converged map winners over
      the whole union (indices into id-sorted union space)
    - ``seq_order``/``seq_seg``/``seq_rank`` [R*N] converged sequence
      document order over the union (id-sorted space, ``seq_order``
      maps back to flattened caller rows) and ``seq_len`` [S]
      per-sequence lengths — the YATA half of the device applyUpdate
      (maps AND sequences, VERDICT r1 weak #5)
    - ``map_order`` [R*N] the MAP kernel's own id-sort permutation —
      ``winners`` decode through THIS, never through ``seq_order``
      (today the two kernels share one sort key, but that is an
      internal coincidence no assembler should couple to)
    """
    axis = mesh.axis_names[0]
    # kernel-dispatch statics, resolved HERE on the host at factory
    # build: the step body is traced, and an env read inside it would
    # bake CRDT_TPU_PALLAS into the compiled program (crdtlint CL702)
    ds_mode = ds_ops.mask_mode()
    sv_deficit_mode = statevec.deficit_mode()

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(None, axis, None), P(None, None)),
        out_specs=P(),
        # the replicated outputs derive only from all_gather'd values,
        # but the vma checker cannot prove that through converge_maps's
        # while_loop (pointer doubling); the P() specs are correct
        check_vma=False,
    )
    def step(packed, dels):
        client, clock, parent_is_root, parent_a, parent_b, key_id, \
            origin_client, origin_clock, valid = _unpack_cols(packed)
        d_client, d_start, d_end = dels[0], dels[1], dels[2]

        # per-replica state vectors: scatter-max over the local shard
        sv_local = jax.vmap(
            lambda c, k, v: statevec.build(c, k, v, num_clients)
        )(client, clock, valid)

        # handshake fan-in: all-gather every replica's SV, derive the
        # merged swarm vector and the pairwise anti-entropy plan
        svs = jax.lax.all_gather(sv_local, axis).reshape(-1, num_clients)
        global_sv = statevec.merge(svs)
        deficit = statevec.missing_static(svs, sv_deficit_mode)

        # gossip fan-in: all-gather the op columns into the union every
        # replica would hold after a full propagate round
        def gather_flat(x):
            return jax.lax.all_gather(x, axis).reshape(-1)

        union = [
            gather_flat(x)
            for x in (client, clock, parent_is_root, parent_a, parent_b,
                      key_id, origin_client, origin_clock, valid)
        ]

        # every replica merges the same union -> replicated converge.
        # named_scope (works under jit, unlike host-side trace
        # annotations): XProf timelines attribute the fused kernels
        with jax.named_scope("crdt.gossip.converge_maps"):
            map_order, _, winners, winner_visible, _, _ = converge_maps(
                *union, d_client, d_start, d_end,
                num_segments=num_segments, ds_mode=ds_mode,
            )
        # ... and orders every sequence in the same union (the YATA
        # half of applyUpdate; same id-sort, XLA CSEs the shared work)
        with jax.named_scope("crdt.gossip.converge_sequences"):
            seq_order, seq_seg, seq_rank, seq_len = converge_sequences(
                *union, num_segments=num_segments
            )
        return jnp.concatenate([
            x.reshape(-1).astype(jnp.int64)
            for x in (svs, global_sv, deficit, winners, winner_visible,
                      seq_order, seq_seg, seq_rank, seq_len, map_order)
        ])

    # the packed column block (the round's big upload) is DONATED: its
    # device buffer is consumed by the step, so back-to-back gossip
    # rounds recycle one allocation instead of holding round k's
    # columns alive while round k+1 uploads. Callers always build the
    # block fresh per round (pack_cols -> xfer_put) — nothing re-reads
    # it after the dispatch. Backends without donation (CPU) skip the
    # reuse and warn once per compiled shape (filtered in the test
    # config and bench).
    return jax.jit(step, donate_argnums=(0,))


def make_hierarchical_gossip_step(mesh: Mesh, num_segments: int,
                                  num_clients: int):
    """Two-tier gossip over a (hosts, replicas) mesh: fan-in happens as
    an all-gather over the intra-host replica axis (ICI) followed by an
    all-gather over the host axis (DCN) — the reference's full-mesh
    swarm mapped onto a pod's physical fabric instead of one flat
    collective. Output vector matches :func:`make_gossip_step` on the
    same flattened columns (differential-tested in
    tests/test_parallel.py).

    Step inputs: packed [9, R, N] block with R sharded over (hosts,
    replicas); replicated packed deletes. Output as in
    :func:`make_gossip_step`."""
    host, rep = mesh.axis_names
    # host-resolved kernel statics (crdtlint CL702, see
    # make_gossip_step)
    ds_mode = ds_ops.mask_mode()
    sv_deficit_mode = statevec.deficit_mode()

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(None, (host, rep), None), P(None, None)),
        out_specs=P(),
        check_vma=False,
    )
    def step(packed, dels):
        client, clock, parent_is_root, parent_a, parent_b, key_id, \
            origin_client, origin_clock, valid = _unpack_cols(packed)
        d_client, d_start, d_end = dels[0], dels[1], dels[2]
        sv_local = jax.vmap(
            lambda c, k, v: statevec.build(c, k, v, num_clients)
        )(client, clock, valid)

        def gather2(x):
            # ICI first (cheap, wide), then DCN (few, slow links carry
            # each host's already-combined slice exactly once)
            x = jax.lax.all_gather(x, rep)
            x = jax.lax.all_gather(x, host)
            return x.reshape(-1, *x.shape[3:])

        svs = gather2(sv_local)  # [R, num_clients]
        global_sv = statevec.merge(svs)
        deficit = statevec.missing_static(svs, sv_deficit_mode)

        union = [
            gather2(x).reshape(-1)
            for x in (client, clock, parent_is_root, parent_a, parent_b,
                      key_id, origin_client, origin_clock, valid)
        ]
        map_order, _, winners, winner_visible, _, _ = converge_maps(
            *union, d_client, d_start, d_end,
            num_segments=num_segments, ds_mode=ds_mode,
        )
        seq_order, seq_seg, seq_rank, seq_len = converge_sequences(
            *union, num_segments=num_segments
        )
        return jnp.concatenate([
            x.reshape(-1).astype(jnp.int64)
            for x in (svs, global_sv, deficit, winners, winner_visible,
                      seq_order, seq_seg, seq_rank, seq_len, map_order)
        ])

    # packed column block donated — see make_gossip_step
    return jax.jit(step, donate_argnums=(0,))


def make_segment_sharded_step(mesh: Mesh, num_segments: int,
                              n_replicas: int):
    """Work-DIVIDED gossip round: the union arrives pre-partitioned by
    SEGMENT (one device owns every row of each (parent, key) chain and
    each sequence — YATA origins and LWW key chains never cross
    segments), so each device converges only its shard and per-device
    merge work drops ~1/nd. Contrast :func:`make_gossip_step`, which
    all-gathers the union and converges it REPLICATED — same result,
    no work division; this step is the scaling mode
    (crdt_tpu.models.fleet.shard_trace builds the partition).

    The per-replica own-op state vectors arrive as an INPUT: they are
    a pure O(rows) function of the staged columns, which the host
    computes while partitioning (crdt_tpu.models.fleet.shard_trace).
    What stays on the mesh is the O(R^2 C) pairwise deficit — the one
    superlinear handshake term — with its rows divided over devices.

    Inputs: a packed [9, nd, N_d] block sharded over the device axis
    (dim 1), the replicated ``svs`` [R, C], and a replicated packed
    delete block. Output: ONE int64 vector sharded over the axis —
    each device contributes its [X] block (X from
    :func:`segment_out_sizes`), so the host reshapes the fetch to
    [nd, X] and slices:

    - ``deficit``   [blk, R] pairwise-plan rows for this device's
      replica block (global rows 0..nd*blk, callers slice [:R])
    - ``winners``/``winner_visible`` [S] per-device map winners in
      the device's LOCAL id-sorted space
    - ``seq_order``/``seq_seg``/``seq_rank`` [N_d] per-device
      sequence outputs (local spaces; segment ids are dense PER
      DEVICE — key them as (device, seg) on the host)
    - ``seq_len`` [S], ``map_order`` [N_d]
    """
    axis = mesh.axis_names[0]
    nd = mesh.devices.size
    blk = -(-n_replicas // nd)  # deficit rows per device
    # host-resolved kernel static (crdtlint CL702, see
    # make_gossip_step); the deficit here rides exact_missing_rows,
    # so only the delete mask needs a mode
    ds_mode = ds_ops.mask_mode()

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(None, axis, None), P(None, None), P(None, None)),
        out_specs=P(axis),
        check_vma=False,
    )
    def step(packed, svs, dels):
        flat = [x.reshape(-1) for x in _unpack_cols(packed)]
        d_client, d_start, d_end = dels[0], dels[1], dels[2]
        map_order, _, winners, winner_visible, _, _ = converge_maps(
            *flat, d_client, d_start, d_end,
            num_segments=num_segments, ds_mode=ds_mode,
        )
        seq_order, seq_seg, seq_rank, seq_len = converge_sequences(
            *flat, num_segments=num_segments
        )
        # deficit rows sharded over the mesh: each device scans only
        # its own replica block against the full vector set (shared
        # scan body — statevec.exact_missing_rows)
        didx = jax.lax.axis_index(axis)
        svs_pad = jnp.pad(svs, ((0, blk * nd - n_replicas), (0, 0)))
        my_rows = jax.lax.dynamic_slice_in_dim(
            svs_pad, didx * blk, blk, axis=0
        )
        deficit_blk = statevec.exact_missing_rows(my_rows, svs)
        return jnp.concatenate([
            x.reshape(-1).astype(jnp.int64)
            for x in (deficit_blk, winners, winner_visible, seq_order,
                      seq_seg, seq_rank, seq_len, map_order)
        ])

    # packed column block donated — see make_gossip_step
    return jax.jit(step, donate_argnums=(0,))


def make_packed_shard_step(mesh: Mesh, *, num_segments: int,
                           seq_bucket: int, map_bucket: int,
                           rank_rounds: int, map_rounds: int,
                           encs: tuple, mode: str, sv_len: int,
                           sv_mode: str):
    """ONE shard_map program carrying the whole multi-chip sharded
    converge (round 13; staged by :mod:`crdt_tpu.ops.shard`): every
    device widens ITS shard's narrow-encoded section block and runs
    the full sortless fused converge
    (:func:`crdt_tpu.ops.packed._converge_packed_body` — argmax scan,
    pointer doubling, document-order scatter) on its own rows, with
    NO collective inside the converge: segments never cross shards,
    so the independent doubling loops overlap across chips.

    The only inter-chip traffic is the BOUNDARY EXCHANGE: each shard
    contributes one narrow wire row — its partial state vector,
    narrow-encoded with the round-9 codec as the inter-chip wire
    format (``sv_mode``: ``'i16'`` one identity int16 stretch when
    every clock fits, ``'hilo'`` two exact int16 stretches below
    2^31, ``'wide'`` int64) — all-gathered over the mesh axis and
    max-merged into the swarm state vector on device.

    Inputs: the [K, L] staged section block (sharded over the axis,
    DONATED — one sharded plan, one dispatch) and the [K, W] wire
    block (sharded). Outputs: the per-shard packed converge results
    [K, S+B] (sharded) and the merged global SV [sv_len] int64
    (replicated)."""
    axis = mesh.axis_names[0]
    from crdt_tpu.ops import packed as pk

    sizes = pk._section_sizes(num_segments, seq_bucket, map_bucket)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis, None)),
        out_specs=(P(axis, None), P()),
        # the replicated SV derives only from all-gathered wires, but
        # the vma checker cannot prove that through the converge
        # body's while_loops (pointer doubling); the specs are correct
        check_vma=False,
    )
    def step(flat_blk, wire_blk):
        secs = pk._decode_sections(flat_blk[0], sizes, encs)
        with jax.named_scope("crdt.shard.converge"):
            out = pk._converge_packed_body(
                *secs, num_segments=num_segments,
                seq_bucket=seq_bucket, map_bucket=map_bucket,
                rank_rounds=rank_rounds, map_rounds=map_rounds,
                mode=mode,
            )
        with jax.named_scope("crdt.shard.boundary_exchange"):
            wires = jax.lax.all_gather(wire_blk, axis)
            wires = wires.reshape(-1, wire_blk.shape[-1])
            if sv_mode == "hilo":
                hi = wires[:, :sv_len].astype(jnp.int64)
                lo = wires[:, sv_len:2 * sv_len].astype(jnp.int64)
                svs = (hi << 16) | ((lo + 0x8000) & 0xFFFF)
            else:  # 'i16' identity / 'wide' int64: plain widen
                svs = wires[:, :sv_len].astype(jnp.int64)
            gsv = svs.max(axis=0)
        return out[None, :], gsv

    # the staged section block is donated — see make_gossip_step
    return jax.jit(step, donate_argnums=(0,))


def segment_out_sizes(blk: int, R: int, N_d: int, S: int):
    """Static (name, size) layout of ONE device's block in the
    segment-sharded step's packed output."""
    return (
        ("deficit", blk * R),
        ("winners", S),
        ("winner_visible", S),
        ("seq_order", N_d),
        ("seq_seg", N_d),
        ("seq_rank", N_d),
        ("seq_len", S),
        ("map_order", N_d),
    )


class GossipFaultPlan:
    """Deterministic fault plan for FLEET gossip rounds — the
    device-mesh analogue of the router-seam fault fabric
    (:mod:`crdt_tpu.net.faults`). A replica "dropped" in a round has
    its contribution withheld from the all-gather (its valid column
    zeroed — exactly what a lost propagate broadcast looks like to
    everyone else); a partition splits the replica axis into groups
    that gossip separately (each group's union excludes the other's
    ops). Because the converge kernels are merges over op unions, a
    later heal round over the full columns lands on EXACTLY the
    fault-free output — CRDT idempotence on device, which
    tests/test_faults.py pins.

    Decisions hash ``(seed, round, replica)`` — no RNG state, so any
    round can be replayed in isolation.
    """

    def __init__(self, seed: int = 0, *, drop: float = 0.0,
                 partition_every: int = 0, groups: int = 2):
        self.seed = seed
        self.drop = drop
        self.partition_every = partition_every
        self.groups = groups

    def _h(self, *key) -> float:
        import zlib

        return zlib.crc32(repr((self.seed,) + key).encode()) / 2**32

    def delivered_mask(self, round_idx: int, n_replicas: int) -> np.ndarray:
        """[R] bool: False = this replica's batch is lost this round."""
        mask = np.array(
            [self._h("drop", round_idx, r) >= self.drop
             for r in range(n_replicas)],
            dtype=bool,
        )
        from crdt_tpu.obs.recorder import get_recorder

        rec = get_recorder()
        if rec.enabled and not mask.all():
            rec.record(
                "gossip.drop", round=round_idx,
                replicas=np.flatnonzero(~mask).tolist(),
            )
        return mask

    def partition_masks(self, round_idx: int,
                        n_replicas: int) -> Optional[list]:
        """List of [R] bool group masks when this round is partitioned
        (round index divisible by ``partition_every``), else None.
        Group assignment is hashed per (round, replica), so healing
        and re-partitioning replay deterministically."""
        if not self.partition_every or round_idx % self.partition_every:
            return None
        assign = np.array(
            [int(self._h("part", round_idx, r) * self.groups)
             for r in range(n_replicas)]
        )
        from crdt_tpu.obs.recorder import get_recorder

        rec = get_recorder()
        if rec.enabled:
            rec.record(
                "gossip.partition", round=round_idx,
                groups=assign.tolist(),
            )
        return [assign == g for g in range(self.groups)]


def mask_packed(packed: np.ndarray, keep: np.ndarray) -> np.ndarray:
    """Withhold replicas' contributions from one packed [9, R, N]
    gossip input: rows where ``keep`` is False get their valid column
    (pack index 8) zeroed, so the gathered union treats every one of
    their ops as padding. The original block is untouched — a heal
    round re-presents it in full."""
    out = np.array(packed, copy=True)
    out[8, ~np.asarray(keep, dtype=bool), :] = 0
    return out


def synth_columns(
    n_replicas: int,
    ops_per_replica: int,
    *,
    num_maps: int = 4,
    keys_per_map: int = 64,
    num_lists: int = 0,
    seq_fraction: float = 0.5,
    seed: int = 0,
):
    """Synthetic replica-parallel workload as padded columns.

    Each replica r (client id r+1) writes `ops_per_replica` ops: map
    sets over `num_maps` root maps × `keys_per_map` interned keys, and
    — when ``num_lists`` > 0 — concurrent appends to shared lists
    (each item's origin is the replica's previous item in that list,
    the shape Yjs produces when isolated replicas append locally and
    then sync). The 1k-replica fan-in shape of the north star. Returns
    a dict of [R, N] arrays plus empty delete ranges. List root ids
    live above the map ids (num_maps..num_maps+num_lists-1).
    """
    rng = np.random.default_rng(seed)
    R, N = n_replicas, ops_per_replica
    n_seq = int(N * seq_fraction) if num_lists else 0
    n_map = N - n_seq
    cols = {
        "client": np.repeat(np.arange(1, R + 1, dtype=np.int32)[:, None], N, 1),
        "clock": np.repeat(np.arange(N, dtype=np.int64)[None, :], R, 0),
        "parent_is_root": np.ones((R, N), bool),
        "parent_a": np.empty((R, N), np.int64),
        "parent_b": np.full((R, N), -1, np.int64),
        "key_id": np.full((R, N), -1, np.int32),
        "origin_client": np.full((R, N), -1, np.int32),
        "origin_clock": np.full((R, N), -1, np.int64),
        "valid": np.ones((R, N), bool),
    }
    cols["parent_a"][:, :n_map] = rng.integers(0, num_maps, (R, n_map))
    cols["key_id"][:, :n_map] = rng.integers(0, keys_per_map, (R, n_map))
    if n_seq:
        lists = rng.integers(0, num_lists, (R, n_seq))
        for r in range(R):
            last_clock: dict = {}
            for j in range(n_seq):
                lst = int(lists[r, j])
                k = n_map + j
                cols["parent_a"][r, k] = num_maps + lst
                prev = last_clock.get(lst)
                if prev is not None:
                    cols["origin_client"][r, k] = r + 1
                    cols["origin_clock"][r, k] = prev
                last_clock[lst] = k  # this op's clock
    dels = (
        np.full(16, -1, np.int32),
        np.full(16, -1, np.int64),
        np.full(16, -1, np.int64),
    )
    return cols, dels
