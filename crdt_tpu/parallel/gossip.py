"""Replica-parallel gossip over a device mesh — ICI as the swarm fabric.

The reference's one parallelism axis is replica parallelism: N peers
full-mesh-gossiping updates over Hyperswarm and converging by CRDT
merge (SURVEY.md §2.2: `propagate` at crdt.js:385,445,...; the
ready/sync handshake at crdt.js:237-291). On TPU that maps to:

- replicas = a sharded batch dimension over a 1D ``Mesh`` axis;
- ``propagate`` (full-mesh gossip) = ``all_gather`` of the replicas'
  op columns over ICI;
- the merge every peer performs on receipt (``Y.applyUpdate``,
  crdt.js:294) = one vectorized ``converge_maps`` over the gathered
  union, computed replicated on every device — exactly the CRDT
  model, where each replica merges the same op set and reaches the
  same state;
- the state-vector handshake = per-replica SV build (scatter-max) +
  all-gather + the pairwise ``missing`` deficit matrix, replacing the
  reference's one-peer-at-a-time `encodeStateVector` exchange.

No tensor/pipeline/expert axes are invented: the reference has no
model compute to shard (SURVEY.md §2.2 parallelism census); the honest
scale story is replicas × ops, and ops scale inside each device's
static-shape columns.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from crdt_tpu.ops import statevec
from crdt_tpu.ops.merge import converge_maps
from crdt_tpu.ops.yata import converge_sequences

REPLICA_AXIS = "replicas"


def make_mesh(n_devices: Optional[int] = None, axis: str = REPLICA_AXIS) -> Mesh:
    """1D replica mesh over the first `n_devices` devices (all when
    None). Multi-host meshes work the same way: jax.devices() spans
    hosts and the collectives ride ICI within a slice / DCN across."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


HOST_AXIS = "hosts"


def make_mesh2d(n_hosts: int, devices_per_host: int) -> Mesh:
    """2D (hosts, replicas) mesh — the multi-host topology. Collectives
    over the inner axis ride ICI within each host's slice; collectives
    over the outer axis cross DCN. On a single-process test rig the
    same mesh shape runs on virtual devices; on a real multi-host pod
    jax.devices() spans processes and the axis split maps onto the
    physical fabric."""
    devs = jax.devices()
    need = n_hosts * devices_per_host
    if len(devs) < need:
        raise ValueError(f"need {need} devices, have {len(devs)}")
    if jax.process_count() > 1 and devices_per_host != jax.local_device_count():
        # the inner axis must stay inside one process, or every "ICI"
        # collective silently crosses DCN and the two-tier rationale
        # inverts
        raise ValueError(
            f"devices_per_host={devices_per_host} must equal "
            f"local_device_count()={jax.local_device_count()} on a "
            "multi-process pod"
        )
    return Mesh(
        np.asarray(devs[:need]).reshape(n_hosts, devices_per_host),
        (HOST_AXIS, REPLICA_AXIS),
    )


def make_gossip_step(mesh: Mesh, num_segments: int, num_clients: int):
    """Build the jitted full gossip+merge step for `mesh`.

    Step inputs (all sharded over the replica axis, shapes [R, N]):
    the op columns of each replica's pending update batch, plus
    replicated delete ranges ([D] triples). Outputs:

    - ``sv_local``  [R, C] per-replica state vectors (sharded)
    - ``global_sv`` [C] merged swarm state vector (replicated)
    - ``deficit``   [R, R] pairwise missing-clock totals (replicated)
      — the anti-entropy plan: entry (i, j) > 0 means i must send to j
    - ``winners``/``winner_visible`` [S] converged map winners over
      the whole union (replicated; indices into id-sorted union space)
    - ``seq_order``/``seq_seg``/``seq_rank`` [R*N] converged sequence
      document order over the union (replicated; id-sorted space,
      ``seq_order`` maps back to flattened caller rows) and
      ``seq_len`` [S] per-sequence lengths — the YATA half of the
      device applyUpdate (maps AND sequences, VERDICT r1 weak #5)
    - ``map_order`` [R*N] the MAP kernel's own id-sort permutation —
      ``winners`` decode through THIS, never through ``seq_order``
      (today the two kernels share one sort key, but that is an
      internal coincidence no assembler should couple to)
    """
    axis = mesh.axis_names[0]
    nd = mesh.devices.size

    col_specs = (P(axis, None),) * 9
    del_specs = (P(), P(), P())

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=col_specs + del_specs,
        out_specs=(P(axis, None),) + (P(),) * 9,
        # the replicated outputs derive only from all_gather'd values,
        # but the vma checker cannot prove that through converge_maps's
        # while_loop (pointer doubling); the P() specs are correct
        check_vma=False,
    )
    def step(
        client,
        clock,
        parent_is_root,
        parent_a,
        parent_b,
        key_id,
        origin_client,
        origin_clock,
        valid,
        d_client,
        d_start,
        d_end,
    ):
        # per-replica state vectors: scatter-max over the local shard
        sv_local = jax.vmap(
            lambda c, k, v: statevec.build(c, k, v, num_clients)
        )(client, clock, valid)

        # handshake fan-in: all-gather every replica's SV, derive the
        # merged swarm vector and the pairwise anti-entropy plan
        svs = jax.lax.all_gather(sv_local, axis).reshape(-1, num_clients)
        global_sv = statevec.merge(svs)
        deficit = statevec.missing(svs)

        # gossip fan-in: all-gather the op columns into the union every
        # replica would hold after a full propagate round
        def gather_flat(x):
            return jax.lax.all_gather(x, axis).reshape(-1)

        (
            u_client,
            u_clock,
            u_root,
            u_pa,
            u_pb,
            u_key,
            u_oc,
            u_ok,
            u_valid,
        ) = (
            gather_flat(x)
            for x in (
                client,
                clock,
                parent_is_root,
                parent_a,
                parent_b,
                key_id,
                origin_client,
                origin_clock,
                valid,
            )
        )

        # every replica merges the same union -> replicated converge
        map_order, _, winners, winner_visible, _, _ = converge_maps(
            u_client,
            u_clock,
            u_root,
            u_pa,
            u_pb,
            u_key,
            u_oc,
            u_ok,
            u_valid,
            d_client,
            d_start,
            d_end,
            num_segments=num_segments,
        )
        # ... and orders every sequence in the same union (the YATA
        # half of applyUpdate; same id-sort, XLA CSEs the shared work)
        seq_order, seq_seg, seq_rank, seq_len = converge_sequences(
            u_client,
            u_clock,
            u_root,
            u_pa,
            u_pb,
            u_key,
            u_oc,
            u_ok,
            u_valid,
            num_segments=num_segments,
        )
        return (
            sv_local,
            global_sv,
            deficit,
            winners,
            winner_visible,
            seq_order,
            seq_seg,
            seq_rank,
            seq_len,
            map_order,
        )

    return jax.jit(step)


def make_hierarchical_gossip_step(mesh: Mesh, num_segments: int,
                                  num_clients: int):
    """Two-tier gossip over a (hosts, replicas) mesh: fan-in happens as
    an all-gather over the intra-host replica axis (ICI) followed by an
    all-gather over the host axis (DCN) — the reference's full-mesh
    swarm mapped onto a pod's physical fabric instead of one flat
    collective. Outputs match :func:`make_gossip_step` on the same
    flattened columns (differential-tested in tests/test_parallel.py).

    Step inputs: [R, N] columns with R sharded over (hosts, replicas);
    replicated delete ranges. Outputs as in :func:`make_gossip_step`.
    """
    host, rep = mesh.axis_names

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P((host, rep), None),) * 9 + (P(), P(), P()),
        out_specs=(P((host, rep), None),) + (P(),) * 9,
        check_vma=False,
    )
    def step(
        client, clock, parent_is_root, parent_a, parent_b, key_id,
        origin_client, origin_clock, valid, d_client, d_start, d_end,
    ):
        sv_local = jax.vmap(
            lambda c, k, v: statevec.build(c, k, v, num_clients)
        )(client, clock, valid)

        def gather2(x):
            # ICI first (cheap, wide), then DCN (few, slow links carry
            # each host's already-combined slice exactly once)
            x = jax.lax.all_gather(x, rep)
            x = jax.lax.all_gather(x, host)
            return x.reshape(-1, *x.shape[3:])

        svs = gather2(sv_local)  # [R, num_clients]
        global_sv = statevec.merge(svs)
        deficit = statevec.missing(svs)

        union = [
            gather2(x).reshape(-1)
            for x in (client, clock, parent_is_root, parent_a, parent_b,
                      key_id, origin_client, origin_clock, valid)
        ]
        map_order, _, winners, winner_visible, _, _ = converge_maps(
            *union, d_client, d_start, d_end, num_segments=num_segments
        )
        seq_order, seq_seg, seq_rank, seq_len = converge_sequences(
            *union, num_segments=num_segments
        )
        return (sv_local, global_sv, deficit, winners, winner_visible,
                seq_order, seq_seg, seq_rank, seq_len, map_order)

    return jax.jit(step)


def synth_columns(
    n_replicas: int,
    ops_per_replica: int,
    *,
    num_maps: int = 4,
    keys_per_map: int = 64,
    num_lists: int = 0,
    seq_fraction: float = 0.5,
    seed: int = 0,
):
    """Synthetic replica-parallel workload as padded columns.

    Each replica r (client id r+1) writes `ops_per_replica` ops: map
    sets over `num_maps` root maps × `keys_per_map` interned keys, and
    — when ``num_lists`` > 0 — concurrent appends to shared lists
    (each item's origin is the replica's previous item in that list,
    the shape Yjs produces when isolated replicas append locally and
    then sync). The 1k-replica fan-in shape of the north star. Returns
    a dict of [R, N] arrays plus empty delete ranges. List root ids
    live above the map ids (num_maps..num_maps+num_lists-1).
    """
    rng = np.random.default_rng(seed)
    R, N = n_replicas, ops_per_replica
    n_seq = int(N * seq_fraction) if num_lists else 0
    n_map = N - n_seq
    cols = {
        "client": np.repeat(np.arange(1, R + 1, dtype=np.int32)[:, None], N, 1),
        "clock": np.repeat(np.arange(N, dtype=np.int64)[None, :], R, 0),
        "parent_is_root": np.ones((R, N), bool),
        "parent_a": np.empty((R, N), np.int64),
        "parent_b": np.full((R, N), -1, np.int64),
        "key_id": np.full((R, N), -1, np.int32),
        "origin_client": np.full((R, N), -1, np.int32),
        "origin_clock": np.full((R, N), -1, np.int64),
        "valid": np.ones((R, N), bool),
    }
    cols["parent_a"][:, :n_map] = rng.integers(0, num_maps, (R, n_map))
    cols["key_id"][:, :n_map] = rng.integers(0, keys_per_map, (R, n_map))
    if n_seq:
        lists = rng.integers(0, num_lists, (R, n_seq))
        for r in range(R):
            last_clock: dict = {}
            for j in range(n_seq):
                lst = int(lists[r, j])
                k = n_map + j
                cols["parent_a"][r, k] = num_maps + lst
                prev = last_clock.get(lst)
                if prev is not None:
                    cols["origin_client"][r, k] = r + 1
                    cols["origin_clock"][r, k] = prev
                last_clock[lst] = k  # this op's clock
    dels = (
        np.full(16, -1, np.int32),
        np.full(16, -1, np.int64),
        np.full(16, -1, np.int64),
    )
    return cols, dels
