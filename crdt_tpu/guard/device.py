"""Device failure policy: the guarded converge-dispatch ladder.

A converge dispatch that dies mid-merge (TPU OOM, preemption, a
transient XLA ``RuntimeError``) used to propagate straight through
``Crdt.apply_updates`` and kill the apply path. Every guarded dispatch
now runs the ladder

    attempt → retry once → split the work in half → host route

where each rung is strictly cheaper in assumptions: the retry covers
transient faults (preemption, a dropped tunnel interaction), the split
covers size-dependent faults (an OOM that a half-size batch survives —
only offered where the work genuinely halves, e.g. independent
parents), and the host route covers a dead device entirely (the scalar
path is the semantics oracle, so the answer is bit-identical, just
slower). Counters: ``device.retries``, ``device.fallback`` (+
``device.fallback_by{route=...}``), ``device.dispatch_errors``.

Fault injection rides :func:`crdt_tpu.ops.device.set_device_fault_hook`
— the hook fires BEFORE each guarded attempt and may raise
``RuntimeError`` to simulate a device fault, so chaos schedules never
need a real dying accelerator (see
:class:`crdt_tpu.guard.faults.DeviceFaultPlan`).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from crdt_tpu.obs.recorder import get_recorder
from crdt_tpu.obs.tracer import get_tracer


def _attempt(stage: str, run: Callable, attempt: int):
    from crdt_tpu.ops.device import device_fault_hook

    hook = device_fault_hook()
    if hook is not None:
        hook(stage, attempt)  # may raise RuntimeError (injected fault)
    return run()


def dispatch_guarded(
    stage: str,
    run: Callable[[], object],
    *,
    split: Optional[Callable[[], Optional[List[Tuple[Callable, Callable]]]]] = None,
    host: Optional[Callable[[], object]] = None,
):
    """Run ``run()`` (a device dispatch) under the failure ladder.

    ``split``, when given, returns a list of ``(run_half, host_half)``
    thunk pairs covering the same work in independent pieces (or
    ``None``/a single pair when the work cannot split); each piece is
    re-guarded individually. ``host`` recomputes the WHOLE result on
    host. With neither rung available the second failure re-raises —
    the caller opted out of degradation.

    Only ``RuntimeError`` (the class XLA device errors subclass) is a
    ladder trigger; anything else is a programming error and
    propagates immediately.
    """
    tracer = get_tracer()
    err: Optional[RuntimeError] = None
    for attempt in (0, 1):
        try:
            if attempt:
                tracer.count("device.retries")
            return _attempt(stage, run, attempt)
        except RuntimeError as e:
            err = e
            tracer.count("device.dispatch_errors")
    rec = get_recorder()
    if rec.enabled:
        rec.record("device.fault", stage=stage, error=repr(err)[:200])
    halves = split() if split is not None else None
    if halves and len(halves) > 1:
        tracer.count("device.fallback")
        tracer.count("device.fallback_by", labels={"route": "split"})
        return [
            dispatch_guarded(stage, run_half, host=host_half)
            for run_half, host_half in halves
        ]
    if host is not None:
        tracer.count("device.fallback")
        tracer.count("device.fallback_by", labels={"route": "host"})
        return host()
    raise err
