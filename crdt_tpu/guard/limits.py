"""Shared bounded-stash eviction policy.

One implementation of the pending-stash eviction ranking, used by both
backends (:class:`crdt_tpu.core.engine.Engine` and
:class:`crdt_tpu.models.incremental.IncrementalReplay`) so the
fairness rule and the recovery bookkeeping cannot drift apart.
"""

from typing import Dict, Iterable, List, Tuple


def evict_deepest(
    keys: Iterable[Tuple[int, int]], limit: int
) -> Tuple[List[Tuple[int, int]], Dict[int, Tuple[int, int]]]:
    """Pick which ``(client, clock)`` ids to evict to shrink a pending
    stash to ``limit``: the ids DEEPEST in their own client's queue.
    Per-client clocks are contiguous, so an id's rank within its
    client (0 = the next to integrate once the gap heals) measures
    distance from its missing dependency — ranking per client, not by
    absolute clock, keeps one flooding fresh client (low clocks) from
    starving a long-lived client's nearly-ready records.

    Returns ``(evicted_keys, ranges)``; ``ranges`` maps client ->
    ``(lo, hi)`` evicted clock range for the replica layer's targeted
    re-probe. Safe by the sync protocol's own math: evicted records
    never advanced the state vector, so any ready-probe answer
    re-ships them.
    """
    keys = sorted(keys)
    n_evict = len(keys) - limit
    if n_evict <= 0:
        return [], {}
    ranked = []
    prev_client, rank = None, 0
    for key in keys:
        rank = rank + 1 if key[0] == prev_client else 0
        prev_client = key[0]
        ranked.append((rank, key[1], key))
    ranked.sort(reverse=True)  # deepest-in-queue first
    evicted = [key for _, _, key in ranked[:n_evict]]
    ranges: Dict[int, Tuple[int, int]] = {}
    for c, k in evicted:
        lo, hi = ranges.get(c, (k, k))
        ranges[c] = (min(lo, k), max(hi, k))
    return evicted, ranges
