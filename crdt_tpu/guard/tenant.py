"""Per-tenant admission budgets + fairness for multi-doc serving.

The round-10 ladders bound ONE replica's memory/disk/device exposure;
a multi-tenant batch server (:class:`crdt_tpu.models.multidoc.
MultiDocServer`) adds the cross-tenant failure mode: one flooding doc
filling the shared admission queue until every other tenant's deltas
wait behind it. Same discipline, tenant-scoped:

- **budget** — each tenant's PENDING (admitted, not yet converged)
  updates are bounded by bytes and count. Overflow sheds the
  tenant's OWN oldest pending updates (keep-the-newest, the
  round-10 inbox rule: a single over-budget repair blob still
  lands whole). A flooding tenant therefore degrades alone — its
  backlog is trimmed — while every other tenant's queue, and the
  bytes they converge to, are untouched (tests/test_multidoc.py
  chaos leg).
- **fairness** — dispatch admission orders dirty docs by how long
  ago they were last served (then doc id for determinism), so a
  tenant that fills every tick's row budget cannot starve the rest:
  the docs left out of this tick are FIRST in line for the next.

Counters (README "Observability" registry): ``tenant.shed`` /
``tenant.shed_bytes`` on every trimmed update, the
``tenant.pending_bytes`` gauge for the queue's live total.
"""

from __future__ import annotations

from typing import Deque, Dict, Iterable, List, Tuple


class TenantBudget:
    """Byte + count budget over one tenant's pending update queue."""

    def __init__(self, max_bytes: int = 1 << 22,
                 max_updates: int = 4096):
        self.max_bytes = int(max_bytes)
        self.max_updates = int(max_updates)

    def trim(self, queue: Deque[bytes]) -> List[bytes]:
        """Shed OLDEST pending updates until ``queue`` fits the
        budget; the newest update is always kept (keep-the-newest).
        Returns the shed blobs (callers count them)."""
        shed: List[bytes] = []
        size = sum(len(b) for b in queue)
        while len(queue) > 1 and (
            size > self.max_bytes or len(queue) > self.max_updates
        ):
            old = queue.popleft()
            size -= len(old)
            shed.append(old)
        return shed


def fair_order(doc_ids: Iterable,
               last_served: Dict) -> List:
    """Dirty docs in service order: least-recently-served first,
    then doc id (deterministic). ``last_served`` maps doc id -> the
    tick index it last converged in (absent = never served, which
    sorts first)."""
    return sorted(doc_ids, key=lambda d: (last_served.get(d, -1), d))


def pack_batches(rows_of: List[Tuple[object, int]],
                 max_rows: int) -> List[List[object]]:
    """Greedy bin-pack of (doc, row_count) pairs — in the given
    fairness order — into dispatch batches of at most ``max_rows``
    rows. A doc larger than ``max_rows`` gets a batch of its own
    (it cannot be split: segments never cross docs, and a doc's
    converge is whole-history)."""
    batches: List[List[object]] = []
    cur: List[object] = []
    cur_rows = 0
    for doc_id, n in rows_of:
        if cur and cur_rows + n > max_rows:
            batches.append(cur)
            cur, cur_rows = [], 0
        cur.append(doc_id)
        cur_rows += n
    if cur:
        batches.append(cur)
    return batches
